"""``obs`` — the layer that closes the loop from raw signals to
decisions.

The platform already *emits* everything (Prometheus families, W3C
traces with critical-path attribution, lockgraph reports); this
package *consumes* them:

- :mod:`.timeseries` — in-process ring-buffer TSDB sampling the shared
  registry, with cross-shard ``/metrics`` federation,
- :mod:`.slo` — declarative SLOs evaluated as multi-window burn rates,
  with an ok/warning/critical state machine and hysteresis,
- :mod:`.flight` — the flight recorder: one self-contained bundle
  (metric window, slow traces + critical paths, alerts, shard
  liveness, lockgraph) per incident,
- :mod:`.runmeta` — the shared artifact header the perf ratchet uses
  to refuse mismatched-arm comparisons.

:class:`Observer` bundles the three runtime pieces behind one object
so the dashboard and the chaos harnesses wire a single thing.
"""

from __future__ import annotations

import threading
import time

from .flight import FlightRecorder
from .runmeta import build_run_meta, compatible
from .slo import (GaugeSLO, LatencySLO, RateSLO, SLO, SLOEngine,
                  TenantRateSLO, Window, default_slos,
                  install_probe_bridges)
from .timeseries import TimeSeriesDB, parse_exposition

__all__ = [
    "FlightRecorder", "GaugeSLO", "LatencySLO", "Observer", "RateSLO",
    "SLO", "SLOEngine", "TenantRateSLO", "TimeSeriesDB", "Window",
    "build_run_meta", "compatible", "default_slos",
    "install_probe_bridges", "parse_exposition",
]


class Observer:
    """TSDB + SLO engine + flight recorder, wired together.

    ``tick()`` is one synchronous sample-and-evaluate pass; callers
    either drive it themselves (harness loops, on-demand dashboard
    reads via :meth:`maybe_tick`) or let :meth:`start` run it on a
    background interval. An SLO transition into ``critical``
    auto-triggers the flight recorder.
    """

    def __init__(self, *, interval_s: float = 2.0,
                 window_s: float = 300.0,
                 shard_urls: dict | None = None,
                 slos: list | None = None,
                 run_meta: dict | None = None,
                 flight_window_s: float = 120.0,
                 liveness=None, registry=None,
                 max_series: int = 4096):
        # 4096 series headroom: federating N shards multiplies every
        # histogram family by its bucket count; at the 1024 default a
        # 4-shard chaos run evicts live series mid-incident
        self.interval_s = float(interval_s)
        self.tsdb = TimeSeriesDB(registry=registry,
                                 interval_s=interval_s,
                                 window_s=window_s,
                                 max_series=max_series)
        for name, url in (shard_urls or {}).items():
            self.tsdb.add_scrape(name, url)
        self.engine = SLOEngine(
            self.tsdb, default_slos() if slos is None else slos)
        # the per-tenant jaxcheck SLOs only burn if the probes feed
        # the counters — hang the bridge the moment an Observer exists
        install_probe_bridges()
        self.flight = FlightRecorder(
            self.tsdb, window_s=flight_window_s, liveness=liveness,
            shard_urls=shard_urls, run_meta=run_meta)
        self.flight.attach_engine(self.engine)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._last_tick = 0.0

    # ---- driving -----------------------------------------------------

    def tick(self, now: float | None = None) -> list[dict]:
        """Sample every source, evaluate every SLO; returns the alert
        transitions this pass caused."""
        self.tsdb.sample(now)
        fired = self.engine.evaluate(now)
        self._last_tick = time.time()
        return fired

    def maybe_tick(self) -> None:
        """Tick if the last pass is older than the interval — the
        on-demand mode ``GET /api/alerts`` uses so webapp construction
        never spawns a thread."""
        if time.time() - self._last_tick >= self.interval_s:
            self.tick()

    def start(self) -> None:
        """Background tick loop (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="obs-observer", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)

    def _run(self) -> None:
        from kubeflow_rm_tpu.controlplane import metrics
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - observer must survive
                metrics.swallowed("obs.observer", "tick")

    # ---- event hooks -------------------------------------------------

    def on_shard_death(self, name: str, exitcode=None) -> dict | None:
        """The ``ShardRunner`` watchdog's hook: fold the death into the
        TSDB/SLO state immediately (the counter was just incremented),
        then record a flight bundle."""
        self.tick()
        return self.flight.trigger(
            "shard_death", detail={"shard": name, "exitcode": exitcode},
            auto=True)

    # ---- snapshots ---------------------------------------------------

    def alerts(self) -> dict:
        snap = self.engine.snapshot()
        snap["tsdb"] = {"series": self.tsdb.series_count(),
                        "evictions": self.tsdb.evictions,
                        "scrape_errors": self.tsdb.scrape_errors,
                        "samples_taken": self.tsdb.samples_taken}
        snap["flight"] = {"bundles": len(self.flight.bundles()),
                          "triggered_total":
                              self.flight.triggered_total,
                          "suppressed_total":
                              self.flight.suppressed_total}
        return snap
