"""In-process ring-buffer TSDB over the shared Prometheus registry.

The platform emits ~20 metric families into ``metrics.REGISTRY`` but,
until this module, nothing retained them over time: every consumer
(dashboard, SLOs, post-mortems) saw only the instantaneous value. The
``TimeSeriesDB`` samples every family on an interval and keeps a
bounded ring of ``(t, value)`` points per labelled series, reducing at
*query* time with the semantics each family type wants:

- counter    -> windowed per-second **rate** (reset-aware),
- gauge      -> **last** value / windowed average,
- histogram  -> windowed **percentiles** from cumulative-bucket deltas.

Cross-shard federation: the dashboard process registers each shard's
REST URL with :meth:`TimeSeriesDB.add_scrape`; the sampler then pulls
every shard's ``/metrics`` exposition alongside the local registry and
ingests the parsed samples with an ``instance=<shard>`` label. Families
that already carry the r11 ``shard`` label (``wal_fsync_seconds``)
disambiguate on their own; the injected ``instance`` label covers the
rest (two shards both exporting ``workqueue_depth{name="notebook"}``
must not collapse into one series).

Memory is bounded twice over: each series ring holds at most
``window_s / interval_s`` points (plus slack), and the series map is
capped at ``max_series`` — when label cardinality grows past the cap
the least-recently-updated series is evicted (and counted), so a
misbehaving label can never OOM the control plane.
"""

from __future__ import annotations

import math
import re
import threading
import time
from collections import deque
from typing import Iterable

from kubeflow_rm_tpu.analysis.lockgraph import make_lock

# sample kinds, normalised across local collection and federation
COUNTER = "counter"
GAUGE = "gauge"
BUCKET = "histogram_bucket"   # cumulative counter per ``le``

_SUFFIX_KINDS = (("_bucket", BUCKET), ("_count", COUNTER),
                 ("_sum", COUNTER), ("_total", COUNTER))

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _sample_kind(family_type: str, sample_name: str) -> str:
    """Kind of one exposition sample given its family's TYPE."""
    if family_type == "gauge":
        return GAUGE
    if family_type in ("histogram", "summary"):
        for suffix, kind in _SUFFIX_KINDS:
            if sample_name.endswith(suffix):
                return kind
        return GAUGE  # summary quantile samples read as gauges
    if family_type == "counter":
        return COUNTER
    # untyped: fall back on the naming convention
    for suffix, kind in _SUFFIX_KINDS:
        if sample_name.endswith(suffix):
            return kind
    return GAUGE


def parse_exposition(text: str) -> list[tuple[str, dict, str, float]]:
    """Parse Prometheus text exposition into
    ``(sample_name, labels, kind, value)`` tuples, keeping labels —
    unlike the metrics-service scraper, which sums them away. ``NaN``
    samples and the ``_created`` timestamps are dropped."""
    types: dict[str, str] = {}
    out: list[tuple[str, dict, str, float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) == 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name, raw_labels, raw_value = m.groups()
        if name.endswith("_created"):
            continue
        try:
            value = float(raw_value)
        except ValueError:
            continue
        if math.isnan(value):
            continue
        labels = {k: v.replace('\\"', '"').replace("\\\\", "\\")
                  .replace("\\n", "\n")
                  for k, v in _LABEL_RE.findall(raw_labels or "")}
        family = name
        for suffix, _ in _SUFFIX_KINDS:
            if family.endswith(suffix) and family[:-len(suffix)] in types:
                family = family[:-len(suffix)]
                break
        out.append((name, labels, _sample_kind(types.get(family, ""),
                                               name), value))
    return out


class _Series:
    __slots__ = ("name", "labels", "kind", "points", "last_t")

    def __init__(self, name: str, labels: dict, kind: str, maxlen: int):
        self.name = name
        self.labels = labels
        self.kind = kind
        self.points: deque = deque(maxlen=maxlen)
        self.last_t = 0.0


class TimeSeriesDB:
    """Bounded in-memory TSDB; see module docstring for semantics."""

    def __init__(self, *, registry=None, interval_s: float = 2.0,
                 window_s: float = 300.0, max_series: int = 1024,
                 max_points: int | None = None):
        if registry is None:
            from kubeflow_rm_tpu.controlplane import metrics
            registry = metrics.REGISTRY
        self._registry = registry
        self.interval_s = float(interval_s)
        self.window_s = float(window_s)
        self._max_points = max_points or max(
            8, int(self.window_s / self.interval_s) + 8)
        self._max_series = int(max_series)
        self._series: dict[tuple, _Series] = {}
        self._lock = make_lock("obs.tsdb")
        self._scrapes: dict[str, str] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.evictions = 0          # series dropped at the cardinality cap
        self.scrape_errors = 0      # failed shard /metrics pulls
        self.samples_taken = 0

    # ---- federation --------------------------------------------------

    def add_scrape(self, name: str, url: str) -> None:
        """Register a shard's base URL; every sampling pass pulls its
        ``/metrics`` and ingests the series with ``instance=name``."""
        self._scrapes[name] = url.rstrip("/")

    def remove_scrape(self, name: str) -> None:
        """Forget a shard (elastic merge retired it) — otherwise every
        pass after the scale-down counts a scrape error against a
        process that was deliberately stopped. Its historical series
        age out of the window naturally."""
        self._scrapes.pop(name, None)

    # ---- sampling ----------------------------------------------------

    def sample(self, now: float | None = None) -> int:
        """One synchronous sampling pass (local registry + every
        registered shard scrape). Returns the number of samples
        ingested. Collection happens with NO TSDB lock held; only the
        final ingest takes it."""
        now = time.time() if now is None else now
        batch: list[tuple[str, dict, str, float]] = []
        batch.extend(self._collect_local())
        for src, url in list(self._scrapes.items()):
            batch.extend(self._collect_scrape(src, url))
        with self._lock:
            for name, labels, kind, value in batch:
                self._ingest_locked(now, name, labels, kind, value)
            self.samples_taken += 1
        return len(batch)

    def _collect_local(self) -> Iterable[tuple[str, dict, str, float]]:
        from kubeflow_rm_tpu.controlplane import metrics
        try:
            # free-chip / fragmentation gauges are recomputed on
            # stats(); refresh so the sample reads the live pool
            from kubeflow_rm_tpu.controlplane import scheduler
            scheduler.refresh_gauges()
        except Exception:
            metrics.swallowed("obs.tsdb", "refresh_gauges")
        out = []
        for fam in self._registry.collect():
            ftype = getattr(fam, "type", "")
            for s in fam.samples:
                if s.name.endswith("_created"):
                    continue
                if isinstance(s.value, float) and math.isnan(s.value):
                    continue
                out.append((s.name, dict(s.labels),
                            _sample_kind(ftype, s.name), float(s.value)))
        return out

    def _collect_scrape(self, src: str, url: str
                        ) -> list[tuple[str, dict, str, float]]:
        import urllib.request

        from kubeflow_rm_tpu.controlplane import metrics
        try:
            with urllib.request.urlopen(url + "/metrics",
                                        timeout=2.0) as resp:
                text = resp.read().decode()
        except Exception:  # noqa: BLE001 - shard may be down mid-chaos
            metrics.swallowed("obs.tsdb", f"scrape {src}")
            self.scrape_errors += 1
            return []
        out = []
        for name, labels, kind, value in parse_exposition(text):
            labels.setdefault("instance", src)
            out.append((name, labels, kind, value))
        return out

    def ingest(self, now: float, name: str, labels: dict | None,
               kind: str, value: float) -> None:
        """Directly ingest one sample (tests, replay, push sources)."""
        with self._lock:
            self._ingest_locked(now, name, dict(labels or {}), kind,
                                float(value))

    def _ingest_locked(self, now: float, name: str, labels: dict,
                       kind: str, value: float) -> None:
        key = (name, tuple(sorted(labels.items())))
        series = self._series.get(key)
        if series is None:
            if len(self._series) >= self._max_series:
                victim = min(self._series,
                             key=lambda k: self._series[k].last_t)
                del self._series[victim]
                self.evictions += 1
            series = _Series(name, labels, kind, self._max_points)
            self._series[key] = series
        series.points.append((now, value))
        series.last_t = now

    # ---- background sampler ------------------------------------------

    def start(self) -> None:
        """Start the background sampler (idempotent)."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="obs-tsdb-sampler", daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)

    def _run(self) -> None:
        from kubeflow_rm_tpu.controlplane import metrics
        while not self._stop.wait(self.interval_s):
            try:
                self.sample()
            except Exception:  # noqa: BLE001 - sampler must survive
                metrics.swallowed("obs.tsdb", "sample pass")

    # ---- queries -----------------------------------------------------

    def _match_locked(self, name: str, labels: dict | None
                      ) -> list[_Series]:
        want = (labels or {}).items()
        return [s for s in self._series.values()
                if s.name == name
                and all(s.labels.get(k) == v for k, v in want)]

    @staticmethod
    def _points_in(series: _Series, cut: float) -> list[tuple]:
        return [p for p in series.points if p[0] >= cut]

    def range(self, name: str, labels: dict | None = None,
              window_s: float | None = None,
              now: float | None = None) -> list[dict]:
        """Raw points for every series matching ``name`` + the label
        subset, trimmed to the trailing window. Returns copies — the
        caller can hold them without pinning the ring."""
        now = time.time() if now is None else now
        cut = now - (window_s if window_s is not None else self.window_s)
        with self._lock:
            return [{"name": s.name, "labels": dict(s.labels),
                     "kind": s.kind,
                     "points": [[t, v] for t, v in s.points if t >= cut]}
                    for s in self._match_locked(name, labels)]

    def latest(self, name: str, labels: dict | None = None
               ) -> float | None:
        """Sum of each matching series' last value (gauge semantics;
        summing mirrors ``metrics.registry_value`` so federated shard
        gauges aggregate the same way the facade does)."""
        with self._lock:
            matched = self._match_locked(name, labels)
            vals = [s.points[-1][1] for s in matched if s.points]
        return sum(vals) if vals else None

    def rate(self, name: str, labels: dict | None = None,
             window_s: float | None = None,
             now: float | None = None) -> float | None:
        """Windowed per-second rate of a (cumulative) counter, summed
        over matching series. Resets are handled by accumulating only
        positive deltas. ``None`` when no series has >=2 points in the
        window."""
        now = time.time() if now is None else now
        window_s = window_s if window_s is not None else self.window_s
        cut = now - window_s
        total = 0.0
        seen = False
        with self._lock:
            matched = self._match_locked(name, labels)
            windows = [self._points_in(s, cut) for s in matched]
        for pts in windows:
            if len(pts) < 2:
                continue
            seen = True
            inc = sum(max(0.0, b[1] - a[1])
                      for a, b in zip(pts, pts[1:]))
            span = pts[-1][0] - pts[0][0]
            if span > 0:
                total += inc / span
        return total if seen else None

    def gauge_avg(self, name: str, labels: dict | None = None,
                  window_s: float | None = None,
                  now: float | None = None) -> float | None:
        """Time-mean of a gauge over the window (sum across matching
        series of their own means)."""
        now = time.time() if now is None else now
        window_s = window_s if window_s is not None else self.window_s
        cut = now - window_s
        vals = []
        with self._lock:
            matched = self._match_locked(name, labels)
            windows = [self._points_in(s, cut) for s in matched]
        for pts in windows:
            if pts:
                vals.append(sum(v for _, v in pts) / len(pts))
        return sum(vals) if vals else None

    def _bucket_deltas(self, name: str, labels: dict | None,
                       window_s: float, now: float) -> dict[float, float]:
        """Windowed increment per ``le`` of a histogram family,
        aggregated across matching series (multi-shard federation sums
        the per-shard buckets, which is exactly Prometheus semantics)."""
        cut = now - window_s
        deltas: dict[float, float] = {}
        with self._lock:
            matched = self._match_locked(name + "_bucket", labels)
            snap = [(dict(s.labels), self._points_in(s, cut))
                    for s in matched]
        for lbls, pts in snap:
            if len(pts) < 2:
                continue
            le_raw = lbls.get("le", "")
            le = math.inf if le_raw in ("+Inf", "inf") else float(le_raw)
            inc = sum(max(0.0, b[1] - a[1]) for a, b in zip(pts, pts[1:]))
            deltas[le] = deltas.get(le, 0.0) + inc
        return deltas

    def percentile(self, name: str, q: float,
                   labels: dict | None = None,
                   window_s: float | None = None,
                   now: float | None = None) -> float | None:
        """Windowed percentile (``q`` in [0,1]) from cumulative-bucket
        deltas with linear interpolation inside the landing bucket.
        ``name`` is the family base name (no ``_bucket`` suffix)."""
        now = time.time() if now is None else now
        window_s = window_s if window_s is not None else self.window_s
        deltas = self._bucket_deltas(name, labels, window_s, now)
        if not deltas:
            return None
        les = sorted(deltas)
        total = deltas.get(math.inf, max(deltas.values()))
        if total <= 0:
            return None
        target = q * total
        prev_le, prev_cum = 0.0, 0.0
        for le in les:
            cum = deltas[le]
            if cum >= target:
                if le is math.inf:
                    return prev_le
                if cum == prev_cum:
                    return le
                frac = (target - prev_cum) / (cum - prev_cum)
                return prev_le + frac * (le - prev_le)
            prev_le, prev_cum = (0.0 if le is math.inf else le), cum
        return prev_le

    def bad_fraction(self, name: str, threshold: float,
                     labels: dict | None = None,
                     window_s: float | None = None,
                     now: float | None = None
                     ) -> tuple[float, float] | None:
        """``(fraction_of_events_above_threshold, total_events)`` over
        the window — the burn-rate numerator for latency SLOs. Uses the
        smallest bucket bound >= threshold (recorded SLOs should pick
        thresholds on bucket bounds). ``None`` when the window saw no
        events."""
        now = time.time() if now is None else now
        window_s = window_s if window_s is not None else self.window_s
        deltas = self._bucket_deltas(name, labels, window_s, now)
        if not deltas:
            return None
        total = deltas.get(math.inf)
        if total is None:
            total = max(deltas.values())
        if total <= 0:
            return None
        good_les = [le for le in deltas if le >= threshold]
        good = deltas[min(good_les)] if good_les else 0.0
        bad = max(0.0, total - good)
        return (bad / total, total)

    def label_values(self, name: str, key: str) -> list[str]:
        """Distinct values of label ``key`` across series of family
        ``name`` — how per-tenant SLOs enumerate the tenants the
        counters have actually seen (no tenant registry needed)."""
        with self._lock:
            return sorted({s.labels[key] for s in self._series.values()
                           if s.name == name and key in s.labels})

    # ---- introspection / dump ---------------------------------------

    def series_names(self) -> list[str]:
        with self._lock:
            return sorted({s.name for s in self._series.values()})

    def series_count(self) -> int:
        with self._lock:
            return len(self._series)

    def dump(self, window_s: float | None = None,
             now: float | None = None) -> list[dict]:
        """Every series' trailing window — the flight recorder's
        ``metrics`` section. Bounded by construction (ring x cap)."""
        now = time.time() if now is None else now
        cut = now - (window_s if window_s is not None else self.window_s)
        with self._lock:
            return [{"name": s.name, "labels": dict(s.labels),
                     "kind": s.kind,
                     "points": [[round(t, 3), v] for t, v in s.points
                                if t >= cut]}
                    for s in self._series.values() if s.points]
