"""Declarative SLOs evaluated as multi-window burn rates over the TSDB.

An SLO here is "target + window set": the target defines an error
budget, and each :class:`Window` pairs a long and a short evaluation
window with a burn-rate threshold and a severity — the classic
multi-window multi-burn-rate alerting shape (long window for
significance, short window so a recovered system stops paging). Burn
rate is always *budget consumption speed*: ``1.0`` means exactly
spending the budget, ``>1`` means on track to blow it.

Three SLO flavours cover every family the registry exports:

- :class:`LatencySLO` — histogram-backed; budget is the allowed
  fraction of events slower than ``threshold_s``; burn =
  bad_fraction / (1 - target).
- :class:`RateSLO` — counter-backed (``swallowed_errors_total``,
  ``shard_deaths_total``); burn = observed rate / allowed rate.
- :class:`GaugeSLO` — gauge-backed (fragmentation); burn =
  windowed mean / threshold, so *sustained* elevation alerts while a
  transient spike does not.

The :class:`SLOEngine` runs the ok -> warning -> critical state
machine with hysteresis: severity escalates the moment any window
pair's burn crosses its threshold, but de-escalates only after the
long-window burn stays below ``clear_ratio x threshold`` for
``hold_s`` — a series oscillating around the boundary latches at its
peak severity instead of flapping. Transitions are recorded and fanned
out to callbacks (the flight recorder hooks ``to == "critical"``).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field

from kubeflow_rm_tpu.analysis.lockgraph import make_lock

from .timeseries import TimeSeriesDB

_RANK = {"ok": 0, "warning": 1, "critical": 2}


@dataclass(frozen=True)
class Window:
    """One (long, short) burn-rate evaluation pair."""
    long_s: float
    short_s: float
    burn: float          # threshold, as a multiple of budget burn speed
    severity: str        # "warning" | "critical"


@dataclass
class SLO:
    """Base declarative objective; subclasses define ``burn_rate``."""
    name: str
    metric: str
    windows: tuple[Window, ...]
    labels: dict | None = None
    description: str = ""

    def burn_rate(self, tsdb: TimeSeriesDB, window_s: float,
                  now: float | None = None) -> float | None:
        raise NotImplementedError

    def spec(self) -> dict:
        return {"name": self.name, "metric": self.metric,
                "labels": dict(self.labels or {}),
                "kind": type(self).__name__,
                "description": self.description,
                "windows": [vars(w) for w in self.windows]}


@dataclass
class LatencySLO(SLO):
    """``target`` fraction of events must complete under
    ``threshold_s``; evaluated from windowed histogram-bucket deltas."""
    threshold_s: float = 1.0
    target: float = 0.95

    def burn_rate(self, tsdb, window_s, now=None):
        got = tsdb.bad_fraction(self.metric, self.threshold_s,
                                self.labels, window_s, now=now)
        if got is None:
            return None           # no events in window -> no signal
        bad_frac, _total = got
        budget = max(1e-9, 1.0 - self.target)
        return bad_frac / budget

    def spec(self):
        d = super().spec()
        d.update(threshold_s=self.threshold_s, target=self.target)
        return d


@dataclass
class RateSLO(SLO):
    """Counter family whose rate must stay under ``allowed_per_s``."""
    allowed_per_s: float = 1.0

    def burn_rate(self, tsdb, window_s, now=None):
        rate = tsdb.rate(self.metric, self.labels, window_s, now=now)
        if rate is None:
            return None
        return rate / max(1e-12, self.allowed_per_s)

    def spec(self):
        d = super().spec()
        d.update(allowed_per_s=self.allowed_per_s)
        return d


@dataclass
class TenantRateSLO(RateSLO):
    """Per-tenant counter family: the SLO burns at the rate of the
    WORST tenant, not the fleet sum — one notebook retracing its jit
    cache every step must page even while a hundred quiet tenants
    dilute the aggregate. The offending tenant is surfaced in
    ``spec()`` so ``/api/alerts`` names the noisy neighbour."""
    label_key: str = "tenant"
    worst_tenant: str | None = None

    def burn_rate(self, tsdb, window_s, now=None):
        worst = None
        offender = None
        for tenant in tsdb.label_values(self.metric, self.label_key):
            rate = tsdb.rate(
                self.metric,
                dict(self.labels or {}, **{self.label_key: tenant}),
                window_s, now=now)
            if rate is None:
                continue
            if worst is None or rate > worst:
                worst, offender = rate, tenant
        if worst is None:
            return None
        self.worst_tenant = offender
        return worst / max(1e-12, self.allowed_per_s)

    def spec(self):
        d = super().spec()
        d.update(label_key=self.label_key, worst_tenant=self.worst_tenant)
        return d


@dataclass
class GaugeSLO(SLO):
    """Gauge whose *windowed mean* must stay under ``threshold`` —
    sustained elevation burns, transient spikes do not."""
    threshold: float = 1.0

    def burn_rate(self, tsdb, window_s, now=None):
        avg = tsdb.gauge_avg(self.metric, self.labels, window_s, now=now)
        if avg is None:
            return None
        return avg / max(1e-12, self.threshold)

    def spec(self):
        d = super().spec()
        d.update(threshold=self.threshold)
        return d


# -- the shipped objective set ----------------------------------------

def default_slos() -> list[SLO]:
    """The concrete SLO set the platform watches out of the box. Window
    lengths are sized for conformance-storm timescales (minutes, not
    the textbook hours); thresholds sit on histogram bucket bounds so
    ``bad_fraction`` reads an exact bucket."""
    crit_warn = (Window(120.0, 30.0, 1.5, "critical"),
                 Window(300.0, 60.0, 1.0, "warning"))
    warn_only = (Window(300.0, 60.0, 1.0, "warning"),)
    return [
        LatencySLO(
            name="provision-p50", metric="provision_latency_seconds",
            windows=crit_warn, threshold_s=2.5, target=0.50,
            description="half of notebook provisions (CR create -> "
                        "readyReplicas == desired) land under 2.5s"),
        LatencySLO(
            name="serving-victim-p95",
            metric="serving_request_latency_seconds",
            windows=crit_warn, threshold_s=4.0, target=0.95,
            description="victim-tenant serving p95 under the 4s "
                        "gateway SLO despite a flooding tenant"),
        LatencySLO(
            name="scheduler-latency", metric="schedule_latency_seconds",
            windows=crit_warn, threshold_s=0.1, target=0.99,
            description="99% of gang placements decided in 100ms"),
        LatencySLO(
            name="wal-fsync", metric="wal_fsync_seconds",
            windows=crit_warn, threshold_s=0.05, target=0.99,
            description="99% of WAL group commits fsync in 50ms"),
        RateSLO(
            name="swallowed-errors", metric="swallowed_errors_total",
            windows=warn_only, allowed_per_s=1.0 / 300.0,
            description="best-effort exception handlers should be "
                        "near-silent; a sustained nonzero swallow rate "
                        "is a hidden fault"),
        GaugeSLO(
            name="scheduler-fragmentation",
            metric="scheduler_fragmentation",
            windows=warn_only, threshold=0.5,
            description="sustained fragmentation >= 0.5 means free "
                        "chips exist but no gang-sized hole does — "
                        "the ROADMAP-3 bin-packing signal"),
        GaugeSLO(
            name="serving-prefix-hit-collapse",
            metric="serving_prefix_miss_ratio",
            windows=warn_only, threshold=0.95,
            description="sustained prefix-cache miss ratio >= 0.95 "
                        "while prompts flow means the shared-prefix "
                        "block cache stopped absorbing prefill "
                        "(thrash/eviction storm, or affinity routing "
                        "gone wrong) — the paged-KV speedup is gone"),
        GaugeSLO(
            name="serving-store-hit-collapse",
            metric="serving_store_miss_ratio",
            windows=warn_only, threshold=0.95,
            description="sustained GlobalBlockStore miss ratio >= "
                        "0.95 while lookups flow means the fleet-wide "
                        "prefix tier stopped absorbing re-prefills "
                        "(byte budget too small, publish path broken, "
                        "or traffic lost all prefix overlap) — decode "
                        "replicas are back to paying full prefill "
                        "after every rebalance or death"),
        GaugeSLO(
            name="declared-hbm-drift",
            metric="declared_hbm_drift_ratio",
            windows=warn_only, threshold=0.2,
            description="warn-only: observed on-chip HBM peak drifts "
                        ">20% from the declared-workload prediction "
                        "for a sustained window — the declaration the "
                        "admission pricer charged against no longer "
                        "describes the job (model update changed the "
                        "footprint); repack before the next bind, do "
                        "not page"),
        TenantRateSLO(
            name="jit-recompile-storm", metric="jit_recompiles_total",
            windows=warn_only, allowed_per_s=1.0 / 30.0,
            description="a tenant minting new jit signatures faster "
                        "than ~2/min is retracing in a hot loop — its "
                        "slice burns XLA compiles instead of steps "
                        "(jaxcheck recompile sentinel, per-tenant)"),
        TenantRateSLO(
            name="implicit-hostsync-storm",
            metric="implicit_hostsyncs_total",
            windows=warn_only, allowed_per_s=1.0 / 30.0,
            description="a tenant tripping unsanctioned device->host "
                        "syncs inside declared hot regions serializes "
                        "its TPU behind Python round-trips (jaxcheck "
                        "hostsync probe, per-tenant)"),
        RateSLO(
            name="shard-deaths", metric="shard_deaths_total",
            windows=(Window(120.0, 15.0, 1.0, "critical"),),
            allowed_per_s=1.0 / 600.0,
            description="any shard process death inside the window "
                        "pages; the watchdog respawns, the alert "
                        "captures that it had to"),
    ]


# -- jaxcheck probe -> per-tenant fleet counters ----------------------

def tenant_of(name: str) -> str:
    """Tenant from a probe entry/region name: the convention is
    ``<tenant>/<site>`` (``teamA/decode-step``); unprefixed names fold
    into ``default``."""
    tenant, sep, _ = name.partition("/")
    return tenant if sep and tenant else "default"


_bridges_installed = False


def install_probe_bridges() -> None:
    """Wire the jaxcheck recompile sentinel and hostsync probe into
    the per-tenant ``jit_recompiles_total`` /
    ``implicit_hostsyncs_total`` counters, which the TSDB samples and
    the :class:`TenantRateSLO` pair above burns against. Idempotent;
    the probes stay importable (and free) without the control plane —
    this is the only coupling point, and it is one-directional."""
    global _bridges_installed
    if _bridges_installed:
        return
    from kubeflow_rm_tpu.analysis.jaxcheck import hostsync, recompile
    from kubeflow_rm_tpu.controlplane import metrics

    def _on_recompile(entry: str, n_signatures: int) -> None:
        metrics.JIT_RECOMPILES_TOTAL.labels(
            tenant=tenant_of(entry)).inc()

    def _on_hostsync(region: str, kind: str) -> None:
        metrics.IMPLICIT_HOSTSYNCS_TOTAL.labels(
            tenant=tenant_of(region)).inc()

    recompile.add_observer(_on_recompile)
    hostsync.add_observer(_on_hostsync)
    _bridges_installed = True


@dataclass
class _State:
    severity: str = "ok"
    since: float = 0.0
    below_since: float | None = None
    burns: dict = field(default_factory=dict)


class SLOEngine:
    """Evaluates every SLO against the TSDB and runs the alert state
    machine. ``evaluate()`` is cheap enough to call on every dashboard
    read; harnesses call it on a tick loop."""

    def __init__(self, tsdb: TimeSeriesDB, slos: list[SLO], *,
                 clear_ratio: float = 0.8, hold_s: float = 30.0,
                 max_transitions: int = 256):
        self.tsdb = tsdb
        self.slos = list(slos)
        self.clear_ratio = float(clear_ratio)
        self.hold_s = float(hold_s)
        self._lock = make_lock("obs.engine")
        self._states: dict[str, _State] = {
            s.name: _State() for s in self.slos}
        self._transitions: deque = deque(maxlen=max_transitions)
        self._callbacks: list = []

    def on_transition(self, cb) -> None:
        """``cb(transition_dict)`` on every state change; called with
        no engine lock held."""
        self._callbacks.append(cb)

    # ---- evaluation --------------------------------------------------

    def _desired(self, slo: SLO, now: float
                 ) -> tuple[str, dict]:
        """(severity the burn rates call for right now, burn detail)."""
        burns: dict = {}
        desired = "ok"
        for w in sorted(slo.windows, key=lambda w: -_RANK[w.severity]):
            long_b = slo.burn_rate(self.tsdb, w.long_s, now=now)
            short_b = slo.burn_rate(self.tsdb, w.short_s, now=now)
            burns[f"{int(w.long_s)}s"] = long_b
            burns[f"{int(w.short_s)}s"] = short_b
            if (long_b is not None and short_b is not None
                    and long_b >= w.burn and short_b >= w.burn
                    and _RANK[w.severity] > _RANK[desired]):
                desired = w.severity
        return desired, burns

    def _clear_floor(self, slo: SLO, severity: str) -> float:
        """Burn level below which the *current* severity may clear."""
        thresholds = [w.burn for w in slo.windows
                      if w.severity == severity]
        return self.clear_ratio * (min(thresholds) if thresholds
                                   else 1.0)

    def evaluate(self, now: float | None = None) -> list[dict]:
        """One evaluation pass; returns the transitions it caused.
        Burn rates are computed before the engine lock is taken (TSDB
        queries take their own lock); callbacks fire after release."""
        now = time.time() if now is None else now
        computed = [(slo, *self._desired(slo, now)) for slo in self.slos]
        fired: list[dict] = []
        with self._lock:
            for slo, desired, burns in computed:
                st = self._states[slo.name]
                st.burns = burns
                cur, des = _RANK[st.severity], _RANK[desired]
                if des > cur:
                    fired.append(self._move_locked(slo, st, desired,
                                                   burns, now))
                elif des < cur:
                    # hysteresis: drop only after the long-window burn
                    # sits below the clear floor for hold_s straight
                    floor = self._clear_floor(slo, st.severity)
                    longest = max(slo.windows, key=lambda w: w.long_s)
                    long_b = burns.get(f"{int(longest.long_s)}s")
                    if long_b is None or long_b < floor:
                        if st.below_since is None:
                            st.below_since = now
                        elif now - st.below_since >= self.hold_s:
                            fired.append(self._move_locked(
                                slo, st, desired, burns, now))
                    else:
                        st.below_since = None
                else:
                    st.below_since = None
        for tr in fired:
            for cb in self._callbacks:
                cb(tr)
        return fired

    def _move_locked(self, slo: SLO, st: _State, to: str,
                     burns: dict, now: float) -> dict:
        tr = {"t": round(now, 3), "slo": slo.name,
              "from": st.severity, "to": to,
              "burns": {k: (None if v is None else round(v, 4))
                        for k, v in burns.items()},
              "description": slo.description}
        st.severity = to
        st.since = now
        st.below_since = None
        self._transitions.append(tr)
        return tr

    # ---- snapshots ---------------------------------------------------

    def snapshot(self) -> dict:
        """Everything ``GET /api/alerts`` and the flight recorder
        serialize: per-SLO state + burns, the active (non-ok) alert
        set, and the transition log."""
        with self._lock:
            slos = []
            active = []
            for slo in self.slos:
                st = self._states[slo.name]
                entry = dict(slo.spec(), state=st.severity,
                             since=round(st.since, 3),
                             burns={k: (None if v is None
                                        else round(v, 4))
                                    for k, v in st.burns.items()})
                slos.append(entry)
                if st.severity != "ok":
                    active.append({"slo": slo.name,
                                   "state": st.severity,
                                   "since": round(st.since, 3),
                                   "burns": entry["burns"],
                                   "description": slo.description})
            return {"slos": slos, "active": active,
                    "transitions": list(self._transitions)}

    def state_of(self, name: str) -> str:
        with self._lock:
            return self._states[name].severity
