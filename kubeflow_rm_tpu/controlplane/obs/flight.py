"""Flight recorder: one self-contained black-box bundle per incident.

Chaos runs (kill-a-shard, preemption storms) used to leave no record
beyond whatever the storm script printed; by the time someone asks
"what did the platform look like when the alert fired", the gauges
have moved on and the span ring has rotated. The recorder freezes all
of it into a single JSON bundle at trigger time:

- the trailing TSDB window (every series, bounded by the ring),
- the SpanCollector's slow traces with their critical paths, merged
  across every shard's ``/debug/traces`` export,
- the active alert set + recent transitions from the SLO engine,
- shard liveness as the ``ShardRunner`` watchdog sees it,
- the lockgraph report when ``KFRM_LOCK_ANALYSIS`` is on.

Three trigger paths: an SLO transition to ``critical`` (wired via
:meth:`attach_engine`), shard death observed by the watchdog, and
explicit calls from chaos scenarios in ``e2e_walk.py``. Automatic
triggers are rate-limited (``min_interval_s``) so a flapping alert
cannot dump-storm the disk; explicit calls always record.
"""

from __future__ import annotations

import json
import time
from collections import deque

from kubeflow_rm_tpu.analysis.lockgraph import make_lock

SCHEMA_VERSION = 1
_MAX_SLOW_TRACES = 5


class FlightRecorder:
    def __init__(self, tsdb=None, engine=None, *,
                 window_s: float = 120.0, keep: int = 8,
                 liveness=None, shard_urls: dict | None = None,
                 run_meta: dict | None = None,
                 min_interval_s: float = 5.0):
        self.tsdb = tsdb
        self.engine = engine
        self.window_s = float(window_s)
        self.run_meta = run_meta
        self._liveness = liveness          # () -> {shard: bool}
        self._shard_urls = dict(shard_urls or {})
        self.min_interval_s = float(min_interval_s)
        self._lock = make_lock("obs.flight")
        self._bundles: deque = deque(maxlen=keep)
        self._last_auto = 0.0
        self.triggered_total = 0
        self.suppressed_total = 0

    # ---- wiring ------------------------------------------------------

    def attach_engine(self, engine) -> None:
        """Subscribe to the SLO engine: any transition *into* critical
        records a bundle (rate-limited)."""
        self.engine = engine
        engine.on_transition(self._on_transition)

    def set_liveness(self, fn) -> None:
        self._liveness = fn

    def _on_transition(self, tr: dict) -> None:
        if tr.get("to") == "critical":
            self.trigger("alert_critical", detail=tr, auto=True)

    # ---- capture -----------------------------------------------------

    def trigger(self, reason: str, *, detail=None,
                auto: bool = False) -> dict | None:
        """Capture one bundle. ``auto`` triggers (alert / watchdog) are
        rate-limited; explicit chaos-scenario calls always record.
        Returns the bundle, or ``None`` when suppressed."""
        now = time.time()
        if auto and (now - self._last_auto) < self.min_interval_s:
            self.suppressed_total += 1
            return None
        bundle = self._capture(reason, detail, now)
        with self._lock:
            if auto:
                self._last_auto = now
            self._bundles.append(bundle)
            self.triggered_total += 1
        return bundle

    def _capture(self, reason: str, detail, now: float) -> dict:
        """Assemble the bundle with NO recorder lock held — every
        sub-capture takes (and releases) its own component lock."""
        from kubeflow_rm_tpu.controlplane import metrics

        bundle: dict = {
            "schema_version": SCHEMA_VERSION,
            "trigger": {"reason": reason, "t": round(now, 3),
                        "detail": detail},
            "window_s": self.window_s,
        }
        if self.run_meta is not None:
            bundle["run_meta"] = self.run_meta
        if self.tsdb is not None:
            bundle["metrics"] = self.tsdb.dump(self.window_s, now=now)
        if self.engine is not None:
            bundle["alerts"] = self.engine.snapshot()
        bundle["slow_traces"] = self._slow_traces()
        if self._liveness is not None:
            try:
                bundle["shard_liveness"] = self._liveness()
            except Exception:  # noqa: BLE001 - runner may be torn down
                metrics.swallowed("obs.flight", "liveness probe")
                bundle["shard_liveness"] = None
        bundle["lockgraph"] = self._lockgraph()
        return bundle

    def _slow_traces(self) -> list[dict]:
        """Slow traces merged across the local collector and every
        shard's ``/debug/traces``, slowest first, each with its
        critical path attached (self_ms sums to the root wallclock)."""
        from kubeflow_rm_tpu.controlplane import metrics, tracing

        local = tracing.collector()
        span_lists = [local.spans()]
        slow = list(local.slow_traces())
        for name, url in self._shard_urls.items():
            import urllib.request
            try:
                with urllib.request.urlopen(
                        url.rstrip("/") + "/debug/traces",
                        timeout=2.0) as resp:
                    payload = json.loads(resp.read().decode())
            except Exception:  # noqa: BLE001 - shard may be down (that
                # can be exactly why we are dumping)
                metrics.swallowed("obs.flight", f"trace fetch {name}")
                continue
            span_lists.append(payload.get("spans") or [])
            slow.extend(payload.get("slow") or [])
        all_spans = tracing.merge_spans(*span_lists)
        by_trace: dict[str, list] = {}
        for s in all_spans:
            by_trace.setdefault(s["trace_id"], []).append(s)
        out, seen = [], set()
        for t in sorted(slow,
                        key=lambda t: -(t.get("duration_ms") or 0)):
            tid = t["trace_id"]
            if tid in seen:
                continue
            seen.add(tid)
            merged = tracing.merge_spans(t.get("spans") or [],
                                         by_trace.get(tid, []))
            out.append({
                "trace_id": tid,
                "duration_ms": t.get("duration_ms"),
                "processes": sorted({s.get("process") or ""
                                     for s in merged}),
                "critical_path": tracing.critical_path(merged),
                "spans": merged,
            })
            if len(out) >= _MAX_SLOW_TRACES:
                break
        return out

    @staticmethod
    def _lockgraph() -> dict | None:
        from kubeflow_rm_tpu.analysis import lockgraph
        if not lockgraph.enabled():
            return None
        return lockgraph.report()

    # ---- access ------------------------------------------------------

    def bundles(self) -> list[dict]:
        with self._lock:
            return list(self._bundles)

    def last(self) -> dict | None:
        with self._lock:
            return self._bundles[-1] if self._bundles else None

    def dump_json(self, path: str, bundle: dict | None = None) -> str:
        """Write the given (default: most recent) bundle to ``path``."""
        if bundle is None:
            bundle = self.last()
        if bundle is None:
            raise ValueError("no flight bundle recorded yet")
        with open(path, "w") as f:
            json.dump(bundle, f, indent=1, default=str)
        return path
