"""Shared ``run_meta`` header stamped on every storm artifact.

``ratchet.py`` diffs timing artifacts across commits; a diff between a
2-shard WAL run and a single-process run is garbage, and a diff across
hosts is suspect. Every harness (``spawn_conformance.py``,
``e2e_walk.py``, ``serve_bench.py``) stamps its output with this
header so the ratchet can *refuse* mismatched-arm comparisons (hard)
and *flag* cross-host ones (soft) instead of producing nonsense
deltas.
"""

from __future__ import annotations

import os
import platform
import time

SCHEMA_VERSION = 1


def build_run_meta(harness: str, arms: dict, *,
                   interleave_index: int | None = None) -> dict:
    """``harness`` names the producing tool; ``arms`` is the flat dict
    of arm-defining flags (mode, shards, wal, cache, ...) — the keys
    two artifacts must agree on to be comparable."""
    return {
        "schema_version": SCHEMA_VERSION,
        "harness": harness,
        "arms": {k: v for k, v in sorted(arms.items())},
        "host": {
            "node": platform.node(),
            "machine": platform.machine(),
            "python": platform.python_version(),
            "cpus": os.cpu_count(),
        },
        "interleave_index": interleave_index,
        "created_at": round(time.time(), 3),
    }


def compatible(a: dict | None, b: dict | None
               ) -> tuple[list[str], list[str]]:
    """``(refusals, warnings)`` for comparing artifact ``a`` (baseline)
    against ``b`` (fresh). Arm-flag or schema-major mismatches refuse;
    a missing header or a different host only warns (checked-in
    baselines predate stamping, CI hosts legitimately differ)."""
    refusals: list[str] = []
    warnings: list[str] = []
    if not a or not b:
        which = [side for side, m in (("baseline", a), ("fresh", b))
                 if not m]
        warnings.append(
            f"run_meta missing on {' and '.join(which)} — arm "
            f"compatibility not verifiable")
        return refusals, warnings
    if a.get("schema_version") != b.get("schema_version"):
        refusals.append(
            f"run_meta schema_version mismatch: "
            f"{a.get('schema_version')} vs {b.get('schema_version')}")
    if a.get("harness") and b.get("harness") \
            and a["harness"] != b["harness"]:
        refusals.append(f"harness mismatch: {a['harness']} vs "
                        f"{b['harness']}")
    arms_a, arms_b = a.get("arms") or {}, b.get("arms") or {}
    for key in sorted(set(arms_a) & set(arms_b)):
        if arms_a[key] != arms_b[key]:
            refusals.append(f"arm mismatch on '{key}': "
                            f"{arms_a[key]!r} vs {arms_b[key]!r}")
    for key in sorted(set(arms_a) ^ set(arms_b)):
        warnings.append(f"arm flag '{key}' present on only one side")
    host_a = (a.get("host") or {}).get("node")
    host_b = (b.get("host") or {}).get("node")
    if host_a and host_b and host_a != host_b:
        warnings.append(f"cross-host comparison ({host_a} vs "
                        f"{host_b}) — timing deltas are soft evidence")
    return refusals, warnings
