"""Multi-replica serving fleet: cache-affinity routing + migration.

Scale-OUT for the serving path (ROADMAP item 2c): N
``ServingGateway``-wrapped engines behind one front door. Three
routing rules, applied in order:

1. **Cache affinity.** The routing key is the request's prompt-prefix
   hash (first ``prefix_tokens`` token ids — one KV block's worth, the
   same granularity ``models.paging`` content-addresses blocks at), so
   requests sharing a system prompt land on the replica that already
   holds those blocks and hit its prefix cache instead of re-prefilling.
   The key rides the same consistent-hash ring as the control plane's
   shard router (``shard/ring.py``): membership changes move only the
   keys that must move.
2. **Session stickiness.** A request carrying ``session`` routes by
   ``s:<session>`` instead — every turn of a conversation returns to
   the replica holding that conversation's KV blocks.
3. **Load spill.** If the affinity owner's queue is ``spill_depth``
   deep and a strictly shallower ready replica exists, the request
   spills to the shallowest one — affinity is a preference, not a
   hostage situation.

Drain-aware rebalancing: the ring is built over READY replicas only
and rebuilt when a replica drains or dies, so new traffic redistributes
with minimal key movement. In-flight requests on a drained/killed
replica are NOT failed: their ``wait`` raises ``ReplicaUnavailable``
(with the tokens produced so far) and ``submit_and_wait`` resubmits
``prompt + tokens_so_far`` with the remaining budget on another
replica — greedy decode continues bit-identically, and the shared
prefix cache on the new replica absorbs most of the re-prefill.

Locking: ``serving.fleet`` (rank 435) guards only the state map and
the cached ring; every blocking call (submit, wait, drain, close)
happens OUTSIDE it. Routing into a gateway (rank 440) from under the
fleet lock is uphill and safe, but we don't do it anyway.
"""

from __future__ import annotations

import hashlib
import json

from kubeflow_rm_tpu.analysis.lockgraph import make_lock
from kubeflow_rm_tpu.controlplane import metrics as cp_metrics
from kubeflow_rm_tpu.controlplane.shard.ring import HashRing
from kubeflow_rm_tpu.controlplane.webapps.serving import (
    ReplicaUnavailable,
    ServingGateway,
)

READY, DRAINING, DEAD = "ready", "draining", "dead"


class NoReadyReplica(Exception):
    """Every replica is draining or dead — the fleet cannot admit."""


class ServingFleet:
    """Affinity router + migration loop over named gateways."""

    def __init__(self, gateways: dict[str, ServingGateway], *,
                 prefix_tokens: int | None = None, spill_depth: int = 8,
                 vnodes: int = 16):
        if not gateways:
            raise ValueError("fleet needs at least one replica")
        self.gateways = dict(gateways)
        if prefix_tokens is None:
            eng = next(iter(self.gateways.values())).engine
            prefix_tokens = getattr(eng, "block_size", None) or 16
        self.prefix_tokens = int(prefix_tokens)
        self.spill_depth = spill_depth
        self._vnodes = vnodes
        self._lock = make_lock("serving.fleet")
        self._state = {name: READY for name in self.gateways}
        self._ring = HashRing(sorted(self.gateways), vnodes=vnodes)
        self.migrations = 0
        self.spills = 0
        self._publish_states()

    # -- membership / state ------------------------------------------------

    def _publish_states(self) -> None:
        counts = {READY: 0, DRAINING: 0, DEAD: 0}
        for s in self._state.values():
            counts[s] += 1
        for s, n in counts.items():
            cp_metrics.SERVING_FLEET_REPLICAS.labels(s).set(n)

    def _set_state(self, name: str, state: str) -> None:
        with self._lock:
            self._state[name] = state
            ready = [m for m in self.gateways
                     if self._state[m] == READY]
            self._ring = (HashRing(ready, vnodes=self._vnodes)
                          if ready else None)
            self._publish_states()

    def drain(self, name: str) -> None:
        """Pull ``name`` out of rotation: ring drops it, its healthz
        flips 503, its queued requests migrate, its active slots
        finish. (Kubernetes analogue: preStop hook before SIGTERM.)"""
        self._set_state(name, DRAINING)
        self.gateways[name].start_drain()

    def kill(self, name: str) -> None:
        """Hard-kill ``name`` (chaos arm): every in-flight request —
        queued AND mid-decode — migrates to another replica."""
        self._set_state(name, DEAD)
        self.gateways[name].close()

    def states(self) -> dict[str, str]:
        with self._lock:
            return dict(self._state)

    # -- routing -----------------------------------------------------------

    def affinity_key(self, prompt: list[int],
                     session: str | None = None) -> str:
        if session:
            return f"s:{session}"
        head = prompt[: self.prefix_tokens]
        return "p:" + hashlib.md5(
            b",".join(str(t).encode() for t in head)).hexdigest()

    def route(self, prompt: list[int], session: str | None = None,
              *, exclude: set[str] | None = None) -> str:
        """Pick the replica for this request. Raises
        ``NoReadyReplica`` when nothing can take it."""
        key = self.affinity_key(prompt, session)
        with self._lock:
            ready = [m for m in sorted(self.gateways)
                     if self._state[m] == READY
                     and m not in (exclude or ())]
            if not ready:
                raise NoReadyReplica("no ready serving replica")
            ring = (self._ring if not exclude and self._ring is not None
                    else HashRing(ready, vnodes=self._vnodes))
            owner = ring.shard_for(key)
        depth = self.gateways[owner].engine.queue_depth
        if depth >= self.spill_depth and len(ready) > 1:
            shallowest = min(
                ready, key=lambda m: self.gateways[m].engine.queue_depth)
            if (self.gateways[shallowest].engine.queue_depth < depth
                    and shallowest != owner):
                self.spills += 1
                return shallowest
        return owner

    # -- request lifecycle -------------------------------------------------

    def submit_and_wait(self, tenant: str, prompt: list[int], *,
                        max_new_tokens: int, eos_id: int | None = None,
                        slo_class: str | None = None,
                        session: str | None = None,
                        timeout_s: float = 300.0):
        """Route, decode, and — if the replica goes away mid-flight —
        migrate and resume. Returns ``(tokens, info)`` on success or
        ``(None, info)`` on shed; ``info`` carries the replica path and
        shed reason. A migrated request resumes from the tokens it
        already produced (greedy continuation is bit-identical to an
        uninterrupted run), so a kill costs latency, never correctness.
        """
        tokens: list[int] = []
        path: list[str] = []
        tried: set[str] = set()
        while True:
            budget = max_new_tokens - len(tokens)
            if budget <= 0:
                return tokens, {"replicas": path, "migrations":
                                len(path) - 1}
            try:
                name = self.route(prompt + tokens, session,
                                  exclude=tried or None)
            except NoReadyReplica:
                return None, {"replicas": path, "reason": "no_replica"}
            gw = self.gateways[name]
            try:
                pending, reason = gw.try_submit(
                    tenant, prompt + tokens, max_new_tokens=budget,
                    eos_id=eos_id, slo_class=slo_class)
            except ValueError:
                # a resume prompt can overflow slot_len even though the
                # original request fit: bucket(Tp + tokens_so_far) may
                # round up to the next power of two while the remaining
                # budget shrinks by less.  Greedy decode is
                # deterministic, so restarting from the original prompt
                # reproduces the same tokens — pay the decode again
                # rather than fail the request.
                if not tokens:
                    raise
                tokens = []
                continue
            if pending is None:
                if reason in ("rate", "tokens"):
                    # per-tenant budgets are fleet policy, not replica
                    # pressure — spilling would launder the quota
                    return None, {"replicas": path, "reason": reason}
                tried.add(name)     # queue/slo/draining: try elsewhere
                continue
            path.append(name)
            try:
                got = gw.wait(pending, timeout_s)
                tokens.extend(got)
                return tokens, {"replicas": path,
                                "migrations": len(path) - 1}
            except ReplicaUnavailable as e:
                tokens.extend(e.tokens_so_far)
                self.migrations += 1
                cp_metrics.SERVING_MIGRATIONS_TOTAL.inc()
                tried.add(name)
                # eos may have landed just before the drain severed us
                if eos_id is not None and tokens and tokens[-1] == eos_id:
                    return tokens, {"replicas": path,
                                    "migrations": len(path) - 1}

    # -- observability -----------------------------------------------------

    def snapshot(self) -> dict:
        states = self.states()
        return {
            "replicas": {
                name: {
                    "state": states[name],
                    "queue_depth": gw.engine.queue_depth,
                    "active_slots": gw.engine.active_slots,
                    "prefix_hit_ratio": gw.engine.stats().get(
                        "prefix_hit_ratio"),
                }
                for name, gw in sorted(self.gateways.items())
            },
            "migrations": self.migrations,
            "spills": self.spills,
            "prefix_tokens": self.prefix_tokens,
        }

    def close(self) -> None:
        for name, gw in self.gateways.items():
            if self._state[name] != DEAD:
                gw.close()


def make_fleet_app(fleet: ServingFleet, cfg):
    """werkzeug WSGI front door over the whole fleet: the thing an
    external LB points at. ``POST /generate`` adds optional
    ``session`` (stickiness) and ``slo_class`` fields to the
    single-replica contract; ``GET /api/fleet`` is the ops view;
    ``POST /replicas/<name>/drain`` is the preStop hook."""
    from werkzeug.exceptions import BadRequest, HTTPException, NotFound
    from werkzeug.routing import Map, Rule
    from werkzeug.wrappers import Request, Response

    urls = Map([
        Rule("/generate", endpoint="generate", methods=["POST"]),
        Rule("/healthz", endpoint="healthz"),
        Rule("/api/fleet", endpoint="fleet"),
        Rule("/metrics", endpoint="metrics"),
        Rule("/replicas/<name>/drain", endpoint="drain",
             methods=["POST"]),
    ])

    def _json(payload, status=200):
        return Response(json.dumps(payload), status=status,
                        content_type="application/json")

    def app(environ, start_response):
        req = Request(environ)
        try:
            endpoint, args = urls.bind_to_environ(environ).match()
            if endpoint == "healthz":
                states = fleet.states()
                ready = sum(1 for s in states.values() if s == READY)
                status = 200 if ready else 503
                return _json({"ok": bool(ready), "ready": ready,
                              "replicas": states}, status)(
                    environ, start_response)
            if endpoint == "fleet":
                return _json(fleet.snapshot())(environ, start_response)
            if endpoint == "metrics":
                resp = Response(cp_metrics.scrape(),
                                content_type="text/plain; version=0.0.4")
                return resp(environ, start_response)
            if endpoint == "drain":
                if args["name"] not in fleet.gateways:
                    raise NotFound(f"no replica {args['name']}")
                fleet.drain(args["name"])
                return _json({"draining": args["name"]})(
                    environ, start_response)
            body = req.get_json(force=True)
            if not isinstance(body, dict):
                raise BadRequest("body must be a JSON object")
            prompt = body.get("prompt")
            if (not isinstance(prompt, list) or not prompt
                    or not all(isinstance(t, int)
                               and 0 <= t < cfg.vocab_size
                               for t in prompt)):
                raise BadRequest("prompt must be a non-empty list of "
                                 f"token ids in [0, {cfg.vocab_size})")
            tenant = body.get("tenant") \
                or req.headers.get("X-Tenant") or "default"
            max_new = body.get("max_new_tokens", 16)
            if not isinstance(max_new, int) or not 1 <= max_new <= 4096:
                raise BadRequest("max_new_tokens must be an int in "
                                 "[1, 4096]")
            session = body.get("session")
            if session is not None and (not isinstance(session, str)
                                        or len(session) > 128):
                raise BadRequest("session must be a short string")
            slo_class = body.get("slo_class")
            if slo_class is not None and slo_class not in (
                    "interactive", "batch", "best_effort"):
                raise BadRequest("slo_class must be one of "
                                 "interactive|batch|best_effort")
            try:
                tokens, info = fleet.submit_and_wait(
                    tenant, prompt, max_new_tokens=max_new,
                    eos_id=body.get("eos_id"), slo_class=slo_class,
                    session=session)
            except ValueError as e:
                raise BadRequest(str(e)) from e
            if tokens is None:
                reason = info.get("reason")
                status = 429 if reason in ("rate", "tokens") else 503
                resp = _json({"error": "shed", "reason": reason},
                             status=status)
                resp.headers["Retry-After"] = "1"
            else:
                resp = _json({"tokens": tokens, **info})
        except HTTPException as e:
            resp = e
        return resp(environ, start_response)

    app.fleet = fleet
    return app
