"""Multi-replica serving fleet: cache-affinity routing + migration.

Scale-OUT for the serving path (ROADMAP item 2c): N
``ServingGateway``-wrapped engines behind one front door. Three
routing rules, applied in order:

1. **Cache affinity.** The routing key is the request's prompt-prefix
   hash (first ``prefix_tokens`` token ids — one KV block's worth, the
   same granularity ``models.paging`` content-addresses blocks at), so
   requests sharing a system prompt land on the replica that already
   holds those blocks and hit its prefix cache instead of re-prefilling.
   The key rides the same consistent-hash ring as the control plane's
   shard router (``shard/ring.py``): membership changes move only the
   keys that must move.
2. **Session stickiness.** A request carrying ``session`` routes by
   ``s:<session>`` instead — every turn of a conversation returns to
   the replica holding that conversation's KV blocks.
3. **Load spill.** If the affinity owner's queue is ``spill_depth``
   deep and a strictly shallower ready replica exists, the request
   spills to the shallowest one — affinity is a preference, not a
   hostage situation.

Drain-aware rebalancing: the ring is built over READY replicas only
and rebuilt when a replica drains or dies, so new traffic redistributes
with minimal key movement. In-flight requests on a drained/killed
replica are NOT failed: their ``wait`` raises ``ReplicaUnavailable``
(with the tokens produced so far) and ``submit_and_wait`` resubmits
``prompt + tokens_so_far`` with the remaining budget on another
replica — greedy decode continues bit-identically, and the shared
prefix cache on the new replica absorbs most of the re-prefill.

**Disaggregated mode** (``roles=...``): the fleet splits into a
*prefill tier* and a *decode tier*. Prefill replicas never hold a
request end-to-end — they run ``paged_prefill`` into block chunks and
export the finished chain (``models.paging.export_chain``); the chain
lands in the fleet-wide :class:`GlobalBlockStore`, content-addressed
by the same chained ``prefix_keys`` hashes the per-replica pools use,
so ANY decode replica can adopt it by hash. Decode replicas are then
chosen by **queue depth**, not prefix affinity — the store makes the
prefix portable, so affinity stops being the load-balancing
constraint. Hot chains a decode pool evicts at ref 0 are *promoted*
into the store on the way out (``BlockPool.on_evict``), which is what
keeps the fleet-wide hit ratio alive when the replica that computed a
prefix dies: the blocks outlive the pool that built them.

Locking: ``serving.fleet`` (rank 435) guards only the state map and
the cached ring; every blocking call (submit, wait, drain, close)
happens OUTSIDE it. Routing into a gateway (rank 440) from under the
fleet lock is uphill and safe, but we don't do it anyway. The store's
``serving.store`` (rank 445) sits above the gateway lock because
promote-on-evict fires from inside an engine step, under the owning
gateway's lock.
"""

from __future__ import annotations

import hashlib
import json
import time
from collections import OrderedDict

import numpy as np

from kubeflow_rm_tpu.analysis.lockgraph import make_lock
from kubeflow_rm_tpu.controlplane import metrics as cp_metrics
from kubeflow_rm_tpu.controlplane.shard.ring import HashRing
from kubeflow_rm_tpu.controlplane.webapps.serving import (
    ReplicaUnavailable,
    ServingGateway,
)
from kubeflow_rm_tpu.models import paging

READY, DRAINING, DEAD = "ready", "draining", "dead"

ROLES = ("prefill", "decode")


def _np_dtype(name: str):
    """``np.dtype`` by name, falling back to ``ml_dtypes`` for the
    accelerator dtypes numpy does not register (bfloat16 etc.)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def chain_to_bytes(chain: dict) -> bytes:
    """Wire format for a prefix chain: 4-byte big-endian header
    length, JSON header (keys/sums hex, shapes, dtype names), then the
    raw array buffers concatenated. Checksums ride in the header, so
    a decode replica verifies before seating anything."""
    arrays = [("chunks_k", chain["chunks_k"]),
              ("chunks_v", chain["chunks_v"]),
              ("chunks_pos", chain["chunks_pos"])]
    if chain.get("last_logits") is not None:
        arrays.append(("last_logits", chain["last_logits"]))
    header = {
        "version": 1,
        "block_size": int(chain["block_size"]),
        "covered": int(chain["covered"]),
        "keys": [k.hex() for k in chain["keys"]],
        "covers": [int(c) for c in chain["covers"]],
        "sums": [s.hex() for s in chain["sums"]],
        "nbytes": int(chain["nbytes"]),
        "arrays": [{"name": n, "shape": list(a.shape),
                    "dtype": a.dtype.name} for n, a in arrays],
    }
    if chain.get("tokens") is not None:
        header["tokens"] = [int(t) for t in chain["tokens"]]
    hj = json.dumps(header).encode()
    payload = b"".join(np.ascontiguousarray(a).tobytes()
                       for _n, a in arrays)
    return len(hj).to_bytes(4, "big") + hj + payload


def chain_from_bytes(buf: bytes) -> dict:
    """Inverse of :func:`chain_to_bytes`. Raises ``ValueError`` on a
    malformed frame; chunk-level integrity is still re-checked by
    ``paging.verify_chain`` when the chain is imported."""
    if len(buf) < 4:
        raise ValueError("chain frame too short")
    hlen = int.from_bytes(buf[:4], "big")
    try:
        header = json.loads(buf[4:4 + hlen])
    except Exception as e:
        raise ValueError(f"chain header is not JSON: {e}") from e
    chain = {
        "version": int(header["version"]),
        "block_size": int(header["block_size"]),
        "covered": int(header["covered"]),
        "keys": [bytes.fromhex(k) for k in header["keys"]],
        "covers": [int(c) for c in header["covers"]],
        "sums": [bytes.fromhex(s) for s in header["sums"]],
        "nbytes": int(header["nbytes"]),
    }
    if "tokens" in header:
        chain["tokens"] = [int(t) for t in header["tokens"]]
    off = 4 + hlen
    for spec in header["arrays"]:
        dt = _np_dtype(spec["dtype"])
        shape = tuple(spec["shape"])
        n = dt.itemsize * int(np.prod(shape)) if shape else dt.itemsize
        raw = buf[off:off + n]
        if len(raw) != n:
            raise ValueError("chain frame truncated")
        chain[spec["name"]] = np.frombuffer(
            raw, dtype=dt).reshape(shape).copy()
        off += n
    return chain


class GlobalBlockStore:
    """Fleet-wide content-addressed prefix-chain store.

    Entries are whole chains keyed by their LAST prefix key (which,
    being a chained hash, commits to every token before it); every
    interior key is indexed too, so a prompt that shares only the
    first few blocks with a stored chain still finds the longest
    usable truncation. Publishing a chain supersedes stored chains
    that are strict prefixes of it (their id is an interior key of
    the newcomer). Eviction is LRU under a byte budget; the
    just-published chain is never evicted by its own publish.

    Two producers feed it: prefill replicas ``publish`` full chains
    (tokens + final logits ride along, so a decode replica can skip
    prefill entirely), and decode pools ``extend`` it one chunk at a
    time when they evict a ref-0 block (*promotion* — no tokens, no
    logits, but adoptable prefix bytes that survive replica death).

    Lock rank 445 (``serving.store``): above the gateway lock, because
    promotion fires from inside an engine step.
    """

    def __init__(self, *, max_bytes: int = 64 << 20):
        self._lock = make_lock("serving.store")
        self.max_bytes = int(max_bytes)
        self._entries: OrderedDict[bytes, dict] = OrderedDict()
        # every prefix key -> (owning chain id, chunks up to that key);
        # overwritten to the newest chain on publish, scrubbed when the
        # owning chain is evicted
        self._by_key: dict[bytes, tuple[bytes, int]] = {}
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.published = 0
        self.promoted = 0
        self.superseded = 0
        self.evicted = 0
        self.skipped_extends = 0

    # -- internals (lock held) -----------------------------------------

    def _drop_locked(self, chain_id: bytes) -> None:
        entry = self._entries.pop(chain_id)
        self.bytes -= entry["nbytes"]
        for k in entry["keys"]:
            if self._by_key.get(k, (None, 0))[0] == chain_id:
                del self._by_key[k]

    def _gauges_locked(self) -> None:
        total = self.hits + self.misses
        if total:
            cp_metrics.SERVING_STORE_HIT_RATIO.set(self.hits / total)
            cp_metrics.SERVING_STORE_MISS_RATIO.set(
                self.misses / total)
        cp_metrics.SERVING_STORE_CHAINS.set(len(self._entries))
        cp_metrics.SERVING_STORE_BYTES.set(self.bytes)

    def _slice_locked(self, entry: dict, nch: int) -> dict:
        """A chain dict truncated to ``nch`` chunks. ``tokens`` and
        ``last_logits`` only survive a FULL match — a truncated chain
        is adoptable prefix bytes, not a prefill replacement."""
        full = nch == len(entry["keys"])
        covered = int(entry["covers"][nch - 1])
        ck = entry["chunks_k"][:, :nch]
        cv = entry["chunks_v"][:, :nch]
        cp = entry["chunks_pos"][:nch]
        out = {
            "version": 1,
            "block_size": entry["block_size"],
            "covered": covered,
            "keys": list(entry["keys"][:nch]),
            "covers": list(entry["covers"][:nch]),
            "chunks_k": ck,
            "chunks_v": cv,
            "chunks_pos": cp,
            "sums": list(entry["sums"][:nch]),
            "nbytes": int(ck.nbytes + cv.nbytes + cp.nbytes),
        }
        if "tokens" in entry:
            out["tokens"] = (list(entry["tokens"]) if full
                             else list(entry["tokens"][:covered]))
        if full and "last_logits" in entry:
            out["last_logits"] = entry["last_logits"]
        return out

    # -- producer side -------------------------------------------------

    def publish(self, chain: dict, *, promoted: bool = False) -> bool:
        """Insert a verified chain; returns False if the exact chain
        (same final key) is already stored (it is freshened in the
        LRU instead)."""
        paging.verify_chain(chain)
        keys = list(chain["keys"])
        chain_id = keys[-1]
        entry = {
            "keys": keys,
            "covers": [int(c) for c in chain["covers"]],
            "chunks_k": np.asarray(chain["chunks_k"]),
            "chunks_v": np.asarray(chain["chunks_v"]),
            "chunks_pos": np.asarray(chain["chunks_pos"]),
            "sums": list(chain["sums"]),
            "block_size": int(chain["block_size"]),
            "covered": int(chain["covered"]),
            "nbytes": int(chain["nbytes"]),
        }
        if chain.get("tokens") is not None:
            entry["tokens"] = [int(t) for t in chain["tokens"]]
        if chain.get("last_logits") is not None:
            entry["last_logits"] = np.asarray(chain["last_logits"])
        with self._lock:
            if chain_id in self._entries:
                self._entries.move_to_end(chain_id)
                self._gauges_locked()
                return False
            for k in keys[:-1]:
                if k in self._entries:   # strict prefix of the newcomer
                    self._drop_locked(k)
                    self.superseded += 1
            self._entries[chain_id] = entry
            self.bytes += entry["nbytes"]
            for i, k in enumerate(keys):
                self._by_key[k] = (chain_id, i + 1)
            self.published += 1
            if promoted:
                self.promoted += 1
                cp_metrics.SERVING_STORE_PROMOTED_TOTAL.inc()
            while (self.bytes > self.max_bytes
                   and len(self._entries) > 1):
                oldest = next(iter(self._entries))
                if oldest == chain_id:
                    break
                self._drop_locked(oldest)
                self.evicted += 1
            self._gauges_locked()
        return True

    def extend(self, parent_key: bytes | None, key: bytes,
               chunk: dict, covered: int) -> bool:
        """Promotion: one sanitized block chunk
        (``paging.export_block_chunk``) grows a stored chain by one
        block. ``parent_key is None`` starts a fresh one-chunk chain;
        an unknown parent is skipped — the store only holds chains it
        can verify end to end."""
        with self._lock:
            if parent_key is None:
                base_k = chunk["k"][:, None]
                base_v = chunk["v"][:, None]
                base_p = chunk["pos"][None]
                keys = [key]
                covers = [int(covered)]
                sums = [chunk["sum"]]
                block_size = int(chunk["pos"].shape[0])
            else:
                got = self._by_key.get(parent_key)
                if got is None:
                    self.skipped_extends += 1
                    return False
                chain_id, nch = got
                entry = self._entries[chain_id]
                block_size = int(entry["block_size"])
                pcov = int(entry["covers"][nch - 1])
                # the parent must end exactly at this chunk's block
                # boundary, on a full block — anything else is a chain
                # the hashes can't vouch for
                if (pcov % block_size
                        or pcov != ((int(covered) - 1)
                                    // block_size) * block_size):
                    self.skipped_extends += 1
                    return False
                base_k = np.concatenate(
                    [entry["chunks_k"][:, :nch], chunk["k"][:, None]],
                    axis=1)
                base_v = np.concatenate(
                    [entry["chunks_v"][:, :nch], chunk["v"][:, None]],
                    axis=1)
                base_p = np.concatenate(
                    [entry["chunks_pos"][:nch], chunk["pos"][None]],
                    axis=0)
                keys = list(entry["keys"][:nch]) + [key]
                covers = list(entry["covers"][:nch]) + [int(covered)]
                sums = list(entry["sums"][:nch]) + [chunk["sum"]]
        chain = {
            "version": 1,
            "block_size": block_size,
            "covered": covers[-1],
            "keys": keys,
            "covers": covers,
            "chunks_k": base_k,
            "chunks_v": base_v,
            "chunks_pos": base_p,
            "sums": sums,
            "nbytes": int(base_k.nbytes + base_v.nbytes
                          + base_p.nbytes),
        }
        return self.publish(chain, promoted=True)

    # -- consumer side -------------------------------------------------

    def lookup(self, keys) -> dict | None:
        """Longest-prefix match of a prompt's ``prefix_keys`` pairs
        against stored chains; returns a (possibly truncated) chain
        dict, or None. Counts toward the hit/miss gauges the
        ``serving-store-hit-collapse`` SLO watches."""
        pairs = list(keys)
        with self._lock:
            for _covered, key in reversed(pairs):
                got = self._by_key.get(key)
                if got is None:
                    continue
                chain_id, nch = got
                entry = self._entries[chain_id]
                self._entries.move_to_end(chain_id)
                self.hits += 1
                self._gauges_locked()
                return self._slice_locked(entry, nch)
            self.misses += 1
            self._gauges_locked()
            return None

    def get_chain(self, key: bytes) -> dict | None:
        """Chain for one prefix key (the ``/api/store/chain/<hex>``
        fetch path), truncated to that key's depth."""
        with self._lock:
            got = self._by_key.get(key)
            if got is None:
                self.misses += 1
                self._gauges_locked()
                return None
            chain_id, nch = got
            entry = self._entries[chain_id]
            self._entries.move_to_end(chain_id)
            self.hits += 1
            self._gauges_locked()
            return self._slice_locked(entry, nch)

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "chains": len(self._entries),
                "bytes": self.bytes,
                "max_bytes": self.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "hit_ratio": (self.hits / total) if total else None,
                "published": self.published,
                "promoted": self.promoted,
                "superseded": self.superseded,
                "evicted": self.evicted,
                "skipped_extends": self.skipped_extends,
            }


class NoReadyReplica(Exception):
    """Every replica is draining or dead — the fleet cannot admit."""


class ServingFleet:
    """Affinity router + migration loop over named gateways."""

    def __init__(self, gateways: dict[str, ServingGateway], *,
                 prefix_tokens: int | None = None, spill_depth: int = 8,
                 vnodes: int = 16,
                 roles: dict[str, str] | None = None,
                 store: GlobalBlockStore | None = None,
                 store_bytes: int = 64 << 20):
        if not gateways:
            raise ValueError("fleet needs at least one replica")
        self.gateways = dict(gateways)
        if prefix_tokens is None:
            eng = next(iter(self.gateways.values())).engine
            prefix_tokens = getattr(eng, "block_size", None) or 16
        self.prefix_tokens = int(prefix_tokens)
        self.spill_depth = spill_depth
        self._vnodes = vnodes
        self._lock = make_lock("serving.fleet")
        self._state = {name: READY for name in self.gateways}
        self._ring = HashRing(sorted(self.gateways), vnodes=vnodes)
        self.migrations = 0
        self.spills = 0
        self.handoffs = 0
        if roles is not None:
            roles = dict(roles)
            if set(roles) != set(self.gateways):
                raise ValueError("roles must name every replica, "
                                 "exactly")
            bad = sorted(set(roles.values()) - set(ROLES))
            if bad:
                raise ValueError(f"unknown roles {bad}; expected "
                                 f"{'|'.join(ROLES)}")
            if "decode" not in roles.values():
                raise ValueError(
                    "disaggregated fleet needs >= 1 decode replica")
            if store is None:
                store = GlobalBlockStore(max_bytes=store_bytes)
        self.roles = roles
        self.store = store
        if self.store is not None:
            # promote-on-evict: a paged pool dropping a ref-0 block
            # hands its bytes to the store on the way out, so a hot
            # chain outlives the pool (and replica) that computed it
            for gw in self.gateways.values():
                if getattr(gw.engine, "paged", False):
                    gw.engine.pool.on_evict = self._promote_hook(
                        gw.engine)
        self._publish_states()
        self._publish_tiers()

    # -- membership / state ------------------------------------------------

    def _publish_states(self) -> None:
        counts = {READY: 0, DRAINING: 0, DEAD: 0}
        for s in self._state.values():
            counts[s] += 1
        for s, n in counts.items():
            cp_metrics.SERVING_FLEET_REPLICAS.labels(s).set(n)

    def _rebuild_ring_locked(self) -> None:
        ready = [m for m in self.gateways
                 if self._state[m] == READY]
        self._ring = (HashRing(ready, vnodes=self._vnodes)
                      if ready else None)
        self._publish_states()

    def _set_state(self, name: str, state: str) -> None:
        with self._lock:
            self._state[name] = state
            self._rebuild_ring_locked()

    def add_replica(self, name: str, gateway: ServingGateway,
                    role: str | None = None) -> None:
        """Grow the fleet live: ``name`` joins the ring READY and new
        traffic starts landing on it immediately (consistent hashing
        moves only the keys that must move). On a disaggregated fleet
        ``role`` is required; the global store makes every previously
        published prefix adoptable by the newcomer at once."""
        with self._lock:
            if name in self.gateways:
                raise ValueError(f"replica {name!r} already in fleet")
            if self.roles is not None:
                if role not in ROLES:
                    raise ValueError(
                        f"disaggregated fleet: role must be one of "
                        f"{'|'.join(ROLES)}, got {role!r}")
                self.roles[name] = role
            elif role is not None:
                raise ValueError("role given but fleet is not "
                                 "disaggregated (no roles=...)")
            self.gateways[name] = gateway
            self._state[name] = READY
            self._rebuild_ring_locked()
        if self.store is not None and getattr(gateway.engine, "paged",
                                              False):
            gateway.engine.pool.on_evict = self._promote_hook(
                gateway.engine)
        self._publish_tiers()

    def remove_replica(self, name: str,
                       *, grace_s: float = 0.0) -> ServingGateway:
        """Shrink the fleet live: drain ``name`` (out of the ring,
        queued work migrates), optionally let active slots finish for
        ``grace_s``, then close it — remaining in-flight requests take
        the r13 kill-migration path and complete bit-identically
        elsewhere. Prefixes the replica promoted/published survive in
        the global store. Returns the detached gateway."""
        with self._lock:
            if name not in self.gateways:
                raise KeyError(f"no replica {name!r}")
            if len(self.gateways) == 1:
                raise ValueError("cannot remove the last replica")
            if (self.roles is not None
                    and self.roles.get(name) == "decode"
                    and sum(1 for m, r in self.roles.items()
                            if r == "decode" and m != name) == 0):
                raise ValueError("cannot remove the last decode "
                                 "replica")
        self.drain(name)
        gw = self.gateways[name]
        if grace_s > 0:
            deadline = time.monotonic() + grace_s
            while (gw.engine.active_slots
                   and time.monotonic() < deadline):
                time.sleep(0.005)
        gw.close()
        with self._lock:
            self.gateways.pop(name, None)
            self._state.pop(name, None)
            if self.roles is not None:
                self.roles.pop(name, None)
            self._rebuild_ring_locked()
        self._publish_tiers()
        return gw

    def drain(self, name: str) -> None:
        """Pull ``name`` out of rotation: ring drops it, its healthz
        flips 503, its queued requests migrate, its active slots
        finish. (Kubernetes analogue: preStop hook before SIGTERM.)"""
        self._set_state(name, DRAINING)
        self.gateways[name].start_drain()

    def kill(self, name: str) -> None:
        """Hard-kill ``name`` (chaos arm): every in-flight request —
        queued AND mid-decode — migrates to another replica."""
        self._set_state(name, DEAD)
        self.gateways[name].close()

    def states(self) -> dict[str, str]:
        with self._lock:
            return dict(self._state)

    def _publish_tiers(self) -> None:
        if self.roles is None:
            return
        for tier in ROLES:
            names = [m for m, r in self.roles.items() if r == tier]
            slots = sum(self.gateways[m].engine.slots for m in names)
            active = sum(self.gateways[m].engine.active_slots
                         for m in names)
            cp_metrics.SERVING_TIER_OCCUPANCY.labels(tier).set(
                active / max(1, slots))

    def _promote_hook(self, eng):
        """Called by ``BlockPool._evict_one`` with the dying block's
        contents still resident, under the owning gateway's lock
        (gateway 440 -> store 445: uphill). LRU evicts oldest-first,
        so a chain's head chunk promotes before its successors — each
        later eviction extends the store-held prefix by one block."""
        def hook(key: bytes, block: int) -> None:
            pool = eng.pool
            covered = pool.covered_of(key)
            if covered is None:
                return      # pre-chain registration; nothing to vouch
            BS = pool.block_size
            valid = covered - ((covered - 1) // BS) * BS
            chunk = paging.export_block_chunk(eng.cache, block, valid)
            self.store.extend(pool.parent_of(key), key, chunk, covered)
        return hook

    # -- routing -----------------------------------------------------------

    def affinity_key(self, prompt: list[int],
                     session: str | None = None) -> str:
        if session:
            return f"s:{session}"
        head = prompt[: self.prefix_tokens]
        return "p:" + hashlib.md5(
            b",".join(str(t).encode() for t in head)).hexdigest()

    def route(self, prompt: list[int], session: str | None = None,
              *, exclude: set[str] | None = None) -> str:
        """Pick the replica for this request. Raises
        ``NoReadyReplica`` when nothing can take it."""
        key = self.affinity_key(prompt, session)
        with self._lock:
            ready = [m for m in sorted(self.gateways)
                     if self._state[m] == READY
                     and m not in (exclude or ())]
            if not ready:
                raise NoReadyReplica("no ready serving replica")
            ring = (self._ring if not exclude and self._ring is not None
                    else HashRing(ready, vnodes=self._vnodes))
            owner = ring.shard_for(key)
            # snapshot the gateway objects under the lock: a concurrent
            # remove_replica may pop names from self.gateways the
            # moment we release it
            gws = {m: self.gateways[m] for m in ready}
        depth = gws[owner].engine.queue_depth
        if depth >= self.spill_depth and len(ready) > 1:
            shallowest = min(
                ready, key=lambda m: gws[m].engine.queue_depth)
            if (gws[shallowest].engine.queue_depth < depth
                    and shallowest != owner):
                self.spills += 1
                return shallowest
        return owner

    def _route_decode(self, *, exclude: set[str] | None = None) -> str:
        """Disaggregated decode routing: shallowest-queue READY decode
        replica. No affinity — the global store makes the prefix
        portable, so queue depth is the only signal that matters."""
        with self._lock:
            ready = [m for m in sorted(self.gateways)
                     if self._state[m] == READY
                     and self.roles[m] == "decode"
                     and m not in (exclude or ())]
            gws = {m: self.gateways[m] for m in ready}
        if not ready:
            raise NoReadyReplica("no ready decode replica")
        return min(ready, key=lambda m: gws[m].engine.queue_depth)

    def _route_prefill(self) -> str | None:
        """Shallowest-queue READY prefill replica, or None when the
        tier is down (callers fall back to decode-local prefill —
        slower, never wrong)."""
        with self._lock:
            ready = [m for m in sorted(self.gateways)
                     if self._state[m] == READY
                     and self.roles[m] == "prefill"]
            gws = {m: self.gateways[m] for m in ready}
        if not ready:
            return None
        return min(ready, key=lambda m: gws[m].engine.queue_depth)

    def _stage_prefix(self, gw: ServingGateway,
                      prompt: list[int]) -> dict | None:
        """Decode-side prefix staging for one disaggregated request.

        Returns a FULL chain to install (the decode replica skips
        prefill entirely), or None after doing the best available
        thing: nothing (prompt already resident locally), adopting a
        partial store hit (the local prefix cache then absorbs the
        covered head), or — on a store miss — routing the prompt
        through the prefill tier and publishing the result so the
        NEXT request for this prefix hits the store."""
        eng = gw.engine
        if gw.chain_coverage(prompt) >= len(prompt) - 1:
            return None     # local blocks already cover the prompt
        keys = paging.prefix_keys(prompt, eng.block_size)
        entry = self.store.lookup(keys)
        if entry is not None:
            if (entry.get("tokens") == prompt
                    and entry.get("last_logits") is not None):
                return entry    # exact hit: install, skip prefill
            gw.adopt_chain(entry)   # partial: seat the covered head
            return None
        pf = self._route_prefill()
        pf_gw = self.gateways.get(pf) if pf is not None else None
        if pf_gw is None:
            return None     # prefill tier down: decode-local prefill
        t0 = time.monotonic()
        try:
            chain = pf_gw.prefill_chain(prompt)
        except ValueError:
            return None     # prompt outside the prefill slot shape
        if chain is None:
            return None     # draining / pool too full to hold it
        self.store.publish(chain)
        self.handoffs += 1
        cp_metrics.SERVING_CHAIN_HANDOFF_SECONDS.observe(
            time.monotonic() - t0)
        return chain

    # -- request lifecycle -------------------------------------------------

    def submit_and_wait(self, tenant: str, prompt: list[int], *,
                        max_new_tokens: int, eos_id: int | None = None,
                        slo_class: str | None = None,
                        session: str | None = None,
                        speculative: bool = False,
                        timeout_s: float = 300.0):
        """Route, decode, and — if the replica goes away mid-flight —
        migrate and resume. Returns ``(tokens, info)`` on success or
        ``(None, info)`` on shed; ``info`` carries the replica path and
        shed reason. A migrated request resumes from the tokens it
        already produced (greedy continuation is bit-identical to an
        uninterrupted run), so a kill costs latency, never correctness.

        Disaggregated fleets route by queue depth over the decode
        tier and stage the prompt's prefix first (store hit, partial
        adoption, or a prefill-tier handoff — see ``_stage_prefix``).
        ``speculative=True`` (batch/best_effort only) runs the fused
        speculative path on the decode replica and bypasses staging:
        the speculative kernel owns its own contiguous cache.
        """
        tokens: list[int] = []
        path: list[str] = []
        tried: set[str] = set()
        disagg = self.roles is not None
        while True:
            budget = max_new_tokens - len(tokens)
            if budget <= 0:
                return tokens, {"replicas": path, "migrations":
                                len(path) - 1}
            full = prompt + tokens
            try:
                name = (self._route_decode(exclude=tried or None)
                        if disagg else
                        self.route(full, session, exclude=tried or None))
            except NoReadyReplica:
                return None, {"replicas": path, "reason": "no_replica"}
            gw = self.gateways.get(name)
            if gw is None:
                # lost the race with remove_replica: the topology was
                # rebuilt after we routed. Re-resolve from the CURRENT
                # ring — never submit to a replica being removed.
                tried.add(name)
                continue
            chain = None
            if (disagg and self.store is not None and not speculative
                    and getattr(gw.engine, "paged", False)):
                chain = self._stage_prefix(gw, full)
            try:
                pending, reason = gw.try_submit(
                    tenant, full, max_new_tokens=budget,
                    eos_id=eos_id, slo_class=slo_class,
                    speculative=speculative, chain=chain)
            except ValueError:
                # a resume prompt can overflow slot_len even though the
                # original request fit: bucket(Tp + tokens_so_far) may
                # round up to the next power of two while the remaining
                # budget shrinks by less.  Greedy decode is
                # deterministic, so restarting from the original prompt
                # reproduces the same tokens — pay the decode again
                # rather than fail the request.
                if not tokens:
                    raise
                tokens = []
                continue
            if pending is None:
                if reason in ("rate", "tokens"):
                    # per-tenant budgets are fleet policy, not replica
                    # pressure — spilling would launder the quota
                    return None, {"replicas": path, "reason": reason}
                tried.add(name)     # queue/slo/draining: try elsewhere
                continue
            path.append(name)
            try:
                got = gw.wait(pending, timeout_s)
                tokens.extend(got)
                return tokens, {"replicas": path,
                                "migrations": len(path) - 1}
            except ReplicaUnavailable as e:
                tokens.extend(e.tokens_so_far)
                self.migrations += 1
                cp_metrics.SERVING_MIGRATIONS_TOTAL.inc()
                tried.add(name)
                # eos may have landed just before the drain severed us
                if eos_id is not None and tokens and tokens[-1] == eos_id:
                    return tokens, {"replicas": path,
                                    "migrations": len(path) - 1}

    # -- observability -----------------------------------------------------

    def snapshot(self) -> dict:
        states = self.states()
        self._publish_tiers()
        return {
            "replicas": {
                name: {
                    "state": states[name],
                    "role": (self.roles[name] if self.roles
                             else None),
                    "queue_depth": gw.engine.queue_depth,
                    "active_slots": gw.engine.active_slots,
                    "prefix_hit_ratio": gw.engine.stats().get(
                        "prefix_hit_ratio"),
                }
                for name, gw in sorted(self.gateways.items())
            },
            "migrations": self.migrations,
            "spills": self.spills,
            "handoffs": self.handoffs,
            "prefix_tokens": self.prefix_tokens,
            "roles": dict(self.roles) if self.roles else None,
            "store": self.store.stats() if self.store else None,
        }

    def close(self) -> None:
        for name, gw in self.gateways.items():
            if self._state[name] != DEAD:
                gw.close()


def make_fleet_app(fleet: ServingFleet, cfg):
    """werkzeug WSGI front door over the whole fleet: the thing an
    external LB points at. ``POST /generate`` adds optional
    ``session`` (stickiness) and ``slo_class`` fields to the
    single-replica contract; ``GET /api/fleet`` is the ops view;
    ``POST /replicas/<name>/drain`` is the preStop hook."""
    from werkzeug.exceptions import BadRequest, HTTPException, NotFound
    from werkzeug.routing import Map, Rule
    from werkzeug.wrappers import Request, Response

    urls = Map([
        Rule("/generate", endpoint="generate", methods=["POST"]),
        Rule("/healthz", endpoint="healthz"),
        Rule("/api/fleet", endpoint="fleet"),
        Rule("/api/store", endpoint="store"),
        Rule("/api/store/chain/<key>", endpoint="chain"),
        Rule("/metrics", endpoint="metrics"),
        Rule("/replicas/<name>/drain", endpoint="drain",
             methods=["POST"]),
    ])

    def _json(payload, status=200):
        return Response(json.dumps(payload), status=status,
                        content_type="application/json")

    def app(environ, start_response):
        req = Request(environ)
        try:
            endpoint, args = urls.bind_to_environ(environ).match()
            if endpoint == "healthz":
                states = fleet.states()
                ready = sum(1 for s in states.values() if s == READY)
                status = 200 if ready else 503
                return _json({"ok": bool(ready), "ready": ready,
                              "replicas": states}, status)(
                    environ, start_response)
            if endpoint == "fleet":
                return _json(fleet.snapshot())(environ, start_response)
            if endpoint == "store":
                if fleet.store is None:
                    return _json({"enabled": False})(
                        environ, start_response)
                return _json({"enabled": True,
                              **fleet.store.stats()})(
                    environ, start_response)
            if endpoint == "chain":
                # chain-by-hash fetch: how a decode replica in another
                # process adopts a prefix — body is chain_to_bytes()
                if fleet.store is None:
                    raise NotFound("fleet has no global block store")
                try:
                    key = bytes.fromhex(args["key"])
                except ValueError as e:
                    raise BadRequest("key must be hex") from e
                got = fleet.store.get_chain(key)
                if got is None:
                    raise NotFound("no chain holds that prefix key")
                resp = Response(
                    chain_to_bytes(got),
                    content_type="application/octet-stream")
                return resp(environ, start_response)
            if endpoint == "metrics":
                resp = Response(cp_metrics.scrape(),
                                content_type="text/plain; version=0.0.4")
                return resp(environ, start_response)
            if endpoint == "drain":
                if args["name"] not in fleet.gateways:
                    raise NotFound(f"no replica {args['name']}")
                fleet.drain(args["name"])
                return _json({"draining": args["name"]})(
                    environ, start_response)
            body = req.get_json(force=True)
            if not isinstance(body, dict):
                raise BadRequest("body must be a JSON object")
            prompt = body.get("prompt")
            if (not isinstance(prompt, list) or not prompt
                    or not all(isinstance(t, int)
                               and 0 <= t < cfg.vocab_size
                               for t in prompt)):
                raise BadRequest("prompt must be a non-empty list of "
                                 f"token ids in [0, {cfg.vocab_size})")
            tenant = body.get("tenant") \
                or req.headers.get("X-Tenant") or "default"
            max_new = body.get("max_new_tokens", 16)
            if not isinstance(max_new, int) or not 1 <= max_new <= 4096:
                raise BadRequest("max_new_tokens must be an int in "
                                 "[1, 4096]")
            session = body.get("session")
            if session is not None and (not isinstance(session, str)
                                        or len(session) > 128):
                raise BadRequest("session must be a short string")
            slo_class = body.get("slo_class")
            if slo_class is not None and slo_class not in (
                    "interactive", "batch", "best_effort"):
                raise BadRequest("slo_class must be one of "
                                 "interactive|batch|best_effort")
            speculative = body.get("speculative", False)
            if not isinstance(speculative, bool):
                raise BadRequest("speculative must be a bool")
            try:
                tokens, info = fleet.submit_and_wait(
                    tenant, prompt, max_new_tokens=max_new,
                    eos_id=body.get("eos_id"), slo_class=slo_class,
                    session=session, speculative=speculative)
            except ValueError as e:
                raise BadRequest(str(e)) from e
            if tokens is None:
                reason = info.get("reason")
                status = 429 if reason in ("rate", "tokens") else 503
                resp = _json({"error": "shed", "reason": reason},
                             status=status)
                resp.headers["Retry-After"] = "1"
            else:
                resp = _json({"tokens": tokens, **info})
        except HTTPException as e:
            resp = e
        return resp(environ, start_response)

    app.fleet = fleet
    return app
