"""Suspend/resume lifecycle + priority preemption: chip oversubscription.

NotebookOS (arxiv 2503.20591) allocates accelerators to interactive
notebooks *on demand*: a notebook between bursts checkpoints its state
and releases its devices, and any incoming request transparently
restores it. This module is that loop for TPU slices, composed from
pieces the platform already had:

- **Suspend** (``SuspendController`` + ``initiate_suspend``): snapshot
  the notebook's training state through a Checkpointer-backed state
  store, stamp ``SUSPEND_ANNOTATION`` — the notebook controller renders
  the StatefulSet to zero replicas exactly as it does for the stop
  annotation, the fake kubelet deletes the ordinal pods, and the
  scheduler cache gives the chips back (``release()`` short-circuits
  the watch-event lag so a waiting gang can bind in the same reconcile).
- **Resume** (``request_resume`` + the controller's rebind half): any
  incoming request — the jupyter readiness long-poll, a PATCH, a log
  fetch — clears the suspend annotation and stamps
  ``RESUME_REQUESTED_ANNOTATION``; the StatefulSet scales back up,
  ``gang_bind`` re-gangs the slice (anywhere it fits — slices are
  location-transparent), the state store restores the checkpoint token,
  and the push-readiness hub wakes the blocked client. Latency is
  recorded per phase (drain / rebind / restore).
- **Preemption** (``try_preempt``): when a higher-priority gang cannot
  bind, pick victim slices — lowest priority first, then longest idle,
  then best fragmentation fit — suspend them through the same
  lifecycle, delete their pods (kube-scheduler's preemption deletes
  victims directly), and bind the newcomer all-or-nothing.

The ``--no-oversubscribe`` arm (``set_oversubscribe(False)``) restores
pin-for-lifetime behavior: no idle suspension, no preemption.
"""

from __future__ import annotations

import contextlib
import datetime
import json
import logging
import time
from typing import Callable

from kubeflow_rm_tpu.analysis.lockgraph import make_lock
from kubeflow_rm_tpu.controlplane import chaos, metrics, scheduler
from kubeflow_rm_tpu.controlplane.api import notebook as nb_api
from kubeflow_rm_tpu.controlplane.api.meta import (
    annotations_of,
    deep_get,
    name_of,
    namespace_of,
    set_annotation,
)
from kubeflow_rm_tpu.controlplane.apiserver import (
    APIServer, Conflict, NotFound,
)
from kubeflow_rm_tpu.controlplane.runtime import (
    Controller, Request, map_by_label,
)

DEFAULT_CHECK_PERIOD_MIN = 1.0

log = logging.getLogger("kubeflow_rm_tpu.suspend")

# annotation bumped on pending pods to requeue their owner StatefulSet
# when a drain returns chips to the pool (see kick_pending_pods)
_KICK_ANNOTATION = "notebooks.kubeflow.org/reschedule-kick"


# ---- the oversubscription A/B switch ---------------------------------

_oversubscribe = True


def set_oversubscribe(enabled: bool) -> None:
    """``--no-oversubscribe`` arm: keep today's pin-for-lifetime
    behavior — no idle suspension, no preemptive gang-bind."""
    global _oversubscribe
    _oversubscribe = bool(enabled)


def oversubscribe() -> bool:
    return _oversubscribe


# ---- Checkpointer-backed state stores --------------------------------

class InMemoryStateStore:
    """Default state store: holds each notebook's snapshot payload in
    process memory, keyed (namespace, name). The snapshot records the
    workload's durable training step (the launcher agent maintains
    ``TRAINING_STEP_ANNOTATION``); restore hands the payload back so
    the controller can prove exactness via ``RESTORED_STEP_ANNOTATION``."""

    def __init__(self):
        self._saved: dict[tuple, dict] = {}

    def snapshot(self, notebook: dict) -> dict:
        chaos.checkpoint_write_fault(
            f"store:{namespace_of(notebook)}/{name_of(notebook)}")
        ann = annotations_of(notebook)
        try:
            step = int(ann.get(nb_api.TRAINING_STEP_ANNOTATION) or 0)
        except (TypeError, ValueError):
            step = 0
        token = {"step": step}
        self._saved[(namespace_of(notebook), name_of(notebook))] = token
        return dict(token)

    def restore(self, notebook: dict, token: dict | None) -> dict | None:
        saved = self._saved.get(
            (namespace_of(notebook), name_of(notebook)))
        if saved is None:
            return dict(token) if token else None
        return dict(saved)


class CheckpointerStateStore:
    """State store bridged to ``training/checkpoint.py``: each notebook
    workspace has a Checkpointer-compatible manager (``latest_step()``,
    optionally ``wait()``). Suspend records the last *durable* step —
    the slice can be torn down because training resumes exactly there;
    resume verifies the checkpoint still holds a step ≥ the token's.

    ``manager_for(namespace, name)`` is injected so deployments map
    notebooks to their PVC/GCS checkpoint directories and tests pass
    fakes or real orbax ``Checkpointer`` instances."""

    def __init__(self, manager_for: Callable[[str, str], object]):
        self._manager_for = manager_for

    def snapshot(self, notebook: dict) -> dict:
        chaos.checkpoint_write_fault(
            f"store:{namespace_of(notebook)}/{name_of(notebook)}")
        mgr = self._manager_for(namespace_of(notebook), name_of(notebook))
        wait = getattr(mgr, "wait", None)
        if wait is not None:
            wait()  # pending async saves must be durable before teardown
        step = mgr.latest_step()
        return {"step": int(step) if step is not None else 0}

    def restore(self, notebook: dict, token: dict | None) -> dict | None:
        mgr = self._manager_for(namespace_of(notebook), name_of(notebook))
        step = mgr.latest_step()
        restored = {"step": int(step) if step is not None else 0}
        want = (token or {}).get("step")
        if want is not None and restored["step"] < want:
            # checkpoint regressed under us (GC raced, storage lost a
            # write): restore the best durable step and say which
            restored["degraded_from"] = want
        return restored


_state_store = InMemoryStateStore()

# ---- per-notebook checkpoint serialization ---------------------------
# A suspend (snapshot + stamp) racing a promote/resume (restore + stamp)
# on the SAME notebook must never interleave: the loser could restore a
# half-written token into a standby. One ranked lock per notebook key,
# held across the store call AND its annotation CAS; distinct notebooks
# never contend.
_store_locks: dict[tuple, object] = {}
_store_locks_guard = make_lock("suspend.store_registry")


@contextlib.contextmanager
def _store_guard(namespace: str, name: str):
    key = (namespace, name)
    with _store_locks_guard:
        lock = _store_locks.get(key)
        if lock is None:
            lock = _store_locks[key] = make_lock(
                "suspend.store", rank=f"{namespace}/{name}")
    with lock:
        yield


def set_state_store(store) -> None:
    """Swap the module-default state store (conformance wires a
    CheckpointerStateStore; tests reset to a fresh InMemoryStateStore)."""
    global _state_store
    _state_store = store


def state_store():
    return _state_store


# ---- lifecycle verbs (shared by controller, webapp, preemption) ------

def _update_retrying(api: APIServer, notebook: dict,
                     mutate: Callable[[dict], bool]) -> dict:
    """Apply ``mutate`` (returns False to abort) and update, retrying
    the read-modify-write on Conflict — suspend races the culler and
    the webapp on the same annotations map. Always starts from a fresh
    ``get()`` copy: callers may hold ``scan()`` store references, and
    mutating those in place would make the write a self-comparing
    no-op under the cache's suppression.

    Kind-agnostic: the suspend annotation vocabulary is shared by
    Notebook and TPUJob, so the verbs below drive both — the kind is
    taken from the object itself."""
    kind = notebook.get("kind") or nb_api.KIND
    notebook = api.get(kind, name_of(notebook),
                       namespace_of(notebook))
    for _ in range(8):
        if not mutate(notebook):
            return notebook
        try:
            return api.update(notebook)
        except Conflict:
            notebook = api.get(kind, name_of(notebook),
                               namespace_of(notebook))
    raise Conflict(f"could not update {kind} {name_of(notebook)} "
                   "after 8 attempts")


def initiate_suspend(api: APIServer, notebook: dict, *,
                     reason: str, store=None) -> dict:
    """Drive a notebook into the Suspended lifecycle: snapshot state,
    stamp the suspend annotations (the notebook controller scales the
    StatefulSet to zero from them), emit the event. Idempotent."""
    store = store if store is not None else _state_store
    token_box: list = []

    def mutate(nb: dict) -> bool:
        ann = annotations_of(nb)
        if nb_api.SUSPEND_ANNOTATION in ann:
            return False  # already suspending/suspended
        if nb_api.RESUME_REQUESTED_ANNOTATION in ann:
            # a resume (or a replica promotion — failover stamps the
            # same annotation) owns the slice right now; parking on
            # top would clobber its checkpoint token mid-restore
            return False
        if not token_box:
            token_box.append(store.snapshot(nb))
        set_annotation(nb, nb_api.SUSPEND_ANNOTATION,
                       api.clock().isoformat())
        set_annotation(nb, nb_api.SUSPEND_REASON_ANNOTATION, reason)
        set_annotation(nb, nb_api.SUSPEND_CHECKPOINT_ANNOTATION,
                       json.dumps(token_box[0]))
        # a fresh cycle: clear residue from any previous one
        ann.pop(nb_api.SUSPEND_DRAINED_ANNOTATION, None)
        return True

    # snapshot + stamp is one critical section per notebook: a
    # concurrent promote/resume must observe either the pre-suspend
    # or the fully-stamped state, never a half-written token
    with _store_guard(namespace_of(notebook), name_of(notebook)):
        live = _update_retrying(api, notebook, mutate)
    if token_box:  # we actually initiated (not a no-op)
        api.record_event(
            live, "Normal", "Suspending",
            f"suspending slice ({reason}); checkpoint token "
            f"{json.dumps(token_box[0])} — chips return to the pool, "
            "the notebook resumes on next request")
        metrics.NOTEBOOK_SUSPEND_TOTAL.labels(reason=reason).inc()
    return live


def request_resume(api: APIServer, notebook: dict, *,
                   source: str = "request") -> dict:
    """Flip a suspended notebook back toward Running: clear the suspend
    annotation (the StatefulSet scales back up and re-gangs) and stamp
    the resume-request time — earliest stamp wins, it is the latency
    clock the rebind phase is measured against. Idempotent."""
    acted: list = []

    def mutate(nb: dict) -> bool:
        ann = annotations_of(nb)
        if nb_api.SUSPEND_ANNOTATION not in ann:
            return False  # not suspended (or resume already in flight)
        ann.pop(nb_api.SUSPEND_ANNOTATION, None)
        if nb_api.RESUME_REQUESTED_ANNOTATION not in ann:
            set_annotation(nb, nb_api.RESUME_REQUESTED_ANNOTATION,
                           api.clock().isoformat())
        acted.append(True)
        return True

    live = _update_retrying(api, notebook, mutate)
    if acted:
        api.record_event(
            live, "Normal", "Resuming",
            f"resume requested ({source}); re-ganging the slice and "
            "restoring checkpointed state")
    return live


def initiate_migration(api: APIServer, notebook: dict, *,
                       trigger: str = "api", store=None) -> dict:
    """Live migration = the suspend/resume primitive aimed at a
    *different* placement: record the nodes the slice currently
    occupies as the rebind's exclusion set, stamp the migrate request,
    and drive the normal suspend lifecycle (reason="migrate"). The
    drain auto-resumes (never parks) and ``gang_bind`` skips the
    excluded nodes, so the slice comes back elsewhere with its state
    restored. ``trigger`` is "api" (explicit drain verb) or
    "fragmentation" (the compaction autopilot). Idempotent."""
    name, ns = name_of(notebook), namespace_of(notebook)
    nodes = sorted({
        deep_get(p, "spec", "nodeName")
        for p in api.list("Pod", ns)
        if (p["metadata"].get("labels") or {}).get(
            nb_api.NOTEBOOK_NAME_LABEL) == name
        and deep_get(p, "spec", "nodeName")})
    acted: list = []

    def mutate(nb: dict) -> bool:
        ann = annotations_of(nb)
        if (nb_api.MIGRATE_REQUESTED_ANNOTATION in ann
                or nb_api.SUSPEND_ANNOTATION in ann
                or nb_api.STOP_ANNOTATION in ann
                or nb_api.RESUME_REQUESTED_ANNOTATION in ann):
            return False  # mid-lifecycle: nothing to migrate from
        set_annotation(nb, nb_api.MIGRATE_REQUESTED_ANNOTATION,
                       api.clock().isoformat())
        set_annotation(nb, nb_api.MIGRATE_EXCLUDE_ANNOTATION,
                       json.dumps(nodes))
        acted.append(True)
        return True

    live = _update_retrying(api, notebook, mutate)
    if acted:
        metrics.NOTEBOOK_MIGRATION_TOTAL.labels(trigger=trigger).inc()
        api.record_event(
            live, "Normal", "Migrating",
            f"live migration requested ({trigger}): checkpoint, drain "
            f"off {nodes}, re-bind elsewhere")
        live = initiate_suspend(api, live, reason="migrate", store=store)
    return live


def kick_pending_pods(api: APIServer, *, now: str) -> None:
    """Requeue every slice still waiting for chips: freed capacity
    doesn't emit an event any controller watches, so after a drain we
    bump an annotation on each unbound Pending pod — its update event
    maps to the owning StatefulSet, whose reconcile retries the
    gang-bind. Best-effort: a lost kick is recovered by the next drain
    or the long-poll's periodic backstop."""
    scan = getattr(api, "scan", api.list)
    for p in scan("Pod"):
        if deep_get(p, "spec", "nodeName"):
            continue
        if deep_get(p, "status", "phase") not in (None, "Pending"):
            continue
        pod = api.try_get("Pod", name_of(p), namespace_of(p))
        if pod is None:
            continue
        set_annotation(pod, _KICK_ANNOTATION, now)
        try:
            api.update(pod)
        except (Conflict, NotFound):
            pass


# ---- the controller --------------------------------------------------

class SuspendController(Controller):
    """Owns both halves of the lifecycle.

    Suspend half: once a suspend-annotated notebook's pods are gone,
    release any cache residue, stamp the drained timestamp, observe the
    drain latency. With ``suspend_idle_minutes`` set it also *initiates*
    suspension for idle notebooks (last-activity / worker-0 start,
    same clock the culler uses) — a gentler tier below culling.

    Resume half: when a resume-requested notebook is ready again,
    restore the state store token, stamp ``RESTORED_STEP_ANNOTATION``,
    observe rebind+restore latency. Pod events requeue it (same label
    watch as the notebook controller), so the loop is event-driven —
    deterministic under ``run_until_idle`` with an injected clock.
    """

    kind = nb_api.KIND

    def __init__(self, suspend_idle_minutes: float | None = None,
                 check_period_minutes: float = DEFAULT_CHECK_PERIOD_MIN,
                 store=None):
        self.suspend_idle = (
            datetime.timedelta(minutes=suspend_idle_minutes)
            if suspend_idle_minutes is not None else None)
        self.check_period = datetime.timedelta(minutes=check_period_minutes)
        self._store = store

    @property
    def store(self):
        return self._store if self._store is not None else _state_store

    def watches(self):
        return (("Pod", map_by_label(nb_api.NOTEBOOK_NAME_LABEL)),)

    def reconcile(self, api: APIServer, req: Request):
        try:
            notebook = api.get(nb_api.KIND, req.name, req.namespace)
        except NotFound:
            return None
        if notebook["metadata"].get("deletionTimestamp"):
            return None
        ann = annotations_of(notebook)
        if nb_api.STOP_ANNOTATION in ann:
            return None  # user-stopped: the stop lifecycle owns it
        if nb_api.SUSPEND_ANNOTATION in ann:
            return self._reconcile_suspending(api, notebook)
        if nb_api.RESUME_REQUESTED_ANNOTATION in ann:
            return self._reconcile_resuming(api, notebook)
        return self._maybe_suspend_idle(api, notebook)

    # -- suspend half --------------------------------------------------
    def _reconcile_suspending(self, api: APIServer, notebook: dict):
        ann = annotations_of(notebook)
        if nb_api.SUSPEND_DRAINED_ANNOTATION in ann:
            if nb_api.MIGRATE_REQUESTED_ANNOTATION in ann:
                # a migration never parks: the drain completing IS the
                # resume trigger — the re-bind excludes the old nodes
                request_resume(api, notebook, source="migration")
            return None  # drained and parked; resume is event-driven
        name, ns = name_of(notebook), namespace_of(notebook)
        pods = [p for p in api.list("Pod", ns)
                if (p["metadata"].get("labels") or {}).get(
                    nb_api.NOTEBOOK_NAME_LABEL) == name]
        if pods:
            return None  # scale-down in flight; pod deletes requeue us
        # drained: purge any cache residue (assumed binds whose delete
        # events haven't cleared the fanout) so the pool sees the chips
        if not scheduler.legacy_scan():
            sched = scheduler.cache_for(api)
            for i in range(nb_api.total_hosts(notebook)):
                sched.release((ns, f"{name}-{i}"))
        now = api.clock()

        def mutate(nb: dict) -> bool:
            a = annotations_of(nb)
            if (nb_api.SUSPEND_ANNOTATION not in a
                    or nb_api.SUSPEND_DRAINED_ANNOTATION in a):
                return False
            set_annotation(nb, nb_api.SUSPEND_DRAINED_ANNOTATION,
                           now.isoformat())
            return True

        live = _update_retrying(api, notebook, mutate)
        drained = annotations_of(live).get(
            nb_api.SUSPEND_DRAINED_ANNOTATION)
        if drained == now.isoformat():  # we won the stamp: observe once
            since = _parse_ts(annotations_of(live).get(
                nb_api.SUSPEND_ANNOTATION))
            if since is not None:
                metrics.SUSPEND_RESUME_SECONDS.labels(
                    phase="drain").observe(
                        max(0.0, (now - since).total_seconds()))
            api.record_event(
                live, "Normal", "Suspended",
                f"slice drained; {nb_api.total_hosts(live)} host(s) of "
                "chips returned to the pool")
            kick_pending_pods(api, now=now.isoformat())
        if nb_api.MIGRATE_REQUESTED_ANNOTATION in annotations_of(live):
            request_resume(api, live, source="migration")
        return None

    # -- resume half -----------------------------------------------------
    def _reconcile_resuming(self, api: APIServer, notebook: dict):
        want = nb_api.total_hosts(notebook)
        ready = deep_get(notebook, "status", "readyReplicas", default=0)
        if ready < want:
            # not re-ganged yet: pod/status events requeue us; the
            # periodic tick below is only a backstop for lost events
            return self.check_period.total_seconds()
        ann = annotations_of(notebook)
        was_migration = nb_api.MIGRATE_REQUESTED_ANNOTATION in ann
        token = None
        raw = ann.get(nb_api.SUSPEND_CHECKPOINT_ANNOTATION)
        if raw:
            try:
                token = json.loads(raw)
            except ValueError:
                token = None
        now = api.clock()
        requested = _parse_ts(ann.get(nb_api.RESUME_REQUESTED_ANNOTATION))

        def mutate(nb: dict) -> bool:
            a = annotations_of(nb)
            if nb_api.RESUME_REQUESTED_ANNOTATION not in a:
                return False
            a.pop(nb_api.RESUME_REQUESTED_ANNOTATION, None)
            a.pop(nb_api.SUSPEND_CHECKPOINT_ANNOTATION, None)
            a.pop(nb_api.SUSPEND_DRAINED_ANNOTATION, None)
            a.pop(nb_api.SUSPEND_REASON_ANNOTATION, None)
            a.pop(nb_api.MIGRATE_REQUESTED_ANNOTATION, None)
            a.pop(nb_api.MIGRATE_EXCLUDE_ANNOTATION, None)
            if restored is not None and "step" in restored:
                set_annotation(nb, nb_api.RESTORED_STEP_ANNOTATION,
                               str(restored["step"]))
            return True

        # restore + finalize under the same per-notebook guard the
        # suspend half holds: two racers (suspend vs promote) serialize
        # here instead of interleaving a half-restored standby
        with _store_guard(namespace_of(notebook), name_of(notebook)):
            t0 = time.perf_counter()
            restored = self.store.restore(notebook, token)
            restore_s = time.perf_counter() - t0
            live = _update_retrying(api, notebook, mutate)
        if nb_api.RESUME_REQUESTED_ANNOTATION not in annotations_of(live):
            metrics.SUSPEND_RESUME_SECONDS.labels(
                phase="restore").observe(restore_s)
            if requested is not None:
                metrics.SUSPEND_RESUME_SECONDS.labels(
                    phase="rebind").observe(
                        max(0.0, (now - requested).total_seconds()))
            metrics.NOTEBOOK_RESUME_TOTAL.inc()
            api.record_event(
                live, "Normal", "Resumed",
                "slice re-ganged and state restored"
                + (f" at step {restored['step']}"
                   if restored and "step" in restored else ""))
            if was_migration:
                nodes = sorted({
                    deep_get(p, "spec", "nodeName")
                    for p in api.list("Pod", namespace_of(live))
                    if (p["metadata"].get("labels") or {}).get(
                        nb_api.NOTEBOOK_NAME_LABEL) == name_of(live)
                    and deep_get(p, "spec", "nodeName")})
                api.record_event(
                    live, "Normal", "Migrated",
                    f"slice live-migrated: re-ganged on {nodes} with "
                    "state restored")
        return None

    # -- idle initiation -------------------------------------------------
    def _maybe_suspend_idle(self, api: APIServer, notebook: dict):
        if self.suspend_idle is None or not oversubscribe():
            return None
        if nb_api.tpu_spec(notebook) is None:
            return None  # CPU notebooks hold no chips worth reclaiming
        ann = annotations_of(notebook)
        if (nb_api.is_pinned(notebook)
                or ann.get(nb_api.CULLING_EXCLUDE_ANNOTATION) == "true"):
            return None
        want = nb_api.total_hosts(notebook)
        ready = deep_get(notebook, "status", "readyReplicas", default=0)
        if ready < want:
            return self.check_period.total_seconds()
        now = api.clock()
        idle_since = _parse_ts(ann.get(nb_api.LAST_ACTIVITY_ANNOTATION))
        pod0 = api.try_get("Pod", f"{name_of(notebook)}-0",
                           namespace_of(notebook))
        started = _parse_ts(deep_get(
            pod0, "status", "containerStatuses", 0, "state", "running",
            "startedAt") if pod0 else None)
        # a freshly (re)started slice restarts its idle clock — a
        # resumed notebook gets a full idle window before re-parking
        if started is not None and (idle_since is None
                                    or started > idle_since):
            idle_since = started
        if idle_since is None:
            idle_since = _parse_ts(
                notebook["metadata"].get("creationTimestamp")) or now
        if now - idle_since >= self.suspend_idle:
            initiate_suspend(api, notebook, reason="idle",
                             store=self.store)
            return None
        return self.check_period.total_seconds()


# ---- replicated kernels: warm standbys + demand-resume failover ------

def _parse_states(ann: dict) -> dict | None:
    raw = ann.get(nb_api.REPLICA_STATES_ANNOTATION)
    if not raw:
        return None
    try:
        st = json.loads(raw)
    except ValueError:
        return None
    return st if isinstance(st, dict) else None


class ReplicaFailoverController(Controller):
    """NotebookOS replicated kernels over the suspend/resume primitive.

    ``spec.replicas: R`` > 1 keeps one *active* replica holding the
    chips and R−1 parked CPU-only standbys (rendered by the notebook
    controller as a ``{name}-standby`` StatefulSet) whose warm state is
    the checkpoint token this controller refreshes as the active
    replica's durable training step advances.

    On active-replica death — a Failed gang pod (kubelet detection) or
    a rump slice — a standby promotes by *demand-resume*: one CAS
    stamps the warm checkpoint token + resume request + failover clock
    and rotates the active-replica pointer; the dead gang's pods are
    deleted and their cache charges released, and the existing resume
    machinery re-binds chips through ``gang_bind`` and restores state.
    Promotion completes (promoting → active, failover latency observed)
    when the resume finishes — warm-standby takeover at resume latency
    instead of cold-provision latency."""

    kind = nb_api.KIND

    def __init__(self, store=None):
        self._store = store

    @property
    def store(self):
        return self._store if self._store is not None else _state_store

    def watches(self):
        return (("Pod", map_by_label(nb_api.NOTEBOOK_NAME_LABEL)),)

    def reconcile(self, api: APIServer, req: Request):
        try:
            nb = api.get(nb_api.KIND, req.name, req.namespace)
        except NotFound:
            return None
        if nb["metadata"].get("deletionTimestamp"):
            return None
        replicas = nb_api.replicas_of(nb)
        ann = annotations_of(nb)
        states = _parse_states(ann)
        if replicas <= 1:
            if states is not None:
                self._clear_replica_state(api, nb)
            return None
        if states is None:
            return self._init_states(api, nb, replicas)
        if nb_api.STOP_ANNOTATION in ann:
            return None  # user-stopped: drained pods are expected
        if (nb_api.SUSPEND_ANNOTATION in ann
                or nb_api.RESUME_REQUESTED_ANNOTATION in ann):
            return None  # mid suspend/resume; pod events requeue us
        hosts = nb_api.total_hosts(nb)
        name, ns = req.name, req.namespace
        pods = [p for p in api.list("Pod", ns)
                if (p["metadata"].get("labels") or {}).get(
                    nb_api.NOTEBOOK_NAME_LABEL) == name
                and not p["metadata"].get("deletionTimestamp")]
        failed = [p for p in pods
                  if deep_get(p, "status", "phase") == "Failed"]
        running = [p for p in pods
                   if deep_get(p, "status", "phase") == "Running"]
        promoting = [i for i, s in states.items() if s == "promoting"]
        ready = deep_get(nb, "status", "readyReplicas", default=0)
        if promoting:
            # promotion in flight: the informer can still hold the dead
            # gang's Failed pods (we just deleted them) and the re-bound
            # slice recreates pods one at a time — both read as "death"
            # and would ping-pong the active pointer. Deaths of the
            # promoted gang itself heal through slice restart, so the
            # only move here is finishing the promotion.
            if ready >= hosts:
                return self._finalize_promotion(api, nb, ann)
            return None
        if ready >= hosts:
            # a Failed pod (or rump) while status still reads fully
            # Ready is single-source evidence: either a genuine death
            # whose status mirror hasn't landed (the mirror write
            # requeues us in ms) or a stale informer view of a gang we
            # already replaced (which must NOT rotate the pointer
            # again). Require both sources to agree before acting.
            self._refresh_warm(api, nb, ann)
            return None
        if failed or (running and len(pods) < hosts):
            evidence = "; ".join(
                [f"{name_of(p)}({p['metadata'].get('uid', '?')})="
                 f"{deep_get(p, 'status', 'phase')}" for p in failed]
                or [f"rump slice {len(pods)}/{hosts}"])
            return self._failover(api, nb, ann, hosts, evidence)
        return None

    def _clear_replica_state(self, api: APIServer, nb: dict):
        def mutate(o: dict) -> bool:
            a = annotations_of(o)
            if nb_api.REPLICA_STATES_ANNOTATION not in a:
                return False
            for k in (nb_api.REPLICA_STATES_ANNOTATION,
                      nb_api.ACTIVE_REPLICA_ANNOTATION,
                      nb_api.WARM_CHECKPOINT_ANNOTATION,
                      nb_api.FAILOVER_T0_ANNOTATION):
                a.pop(k, None)
            return True
        _update_retrying(api, nb, mutate)
        return None

    def _init_states(self, api: APIServer, nb: dict, replicas: int):
        def mutate(o: dict) -> bool:
            a = annotations_of(o)
            if nb_api.REPLICA_STATES_ANNOTATION in a:
                return False
            st = {"0": "active"}
            st.update({str(i): "standby" for i in range(1, replicas)})
            set_annotation(o, nb_api.REPLICA_STATES_ANNOTATION,
                           json.dumps(st))
            set_annotation(o, nb_api.ACTIVE_REPLICA_ANNOTATION, "0")
            return True
        live = _update_retrying(api, nb, mutate)
        if _parse_states(annotations_of(live)):
            api.record_event(
                live, "Normal", "ReplicasInitialized",
                f"replica 0 active, {replicas - 1} warm standby(s)")
        return None

    def _refresh_warm(self, api: APIServer, nb: dict, ann: dict):
        """Keep the standbys' warm token at the active replica's
        durable step — what a promotion will restore."""
        try:
            cur = int(ann.get(nb_api.TRAINING_STEP_ANNOTATION) or 0)
        except (TypeError, ValueError):
            cur = 0
        raw = ann.get(nb_api.WARM_CHECKPOINT_ANNOTATION)
        if raw:
            try:
                if json.loads(raw).get("step", -1) >= cur:
                    return  # warm state already current
            except ValueError:
                pass
        token = self.store.snapshot(nb)
        blob = json.dumps(token)

        def mutate(o: dict) -> bool:
            a = annotations_of(o)
            if (nb_api.SUSPEND_ANNOTATION in a
                    or nb_api.RESUME_REQUESTED_ANNOTATION in a
                    or a.get(nb_api.WARM_CHECKPOINT_ANNOTATION) == blob):
                return False
            set_annotation(o, nb_api.WARM_CHECKPOINT_ANNOTATION, blob)
            return True
        _update_retrying(api, nb, mutate)

    def _failover(self, api: APIServer, nb: dict, ann: dict,
                  hosts: int, evidence: str = ""):
        """Active replica died: promote the lowest standby by
        demand-resume. One CAS stamps checkpoint token + resume request
        + failover clock and rotates the pointer; then the dead gang is
        torn down so the resume machinery re-binds cleanly."""
        name, ns = name_of(nb), namespace_of(nb)
        t0 = api.clock().isoformat()
        warm = None
        raw = ann.get(nb_api.WARM_CHECKPOINT_ANNOTATION)
        if raw:
            try:
                warm = json.loads(raw)
            except ValueError:
                warm = None
        acted: list = []

        def mutate(o: dict) -> bool:
            a = annotations_of(o)
            if (nb_api.SUSPEND_ANNOTATION in a
                    or nb_api.RESUME_REQUESTED_ANNOTATION in a
                    or nb_api.STOP_ANNOTATION in a
                    # failover clock still stamped: the previous
                    # promotion hasn't finalized — refuse inside the
                    # CAS so a stale reread can't double-rotate
                    or nb_api.FAILOVER_T0_ANNOTATION in a):
                return False  # a lifecycle already owns the slice
            st = _parse_states(a)
            if not st:
                return False
            standbys = sorted(int(i) for i, s in st.items()
                              if s == "standby")
            if not standbys:
                return False  # nothing to promote
            target = standbys[0]
            old = a.get(nb_api.ACTIVE_REPLICA_ANNOTATION, "0")
            token = warm if warm is not None else self.store.snapshot(o)
            set_annotation(o, nb_api.SUSPEND_REASON_ANNOTATION,
                           "failover")
            set_annotation(o, nb_api.SUSPEND_CHECKPOINT_ANNOTATION,
                           json.dumps(token))
            set_annotation(o, nb_api.RESUME_REQUESTED_ANNOTATION, t0)
            set_annotation(o, nb_api.FAILOVER_T0_ANNOTATION, t0)
            set_annotation(o, nb_api.ACTIVE_REPLICA_ANNOTATION,
                           str(target))
            if str(old) in st:
                st[str(old)] = "standby"
            st[str(target)] = "promoting"
            set_annotation(o, nb_api.REPLICA_STATES_ANNOTATION,
                           json.dumps(st))
            acted[:] = [old, target]
            return True

        # the promotion CAS is a restore-path writer: serialize with
        # any concurrent suspend of the same notebook
        with _store_guard(ns, name):
            live = _update_retrying(api, nb, mutate)
        if not acted:
            return None
        api.record_event(
            live, "Warning", "FailingOver",
            f"active replica {acted[0]} died"
            + (f" ({evidence})" if evidence else "")
            + f"; standby {acted[1]} promoting by demand-resume "
            "(warm checkpoint, re-binding chips)")
        # tear the dead gang down by ordinal and release cache charges
        # so the re-bind sees the chips immediately
        sched = (scheduler.cache_for(api)
                 if not scheduler.legacy_scan() else None)
        for i in range(hosts):
            try:
                api.delete("Pod", f"{name}-{i}", ns)
            except NotFound:
                pass
            if sched is not None:
                sched.release((ns, f"{name}-{i}"))
        return None

    def _finalize_promotion(self, api: APIServer, nb: dict, ann: dict):
        now = api.clock()
        t0 = _parse_ts(ann.get(nb_api.FAILOVER_T0_ANNOTATION))
        acted: list = []

        def mutate(o: dict) -> bool:
            a = annotations_of(o)
            if nb_api.RESUME_REQUESTED_ANNOTATION in a:
                return False  # resume still in flight
            st = _parse_states(a)
            if not st:
                return False
            promoting = [i for i, s in st.items() if s == "promoting"]
            if not promoting:
                return False
            for i in promoting:
                st[i] = "active"
            set_annotation(o, nb_api.REPLICA_STATES_ANNOTATION,
                           json.dumps(st))
            a.pop(nb_api.FAILOVER_T0_ANNOTATION, None)
            acted[:] = promoting
            return True

        live = _update_retrying(api, nb, mutate)
        if acted:
            metrics.NOTEBOOK_FAILOVER_TOTAL.inc()
            if t0 is not None:
                metrics.NOTEBOOK_FAILOVER_SECONDS.observe(
                    max(0.0, (now - t0).total_seconds()))
            step = annotations_of(live).get(
                nb_api.RESTORED_STEP_ANNOTATION)
            api.record_event(
                live, "Normal", "FailedOver",
                f"replica {acted[0]} promoted to active; state restored"
                + (f" at step {step}" if step is not None else ""))
        return None


# ---- fragmentation-triggered live migration (compaction) -------------

_auto_migration = False


def set_auto_migration(enabled: bool) -> None:
    """Enable the compaction autopilot: a gang admissible only after
    defragmentation triggers a live migration of a small victim slice.
    Off by default — the static-placement arm and pre-existing suites
    keep today's behavior."""
    global _auto_migration
    _auto_migration = bool(enabled)


def auto_migration() -> bool:
    return _auto_migration


def try_compact_migration(api: APIServer, sts: dict,
                          unbound: list[dict],
                          sched: "scheduler.SchedulerCache", *,
                          allow_virtual: bool) -> None:
    """A gang failed to bind AND the fragmentation gauge says the free
    chips would seat it if they weren't stranded: live-migrate the
    smallest victim whose removal admits the waiter. The victim drains
    off its nodes (checkpoint → drain, excluded from rebinding there)
    and the freed contiguous capacity admits the waiter; the victim
    re-gangs wherever fits (best-effort — it parks until capacity
    otherwise). At most one migration in flight cluster-wide keeps the
    autopilot deterministic and non-thrashing."""
    if (not _auto_migration or not oversubscribe()
            or scheduler.legacy_scan()):
        return
    needed = sum(scheduler._pod_chips(p) for p in unbound)
    if not needed:
        return
    stats = sched.stats()
    if stats["free_chips"] < needed or stats["fragmentation"] <= 0.0:
        return  # not a fragmentation problem: capacity is simply short
    scan = getattr(api, "scan", api.list)
    waiter_key = (namespace_of(sts),
                  (sts["metadata"].get("labels") or {}).get(
                      nb_api.NOTEBOOK_NAME_LABEL) or name_of(sts))
    candidates: list[_Victim] = []
    for nb in scan(nb_api.KIND):
        ann = annotations_of(nb)
        if nb_api.MIGRATE_REQUESTED_ANNOTATION in ann:
            return  # a migration is already in flight: let it land
        if (nb["metadata"].get("deletionTimestamp")
                or nb_api.SUSPEND_ANNOTATION in ann
                or nb_api.STOP_ANNOTATION in ann
                or nb_api.RESUME_REQUESTED_ANNOTATION in ann
                or nb_api.is_pinned(nb)):
            continue
        if (namespace_of(nb), name_of(nb)) == waiter_key:
            continue
        name, ns = name_of(nb), namespace_of(nb)
        pods = [p for p in scan("Pod", ns)
                if (p["metadata"].get("labels") or {}).get(
                    nb_api.NOTEBOOK_NAME_LABEL) == name
                and deep_get(p, "spec", "nodeName")
                and deep_get(p, "status", "phase")
                not in scheduler.TERMINAL_PHASES]
        v = _Victim(nb, pods, nb_api.priority_of(nb), "")
        if v.chips:
            candidates.append(v)
    # smallest slice first: compaction should shuffle the cheapest
    # tenant, not shatter a big one
    candidates.sort(key=lambda v: (v.chips, name_of(v.notebook)))
    by_node = sched.free_by_node()
    free = {node: f for node, (f, _labels) in by_node.items()}
    labels = {node: lb for node, (_f, lb) in by_node.items()}
    for v in candidates:
        if _fits(unbound, free, dict(v.per_node), labels, allow_virtual):
            api.record_event(
                sts, "Normal", "CompactionMigration",
                f"gang admissible only after compaction (fragmentation "
                f"{stats['fragmentation']:.2f}, {stats['free_chips']:.0f}"
                f" chips free); live-migrating "
                f"{name_of(v.notebook)} ({v.chips:.0f} chips) off "
                f"{sorted(v.per_node)}")
            initiate_migration(api, v.notebook, trigger="fragmentation")
            return
    return


# ---- active fragmentation-driven defrag (scheduler policy arm) -------
# r11 added the fragmentation gauge; r15 made compaction migration a
# LAST RESORT (only when a gang already failed to bind). This promotes
# it to an ACTIVE placement policy: whenever fragmentation crosses the
# threshold, proactively migrate the cheapest victim whose removal
# grows the largest contiguous free block — so the next gang arrival
# finds contiguous capacity instead of paying the migrate-under-
# pressure latency. ON by default since the ratchet A/B proved the
# admission-latency win (the off arm failed the provision gate the
# active arm passed, ~30% higher spawn p50); --no-active-defrag is the
# escape hatch / baseline arm.

_active_defrag = True
ACTIVE_DEFRAG_FRAGMENTATION = 0.5


def set_active_defrag(enabled: bool) -> None:
    global _active_defrag
    _active_defrag = bool(enabled)


def active_defrag() -> bool:
    return _active_defrag


def maybe_active_defrag(api: APIServer,
                        sched: "scheduler.SchedulerCache", *,
                        allow_virtual: bool = False) -> bool:
    """One proactive compaction step, threshold-gated. Returns True if
    a migration was initiated. Reuses the last-resort machinery's
    victim model and in-flight guard (at most one migration cluster-
    wide), but is driven by the fragmentation gauge alone — no waiting
    gang required. The victim must (a) grow the largest contiguous
    free block and (b) plausibly re-land elsewhere (its biggest pod
    fits on some node it does not currently occupy), so defrag never
    evicts a slice into indefinite parking."""
    if not _active_defrag or not oversubscribe() \
            or scheduler.legacy_scan():
        return False
    stats = sched.stats()
    if stats["free_chips"] <= 0 \
            or stats["fragmentation"] < ACTIVE_DEFRAG_FRAGMENTATION:
        return False
    scan = getattr(api, "scan", api.list)
    candidates: list[_Victim] = []
    for nb in scan(nb_api.KIND):
        ann = annotations_of(nb)
        if nb_api.MIGRATE_REQUESTED_ANNOTATION in ann:
            return False  # one migration in flight: let it land
        if (nb["metadata"].get("deletionTimestamp")
                or nb_api.SUSPEND_ANNOTATION in ann
                or nb_api.STOP_ANNOTATION in ann
                or nb_api.RESUME_REQUESTED_ANNOTATION in ann
                or nb_api.is_pinned(nb)):
            continue
        name, ns = name_of(nb), namespace_of(nb)
        pods = [p for p in scan("Pod", ns)
                if (p["metadata"].get("labels") or {}).get(
                    nb_api.NOTEBOOK_NAME_LABEL) == name
                and deep_get(p, "spec", "nodeName")
                and deep_get(p, "status", "phase")
                not in scheduler.TERMINAL_PHASES]
        v = _Victim(nb, pods, nb_api.priority_of(nb), "")
        if v.chips:
            candidates.append(v)
    candidates.sort(key=lambda v: (v.chips, name_of(v.notebook)))
    by_node = sched.free_by_node()
    free = {node: f for node, (f, _labels) in by_node.items()}
    cur_block = max(free.values(), default=0.0)
    for v in candidates:
        grown = dict(free)
        for node, c in v.per_node.items():
            grown[node] = grown.get(node, 0.0) + c
        if max(grown.values(), default=0.0) <= cur_block:
            continue  # moving it wouldn't consolidate anything
        biggest_pod = max(
            (scheduler._pod_chips(p) for p in v.pods), default=0.0)
        elsewhere = max(
            (f for node, f in free.items() if node not in v.per_node),
            default=0.0)
        if elsewhere < biggest_pod and not allow_virtual:
            continue  # nowhere to re-land: would park, not defrag
        api.record_event(
            v.notebook, "Normal", "ActiveDefrag",
            f"fragmentation {stats['fragmentation']:.2f} >= "
            f"{ACTIVE_DEFRAG_FRAGMENTATION}: proactively migrating "
            f"{name_of(v.notebook)} ({v.chips:.0f} chips) off "
            f"{sorted(v.per_node)} to consolidate free capacity")
        initiate_migration(api, v.notebook, trigger="fragmentation")
        return True
    return False


# ---- preemptive gang-bind --------------------------------------------

class _Victim:
    __slots__ = ("notebook", "pods", "chips", "per_node", "priority",
                 "idle_key")

    def __init__(self, notebook, pods, priority, idle_key):
        self.notebook = notebook
        self.pods = pods
        self.priority = priority
        self.idle_key = idle_key
        self.per_node: dict[str, float] = {}
        self.chips = 0.0
        for p in pods:
            node = deep_get(p, "spec", "nodeName")
            c = scheduler._pod_chips(p)
            if node and c:
                self.per_node[node] = self.per_node.get(node, 0.0) + c
                self.chips += c


def try_preempt(api: APIServer, sts: dict, unbound: list[dict],
                sched: "scheduler.SchedulerCache", *,
                allow_virtual: bool) -> dict[tuple, str] | None:
    """A gang that couldn't bind gets one more chance: suspend strictly
    lower-priority victim slices (never pinned ones) through the normal
    suspend lifecycle, delete their pods (kube-scheduler's preemption
    semantics — the victim's controller converges on replicas=0 from
    the suspend annotation), and retry the gang-bind. Victim choice is
    simulated first so an insufficient pool suspends nobody; selection
    order is (priority asc, idleness desc, fragmentation fit). Returns
    a bind plan like ``gang_bind`` or None."""
    if not oversubscribe() or scheduler.legacy_scan():
        _preempt_skipped(
            "oversubscribe_off" if not oversubscribe() else "legacy_scan",
            sts)
        return None
    # harvest leases first (r20): serving work on borrowed notebook
    # chips is instantly reclaimable by ANY gang — no priority check,
    # no victim simulation. A resuming notebook's failed re-bind lands
    # here, which is exactly the "notebook resume outranks serving"
    # contract.
    plan = _try_harvest_reclaim(api, sts, unbound, sched,
                                allow_virtual=allow_virtual)
    if plan is not None:
        return plan
    nb_name = (sts["metadata"].get("labels") or {}).get(
        nb_api.NOTEBOOK_NAME_LABEL)
    if not nb_name:
        # TPUJob-vs-TPUJob preemption (ROADMAP item 5) lands here: the
        # gang's owner carries no Notebook priority to preempt with —
        # a visible gap now, not a silent one
        _preempt_skipped("not_notebook_owner", sts)
        return None
    ns = namespace_of(sts)
    incoming = api.try_get(nb_api.KIND, nb_name, ns)
    if incoming is None:
        _preempt_skipped("owner_missing", sts)
        return None
    incoming_pri = nb_api.priority_of(incoming)
    needed = sum(scheduler._pod_chips(p) for p in unbound)
    if not needed:
        _preempt_skipped("no_chips_needed", sts)
        return None

    victims = _candidate_victims(api, incoming, incoming_pri, needed)
    if not victims:
        _preempt_skipped("no_viable_victims", sts)
        return None

    by_node = sched.free_by_node()
    free = {node: f for node, (f, _labels) in by_node.items()}
    labels = {node: lb for node, (_f, lb) in by_node.items()}
    chosen: list[_Victim] = []
    for v in victims:
        chosen.append(v)
        extra: dict[str, float] = {}
        for c in chosen:
            for node, chips in c.per_node.items():
                extra[node] = extra.get(node, 0.0) + chips
        if _fits(unbound, free, extra, labels, allow_virtual):
            break
    else:
        # even suspending every candidate wouldn't fit
        _preempt_skipped("insufficient_victims", sts)
        return None

    for v in chosen:
        initiate_suspend(api, v.notebook, reason="preempted")
        # scale the victim's StatefulSet down ourselves before deleting
        # its pods — its kubelet reconcile must not race a recreate in
        # the window before the notebook controller re-renders
        v_sts = api.try_get("StatefulSet", name_of(v.notebook),
                            namespace_of(v.notebook))
        if v_sts is not None and deep_get(
                v_sts, "spec", "replicas", default=0):
            for _ in range(4):
                v_sts["spec"]["replicas"] = 0
                try:
                    api.update(v_sts)
                    break
                except Conflict:
                    v_sts = api.try_get(
                        "StatefulSet", name_of(v.notebook),
                        namespace_of(v.notebook))
                    if v_sts is None:
                        break
        for p in v.pods:
            key = (namespace_of(p), name_of(p))
            try:
                api.delete("Pod", key[1], key[0])
            except NotFound:
                pass
            sched.release(key)
        metrics.NOTEBOOK_PREEMPT_TOTAL.inc()
    api.record_event(
        sts, "Normal", "Preempted",
        f"suspended {len(chosen)} lower-priority slice(s) "
        f"({', '.join(name_of(v.notebook) for v in chosen)}) to admit "
        f"this {len(unbound)}-host gang")
    return sched.gang_bind(unbound, allow_virtual=allow_virtual)


def _try_harvest_reclaim(api: APIServer, sts: dict,
                         unbound: list[dict],
                         sched: "scheduler.SchedulerCache", *,
                         allow_virtual: bool
                         ) -> dict[tuple, str] | None:
    """Give harvested chips back to a waiting gang. The attached
    ChipHarvestController drains its serving replicas (in-flight
    requests migrate bit-exactly through the fleet) and releases the
    leases synchronously; the gang then retries its bind against the
    freed capacity. Returns a bind plan or None."""
    if sched.harvested_chips() <= 0:
        return None
    trigger = "preempt"
    nb_name = (sts["metadata"].get("labels") or {}).get(
        nb_api.NOTEBOOK_NAME_LABEL)
    if nb_name:
        owner = api.try_get(nb_api.KIND, nb_name, namespace_of(sts))
        if owner is not None and (nb_api.RESUME_REQUESTED_ANNOTATION
                                  in annotations_of(owner)):
            trigger = "resume"
    freed = sched.reclaim_harvested(trigger=trigger)
    if freed <= 0:
        return None
    api.record_event(
        sts, "Normal", "HarvestReclaimed",
        f"reclaimed {freed:.0f} harvested chip(s) from the serving "
        f"fleet ({trigger}) — notebook demand outranks harvested "
        "serving")
    return sched.gang_bind(unbound, allow_virtual=allow_virtual)


def _preempt_skipped(reason: str, sts: dict) -> None:
    """Account for a preemption opportunity that could not be served —
    the counter (``preempt_skipped_total{reason}``) plus a log line
    turn the silent skips (notably non-Notebook gang owners) into a
    measurable gap."""
    metrics.PREEMPT_SKIPPED_TOTAL.labels(reason=reason).inc()
    log.info("preemption skipped for %s/%s: %s",
             namespace_of(sts), name_of(sts), reason)


def _candidate_victims(api: APIServer, incoming: dict,
                       incoming_pri: int, needed: float) -> list:
    scan = getattr(api, "scan", api.list)
    out: list[_Victim] = []
    in_key = (namespace_of(incoming), name_of(incoming))
    for nb in scan(nb_api.KIND):
        if (namespace_of(nb), name_of(nb)) == in_key:
            continue
        if nb["metadata"].get("deletionTimestamp"):
            continue
        ann = annotations_of(nb)
        if (nb_api.SUSPEND_ANNOTATION in ann
                or nb_api.STOP_ANNOTATION in ann
                or nb_api.RESUME_REQUESTED_ANNOTATION in ann):
            continue
        if nb_api.is_pinned(nb):
            continue
        pri = nb_api.priority_of(nb)
        if pri >= incoming_pri:
            continue  # preemption displaces strictly lower priority only
        name, ns2 = name_of(nb), namespace_of(nb)
        pods = [p for p in scan("Pod", ns2)
                if (p["metadata"].get("labels") or {}).get(
                    nb_api.NOTEBOOK_NAME_LABEL) == name
                and deep_get(p, "spec", "nodeName")
                and deep_get(p, "status", "phase")
                not in scheduler.TERMINAL_PHASES]
        v = _Victim(nb, pods, pri,
                    ann.get(nb_api.LAST_ACTIVITY_ANNOTATION)
                    or nb["metadata"].get("creationTimestamp") or "")
        if not v.chips:
            continue
        out.append(v)
    # lowest priority, then longest idle (oldest activity stamp), then
    # the fragmentation fit: smallest sufficient slice first so a big
    # victim isn't shattered to seat a small gang
    out.sort(key=lambda v: (
        v.priority, v.idle_key,
        (v.chips < needed, abs(v.chips - needed))))
    return out


def _fits(unbound: list[dict], free: dict[str, float],
          extra: dict[str, float], labels: dict[str, dict],
          allow_virtual: bool) -> bool:
    """Dry-run of the gang first-fit against free+released capacity —
    mirrors ``SchedulerCache._try_gang`` selection without locks."""
    from kubeflow_rm_tpu.controlplane.api.meta import matches_selector
    tentative: dict[str, float] = {}
    for pod in sorted(unbound, key=name_of):
        selector = deep_get(pod, "spec", "nodeSelector", default={}) or {}
        need = scheduler._pod_chips(pod)
        chosen = None
        for node, f in free.items():
            if selector and not matches_selector(
                    labels.get(node, {}), {"matchLabels": selector}):
                continue
            if need:
                avail = f + extra.get(node, 0.0) - tentative.get(node, 0.0)
                if need > avail:
                    continue
            chosen = node
            break
        if chosen is None:
            if allow_virtual and not selector and not need:
                continue
            return False
        if need:
            tentative[chosen] = tentative.get(chosen, 0.0) + need
    return True


def _parse_ts(raw) -> datetime.datetime | None:
    if not raw:
        return None
    try:
        return datetime.datetime.fromisoformat(
            str(raw).replace("Z", "+00:00"))
    except ValueError:
        return None
