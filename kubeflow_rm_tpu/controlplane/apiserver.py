"""In-memory Kubernetes-style apiserver: the envtest of this repo.

The reference tests its controllers against a real kube-apiserver booted
by envtest (``notebook-controller/controllers/suite_test.go:50-110``);
this module provides the same contract hermetically: typed CRUD with
resourceVersion conflicts, admission chains (where the mutating
webhooks plug in), label-selector lists, watch events, finalizers +
deletionTimestamp semantics, ownerReference cascade deletion, and
ResourceQuota enforcement on pod admission. Controllers drive it
through the same verbs they would use against a cluster.

Cluster-scoped kinds are stored with namespace ``None``. Time is
injected (``clock``) so culling/idleness tests are deterministic.
"""

from __future__ import annotations

import collections
import contextlib
import copy
import datetime
import fnmatch
import json
import logging
import os
import threading
import time
from typing import Callable

from kubeflow_rm_tpu.controlplane.api.meta import (
    deep_get,
    fast_deepcopy,
    labels_of,
    matches_selector,
    name_of,
    namespace_of,
    new_uid,
    parse_quantity,
    strategic_merge,
)
from kubeflow_rm_tpu.controlplane import chaos, tracing
from kubeflow_rm_tpu.analysis.lockgraph import (
    make_condition,
    make_lock,
    make_rlock,
)

CLUSTER_SCOPED_KINDS = {
    "Namespace", "Profile", "Node", "ClusterRole", "ClusterRoleBinding",
    "PersistentVolume", "CustomResourceDefinition",
}


class APIError(Exception):
    pass


class NotFound(APIError):
    pass


class AlreadyExists(APIError):
    pass


class Conflict(APIError):
    pass


class Invalid(APIError):
    pass


class AdmissionDenied(APIError):
    pass


def _utcnow() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


# store objects are always JSON-shaped (they arrive through
# create/update, which copy), so the JSON-round-trip copy applies
_fastcopy = fast_deepcopy


log = logging.getLogger("kubeflow_rm_tpu.apiserver")

# event type delivered to a watcher whose fanout queue overflowed: the
# dropped window cannot be replayed, so the watcher must relist (the
# same contract as a kube watch 410 Gone — cache/informer.py and the
# REST facade both turn it into their existing relist paths)
TOO_OLD = "TOO_OLD"

_NULL_CTX = contextlib.nullcontext()
_EMPTY: dict = {}

# admission chains for bulk creates run on this shared bounded pool in
# sharded mode (plugins only read, and sharded reads are lock-free);
# lazily built so import stays thread-free
_admission_pool = None
_admission_pool_guard = make_lock("apiserver.admission_pool")


def _bulk_admission_pool():
    global _admission_pool
    with _admission_pool_guard:
        if _admission_pool is None:
            from concurrent.futures import ThreadPoolExecutor
            _admission_pool = ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="bulk-admit")
        return _admission_pool


def status_from_error(exc: Exception) -> dict:
    """Kube Status-shaped failure dict for one member of a bulk write
    (the REST facade serializes these verbatim into the List reply)."""
    code, reason = 500, type(exc).__name__
    if isinstance(exc, NotFound):
        code = 404
    elif isinstance(exc, AlreadyExists):
        code = 409
    elif isinstance(exc, Conflict):
        code = 409
    elif isinstance(exc, (Invalid, AdmissionDenied)):
        code = 422
    return {"kind": "Status", "apiVersion": "v1", "status": "Failure",
            "reason": reason, "message": str(exc), "code": code}


def is_status(obj: dict) -> bool:
    """True for a per-item bulk failure marker (vs a created object)."""
    return isinstance(obj, dict) and obj.get("kind") == "Status"


class _WatcherChannel:
    """Bounded per-watcher FIFO drained by a dedicated dispatch thread.

    ``publish`` never blocks and never runs the callback — writers are
    decoupled from watch delivery entirely. Ordered delivery per
    watcher is preserved (one FIFO, one drainer). On overflow the
    backlog is dropped wholesale and a single ``TOO_OLD`` sentinel is
    queued, forcing the watcher through its relist recovery path.
    The dispatch thread is started lazily and exits after a few idle
    seconds so short-lived apiservers (tests build hundreds) don't
    accumulate parked threads."""

    IDLE_EXIT_S = 5.0

    def __init__(self, fn: Callable, maxlen: int, name: str):
        self.fn = fn
        self.name = name
        self.maxlen = maxlen
        self._q: collections.deque = collections.deque()
        self._cond = make_condition(
            "apiserver.watch_channel",
            lock=make_lock("apiserver.watch_channel"))
        self._thread: threading.Thread | None = None
        self._busy = False  # a callback is in flight
        self.overflows = 0
        self.delivered = 0
        from kubeflow_rm_tpu.controlplane import metrics
        self._m_depth = metrics.WATCH_FANOUT_QUEUE_DEPTH.labels(
            watcher=name)
        self._m_overflow = metrics.WATCH_FANOUT_OVERFLOWS_TOTAL.labels(
            watcher=name)
        self._m_delivered = metrics.WATCH_FANOUT_DELIVERED_TOTAL.labels(
            watcher=name)
        self._m_lag = metrics.WATCH_FANOUT_DISPATCH_LAG.labels(
            watcher=name)

    def _chaos_item(self, item: tuple) -> list[tuple]:
        """Chaos-engine watch faults (no-op without an installed plan):
        a *drop* substitutes the channel's own ``TOO_OLD`` gap sentinel
        — the watch contract is "ordered window or detectable gap", so
        a lost event manifests as the gap and the watcher relists; a
        *dup* delivers the item twice (idempotency probe). The verdict
        is drawn before the channel lock; injected sentinels follow the
        normal overflow path."""
        verdict = chaos.watch_fault(self.name, item[0])
        if verdict is None:
            return [item]
        if verdict == "drop":
            self.overflows += 1
            self._m_overflow.inc()
            return [(TOO_OLD, {}, None, time.monotonic())]
        return [item, item]  # dup

    def publish(self, item: tuple) -> None:
        items = [item]
        if chaos.active() is not None:
            items = self._chaos_item(item)
        with self._cond:
            if len(self._q) + len(items) > self.maxlen:
                # drop the whole window: partial delivery after a gap
                # would be indistinguishable from ordered delivery
                self._q.clear()
                self.overflows += 1
                self._m_overflow.inc()
                self._q.append((TOO_OLD, {}, None, time.monotonic()))
            else:
                self._q.extend(items)
            self._m_depth.set(len(self._q))
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, daemon=True,
                    name=f"watch-fanout-{self.name}")
                self._thread.start()
            self._cond.notify()

    def publish_many(self, items: list[tuple]) -> None:
        """Enqueue a whole batch under ONE lock acquisition and one
        notify — the bulk-create path's coalesced emit (per-event
        ``publish`` paid a lock round-trip per object per watcher).
        Batch order is preserved; overflow collapses the window to a
        single ``TOO_OLD`` exactly like ``publish``."""
        if not items:
            return
        if chaos.active() is not None:
            items = [out for it in items for out in self._chaos_item(it)]
        with self._cond:
            if len(self._q) + len(items) > self.maxlen:
                self._q.clear()
                self.overflows += 1
                self._m_overflow.inc()
                self._q.append((TOO_OLD, {}, None, time.monotonic()))
            else:
                self._q.extend(items)
            self._m_depth.set(len(self._q))
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, daemon=True,
                    name=f"watch-fanout-{self.name}")
                self._thread.start()
            self._cond.notify()

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._q:
                    if not self._cond.wait(timeout=self.IDLE_EXIT_S) \
                            and not self._q:
                        self._thread = None
                        return
                item = self._q.popleft()
                self._m_depth.set(len(self._q))
                self._busy = True
            etype, obj, old, t_enq = item
            try:
                self.fn(etype, obj, old)
            except Exception:  # noqa: BLE001 - a watcher must not
                log.exception("watcher %s raised", self.name)  # kill fanout
            finally:
                self.delivered += 1
                self._m_delivered.inc()
                self._m_lag.set(time.monotonic() - t_enq)
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()

    def idle(self) -> bool:
        with self._cond:
            return not self._q and not self._busy

    def drain(self, deadline: float) -> bool:
        """Block until every event queued so far has been delivered
        (queue empty AND no callback in flight)."""
        with self._cond:
            while self._q or self._busy:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True


class APIServer:
    def __init__(self, clock: Callable[[], datetime.datetime] = _utcnow,
                 *, global_lock: bool = False,
                 watch_queue_maxlen: int = 4096,
                 wal_dir: str | None = None, wal_fsync: bool = True,
                 wal_snapshot_every: int = 4096,
                 shard: str | None = None):
        self.clock = clock
        # shard identity ("" outside sharded deployments) — labels this
        # process's per-shard metric series and the /debug surfaces
        self.shard = shard or ""
        # ---- locking model ------------------------------------------
        # Sharded (default): one RLock PER KIND serializes writes to
        # that kind (the Conflict read-compare-write and rv ordering
        # within a kind stay atomic), a separate atomic counter hands
        # out resourceVersions, and reads come from copy-on-write
        # per-kind snapshots WITHOUT any lock — so a Pod list never
        # waits on an Event write and vice versa. Locks are reentrant
        # because verbs nest (patch→update, delete→_finalize_delete→
        # garbage-collect→delete); cross-kind nesting follows the
        # ownerReference DAG (owner's kind lock held while dependents'
        # are taken), which is acyclic for every object graph the
        # platform builds.
        #
        # ``global_lock=True`` restores the pre-r08 model — ONE
        # reentrant lock around every verb, watchers fired
        # synchronously inside the write path — as the A/B baseline
        # arm (`spawn_conformance --global-lock`).
        self._global = global_lock
        self._lock = make_rlock("apiserver.global")  # global-arm verb lock
        self._locks: dict[str, threading.RLock] = {}
        self._locks_guard = make_lock("apiserver.kind_locks_map")
        self._rv_lock = make_lock("apiserver.rv")
        self._seq_lock = make_lock("apiserver.event_seq")
        self._watch_queue_maxlen = watch_queue_maxlen
        # per-kind working dicts (kind -> {full key: obj}) — mutated
        # only under that kind's lock — plus the published COW
        # snapshots reads iterate lock-free (sharded mode only)
        self._by_kind: dict[str, dict[tuple, dict]] = {}
        self._snap: dict[str, dict[tuple, dict]] = {}
        self._rv = 0
        # admission plugins: fn(op, obj, old) -> obj | None (op: CREATE/UPDATE)
        self._admission: list[tuple[str, Callable]] = []
        # validators per kind: fn(obj) raising on bad spec (CRD schema stand-in)
        self._validators: dict[str, Callable[[dict], None]] = {}
        self._watchers: list[Callable[[str, dict, dict | None], None]] = []
        self._channels: list[_WatcherChannel] = []
        self._event_seq = 0
        self.quota_enforcement = True
        # container stdout per pod (the kubelet's log store; the fake
        # kubelet appends boot lines, the `pods/<name>/log` subresource
        # reads them — ref jupyter backend get_pod_logs)
        self._pod_logs: dict[tuple[str, str], list[str]] = {}
        self._pod_log_lock = make_lock("apiserver.pod_logs")
        # bounded audit trail of writes, tagged with the writer identity
        # set via set_writer (the REST facade stamps it from the
        # X-Writer-Identity header). The failover conformance asserts
        # "no overlapping reconciles" over this: once a standby's first
        # write lands, the dead leader must never write again.
        self.write_log: collections.deque = collections.deque(maxlen=8192)
        self._write_seq = 0
        self._write_lock = make_lock("apiserver.write_log")
        self._writer = threading.local()
        # ---- durability (persistence/: WAL + compacting snapshots) --
        # wal_dir=None (the default, and the --no-wal arm) keeps the
        # store purely in-memory with ZERO extra work on the write
        # path; with a wal_dir every acked write is group-committed to
        # a CRC-framed log before the verb returns, and boot replays
        # snapshot + WAL tail so a SIGKILLed shard recovers its full
        # store and resumes its rv sequence (no duplicate watch events
        # — watchers attach after replay, which emits nothing).
        self._persistence = None
        self._wal_tls = threading.local()  # _write_verb depth + ticket
        self._wal_dir = wal_dir
        # range tombstones: partition keys this shard has handed OFF
        # (elastic FLIP done, donor cleanup maybe not). A respawn
        # replaying the WAL must not resurrect the moved range — the
        # recipient owns it now — so recovery drops tombstoned keys
        # between populate and publish. Durable next to the WAL.
        self._tombstones: set[str] = set()
        self.tombstone_purged = 0
        if wal_dir:
            from kubeflow_rm_tpu.controlplane.persistence import (
                Persistence,
            )
            self._persistence = Persistence(
                wal_dir, fsync=wal_fsync,
                snapshot_every=wal_snapshot_every, shard=self.shard)
            self._tombstones = self._load_tombstones()
            rec = self._persistence.recover(CLUSTER_SCOPED_KINDS)
            for key, obj in rec.objects.items():
                self._by_kind.setdefault(key[0], {})[key] = obj
            if self._tombstones:
                self._purge_tombstoned()
            for kind in self._by_kind:
                self._publish(kind)
            self._rv = rec.rv
            self._write_seq = rec.seq
            # the event-name sequence must also resume, or the first
            # post-restart record_event collides with a replayed Event
            for (_, _, name) in self._by_kind.get("Event", _EMPTY):
                try:
                    self._event_seq = max(
                        self._event_seq,
                        int(str(name).rsplit(".", 1)[1], 16))
                except (IndexError, ValueError):
                    pass

    # ---- wiring ------------------------------------------------------
    def register_admission(self, kind_pattern: str, fn: Callable) -> None:
        """Register a mutating/validating admission plugin for kinds
        matching ``kind_pattern`` (fnmatch, e.g. "Pod" or "*")."""
        self._admission.append((kind_pattern, fn))

    def register_validator(self, kind: str, fn: Callable[[dict], None]) -> None:
        self._validators[kind] = fn

    def add_watcher(self, fn: Callable[[str, dict, dict | None], None],
                    name: str | None = None) -> None:
        """Subscribe to store events. Sharded mode delivers them
        asynchronously (ordered per watcher) off a bounded FIFO; a
        watcher that falls behind gets a ``TOO_OLD`` event and must
        relist. ``name`` labels the fanout gauges."""
        self._watchers.append(fn)
        if not self._global:
            self._channels.append(_WatcherChannel(
                fn, self._watch_queue_maxlen,
                name or f"watcher-{len(self._channels)}"))

    def drain_watchers(self, timeout: float = 30.0) -> bool:
        """Barrier: block until every event emitted so far has been
        delivered to every watcher. Deterministic tests and
        ``Manager.run_until_idle`` call this so async fanout never
        races a readiness assertion. No-op (True) in global-lock mode,
        where delivery is synchronous."""
        deadline = time.monotonic() + timeout
        # one delivered event can enqueue follow-on events for another
        # channel only through a write, and watchers never write — but
        # a TOO_OLD relist repopulates stores, so settle until every
        # channel is simultaneously idle
        while True:
            ok = all(ch.drain(deadline) for ch in list(self._channels))
            if not ok:
                return False
            if all(ch.idle() for ch in self._channels):
                return True
            if time.monotonic() > deadline:
                return False

    # ---- helpers -----------------------------------------------------
    def _key(self, kind: str, name: str, namespace: str | None):
        if kind in CLUSTER_SCOPED_KINDS:
            return (kind, None, name)
        return (kind, namespace, name)

    def _kind_lock(self, kind: str) -> threading.RLock:
        """The write lock for ``kind`` (the one global lock in the
        legacy arm)."""
        if self._global:
            return self._lock
        lk = self._locks.get(kind)
        if lk is None:
            with self._locks_guard:
                lk = self._locks.setdefault(
                    kind, make_rlock("apiserver.kind"))
        return lk

    def _read_lock(self):
        """Reads are lock-free against COW snapshots in sharded mode;
        the legacy arm serializes them on the verb lock as before."""
        return self._lock if self._global else _NULL_CTX

    def _view(self, kind: str) -> dict:
        """The mapping a read of ``kind`` iterates: the published COW
        snapshot (sharded — safe without any lock, never mutated after
        publication) or the live working dict (global arm — callers
        hold the verb lock)."""
        return (self._by_kind if self._global else self._snap).get(
            kind, _EMPTY)

    def _publish(self, kind: str) -> None:
        """Publish a fresh immutable snapshot of ``kind`` (caller holds
        the kind lock). Shallow copy: stored objects are replaced, not
        mutated, on update — so an old snapshot stays internally
        consistent for readers mid-iteration."""
        if not self._global:
            self._snap[kind] = dict(self._by_kind.get(kind, _EMPTY))

    def _next_rv(self) -> str:
        with self._rv_lock:
            self._rv += 1
            return str(self._rv)

    def _next_rvs(self, n: int) -> list[str]:
        """Reserve a contiguous block of ``n`` resourceVersions in one
        counter acquisition (bulk create). Failed batch members leave
        gaps — rv is an ordering token, not a dense sequence."""
        with self._rv_lock:
            start = self._rv + 1
            self._rv += n
            return [str(v) for v in range(start, start + n)]

    def set_writer(self, identity: str | None) -> None:
        """Tag subsequent writes from THIS thread with ``identity`` in
        the write log (thread-local: the REST facade serves each
        request on its own thread)."""
        self._writer.identity = identity

    def _log_write(self, verb: str, obj: dict) -> None:
        rv = int(obj["metadata"].get("resourceVersion") or 0)
        with self._write_lock:
            self._write_seq += 1
            seq = self._write_seq
            self.write_log.append({
                "seq": seq,
                "rv": rv,
                "verb": verb,
                "kind": obj["kind"],
                "namespace": namespace_of(obj),
                "name": name_of(obj),
                "writer": getattr(self._writer, "identity", None),
                "t": time.time(),
            })
        p = self._persistence
        if p is not None:
            # durable before ack, but never fsync under the kind lock:
            # the record is buffered here (cheap — wal.cv only) and its
            # ticket accumulated on the thread; the enclosing
            # _write_verb flushes AFTER the kind lock is released, so
            # one kind's fsync wait never blocks that kind's (or any
            # other kind's) writers, and concurrent verbs share one
            # group commit. create_many's whole batch rides one flush.
            ticket = p.log(seq=seq, rv=rv, verb=verb, obj=obj,
                           wait=False)
            tls = self._wal_tls
            if getattr(tls, "depth", 0) > 0:
                tls.ticket = max(getattr(tls, "ticket", 0), ticket)
            else:
                # defensive: a write outside any _write_verb still
                # acks only after durability
                p.flush(upto=ticket)
            if p.snapshot_due() and p.begin_snapshot():
                threading.Thread(target=self._run_snapshot, daemon=True,
                                 name="wal-snapshot").start()

    @contextlib.contextmanager
    def _write_verb(self, kind: str):
        """The kind lock plus deferred WAL durability: records logged
        inside the block are fsynced once, AFTER the lock is released,
        and the verb returns only when they are durable. Reentrant —
        nested verbs (patch → update, ensure_namespace → create,
        cascading deletes across kinds) accumulate tickets and the
        outermost exit does the single flush, outside every lock."""
        tls = self._wal_tls
        depth = getattr(tls, "depth", 0)
        tls.depth = depth + 1
        try:
            with self._kind_lock(kind):
                yield
        finally:
            tls.depth = depth
            if depth == 0:
                ticket = getattr(tls, "ticket", 0)
                tls.ticket = 0
                if ticket and self._persistence is not None:
                    self._persistence.flush(upto=ticket)

    def _run_snapshot(self) -> None:
        """Cut a consistent snapshot and compact the WAL. The cut +
        segment rotation happen under the write lock (and, in the
        global arm, the verb lock — taken FIRST to respect the
        verb-lock → write-lock order every writer uses); JSON
        serialization and the fsync of the snapshot file happen off
        the write path."""
        p = self._persistence
        outer = self._lock if self._global else _NULL_CTX
        with outer:
            with self._write_lock:
                seq = self._write_seq
                with self._rv_lock:
                    rv = self._rv
                view = self._by_kind if self._global else self._snap
                objects = [o for m in list(view.values())
                           for o in list(m.values())]
                p.wal.rotate()
        p.complete_snapshot(seq=seq, rv=rv, objects=objects)

    def snapshot_now(self) -> bool:
        """Force a compacting snapshot (tests, pre-shutdown). Returns
        False without a WAL or when one is already in flight."""
        p = self._persistence
        if p is None or not p.begin_snapshot():
            return False
        self._run_snapshot()
        return True

    # ---- range tombstones (elastic handoff crash fencing) ------------

    def _tombstone_path(self) -> str | None:
        if not self._wal_dir:
            return None
        return os.path.join(self._wal_dir, "range_tombstones.json")

    def _load_tombstones(self) -> set[str]:
        path = self._tombstone_path()
        if path is None or not os.path.exists(path):
            return set()
        try:
            with open(path, encoding="utf-8") as f:
                return {str(k) for k in json.load(f)}
        except (OSError, ValueError):
            # an unreadable stone file fails OPEN: worst case the shard
            # serves moved objects until cleanup, which is the exact
            # pre-tombstone behavior, never data loss
            return set()

    def _save_tombstones(self) -> None:
        path = self._tombstone_path()
        if path is None:
            return        # no WAL: stones are in-memory only
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(sorted(self._tombstones), f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _purge_tombstoned(self) -> None:
        """Drop recovered objects whose partition key is tombstoned.
        Runs between WAL-replay populate and snapshot publish, so the
        moved range is never observable post-respawn. Broadcast kinds
        replicate everywhere (no single owner to fence) and Leases are
        shard-local by design; both are exempt."""
        try:
            from kubeflow_rm_tpu.controlplane.deploy.kubeclient import (
                BROADCAST_KINDS,
            )
        except ImportError:
            BROADCAST_KINDS = frozenset()
        for kind, objs in self._by_kind.items():
            if kind in BROADCAST_KINDS or kind == "Lease":
                continue
            cluster = kind in CLUSTER_SCOPED_KINDS
            doomed = [k for k in objs
                      if (k[2] if cluster else k[1]) in self._tombstones]
            for k in doomed:
                del objs[k]
            self.tombstone_purged += len(doomed)

    def set_range_tombstone(self, keys) -> list[str]:
        """Durably mark partition keys as handed off: a respawn of
        this shard will refuse to resurrect them from its WAL. The
        elastic coordinator sets this on the donor right after the
        router FLIP (the moment ownership transfers) and clears it
        after donor cleanup deletes the moved objects for real."""
        self._tombstones.update(str(k) for k in keys)
        self._save_tombstones()
        return sorted(self._tombstones)

    def clear_range_tombstone(self, keys=None) -> list[str]:
        """Lift stones — the listed partition keys, or all of them
        when ``keys`` is None. A recipient ADOPTING a range must clear
        any stale stone it holds for it (a range that left this shard
        once and is now coming back), or its next respawn would purge
        live data."""
        if keys is None:
            self._tombstones.clear()
        else:
            for k in keys:
                self._tombstones.discard(str(k))
        self._save_tombstones()
        return sorted(self._tombstones)

    def range_tombstones(self) -> list[str]:
        return sorted(self._tombstones)

    def advance_rv_floor(self, rv: int) -> int:
        """Raise the resourceVersion counter to at least ``rv`` (no-op
        when already past). The elastic handoff calls this on a
        recipient shard before copying a donor's range: every re-created
        object then gets an rv ABOVE anything the donor ever issued for
        it, so the router cache's per-object rv monotonicity keeps
        accepting events for moved objects after the flip."""
        with self._rv_lock:
            self._rv = max(self._rv, int(rv))
            return self._rv

    def close_persistence(self) -> None:
        if self._persistence is not None:
            self._persistence.close()

    def _emit(self, event: str, obj: dict, old: dict | None = None) -> None:
        # ONE defensive copy shared by all watchers — the watcher
        # contract is read-only; per-watcher deepcopies measurably
        # dominated the 20-way spawn event storm. Sharded mode only
        # ENQUEUES here (still under the kind lock, so per-kind order
        # per watcher matches rv order) and a dedicated thread per
        # watcher delivers — a slow or blocked watcher can no longer
        # hold the write path. The legacy arm fires synchronously
        # inside the verb, as before r08.
        obj_c = _fastcopy(obj)
        old_c = _fastcopy(old) if old else None
        if self._global:
            for w in list(self._watchers):
                w(event, obj_c, old_c)
            return
        t = time.monotonic()
        for ch in self._channels:
            ch.publish((event, obj_c, old_c, t))

    def _run_admission(self, op: str, obj: dict, old: dict | None) -> dict:
        matched = [fn for pattern, fn in self._admission
                   if fnmatch.fnmatch(obj["kind"], pattern)]
        if not matched:
            return obj
        # one child span covers the whole mutating chain — webhook
        # latency (PodDefault merges, TPU injection) shows up as its
        # own hop in the trace instead of hiding inside the verb
        with tracing.start_span_if_active(f"admit {obj['kind']}",
                                          attrs={"op": op,
                                                 "hooks": len(matched)}):
            for fn in matched:
                result = fn(op, obj, old)
                if result is not None:
                    obj = result
        return obj

    def ensure_namespace(self, namespace: str) -> dict:
        with self._write_verb("Namespace"):
            try:
                return self.get("Namespace", namespace)
            except NotFound:
                return self.create({"apiVersion": "v1", "kind": "Namespace",
                                    "metadata": {"name": namespace}})

    # ---- verbs -------------------------------------------------------
    def create(self, obj: dict) -> dict:
        obj = _fastcopy(obj)
        # persist the causal chain: the creating request's trace
        # context rides the object's annotations so watch consumers
        # (workqueues, reconciles) resume the SAME trace later,
        # possibly in another process
        tracing.stamp(obj)
        kind = obj["kind"]
        name, ns = name_of(obj), namespace_of(obj)
        with self._write_verb(kind):
            if kind in CLUSTER_SCOPED_KINDS:
                ns = None
                obj["metadata"].pop("namespace", None)
            elif ns is None:
                raise Invalid(
                    f"{kind}/{name}: namespaced kind requires namespace")
            else:
                if ("Namespace", None, ns) not in self._view("Namespace"):
                    raise NotFound(f"namespace {ns!r} not found")
            key = self._key(kind, name, ns)
            if key in self._by_kind.get(kind, _EMPTY):
                raise AlreadyExists(f"{kind} {ns}/{name} already exists")
            if kind in self._validators:
                try:
                    self._validators[kind](obj)
                except Exception as e:
                    raise Invalid(f"{kind} {ns}/{name}: {e}") from e
            obj = self._run_admission("CREATE", obj, None)
            if self.quota_enforcement and kind == "Pod":
                self._enforce_quota(obj)
            meta = obj["metadata"]
            meta["uid"] = new_uid()
            meta["resourceVersion"] = self._next_rv()
            meta["creationTimestamp"] = self.clock().isoformat()
            self._by_kind.setdefault(kind, {})[key] = obj
            self._publish(kind)
            self._log_write("CREATE", obj)
            self._emit("ADDED", obj)
            return _fastcopy(obj)

    def create_many(self, objs: list[dict]) -> list[dict]:
        """Create a same-kind batch with ONE kind-lock acquisition, one
        contiguous resourceVersion range, and one coalesced watch emit
        per channel. Per-object failures (validation, admission, quota,
        duplicate name) come back as Status-shaped dicts at that
        object's index — one bad pod rejects only itself, the rest of
        the slice lands. The admission chain runs per object IN
        PARALLEL in sharded mode (plugins only read, and sharded reads
        are lock-free; the global arm keeps it on this thread, whose
        reentrant verb lock the plugins' reads reenter). Quota and
        duplicate checks run sequentially in input order so batch-mates
        count against each other exactly as serial creates would.
        Watchers observe exactly one ADDED per created object, in rv
        order."""
        from kubeflow_rm_tpu.controlplane import metrics
        if not objs:
            return []
        objs = [_fastcopy(o) for o in objs]
        kind = objs[0]["kind"]
        for o in objs:
            if o["kind"] != kind:
                raise Invalid(
                    "create_many: all objects must share one kind "
                    f"(got {o['kind']} in a {kind} batch)")
        metrics.BULK_CREATE_BATCHES_TOTAL.labels(kind=kind).inc()
        m_obj = metrics.BULK_CREATE_OBJECTS_TOTAL
        results: list = [None] * len(objs)
        admitted: list = [None] * len(objs)

        # bulk creates stamp on the CALLER's thread: _admit may run on
        # the shared admission pool where the thread-local trace
        # context of the submitting request is absent
        for o in objs:
            tracing.stamp(o)

        def _admit(i: int) -> None:
            o = objs[i]
            name, ns = name_of(o), namespace_of(o)
            if kind in CLUSTER_SCOPED_KINDS:
                o["metadata"].pop("namespace", None)
            elif ns is None:
                raise Invalid(
                    f"{kind}/{name}: namespaced kind requires namespace")
            elif ("Namespace", None, ns) not in self._view("Namespace"):
                raise NotFound(f"namespace {ns!r} not found")
            if kind in self._validators:
                try:
                    self._validators[kind](o)
                except Exception as e:
                    raise Invalid(f"{kind} {ns}/{name}: {e}") from e
            if tracing.enabled():
                # pool threads lack the submitter's thread-local span;
                # re-attach from the stamp so admission spans join the
                # originating trace instead of orphaning
                with tracing.attach(tracing.context_of(o)):
                    admitted[i] = self._run_admission("CREATE", o, None)
            else:
                admitted[i] = self._run_admission("CREATE", o, None)

        with self._write_verb(kind):
            if self._global or len(objs) == 1:
                for i in range(len(objs)):
                    try:
                        _admit(i)
                    except APIError as e:
                        results[i] = status_from_error(e)
            else:
                futs = [_bulk_admission_pool().submit(_admit, i)
                        for i in range(len(objs))]
                for i, fut in enumerate(futs):
                    try:
                        # deliberate wait under the kind lock: holding
                        # it across parallel admission is the batch's
                        # point (one atomic slice); plugins only read
                        fut.result()  # kfrm: disable=KFRM002
                    except APIError as e:
                        results[i] = status_from_error(e)
            pending = [i for i in range(len(objs)) if results[i] is None]
            rvs = self._next_rvs(len(pending))
            created: list[dict] = []
            for j, i in enumerate(pending):
                o = admitted[i]
                name = name_of(o)
                ns = None if kind in CLUSTER_SCOPED_KINDS \
                    else namespace_of(o)
                key = self._key(kind, name, ns)
                try:
                    if key in self._by_kind.get(kind, _EMPTY):
                        raise AlreadyExists(
                            f"{kind} {ns}/{name} already exists")
                    if self.quota_enforcement and kind == "Pod":
                        self._enforce_quota(o)
                except APIError as e:
                    results[i] = status_from_error(e)
                    m_obj.labels(kind=kind, result="rejected").inc()
                    continue
                meta = o["metadata"]
                meta["uid"] = new_uid()
                meta["resourceVersion"] = rvs[j]
                meta["creationTimestamp"] = self.clock().isoformat()
                self._by_kind.setdefault(kind, {})[key] = o
                # publish per insert (cheap shallow copy) so the
                # quota scan for the NEXT batch-mate sees this one;
                # the watch emit below stays one coalesced batch
                self._publish(kind)
                self._log_write("CREATE", o)
                results[i] = _fastcopy(o)
                created.append(o)
                m_obj.labels(kind=kind, result="created").inc()
            for i in range(len(objs)):
                if results[i] is not None and is_status(results[i]) \
                        and admitted[i] is None:
                    m_obj.labels(kind=kind, result="rejected").inc()
            if created:
                t = time.monotonic()
                batch = [("ADDED", _fastcopy(o), None, t) for o in created]
                if self._global:
                    for w in list(self._watchers):
                        for etype, obj_c, old_c, _t in batch:
                            w(etype, obj_c, old_c)
                else:
                    for ch in self._channels:
                        ch.publish_many(batch)
        return results

    def get(self, kind: str, name: str, namespace: str | None = None) -> dict:
        with self._read_lock():
            obj = self._view(kind).get(self._key(kind, name, namespace))
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            return _fastcopy(obj)

    def try_get(self, kind: str, name: str,
                namespace: str | None = None) -> dict | None:
        try:
            return self.get(kind, name, namespace)
        except NotFound:
            return None

    def list(self, kind: str, namespace: str | None = None,
             label_selector: dict | None = None) -> list[dict]:
        out = []
        with self._read_lock():
            for (_, ns, _), obj in self._view(kind).items():
                if namespace is not None and ns != namespace:
                    continue
                if label_selector and not matches_selector(
                        labels_of(obj), label_selector):
                    continue
                out.append(_fastcopy(obj))
        out.sort(key=lambda o: (namespace_of(o) or "", name_of(o)))
        return out

    def scan(self, kind: str, namespace: str | None = None) -> list[dict]:
        """READ-ONLY ``list``: returns live store references WITHOUT
        copying. For in-process consumers on hot paths (the fake
        kubelet's scheduler sums chip usage over every Pod per
        reconcile — copy-per-object turned that O(pods) read into the
        top CPU entry of the 20-way spawn profile). Callers MUST NOT
        mutate the returned objects; mutate a ``get()`` copy and write
        it back through ``update``. Remote adapters don't have this
        method — use ``getattr(api, "scan", api.list)``."""
        with self._read_lock():
            return [o for (_, ns, _), o in self._view(kind).items()
                    if namespace is None or ns == namespace]

    def update(self, obj: dict) -> dict:
        obj = _fastcopy(obj)
        kind, name, ns = obj["kind"], name_of(obj), namespace_of(obj)
        if kind in CLUSTER_SCOPED_KINDS:
            ns = None
        key = self._key(kind, name, ns)
        with self._write_verb(kind):
            working = self._by_kind.get(kind, _EMPTY)
            if key not in working:
                raise NotFound(f"{kind} {ns}/{name} not found")
            old = working[key]
            rv = obj["metadata"].get("resourceVersion")
            if rv is not None and rv != old["metadata"]["resourceVersion"]:
                raise Conflict(
                    f"{kind} {ns}/{name}: resourceVersion {rv} != "
                    f"{old['metadata']['resourceVersion']}"
                )
            if kind in self._validators:
                try:
                    self._validators[kind](obj)
                except Exception as e:
                    raise Invalid(f"{kind} {ns}/{name}: {e}") from e
            obj = self._run_admission("UPDATE", obj, _fastcopy(old))
            # immutable fields
            obj["metadata"]["uid"] = old["metadata"]["uid"]
            obj["metadata"]["creationTimestamp"] = \
                old["metadata"]["creationTimestamp"]
            if old["metadata"].get("deletionTimestamp"):
                obj["metadata"]["deletionTimestamp"] = \
                    old["metadata"]["deletionTimestamp"]
            obj["metadata"]["resourceVersion"] = self._next_rv()
            working[key] = obj
            self._publish(kind)
            self._log_write("UPDATE", obj)
            # a deleting object whose finalizers have all been removed
            # goes away
            if obj["metadata"].get("deletionTimestamp") and \
                    not obj["metadata"].get("finalizers"):
                return self._finalize_delete(key)
            self._emit("MODIFIED", obj, old)
            return _fastcopy(obj)

    def patch(self, kind: str, name: str, patch: dict,
              namespace: str | None = None) -> dict:
        with self._write_verb(kind):
            current = self.get(kind, name, namespace)
            merged = strategic_merge(current, patch)
            merged["metadata"]["resourceVersion"] = \
                current["metadata"]["resourceVersion"]
            return self.update(merged)

    def update_status(self, obj: dict) -> dict:
        """Status-subresource write: only ``status`` is applied."""
        with self._write_verb(obj["kind"]):
            current = self.get(obj["kind"], name_of(obj),
                               namespace_of(obj))
            current["status"] = _fastcopy(obj.get("status", {}))
            return self.update(current)

    def delete(self, kind: str, name: str, namespace: str | None = None) -> None:
        key = self._key(kind, name, namespace)
        with self._write_verb(kind):
            working = self._by_kind.get(kind, _EMPTY)
            if key not in working:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            obj = working[key]
            if obj["metadata"].get("finalizers"):
                if not obj["metadata"].get("deletionTimestamp"):
                    # replace, don't mutate in place: published
                    # snapshots share the stored reference and lock-
                    # free readers must never see a half-written object
                    obj = _fastcopy(obj)
                    obj["metadata"]["deletionTimestamp"] = \
                        self.clock().isoformat()
                    obj["metadata"]["resourceVersion"] = self._next_rv()
                    working[key] = obj
                    self._publish(kind)
                    self._log_write("UPDATE", obj)
                    self._emit("MODIFIED", obj)
                return
            self._finalize_delete(key)

    def append_pod_log(self, namespace: str, pod_name: str,
                       line: str) -> None:
        with self._pod_log_lock:
            self._pod_logs.setdefault(
                (namespace, pod_name), []).append(line)

    def pod_logs(self, namespace: str, pod_name: str,
                 tail_lines: int | None = None) -> str:
        """Stored container stdout for a pod (kube ``pods/.../log``).
        Raises NotFound for a pod that does not exist."""
        self.get("Pod", pod_name, namespace)
        with self._pod_log_lock:
            lines = list(self._pod_logs.get((namespace, pod_name), ()))
        if tail_lines is not None:
            if tail_lines < 0:
                raise Invalid(f"tailLines must be >= 0, got {tail_lines}")
            lines = lines[-tail_lines:] if tail_lines else []
        return "".join(f"{line}\n" for line in lines)

    def _finalize_delete(self, key) -> dict:
        """Caller holds ``key``'s kind lock."""
        kind = key[0]
        obj = self._by_kind.get(kind, _EMPTY).pop(key)
        self._publish(kind)
        self._log_write("DELETE", obj)
        if obj["kind"] == "Pod":
            with self._pod_log_lock:
                self._pod_logs.pop(
                    (namespace_of(obj) or "default", name_of(obj)), None)
        self._emit("DELETED", obj)
        self._garbage_collect(obj)
        if obj["kind"] == "Namespace":
            # namespace deletion drains everything inside it
            ns = name_of(obj)
            doomed = []
            with self._read_lock():
                for k, snapmap in list(
                        (self._by_kind if self._global
                         else self._snap).items()):
                    doomed.extend(kk for kk in snapmap if kk[1] == ns)
            for (kkind, kns, kname) in doomed:
                try:
                    self.delete(kkind, kname, kns)
                except NotFound:
                    pass
        return _fastcopy(obj)

    def _garbage_collect(self, owner: dict) -> None:
        """Cascade-delete dependents referencing the deleted owner's
        uid. Lock acquisition follows the ownerReference DAG (the
        owner's kind lock is held while each dependent's is taken) —
        acyclic for every graph the platform builds, and the CI
        contention-stress step runs with a faulthandler hang dump so a
        future cycle fails fast instead of deadlocking silently."""
        owner_uid = owner["metadata"].get("uid")
        if not owner_uid:
            return
        dependents = []
        with self._read_lock():
            for kind, snapmap in list(
                    (self._by_kind if self._global
                     else self._snap).items()):
                for k, obj in snapmap.items():
                    if any(r.get("uid") == owner_uid for r in
                           obj["metadata"].get("ownerReferences", [])):
                        dependents.append(k)
        for (kind, ns, name) in dependents:
            try:
                self.delete(kind, name, ns)
            except NotFound:
                pass

    # ---- events ------------------------------------------------------
    def record_event(self, involved: dict, etype: str, reason: str,
                     message: str) -> dict:
        """Create a v1 Event for ``involved`` (controller event recorder)."""
        with self._seq_lock:
            self._event_seq += 1
            seq = self._event_seq
        ns = namespace_of(involved) or "default"
        ev = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {
                "name": f"{name_of(involved)}.{seq:08x}",
                "namespace": ns,
            },
            "type": etype,
            "reason": reason,
            "message": message,
            "involvedObject": {
                "kind": involved["kind"],
                "name": name_of(involved),
                "namespace": ns,
                "uid": involved["metadata"].get("uid"),
            },
            "firstTimestamp": self.clock().isoformat(),
            "lastTimestamp": self.clock().isoformat(),
            "count": 1,
        }
        return self.create(ev)

    def events_for(self, involved: dict) -> list[dict]:
        # scan() + copy-on-match: list() deep-copied EVERY Event in the
        # namespace per call, and the notebook controller re-emits pod
        # events each reconcile — under the spawn storm that went
        # O(notebooks × events)
        ns = namespace_of(involved)
        name, kind = name_of(involved), involved["kind"]
        out = [
            _fastcopy(e) for e in self.scan("Event", ns)
            if deep_get(e, "involvedObject", "name") == name
            and deep_get(e, "involvedObject", "kind") == kind
        ]
        out.sort(key=lambda e: (namespace_of(e) or "", name_of(e)))
        return out

    # ---- SubjectAccessReview (kube-apiserver authorization) ----------
    READ_VERBS = frozenset({"get", "list", "watch"})

    def access_review(self, user: str | None, verb: str, resource: str,
                      namespace: str | None = None) -> bool:
        """Evaluate RBAC the way a SubjectAccessReview does: the web
        apps' authz decorator submits one per request (reference:
        ``crud_backend/authz.py:46-80`` builds a V1SubjectAccessReview;
        here the apiserver evaluates the RoleBindings the profile
        controller / KFAM wrote instead of delegating to kube).

        Semantics covered: cluster-admin ClusterRoleBindings grant
        everything; other ClusterRoleBindings grant their role's rules
        cluster-wide; namespace RoleBindings grant their role's rules
        in that namespace. A role's rules come from a stored
        ClusterRole object when one exists (``rules: [{resources,
        verbs}]``, ``*`` wildcards honored — real per-resource RBAC);
        absent a stored object, the kubeflow-{admin,edit,view} names
        fall back to their aggregated-deployment tiers (admin/edit =
        all verbs, view = read verbs), matching the reference's
        default roles.
        """
        if user is None:
            return False
        # scan(): read-only store references (we hold the verb lock) —
        # SARs arrive per web-app request, and copy-per-binding made
        # authorization a measurable slice of spawn-storm CPU
        for crb in self.scan("ClusterRoleBinding"):
            if not self._binding_has_subject(crb, user, None):
                continue
            role = deep_get(crb, "roleRef", "name") or ""
            if role == "cluster-admin":
                return True
            if self._role_allows(role, verb, resource):
                return True
        if namespace is None:
            return False
        for rb in self.scan("RoleBinding", namespace):
            if not self._binding_has_subject(rb, user, namespace):
                continue
            role = deep_get(rb, "roleRef", "name") or ""
            if self._role_allows(role, verb, resource):
                return True
        return False

    def _role_allows(self, role_name: str, verb: str,
                     resource: str) -> bool:
        """Evaluate one (Cluster)Role against a verb+resource pair.

        Stored ClusterRole rules win (the finer-role case VERDICT r2
        weak #2 calls out); the name-based tiers are the fallback for
        the aggregated-role deployment where role objects aren't
        materialized in the store.
        """
        role = self.try_get("ClusterRole", role_name)
        if role is not None and role.get("rules") is not None:
            for rule in role["rules"]:
                resources = rule.get("resources") or []
                verbs = rule.get("verbs") or []
                if (("*" in resources or resource in resources)
                        and ("*" in verbs or verb in verbs)):
                    return True
            return False
        if role_name in ("kubeflow-admin", "kubeflow-edit", "admin",
                         "edit"):
            return True
        if role_name in ("kubeflow-view", "view"):
            return verb in self.READ_VERBS
        return False

    @staticmethod
    def _binding_has_subject(binding: dict, user: str,
                             binding_ns: str | None) -> bool:
        """User subjects match the identity-header name; ServiceAccount
        subjects ONLY match the ``system:serviceaccount:<ns>:<name>``
        rendering (as a real SubjectAccessReview would) — a request
        whose userid header is literally "default-editor" must NOT
        inherit that SA's grants."""
        for s in binding.get("subjects") or []:
            kind, name = s.get("kind"), s.get("name")
            if kind == "User" and name == user:
                return True
            if kind == "ServiceAccount":
                sa_ns = s.get("namespace") or binding_ns
                if user == f"system:serviceaccount:{sa_ns}:{name}":
                    return True
        return False

    # ---- ResourceQuota enforcement (kube-apiserver built-in) ---------
    def _enforce_quota(self, pod: dict) -> None:
        # scan(): read-only references — list() would deep-copy every
        # pod in the namespace per admission, turning an N-pod spawn
        # burst into O(N²) copies
        ns = namespace_of(pod)
        quotas = self.scan("ResourceQuota", ns)
        if not quotas:
            return
        pods = [p for p in self.scan("Pod", ns)
                if not p["metadata"].get("deletionTimestamp")]

        def pod_resource(p: dict, resource: str, kind: str) -> float:
            """kind='requests': requests, defaulting to limits (kube
            defaulting); kind='limits': limits only."""
            total = 0.0
            for c in deep_get(p, "spec", "containers", default=[]) or []:
                if kind == "limits":
                    amount = deep_get(c, "resources", "limits", resource)
                else:
                    amount = deep_get(c, "resources", "requests", resource)
                    if amount is None:
                        amount = deep_get(c, "resources", "limits", resource)
                if amount is not None:
                    total += parse_quantity(amount)
            return total

        for quota in quotas:
            hard = deep_get(quota, "spec", "hard", default={}) or {}
            for resource, limit in hard.items():
                limit_v = parse_quantity(limit)
                if resource == "pods":
                    used = float(len(pods))
                    requested = 1.0
                else:
                    rname, rkind = resource, "requests"
                    if rname.startswith("requests."):
                        rname = rname[len("requests."):]
                    elif rname.startswith("limits."):
                        rname = rname[len("limits."):]
                        rkind = "limits"
                    used = sum(pod_resource(p, rname, rkind) for p in pods)
                    requested = pod_resource(pod, rname, rkind)
                if requested and used + requested > limit_v:
                    raise AdmissionDenied(
                        f"exceeded quota {name_of(quota)}: requested "
                        f"{resource}={requested:g}, used {used:g}, "
                        f"limited {limit_v:g}"
                    )
