"""In-memory Kubernetes-style apiserver: the envtest of this repo.

The reference tests its controllers against a real kube-apiserver booted
by envtest (``notebook-controller/controllers/suite_test.go:50-110``);
this module provides the same contract hermetically: typed CRUD with
resourceVersion conflicts, admission chains (where the mutating
webhooks plug in), label-selector lists, watch events, finalizers +
deletionTimestamp semantics, ownerReference cascade deletion, and
ResourceQuota enforcement on pod admission. Controllers drive it
through the same verbs they would use against a cluster.

Cluster-scoped kinds are stored with namespace ``None``. Time is
injected (``clock``) so culling/idleness tests are deterministic.
"""

from __future__ import annotations

import collections
import copy
import datetime
import fnmatch
import functools
import json
import threading
import time
from typing import Callable

from kubeflow_rm_tpu.controlplane.api.meta import (
    deep_get,
    fast_deepcopy,
    labels_of,
    matches_selector,
    name_of,
    namespace_of,
    new_uid,
    parse_quantity,
    strategic_merge,
)

CLUSTER_SCOPED_KINDS = {
    "Namespace", "Profile", "Node", "ClusterRole", "ClusterRoleBinding",
    "PersistentVolume", "CustomResourceDefinition",
}


class APIError(Exception):
    pass


class NotFound(APIError):
    pass


class AlreadyExists(APIError):
    pass


class Conflict(APIError):
    pass


class Invalid(APIError):
    pass


class AdmissionDenied(APIError):
    pass


def _utcnow() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


# store objects are always JSON-shaped (they arrive through
# create/update, which copy), so the JSON-round-trip copy applies
_fastcopy = fast_deepcopy


def _synchronized(fn):
    """Serialize a verb on the store lock. The real apiserver runs
    writes through etcd transactions; here a reentrant lock gives the
    same guarantee the Conflict check needs (read-compare-write of
    resourceVersion is atomic) once callers are multithreaded — the
    REST facade's ThreadingHTTPServer and the parallel Manager both
    are. Reentrant because verbs nest (patch→update,
    delete→_finalize_delete→garbage-collect→delete). Watchers fire
    under the lock, in rv order; they must stay non-blocking (ours
    enqueue and return)."""
    @functools.wraps(fn)
    def wrapper(self, *a, **k):
        with self._lock:
            return fn(self, *a, **k)
    return wrapper


class APIServer:
    def __init__(self, clock: Callable[[], datetime.datetime] = _utcnow):
        self.clock = clock
        self._lock = threading.RLock()
        self._store: dict[tuple[str, str | None, str], dict] = {}
        # per-kind secondary index (kind -> {full key: obj}) so list/
        # scan iterate only the requested kind instead of every object
        # of every kind under the verb lock — at 20-way spawn scale the
        # flat walk made Pod lists O(all events + pods + leases + ...)
        self._by_kind: dict[str, dict[tuple, dict]] = {}
        self._rv = 0
        # admission plugins: fn(op, obj, old) -> obj | None (op: CREATE/UPDATE)
        self._admission: list[tuple[str, Callable]] = []
        # validators per kind: fn(obj) raising on bad spec (CRD schema stand-in)
        self._validators: dict[str, Callable[[dict], None]] = {}
        self._watchers: list[Callable[[str, dict, dict | None], None]] = []
        self._event_seq = 0
        self.quota_enforcement = True
        # container stdout per pod (the kubelet's log store; the fake
        # kubelet appends boot lines, the `pods/<name>/log` subresource
        # reads them — ref jupyter backend get_pod_logs)
        self._pod_logs: dict[tuple[str, str], list[str]] = {}
        # bounded audit trail of writes, tagged with the writer identity
        # set via set_writer (the REST facade stamps it from the
        # X-Writer-Identity header). The failover conformance asserts
        # "no overlapping reconciles" over this: once a standby's first
        # write lands, the dead leader must never write again.
        self.write_log: collections.deque = collections.deque(maxlen=8192)
        self._write_seq = 0
        self._writer = threading.local()

    # ---- wiring ------------------------------------------------------
    def register_admission(self, kind_pattern: str, fn: Callable) -> None:
        """Register a mutating/validating admission plugin for kinds
        matching ``kind_pattern`` (fnmatch, e.g. "Pod" or "*")."""
        self._admission.append((kind_pattern, fn))

    def register_validator(self, kind: str, fn: Callable[[dict], None]) -> None:
        self._validators[kind] = fn

    def add_watcher(self, fn: Callable[[str, dict, dict | None], None]) -> None:
        self._watchers.append(fn)

    # ---- helpers -----------------------------------------------------
    def _key(self, kind: str, name: str, namespace: str | None):
        if kind in CLUSTER_SCOPED_KINDS:
            return (kind, None, name)
        return (kind, namespace, name)

    def _next_rv(self) -> str:
        self._rv += 1
        return str(self._rv)

    def set_writer(self, identity: str | None) -> None:
        """Tag subsequent writes from THIS thread with ``identity`` in
        the write log (thread-local: the REST facade serves each
        request on its own thread)."""
        self._writer.identity = identity

    def _log_write(self, verb: str, obj: dict) -> None:
        self._write_seq += 1
        self.write_log.append({
            "seq": self._write_seq,
            "rv": int(obj["metadata"].get("resourceVersion") or 0),
            "verb": verb,
            "kind": obj["kind"],
            "namespace": namespace_of(obj),
            "name": name_of(obj),
            "writer": getattr(self._writer, "identity", None),
            "t": time.time(),
        })

    def _emit(self, event: str, obj: dict, old: dict | None = None) -> None:
        # ONE defensive copy shared by all watchers — the watcher
        # contract is read-only + non-blocking (Manager._on_event
        # enqueues, RestServer._on_event serializes); per-watcher
        # deepcopies measurably dominated the 20-way spawn event storm
        obj_c = _fastcopy(obj)
        old_c = _fastcopy(old) if old else None
        for w in list(self._watchers):
            w(event, obj_c, old_c)

    def _run_admission(self, op: str, obj: dict, old: dict | None) -> dict:
        for pattern, fn in self._admission:
            if fnmatch.fnmatch(obj["kind"], pattern):
                result = fn(op, obj, old)
                if result is not None:
                    obj = result
        return obj

    @_synchronized
    def ensure_namespace(self, namespace: str) -> dict:
        try:
            return self.get("Namespace", namespace)
        except NotFound:
            return self.create({"apiVersion": "v1", "kind": "Namespace",
                                "metadata": {"name": namespace}})

    # ---- verbs -------------------------------------------------------
    @_synchronized
    def create(self, obj: dict) -> dict:
        obj = _fastcopy(obj)
        kind = obj["kind"]
        name, ns = name_of(obj), namespace_of(obj)
        if kind in CLUSTER_SCOPED_KINDS:
            ns = None
            obj["metadata"].pop("namespace", None)
        elif ns is None:
            raise Invalid(f"{kind}/{name}: namespaced kind requires namespace")
        else:
            if ("Namespace", None, ns) not in self._store:
                raise NotFound(f"namespace {ns!r} not found")
        key = self._key(kind, name, ns)
        if key in self._store:
            raise AlreadyExists(f"{kind} {ns}/{name} already exists")
        if kind in self._validators:
            try:
                self._validators[kind](obj)
            except Exception as e:
                raise Invalid(f"{kind} {ns}/{name}: {e}") from e
        obj = self._run_admission("CREATE", obj, None)
        if self.quota_enforcement and kind == "Pod":
            self._enforce_quota(obj)
        meta = obj["metadata"]
        meta["uid"] = new_uid()
        meta["resourceVersion"] = self._next_rv()
        meta["creationTimestamp"] = self.clock().isoformat()
        self._store[key] = obj
        self._by_kind.setdefault(kind, {})[key] = obj
        self._log_write("CREATE", obj)
        self._emit("ADDED", obj)
        return _fastcopy(obj)

    @_synchronized
    def get(self, kind: str, name: str, namespace: str | None = None) -> dict:
        key = self._key(kind, name, namespace)
        if key not in self._store:
            raise NotFound(f"{kind} {namespace}/{name} not found")
        return _fastcopy(self._store[key])

    @_synchronized
    def try_get(self, kind: str, name: str,
                namespace: str | None = None) -> dict | None:
        try:
            return self.get(kind, name, namespace)
        except NotFound:
            return None

    @_synchronized
    def list(self, kind: str, namespace: str | None = None,
             label_selector: dict | None = None) -> list[dict]:
        out = []
        for (_, ns, _), obj in self._by_kind.get(kind, {}).items():
            if namespace is not None and ns != namespace:
                continue
            if label_selector and not matches_selector(
                    labels_of(obj), label_selector):
                continue
            out.append(_fastcopy(obj))
        out.sort(key=lambda o: (namespace_of(o) or "", name_of(o)))
        return out

    @_synchronized
    def scan(self, kind: str, namespace: str | None = None) -> list[dict]:
        """READ-ONLY ``list``: returns live store references WITHOUT
        copying. For in-process consumers on hot paths (the fake
        kubelet's scheduler sums chip usage over every Pod per
        reconcile — copy-per-object turned that O(pods) read into the
        top CPU entry of the 20-way spawn profile). Callers MUST NOT
        mutate the returned objects; mutate a ``get()`` copy and write
        it back through ``update``. Remote adapters don't have this
        method — use ``getattr(api, "scan", api.list)``."""
        return [o for (_, ns, _), o in self._by_kind.get(kind, {}).items()
                if namespace is None or ns == namespace]

    @_synchronized
    def update(self, obj: dict) -> dict:
        obj = _fastcopy(obj)
        kind, name, ns = obj["kind"], name_of(obj), namespace_of(obj)
        if kind in CLUSTER_SCOPED_KINDS:
            ns = None
        key = self._key(kind, name, ns)
        if key not in self._store:
            raise NotFound(f"{kind} {ns}/{name} not found")
        old = self._store[key]
        rv = obj["metadata"].get("resourceVersion")
        if rv is not None and rv != old["metadata"]["resourceVersion"]:
            raise Conflict(
                f"{kind} {ns}/{name}: resourceVersion {rv} != "
                f"{old['metadata']['resourceVersion']}"
            )
        if kind in self._validators:
            try:
                self._validators[kind](obj)
            except Exception as e:
                raise Invalid(f"{kind} {ns}/{name}: {e}") from e
        obj = self._run_admission("UPDATE", obj, _fastcopy(old))
        # immutable fields
        obj["metadata"]["uid"] = old["metadata"]["uid"]
        obj["metadata"]["creationTimestamp"] = old["metadata"]["creationTimestamp"]
        if old["metadata"].get("deletionTimestamp"):
            obj["metadata"]["deletionTimestamp"] = \
                old["metadata"]["deletionTimestamp"]
        obj["metadata"]["resourceVersion"] = self._next_rv()
        self._store[key] = obj
        self._by_kind.setdefault(kind, {})[key] = obj
        self._log_write("UPDATE", obj)
        # a deleting object whose finalizers have all been removed goes away
        if obj["metadata"].get("deletionTimestamp") and \
                not obj["metadata"].get("finalizers"):
            return self._finalize_delete(key)
        self._emit("MODIFIED", obj, old)
        return _fastcopy(obj)

    @_synchronized
    def patch(self, kind: str, name: str, patch: dict,
              namespace: str | None = None) -> dict:
        current = self.get(kind, name, namespace)
        merged = strategic_merge(current, patch)
        merged["metadata"]["resourceVersion"] = \
            current["metadata"]["resourceVersion"]
        return self.update(merged)

    @_synchronized
    def update_status(self, obj: dict) -> dict:
        """Status-subresource write: only ``status`` is applied."""
        current = self.get(obj["kind"], name_of(obj), namespace_of(obj))
        current["status"] = _fastcopy(obj.get("status", {}))
        return self.update(current)

    @_synchronized
    def delete(self, kind: str, name: str, namespace: str | None = None) -> None:
        key = self._key(kind, name, namespace)
        if key not in self._store:
            raise NotFound(f"{kind} {namespace}/{name} not found")
        obj = self._store[key]
        if obj["metadata"].get("finalizers"):
            if not obj["metadata"].get("deletionTimestamp"):
                obj["metadata"]["deletionTimestamp"] = self.clock().isoformat()
                obj["metadata"]["resourceVersion"] = self._next_rv()
                self._log_write("UPDATE", obj)
                self._emit("MODIFIED", obj)
            return
        self._finalize_delete(key)

    @_synchronized
    def append_pod_log(self, namespace: str, pod_name: str,
                       line: str) -> None:
        self._pod_logs.setdefault((namespace, pod_name), []).append(line)

    @_synchronized
    def pod_logs(self, namespace: str, pod_name: str,
                 tail_lines: int | None = None) -> str:
        """Stored container stdout for a pod (kube ``pods/.../log``).
        Raises NotFound for a pod that does not exist."""
        self.get("Pod", pod_name, namespace)
        lines = self._pod_logs.get((namespace, pod_name), [])
        if tail_lines is not None:
            if tail_lines < 0:
                raise Invalid(f"tailLines must be >= 0, got {tail_lines}")
            lines = lines[-tail_lines:] if tail_lines else []
        return "".join(f"{line}\n" for line in lines)

    def _finalize_delete(self, key) -> dict:
        obj = self._store.pop(key)
        self._by_kind.get(key[0], {}).pop(key, None)
        self._log_write("DELETE", obj)
        if obj["kind"] == "Pod":
            self._pod_logs.pop(
                (namespace_of(obj) or "default", name_of(obj)), None)
        self._emit("DELETED", obj)
        self._garbage_collect(obj)
        if obj["kind"] == "Namespace":
            # namespace deletion drains everything inside it
            ns = name_of(obj)
            for (kind, kns, name) in [k for k in self._store if k[1] == ns]:
                try:
                    self.delete(kind, name, kns)
                except NotFound:
                    pass
        return _fastcopy(obj)

    def _garbage_collect(self, owner: dict) -> None:
        """Cascade-delete dependents referencing the deleted owner's uid."""
        owner_uid = owner["metadata"].get("uid")
        if not owner_uid:
            return
        dependents = [
            (k, obj) for k, obj in list(self._store.items())
            if any(r.get("uid") == owner_uid
                   for r in obj["metadata"].get("ownerReferences", []))
        ]
        for (kind, ns, name), _ in dependents:
            try:
                self.delete(kind, name, ns)
            except NotFound:
                pass

    # ---- events ------------------------------------------------------
    @_synchronized
    def record_event(self, involved: dict, etype: str, reason: str,
                     message: str) -> dict:
        """Create a v1 Event for ``involved`` (controller event recorder)."""
        self._event_seq += 1
        ns = namespace_of(involved) or "default"
        ev = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {
                "name": f"{name_of(involved)}.{self._event_seq:08x}",
                "namespace": ns,
            },
            "type": etype,
            "reason": reason,
            "message": message,
            "involvedObject": {
                "kind": involved["kind"],
                "name": name_of(involved),
                "namespace": ns,
                "uid": involved["metadata"].get("uid"),
            },
            "firstTimestamp": self.clock().isoformat(),
            "lastTimestamp": self.clock().isoformat(),
            "count": 1,
        }
        return self.create(ev)

    @_synchronized
    def events_for(self, involved: dict) -> list[dict]:
        ns = namespace_of(involved)
        return [
            e for e in self.list("Event", ns)
            if deep_get(e, "involvedObject", "name") == name_of(involved)
            and deep_get(e, "involvedObject", "kind") == involved["kind"]
        ]

    # ---- SubjectAccessReview (kube-apiserver authorization) ----------
    READ_VERBS = frozenset({"get", "list", "watch"})

    @_synchronized
    def access_review(self, user: str | None, verb: str, resource: str,
                      namespace: str | None = None) -> bool:
        """Evaluate RBAC the way a SubjectAccessReview does: the web
        apps' authz decorator submits one per request (reference:
        ``crud_backend/authz.py:46-80`` builds a V1SubjectAccessReview;
        here the apiserver evaluates the RoleBindings the profile
        controller / KFAM wrote instead of delegating to kube).

        Semantics covered: cluster-admin ClusterRoleBindings grant
        everything; other ClusterRoleBindings grant their role's rules
        cluster-wide; namespace RoleBindings grant their role's rules
        in that namespace. A role's rules come from a stored
        ClusterRole object when one exists (``rules: [{resources,
        verbs}]``, ``*`` wildcards honored — real per-resource RBAC);
        absent a stored object, the kubeflow-{admin,edit,view} names
        fall back to their aggregated-deployment tiers (admin/edit =
        all verbs, view = read verbs), matching the reference's
        default roles.
        """
        if user is None:
            return False
        # scan(): read-only store references (we hold the verb lock) —
        # SARs arrive per web-app request, and copy-per-binding made
        # authorization a measurable slice of spawn-storm CPU
        for crb in self.scan("ClusterRoleBinding"):
            if not self._binding_has_subject(crb, user, None):
                continue
            role = deep_get(crb, "roleRef", "name") or ""
            if role == "cluster-admin":
                return True
            if self._role_allows(role, verb, resource):
                return True
        if namespace is None:
            return False
        for rb in self.scan("RoleBinding", namespace):
            if not self._binding_has_subject(rb, user, namespace):
                continue
            role = deep_get(rb, "roleRef", "name") or ""
            if self._role_allows(role, verb, resource):
                return True
        return False

    def _role_allows(self, role_name: str, verb: str,
                     resource: str) -> bool:
        """Evaluate one (Cluster)Role against a verb+resource pair.

        Stored ClusterRole rules win (the finer-role case VERDICT r2
        weak #2 calls out); the name-based tiers are the fallback for
        the aggregated-role deployment where role objects aren't
        materialized in the store.
        """
        role = self.try_get("ClusterRole", role_name)
        if role is not None and role.get("rules") is not None:
            for rule in role["rules"]:
                resources = rule.get("resources") or []
                verbs = rule.get("verbs") or []
                if (("*" in resources or resource in resources)
                        and ("*" in verbs or verb in verbs)):
                    return True
            return False
        if role_name in ("kubeflow-admin", "kubeflow-edit", "admin",
                         "edit"):
            return True
        if role_name in ("kubeflow-view", "view"):
            return verb in self.READ_VERBS
        return False

    @staticmethod
    def _binding_has_subject(binding: dict, user: str,
                             binding_ns: str | None) -> bool:
        """User subjects match the identity-header name; ServiceAccount
        subjects ONLY match the ``system:serviceaccount:<ns>:<name>``
        rendering (as a real SubjectAccessReview would) — a request
        whose userid header is literally "default-editor" must NOT
        inherit that SA's grants."""
        for s in binding.get("subjects") or []:
            kind, name = s.get("kind"), s.get("name")
            if kind == "User" and name == user:
                return True
            if kind == "ServiceAccount":
                sa_ns = s.get("namespace") or binding_ns
                if user == f"system:serviceaccount:{sa_ns}:{name}":
                    return True
        return False

    # ---- ResourceQuota enforcement (kube-apiserver built-in) ---------
    def _enforce_quota(self, pod: dict) -> None:
        # scan(): read-only references — list() would deep-copy every
        # pod in the namespace per admission, turning an N-pod spawn
        # burst into O(N²) copies
        ns = namespace_of(pod)
        quotas = self.scan("ResourceQuota", ns)
        if not quotas:
            return
        pods = [p for p in self.scan("Pod", ns)
                if not p["metadata"].get("deletionTimestamp")]

        def pod_resource(p: dict, resource: str, kind: str) -> float:
            """kind='requests': requests, defaulting to limits (kube
            defaulting); kind='limits': limits only."""
            total = 0.0
            for c in deep_get(p, "spec", "containers", default=[]) or []:
                if kind == "limits":
                    amount = deep_get(c, "resources", "limits", resource)
                else:
                    amount = deep_get(c, "resources", "requests", resource)
                    if amount is None:
                        amount = deep_get(c, "resources", "limits", resource)
                if amount is not None:
                    total += parse_quantity(amount)
            return total

        for quota in quotas:
            hard = deep_get(quota, "spec", "hard", default={}) or {}
            for resource, limit in hard.items():
                limit_v = parse_quantity(limit)
                if resource == "pods":
                    used = float(len(pods))
                    requested = 1.0
                else:
                    rname, rkind = resource, "requests"
                    if rname.startswith("requests."):
                        rname = rname[len("requests."):]
                    elif rname.startswith("limits."):
                        rname = rname[len("limits."):]
                        rkind = "limits"
                    used = sum(pod_resource(p, rname, rkind) for p in pods)
                    requested = pod_resource(pod, rname, rkind)
                if requested and used + requested > limit_v:
                    raise AdmissionDenied(
                        f"exceeded quota {name_of(quota)}: requested "
                        f"{resource}={requested:g}, used {used:g}, "
                        f"limited {limit_v:g}"
                    )
