"""Deterministic seeded fault-injection engine.

PR 12 proved the ratchet gate with one ad-hoc env hook
(``KFRM_CHAOS_RECONCILE_SLEEP_MS`` stalling reconciles); this module
subsumes it into a first-class engine: a seeded :class:`FaultPlan`
describes WHICH faults fire, WHERE (substring match on the injection
site), HOW OFTEN (per-opportunity probability) and HOW MANY times
(optional cap), and the existing choke points ask the engine at every
opportunity:

- ``maybe_stall``        — runtime reconcile span (``Manager``)
- ``api_request_fault``  — ``_FastSession._request`` (every kubeclient
                           verb of every session, incl. shard routes)
- ``watch_fault``        — ``_WatcherChannel.publish``/``publish_many``
- ``checkpoint_write_fault`` — suspend state stores + ``Checkpointer``
- ``pod_kill_victim``    — the fake kubelet (StatefulSetController)
- ``shard_kill_victim``  — ``ShardRunner``'s watchdog tick

Every hook is a no-op returning on the first branch while no plan is
installed — the engine costs one module-global load on hot paths, so
the ``--no-chaos`` arms and the perf ratchet see the unpolluted system.

Semantics notes:

- A dropped watch event is injected as the channel's ``TOO_OLD``
  sentinel in place of the item: the platform's watch contract is
  "ordered window or a detectable gap" (kube's 410), so a drop
  manifests as the gap and exercises the relist/resync recovery path
  rather than silently corrupting an informer forever.
- An injected apiserver 5xx is a synthesized HTTP 503 response object
  (``Synthetic503``) returned from the client choke point, so the
  normal ``_raise_for`` → ``APIError`` → reconcile-retry machinery
  runs exactly as it would for a real overloaded shard.
- Determinism: each spec owns its own ``random.Random`` stream seeded
  from ``(seed, spec index, fault)``, so one spec's draw sequence is
  independent of how often other faults are consulted. Under free
  threading the *interleaving* of opportunities is scheduling-
  dependent, but a fixed seed reproduces the same fault mix and the
  per-fault counts are attributable injection by injection via the
  ledger.
- Attribution: every injection increments
  ``chaos_faults_injected_total{fault}``, appends a ledger row, and
  (when a flight recorder is attached) triggers a rate-limited
  ``chaos_<fault>`` bundle. Watch-channel injections defer their
  flight trigger — the publisher may hold verb locks, and a bundle
  capture does network I/O — and the next lock-free injection (or an
  explicit ``flush_flight``) emits them.
"""

from __future__ import annotations

import json
import os
import random
import time
from collections import Counter, deque
from dataclasses import dataclass, field

from kubeflow_rm_tpu.analysis.lockgraph import make_lock

#: the fault vocabulary (README "chaos engine" section documents each)
FAULT_KINDS = (
    "reconcile_stall",   # stall a reconcile inside its span
    "api_error",         # synthesized HTTP 503 from the client choke point
    "api_timeout",       # injected TimeoutError before the request is sent
    "watch_drop",        # watch event replaced by a TOO_OLD gap sentinel
    "watch_dup",         # watch event delivered twice (idempotency probe)
    "checkpoint_fail",   # checkpoint write raises OSError
    "pod_kill",          # fake kubelet SIGKILLs one running pod
    "shard_kill",        # ShardRunner watchdog SIGKILLs one shard
    "shard_split",       # SIGKILL the donor mid-split (tail-replay
                         # must recover from the respawned donor's WAL
                         # with zero loss)
    "replica_kill",      # serving-fleet replica SIGKILLed mid-decode
                         # (in-flight requests migrate bit-exact; a
                         # HARVESTED replica's chips must still return
                         # to the notebook pool clean)
)


@dataclass(frozen=True)
class FaultSpec:
    """One fault arm of a plan.

    ``rate`` is the per-opportunity injection probability; ``match``
    is a substring filter on the site string each choke point passes
    (controller name, ``"VERB /path"``, watcher name, ``"ns/name"``);
    ``limit`` caps total injections (None = unbounded);
    ``stall_ms`` is the stall duration for ``reconcile_stall``."""

    fault: str
    rate: float = 0.0
    match: str = ""
    limit: int | None = None
    stall_ms: float = 0.0

    def __post_init__(self):
        if self.fault not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault {self.fault!r}; known: {FAULT_KINDS}")


@dataclass
class _Ledger:
    rows: deque = field(default_factory=lambda: deque(maxlen=4096))


class FaultPlan:
    """A seeded set of :class:`FaultSpec` arms plus the injection
    ledger. Install with :func:`install`; the choke-point hooks below
    consult the installed plan on every opportunity."""

    def __init__(self, seed: int, specs: list[FaultSpec], *,
                 flight=None):
        self.seed = int(seed)
        self.specs = list(specs)
        self.flight = flight
        self._lock = make_lock("chaos.plan")
        self._rngs = [random.Random(f"{self.seed}:{i}:{s.fault}")
                      for i, s in enumerate(self.specs)]
        self.counts: Counter = Counter()
        self.opportunities: Counter = Counter()
        self._ledger = _Ledger()
        self._pending_flight: deque = deque(maxlen=256)

    # ---- decision ----------------------------------------------------

    def _draw(self, fault: str, site: str) -> FaultSpec | None:
        """Roll every matching spec's stream; first hit wins. Runs
        under the plan lock; callers fire flight triggers AFTER
        release (bundle capture does I/O)."""
        with self._lock:
            self.opportunities[fault] += 1
            for i, spec in enumerate(self.specs):
                if spec.fault != fault:
                    continue
                if spec.match and spec.match not in site:
                    continue
                if spec.limit is not None and \
                        self.counts[fault] >= spec.limit:
                    continue
                if spec.rate < 1.0 and \
                        self._rngs[i].random() >= spec.rate:
                    continue
                self.counts[fault] += 1
                self._ledger.rows.append({
                    "n": sum(self.counts.values()), "fault": fault,
                    "site": site, "t": round(time.time(), 4)})
                return spec
        return None

    def _record(self, fault: str, site: str, *,
                defer_flight: bool) -> None:
        from kubeflow_rm_tpu.controlplane import metrics
        metrics.CHAOS_FAULTS_INJECTED_TOTAL.labels(fault=fault).inc()
        if self.flight is None:
            return
        if defer_flight:
            self._pending_flight.append((fault, site))
        else:
            self.flush_flight()
            try:
                self.flight.trigger(f"chaos_{fault}",
                                    detail={"site": site}, auto=True)
            except Exception:  # noqa: BLE001
                metrics.swallowed("chaos", "flight trigger")

    def flush_flight(self) -> None:
        """Emit deferred (lock-context) injection bundles. Safe to call
        from harness loops; never raises."""
        from kubeflow_rm_tpu.controlplane import metrics
        while self._pending_flight:
            try:
                fault, site = self._pending_flight.popleft()
            except IndexError:
                return
            try:
                self.flight.trigger(f"chaos_{fault}",
                                    detail={"site": site}, auto=True)
            except Exception:  # noqa: BLE001 - attribution must never
                metrics.swallowed("chaos", "flight trigger")  # hurt SUT

    def ledger(self) -> list[dict]:
        with self._lock:
            return list(self._ledger.rows)

    def summary(self) -> dict:
        with self._lock:
            return {"seed": self.seed,
                    "faults": dict(self.counts),
                    "opportunities": dict(self.opportunities)}


# ---- global install point --------------------------------------------

_plan: FaultPlan | None = None


def install(plan: FaultPlan) -> FaultPlan:
    """Install ``plan`` process-wide. Returns it for chaining."""
    global _plan
    _plan = plan
    return plan


def uninstall() -> FaultPlan | None:
    """Remove the installed plan (hooks go back to zero-cost no-ops)
    and return it so the harness can read counts/ledger."""
    global _plan
    plan, _plan = _plan, None
    return plan


def active() -> FaultPlan | None:
    return _plan


def plan_from_args(seed: int, faults: str, *, flight=None) -> FaultPlan:
    """Build a plan from a CLI string like
    ``"reconcile_stall:0.05:25,api_error:0.03,watch_drop:0.02"``
    (fault[:rate[:stall_ms]], comma-separated)."""
    specs = []
    for part in faults.split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        spec = FaultSpec(
            fault=bits[0],
            rate=float(bits[1]) if len(bits) > 1 else 0.05,
            stall_ms=float(bits[2]) if len(bits) > 2 else 0.0)
        specs.append(spec)
    return FaultPlan(seed, specs, flight=flight)


# ---- choke-point hooks -----------------------------------------------

def maybe_stall(controller: str) -> None:
    """Runtime reconcile-span hook. Subsumes (and keeps honoring) the
    PR 12 env hook: ``KFRM_CHAOS_RECONCILE_SLEEP_MS=<ms>`` stalls every
    reconcile (or only ``KFRM_CHAOS_RECONCILE_CONTROLLER=<name>``'s) —
    the perf-ratchet red-run demo keeps working unchanged."""
    plan = _plan
    if plan is not None:
        spec = plan._draw("reconcile_stall", controller)
        if spec is not None:
            plan._record("reconcile_stall", controller,
                         defer_flight=False)
            if spec.stall_ms > 0:
                time.sleep(spec.stall_ms / 1000.0)
    ms = os.environ.get("KFRM_CHAOS_RECONCILE_SLEEP_MS")
    if not ms:
        return
    only = os.environ.get("KFRM_CHAOS_RECONCILE_CONTROLLER", "")
    if only and only != controller:
        return
    time.sleep(float(ms) / 1000.0)


class Synthetic503:
    """Duck-typed stand-in for the kubeclient's ``_Resp`` carrying an
    injected apiserver 5xx: ``_raise_for`` turns it into the same
    ``APIError`` a real overloaded shard would produce."""

    status_code = 503
    ok = False

    def __init__(self, site: str):
        self.text = json.dumps({
            "kind": "Status", "status": "Failure", "code": 503,
            "message": f"chaos: injected 503 on {site}"})

    def json(self):
        return json.loads(self.text)


def api_request_fault(method: str, path: str):
    """kubeclient choke point. Returns None (no fault), a
    :class:`Synthetic503` the caller must return as the response, or
    raises ``TimeoutError`` for an injected client-side timeout."""
    plan = _plan
    if plan is None:
        return None
    site = f"{method} {path}"
    if plan._draw("api_timeout", site) is not None:
        plan._record("api_timeout", site, defer_flight=False)
        raise TimeoutError(f"chaos: injected timeout on {site}")
    if plan._draw("api_error", site) is not None:
        plan._record("api_error", site, defer_flight=False)
        return Synthetic503(site)
    return None


def watch_fault(watcher: str, etype: str) -> str | None:
    """Watch-fanout choke point. Returns ``"drop"`` (the publisher
    substitutes a ``TOO_OLD`` gap sentinel), ``"dup"`` (publish the
    item twice), or None. ``TOO_OLD`` sentinels themselves are never
    faulted — the recovery path must stay reliable."""
    plan = _plan
    if plan is None or etype == "TOO_OLD":
        return None
    site = f"{watcher}:{etype}"
    if plan._draw("watch_drop", site) is not None:
        plan._record("watch_drop", site, defer_flight=True)
        return "drop"
    if plan._draw("watch_dup", site) is not None:
        plan._record("watch_dup", site, defer_flight=True)
        return "dup"
    return None


def checkpoint_write_fault(site: str) -> None:
    """State-store / Checkpointer choke point: raises ``OSError`` when
    the plan injects a checkpoint-write failure (the suspend reconcile
    retries with backoff, exactly like a wedged storage backend)."""
    plan = _plan
    if plan is None:
        return
    if plan._draw("checkpoint_fail", site) is not None:
        plan._record("checkpoint_fail", site, defer_flight=False)
        raise OSError(f"chaos: injected checkpoint write failure "
                      f"({site})")


def pod_kill_victim(site: str, pod_names: list[str]) -> str | None:
    """Fake-kubelet choke point: one opportunity per reconcile of an
    StatefulSet with running pods; returns the pod to kill."""
    plan = _plan
    if plan is None or not pod_names:
        return None
    spec = plan._draw("pod_kill", site)
    if spec is None:
        return None
    plan._record("pod_kill", site, defer_flight=False)
    # deterministic victim given the ledger position: hash-free pick
    with plan._lock:
        n = plan.counts["pod_kill"]
    return sorted(pod_names)[n % len(pod_names)]


def split_kill_fault(site: str) -> bool:
    """Elastic-handoff choke point: one opportunity per split, drawn
    between the bulk copy and the tail-replay loop (the window where a
    donor death is most likely to lose the moving range). True tells
    the coordinator to SIGKILL the donor; the watchdog respawns it
    from its WAL and the tail-replay loop resumes against the
    recovered log — the zero-loss assertion covers exactly this."""
    plan = _plan
    if plan is None:
        return False
    if plan._draw("shard_split", site) is None:
        return False
    plan._record("shard_split", site, defer_flight=False)
    return True


def replica_kill_victim(names: list[str]) -> str | None:
    """Serving-fleet choke point: one opportunity per harness tick;
    returns the replica to SIGKILL (``fleet.kill`` — queued AND
    mid-decode requests migrate to surviving replicas via the
    store-held prefixes). The harvest chaos arm feeds HARVESTED
    replica names here: the assertion downstream is that the donor
    notebook's chips come back clean even when the borrower dies
    without a drain."""
    plan = _plan
    if plan is None or not names:
        return None
    spec = plan._draw("replica_kill", "serving_fleet")
    if spec is None:
        return None
    plan._record("replica_kill", "serving_fleet", defer_flight=False)
    with plan._lock:
        n = plan.counts["replica_kill"]
    return sorted(names)[n % len(names)]


def shard_kill_victim(names: list[str]) -> str | None:
    """ShardRunner watchdog choke point: one opportunity per tick."""
    plan = _plan
    if plan is None or not names:
        return None
    spec = plan._draw("shard_kill", "watchdog")
    if spec is None:
        return None
    plan._record("shard_kill", "watchdog", defer_flight=False)
    with plan._lock:
        n = plan.counts["shard_kill"]
    return sorted(names)[n % len(names)]
