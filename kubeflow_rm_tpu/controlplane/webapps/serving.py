"""Multi-tenant serving gateway over the continuous-batching engine.

The serving analogue of what the provision path grew in PRs 3-5: the
decode engine (``models.generate.ContinuousBatchingEngine``) gives us
slot-level admission/retirement at token boundaries; this module puts
a tenant-aware front door on it so one tenant's storm cannot blow
another's p95 — the failure mode ``benchmarks/serve_bench.py``'s
static batches had no answer to.

Admission control, in order (first failure sheds the request before it
ever touches the engine):

1. **Request rate** — per-tenant ``TokenBucket.try_acquire(1)``
   (the same client-go-style bucket kubeclient throttles writes with,
   non-blocking: an over-rate request is shed with 429 immediately
   instead of queueing into everyone else's latency).
2. **Token budget** — a second per-tenant bucket denominated in
   TOKENS (``try_acquire(max_new_tokens)``): a tenant asking for long
   generations spends its budget proportionally.
3. **Queue cap** — a bounded engine queue; beyond it, 503.
4. **p95 SLO projection** — shed (503) when the queue-depth-scaled
   EMA of recent request service times projects past the configured
   SLO: ``(queue/slots + 1) * ema_ms > slo_ms``. This is what keeps
   ACCEPTED requests inside the SLO under overload: the gateway sheds
   load instead of violating latency.

Everything is observable: queue depth, batch occupancy, per-tenant
request/shed counters and latency histograms land in the control-plane
prometheus registry (``controlplane/metrics.py``), flow into the
dashboard's ``/api/metrics`` controlplane section
(``webapps/metrics_service._controlplane_section``), and are also
served directly by this app's own ``/metrics`` + ``/api/metrics``
routes — the serving pod is scrape-compatible with the rest of the
platform.

API: ``POST /generate {"prompt": [ids...], "tenant"?: "name",
"max_new_tokens"?: n}`` → ``{"tokens": [ids...], "latency_ms": ...}``;
``GET /healthz``; ``GET /metrics`` (prometheus text);
``GET /api/metrics`` (the serving JSON section).
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass

from kubeflow_rm_tpu.controlplane import metrics as cp_metrics
from kubeflow_rm_tpu.controlplane import tracing
from kubeflow_rm_tpu.controlplane.deploy.kubeclient import TokenBucket
from kubeflow_rm_tpu.analysis.lockgraph import make_lock


@dataclass(frozen=True)
class TenantPolicy:
    """Per-tenant admission knobs. ``qps``/``burst`` bound request
    RATE; ``tokens_per_s``/``token_burst`` bound decoded-token SPEND;
    ``slo_p95_ms`` is the latency promise the gateway sheds to keep;
    ``slo_class`` is the engine queue the tenant's admitted requests
    drain from (interactive | batch | best_effort)."""
    qps: float = 20.0
    burst: int = 40
    tokens_per_s: float = 2000.0
    token_burst: int = 4000
    slo_p95_ms: float = 2000.0
    slo_class: str = "interactive"


class ReplicaUnavailable(Exception):
    """The replica gave this request up before finishing it (drain or
    death). The request is NOT failed — the caller (serving fleet, or
    any retrying client) resubmits it elsewhere and the generation
    resumes from the tokens already produced."""

    def __init__(self, msg: str, tokens_so_far=None):
        super().__init__(msg)
        self.tokens_so_far = list(tokens_so_far or [])


class _Pending:
    """A request in flight: the HTTP thread parks on ``event`` while
    the drain thread decodes."""

    __slots__ = ("req", "tenant", "event", "t_submit", "t_done",
                 "trace", "t_submit_epoch", "failed")

    def __init__(self, req, tenant, trace=None):
        self.req = req
        self.tenant = tenant
        self.event = threading.Event()
        self.t_submit = time.monotonic()
        self.t_done = None
        # set when the replica abandons the request (drain/close)
        # before the engine finishes it — wait() then raises
        # ReplicaUnavailable instead of returning a torn result
        self.failed = False
        # traceparent of the admitting request, if it carried one —
        # the drain thread stamps the decode span against it; epoch
        # twin of t_submit because spans use wall time
        self.trace = trace
        self.t_submit_epoch = time.time()


class ServingGateway:
    """Admission control + drain loop around one decode engine.

    ``admission=False`` turns checks 1/2/4 off (the noisy-neighbor A/B
    baseline arm: everything is admitted, victims eat the flood). The
    queue cap stays on in both arms — an unbounded queue is an OOM,
    not a policy choice.
    """

    def __init__(self, engine, *, policies: dict | None = None,
                 default_policy: TenantPolicy | None = None,
                 max_queue: int = 64, admission: bool = True,
                 clock=None):
        self.engine = engine
        self.policies = dict(policies or {})
        self.default_policy = default_policy or TenantPolicy()
        self.max_queue = max_queue
        self.admission = admission
        self._clock = clock or time.monotonic
        self._lock = make_lock("serving.gateway")  # engine + pending
        self._rate_buckets: dict[str, TokenBucket] = {}
        self._token_buckets: dict[str, TokenBucket] = {}
        self._pending: list[_Pending] = []
        # sliding per-tenant latency windows for p95 reporting, plus
        # the EMA the SLO projection sheds on
        self._lat_windows: dict[str, list[float]] = {}
        # per-tenant slowest traced request — the exemplar id reported
        # next to the latency summary so "p95 is bad" links straight
        # to a trace you can pull from /api/traces/<id>
        self._exemplars: dict[str, dict] = {}
        self._ema_ms: float | None = None
        self.shed_counts: dict[str, int] = {}
        self.draining = False
        self._stop = threading.Event()
        cp_metrics.SERVING_SLOT_CAPACITY.set(engine.slots)
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    # -- policy plumbing ---------------------------------------------------

    def _policy(self, tenant: str) -> TenantPolicy:
        return self.policies.get(tenant, self.default_policy)

    def _buckets(self, tenant: str) -> tuple[TokenBucket, TokenBucket]:
        if tenant not in self._rate_buckets:
            pol = self._policy(tenant)
            self._rate_buckets[tenant] = TokenBucket(
                pol.qps, pol.burst, clock=self._clock)
            self._token_buckets[tenant] = TokenBucket(
                pol.tokens_per_s, pol.token_burst, clock=self._clock)
        return self._rate_buckets[tenant], self._token_buckets[tenant]

    # -- admission ---------------------------------------------------------

    def _shed(self, tenant: str, reason: str) -> None:
        cp_metrics.SERVING_SHED_TOTAL.labels(tenant, reason).inc()
        cp_metrics.SERVING_REQUESTS_TOTAL.labels(tenant, "shed").inc()
        self.shed_counts[reason] = self.shed_counts.get(reason, 0) + 1

    def try_submit(self, tenant: str, prompt: list[int], *,
                   max_new_tokens: int, eos_id: int | None = None,
                   slo_class: str | None = None,
                   speculative: bool = False, chain=None
                   ) -> tuple[_Pending | None, str | None]:
        """Admit or shed. Returns (pending, None) on admit,
        (None, reason) on shed — reason in
        rate|tokens|queue|slo|draining. ``slo_class`` overrides the
        tenant policy's default engine queue. ``chain`` is an exported
        prefix chain (from a prefill replica / the global store): the
        engine seats it directly via ``install_chain`` and skips
        prefill entirely. ``speculative`` routes the request through
        the fused speculative-decode path (batch/best_effort only)."""
        pol = self._policy(tenant)
        trace = tracing.current_traceparent()
        with tracing.start_span_if_active(
                "serving.admit", attrs={"tenant": tenant}) as sp:
            if self.draining:
                self._shed(tenant, "draining")
                sp.set_attr("shed", "draining")
                return None, "draining"
            if self.admission:
                rate, budget = self._buckets(tenant)
                if not rate.try_acquire(1.0):
                    self._shed(tenant, "rate")
                    sp.set_attr("shed", "rate")
                    return None, "rate"
                if not budget.try_acquire(float(max_new_tokens)):
                    self._shed(tenant, "tokens")
                    sp.set_attr("shed", "tokens")
                    return None, "tokens"
            with self._lock:
                # re-check under the lock: a drain/close that began
                # after the fast-path check above must not let this
                # request enqueue onto a stopping replica (it would
                # never be drained OR failed — a silent hang)
                if self.draining:
                    self._shed(tenant, "draining")
                    sp.set_attr("shed", "draining")
                    return None, "draining"
                depth = self.engine.queue_depth
                if depth >= self.max_queue:
                    self._shed(tenant, "queue")
                    sp.set_attr("shed", "queue")
                    return None, "queue"
                if self.admission and self._ema_ms is not None:
                    projected = (depth / self.engine.slots + 1.0) \
                        * self._ema_ms
                    if projected > pol.slo_p95_ms:
                        self._shed(tenant, "slo")
                        sp.set_attr("shed", "slo")
                        return None, "slo"
                if chain is not None:
                    req = self.engine.install_chain(
                        chain, max_new_tokens=max_new_tokens,
                        eos_id=eos_id,
                        slo_class=slo_class or pol.slo_class)
                else:
                    req = self.engine.submit(
                        prompt, max_new_tokens=max_new_tokens,
                        eos_id=eos_id,
                        slo_class=slo_class or pol.slo_class,
                        speculative=speculative)
                pending = _Pending(req, tenant, trace=trace)
                self._pending.append(pending)
                cp_metrics.SERVING_QUEUE_DEPTH.set(
                    self.engine.queue_depth)
        return pending, None

    # -- disaggregated-serving surface -------------------------------------
    # A prefill replica runs ``prefill_chain`` (compute + export, no
    # decode slot consumed); decode replicas ``adopt_chain`` (seat a
    # store-served chain into the local pool) or install it per-request
    # via ``try_submit(chain=...)``. All three hold the gateway lock:
    # they touch the same engine the drain thread steps.

    def prefill_chain(self, prompt: list[int]):
        """Run prefill into cache blocks and export the serialized
        chain (see ``models.paging.export_chain``). Returns None when
        draining, not paged, or the pool is too full to hold it."""
        with self._lock:
            if self.draining:
                return None
            if not getattr(self.engine, "paged", False):
                return None
            return self.engine.prefill_chain(prompt)

    def adopt_chain(self, chain) -> int:
        """Seat an exported chain into the local block pool (no
        request attached). Returns blocks imported (0 = already local,
        pool full, or draining)."""
        with self._lock:
            if self.draining or not getattr(self.engine, "paged", False):
                return 0
            return self.engine.adopt_chain(chain)

    def chain_coverage(self, prompt: list[int]) -> int:
        """Tokens of ``prompt`` already covered by locally-resident
        prefix blocks — the fleet uses this to decide whether routing
        through the prefill tier would save anything."""
        with self._lock:
            if not getattr(self.engine, "paged", False):
                return 0
            return self.engine.chain_coverage(prompt)

    def wait(self, pending: _Pending, timeout_s: float = 300.0
             ) -> list[int]:
        if not pending.event.wait(timeout_s):
            raise TimeoutError("generation timed out")
        if pending.failed and not pending.req.done:
            raise ReplicaUnavailable(
                "replica gave up this request mid-flight "
                "(drain or shutdown) — resubmit elsewhere",
                tokens_so_far=pending.req.tokens)
        lat_s = pending.t_done - pending.t_submit
        tenant = pending.tenant
        cp_metrics.SERVING_REQUESTS_TOTAL.labels(tenant, "ok").inc()
        cp_metrics.SERVING_REQUEST_LATENCY_SECONDS.labels(
            tenant).observe(lat_s)
        cp_metrics.SERVING_GENERATED_TOKENS_TOTAL.labels(tenant).inc(
            len(pending.req.tokens))
        return pending.req.tokens

    # -- drain loop --------------------------------------------------------

    def _drain(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                busy = (self.engine.queue_depth
                        or self.engine.active_slots)
                finished = self.engine.step() if busy else []
                if busy:
                    stats = self.engine.stats()
                    cp_metrics.SERVING_QUEUE_DEPTH.set(
                        stats["queue_depth"])
                    cp_metrics.SERVING_ACTIVE_SLOTS.set(
                        stats["active_slots"])
                    cp_metrics.SERVING_BATCH_OCCUPANCY.set(
                        stats["batch_occupancy"])
                    for c, d in stats.get("queue_depth_by_class",
                                          {}).items():
                        cp_metrics.SERVING_CLASS_QUEUE_DEPTH.labels(
                            c).set(d)
                    if stats.get("paged"):
                        cp_metrics.SERVING_FREE_BLOCK_FRACTION.set(
                            stats["free_block_fraction"])
                        if stats.get("prompt_tokens"):
                            hr = stats["prefix_hit_ratio"]
                            cp_metrics.SERVING_PREFIX_HIT_RATIO.set(hr)
                            cp_metrics.SERVING_PREFIX_MISS_RATIO.set(
                                1.0 - hr)
                if finished:
                    done_ids = {id(p.req) for p in self._pending
                                if p.req.done}
                    now = time.monotonic()
                    ready = [p for p in self._pending
                             if id(p.req) in done_ids]
                    self._pending = [p for p in self._pending
                                     if id(p.req) not in done_ids]
                else:
                    ready = []
            for p in ready:
                p.t_done = now
                lat_ms = (p.t_done - p.t_submit) * 1e3
                window = self._lat_windows.setdefault(p.tenant, [])
                window.append(lat_ms)
                del window[:-256]
                self._ema_ms = (lat_ms if self._ema_ms is None else
                                0.8 * self._ema_ms + 0.2 * lat_ms)
                if p.trace is not None:
                    # retroactive span: the interval was measured here
                    # on the drain thread, parented on the admitting
                    # request so prefill+decode joins its trace
                    tracing.record_span(
                        "serving.decode",
                        start=p.t_submit_epoch, end=time.time(),
                        parent=p.trace,
                        attrs={"tenant": p.tenant,
                               "tokens": len(p.req.tokens)})
                    ctx = tracing.parse_traceparent(p.trace)
                    ex = self._exemplars.get(p.tenant)
                    if ctx is not None and (ex is None
                                            or lat_ms > ex["latency_ms"]):
                        self._exemplars[p.tenant] = {
                            "trace_id": ctx.trace_id,
                            "latency_ms": round(lat_ms, 3)}
                p.event.set()
            if not busy:
                self._stop.wait(0.001)

    def start_drain(self) -> list[_Pending]:
        """Begin pulling this replica out of rotation: new submits
        shed with reason ``draining`` (healthz flips 503 so LBs stop
        routing here), QUEUED requests are evicted and handed back to
        the caller for re-routing (their ``wait`` raises
        ``ReplicaUnavailable``), and requests already holding a decode
        slot finish normally. Returns the evicted pendings."""
        with self._lock:
            self.draining = True
            evicted_reqs = {id(r) for r in self.engine.evict_queued()}
            evicted = [p for p in self._pending
                       if id(p.req) in evicted_reqs]
            self._pending = [p for p in self._pending
                             if id(p.req) not in evicted_reqs]
            cp_metrics.SERVING_QUEUE_DEPTH.set(self.engine.queue_depth)
        for p in evicted:
            p.failed = True
            p.t_done = time.monotonic()
            p.event.set()
        return evicted

    def close(self) -> None:
        with self._lock:
            # flip draining first so a submit racing with close sheds
            # instead of enqueueing onto the stopped drain thread
            self.draining = True
            orphans = list(self._pending)
            self._pending = []
        self._stop.set()
        self._thread.join(timeout=5)
        for p in orphans:         # fail any orphans; a request the
            p.failed = True       # engine DID finish stays ok (wait
            p.t_done = time.monotonic()   # checks req.done first)
            p.event.set()

    # -- observability -----------------------------------------------------

    def tenant_latency(self, tenant: str) -> dict:
        window = sorted(self._lat_windows.get(tenant, []))
        if not window:
            return {"count": 0, "p50_ms": None, "p95_ms": None,
                    "slowest_trace": self._exemplars.get(tenant)}
        return {
            "count": len(window),
            "p50_ms": window[int(0.50 * (len(window) - 1))],
            "p95_ms": window[int(0.95 * (len(window) - 1))],
            # exemplar: the slowest TRACED request seen for this tenant
            # — resolves via GET /api/traces/<trace_id>
            "slowest_trace": self._exemplars.get(tenant),
        }

    def snapshot(self) -> dict:
        stats = self.engine.stats()
        return {
            "admission": self.admission,
            "draining": self.draining,
            "paged": stats.get("paged", False),
            "queue_depth_by_class": stats.get("queue_depth_by_class"),
            "prefix_hit_ratio": stats.get("prefix_hit_ratio"),
            "free_block_fraction": stats.get("free_block_fraction"),
            "cow_forks": stats.get("cow_forks"),
            "queue_depth": stats["queue_depth"],
            "active_slots": stats["active_slots"],
            "slot_capacity": stats["slots"],
            "batch_occupancy": stats["batch_occupancy"],
            "decode_steps": stats["decode_steps"],
            "finished_total": stats["finished_total"],
            "shed": dict(self.shed_counts),
            "ema_service_ms": self._ema_ms,
            "tenants": {t: self.tenant_latency(t)
                        for t in sorted(self._lat_windows)},
        }


def make_serving_app(gateway: ServingGateway, cfg):
    """werkzeug WSGI app over a gateway: the tenant-facing front door.

    Requests carry a ``tenant`` field (header ``X-Tenant`` also
    accepted — the auth companion injects it in-cluster); sheds map to
    429 (per-tenant rate/budget — the client should back off) or 503
    (gateway-wide queue/SLO pressure — retry against another replica).
    """
    from werkzeug.exceptions import BadRequest, HTTPException
    from werkzeug.routing import Map, Rule
    from werkzeug.wrappers import Request, Response

    urls = Map([Rule("/generate", endpoint="generate", methods=["POST"]),
                Rule("/healthz", endpoint="healthz"),
                Rule("/metrics", endpoint="metrics"),
                Rule("/api/metrics", endpoint="api_metrics")])

    def _json(payload, status=200):
        return Response(json.dumps(payload), status=status,
                        content_type="application/json")

    def app(environ, start_response):
        # same server-span contract as WebApp: context-bearing requests
        # join their caller's trace (admission + parked wait happen
        # inside; the decode span is stamped by the drain thread)
        if tracing.enabled():
            parent = tracing.parse_traceparent(
                environ.get("HTTP_TRACEPARENT"))
            if parent is not None:
                with tracing.start_span(
                        f"{environ.get('REQUEST_METHOD', 'GET')} "
                        f"{environ.get('PATH_INFO', '/')}",
                        kind="server", parent=parent,
                        attrs={"component": "serving"}):
                    return _app_inner(environ, start_response)
        return _app_inner(environ, start_response)

    def _app_inner(environ, start_response):
        req = Request(environ)
        try:
            endpoint, _ = urls.bind_to_environ(environ).match()
            if endpoint == "healthz":
                # a draining replica must fail its health check BEFORE
                # its queue is severed, so routers/LBs stop sending new
                # work while in-flight requests still finish here
                if gateway.draining:
                    return _json({"ok": False, "state": "draining"},
                                 status=503)(environ, start_response)
                return _json({"ok": True, "state": "ready"})(
                    environ, start_response)
            if endpoint == "metrics":
                resp = Response(cp_metrics.scrape(),
                                content_type="text/plain; version=0.0.4")
                return resp(environ, start_response)
            if endpoint == "api_metrics":
                return _json({"serving": gateway.snapshot()})(
                    environ, start_response)
            body = req.get_json(force=True)
            if not isinstance(body, dict):
                raise BadRequest("body must be a JSON object")
            prompt = body.get("prompt")
            if (not isinstance(prompt, list) or not prompt
                    or not all(isinstance(t, int)
                               and 0 <= t < cfg.vocab_size
                               for t in prompt)):
                raise BadRequest("prompt must be a non-empty list of "
                                 f"token ids in [0, {cfg.vocab_size})")
            tenant = body.get("tenant") \
                or req.headers.get("X-Tenant") or "default"
            if not isinstance(tenant, str) or len(tenant) > 64:
                raise BadRequest("tenant must be a short string")
            max_new = body.get("max_new_tokens", 16)
            if not isinstance(max_new, int) or not 1 <= max_new <= 4096:
                raise BadRequest("max_new_tokens must be an int in "
                                 "[1, 4096]")
            eos_id = body.get("eos_id")
            if eos_id is not None and not isinstance(eos_id, int):
                raise BadRequest("eos_id must be an int")
            slo_class = body.get("slo_class")
            if slo_class is not None and slo_class not in (
                    "interactive", "batch", "best_effort"):
                raise BadRequest("slo_class must be one of "
                                 "interactive|batch|best_effort")
            speculative = body.get("speculative", False)
            if not isinstance(speculative, bool):
                raise BadRequest("speculative must be a bool")
            try:
                pending, reason = gateway.try_submit(
                    tenant, prompt, max_new_tokens=max_new,
                    eos_id=eos_id, slo_class=slo_class,
                    speculative=speculative)
            except ValueError as e:   # request cannot fit a slot
                raise BadRequest(str(e)) from e
            if pending is None:
                status = 429 if reason in ("rate", "tokens") else 503
                resp = _json({"error": "shed", "reason": reason},
                             status=status)
                resp.headers["Retry-After"] = "1"
                return resp(environ, start_response)
            tokens = gateway.wait(pending)
            lat_ms = (pending.t_done - pending.t_submit) * 1e3
            resp = _json({"tokens": tokens, "latency_ms": lat_ms})
        except HTTPException as e:
            resp = e
        return resp(environ, start_response)

    app.gateway = gateway
    return app
