"""Central dashboard backend.

Behavioral mirror of the reference centraldashboard's Express server
(``centraldashboard/app/server.ts:56-91``, ``api.ts:32-99``,
``api_workgroup.ts``): the navigation shell's API — namespaces,
per-namespace activity feeds (Events), cluster metrics, dashboard
links, and the workgroup (profile registration) flow the first-login
page drives. Identity arrives as the trusted ``kubeflow-userid``
header exactly as in the reference (``attach_user_middleware.ts``).

TPU differences:
- ``/api/metrics`` exposes TPU-chip utilization (requested vs
  allocatable chips per node pool) instead of GPU charts — the
  numbers come from the same prometheus collectors the controllers
  maintain (``controlplane/metrics.py``).
- env-info reports slice inventory so the dashboard can render a
  fleet view.
"""

from __future__ import annotations

from werkzeug.exceptions import BadRequest

from kubeflow_rm_tpu.controlplane.api import tpu as tpu_api
from kubeflow_rm_tpu.controlplane.api.meta import deep_get
from kubeflow_rm_tpu.controlplane.api.profile import (
    KIND as PROFILE_KIND, OWNER_ANNOTATION, make_profile,
)
from kubeflow_rm_tpu.controlplane.apiserver import APIServer
from kubeflow_rm_tpu.controlplane.webapps.core import WebApp, json_body

DEFAULT_LINKS = {
    "menuLinks": [
        {"link": "/jupyter/", "text": "Notebooks", "icon": "book"},
        {"link": "/volumes/", "text": "Volumes", "icon": "device:storage"},
        {"link": "/tensorboards/", "text": "TensorBoards",
         "icon": "assessment"},
    ],
    "externalLinks": [],
    "quickLinks": [
        {"desc": "Create a new Notebook server",
         "link": "/jupyter/new"},
    ],
    "documentationItems": [],
}


def create_app(api: APIServer, *, disable_auth: bool = False,
               prefix: str = "", links: dict | None = None,
               metrics_backend: str | None = None,
               history_interval_s: float = 10.0,
               observer=None,
               **app_kwargs) -> WebApp:
    from kubeflow_rm_tpu.controlplane.webapps.metrics_service import (
        MetricsHistory, make_metrics_service,
    )

    app = WebApp("centraldashboard", api, prefix=prefix,
                 disable_auth=disable_auth, **app_kwargs)
    links = links or DEFAULT_LINKS
    # pluggable chart data source (metrics_service_factory.ts
    # equivalent) + the ring buffer behind utilization-over-time
    metrics_svc = make_metrics_service(api, metrics_backend)
    history = MetricsHistory(metrics_svc,
                             interval_s=history_interval_s)
    app.metrics_history = history
    if observer is None:
        # TSDB + SLO engine + flight recorder over the same registry
        # the facade reads; ticked on demand from /api/alerts (no
        # thread spawned by construction — callers that want the
        # background loop call app.observer.start())
        from kubeflow_rm_tpu.controlplane import obs
        observer = obs.Observer(
            shard_urls=getattr(api, "shard_urls", None))
    app.observer = observer

    # ---- api.ts surface ---------------------------------------------
    @app.route("/api/namespaces")
    def get_namespaces(req):
        return {"namespaces": [n["metadata"]["name"]
                               for n in api.list("Namespace")]}

    @app.route("/api/activities/<namespace>")
    def get_activities(req, namespace):
        evs = sorted(api.list("Event", namespace),
                     key=lambda e: e.get("lastTimestamp") or "",
                     reverse=True)
        # "activities" is what the SPA (and the reference's api.ts
        # naming) reads; "events" kept for existing consumers
        return {"events": evs, "activities": evs}

    @app.route("/api/dashboard-links")
    def get_links(req):
        return dict(links)

    @app.route("/api/metrics")
    def get_metrics(req):
        """TPU fleet utilization: the dashboard's resource numbers
        (reference queries Prometheus/Stackdriver behind a factory —
        ``metrics_service_factory.ts``; the backend here is pluggable
        the same way, defaulting to live inventory).

        ``?profile=cpu`` (opt-in, gated on KFRM_ENABLE_PROFILING=1)
        wraps the snapshot in cProfile and returns the stats table —
        the pprof-style "why is this scrape slow" hook."""
        if req.args.get("profile") == "cpu":
            import os
            if os.environ.get("KFRM_ENABLE_PROFILING") != "1":
                from werkzeug.exceptions import Forbidden
                raise Forbidden(
                    "profiling is disabled; set KFRM_ENABLE_PROFILING=1")
            from kubeflow_rm_tpu.utils import profiling
            with profiling.profile_wsgi() as table:
                snap = metrics_svc.snapshot()
            return {"snapshot": snap, "profile": table.getvalue()}
        return metrics_svc.snapshot()

    @app.route("/api/metrics/history")
    def get_metrics_history(req):
        """Utilization over time for the dashboard charts (the
        reference's ``resource-chart.js`` interval queries; here a
        ring of snapshots sampled in-process)."""
        return {"interval_s": history.interval_s,
                "series": history.series()}

    @app.route("/api/alerts")
    def get_alerts(req):
        """The SLO engine's view: every declared objective with its
        multi-window burn rates and alert state, the active (non-ok)
        alert set, the transition log, and TSDB/flight-recorder health
        counters. Each read ticks the observer at most once per
        sampling interval, so the endpoint is live without a
        background thread."""
        observer.maybe_tick()
        return observer.alerts()

    @app.route("/api/harvest")
    def get_harvest(req):
        """The chip-harvesting picture: which notebook slices are on
        loan to the serving fleet right now (the scheduler's lease
        ledger — ground truth, present even when no controller is
        attached to this process), plus the lifetime grant/reclaim
        counters and, when a :class:`ChipHarvestController` is wired
        up via ``app.harvest``, its live lease specs."""
        from kubeflow_rm_tpu.controlplane import metrics, scheduler
        sched = scheduler.cache_for(api)
        ctl = getattr(app, "harvest", None)
        return {
            "harvested_chips": sched.harvested_chips(),
            "leases": [
                {"namespace": ns, "pod": name, "node": node,
                 "chips": chips}
                for (ns, name), (node, chips)
                in sorted(sched.harvested_entries().items())
            ],
            "controller": ctl.leases() if ctl is not None else None,
            "grants_total": metrics.registry_value(
                "harvest_grants_total") or 0.0,
            "reclaims": {
                trigger: metrics.registry_value(
                    "harvest_reclaims_total",
                    {"trigger": trigger}) or 0.0
                for trigger in ("resume", "preempt", "idle_giveback")
            },
            "reclaim_seconds_count": metrics.registry_value(
                "harvest_reclaim_seconds_count") or 0.0,
            "reclaim_seconds_sum": metrics.registry_value(
                "harvest_reclaim_seconds_sum") or 0.0,
        }

    # ---- distributed traces -----------------------------------------
    def _merged_spans():
        """This process's collector merged with every shard's
        ``/debug/traces`` export (a sharded api hops cross-process, so
        one trace's spans are scattered over the shard collectors)."""
        from kubeflow_rm_tpu.controlplane import metrics as cp_metrics
        from kubeflow_rm_tpu.controlplane import tracing
        local = tracing.collector()
        span_lists = [local.spans()]
        slow = list(local.slow_traces())
        shard_urls = getattr(api, "shard_urls", None) or {}
        if shard_urls:
            import json as _json
            import urllib.request
            for url in shard_urls.values():
                try:
                    with urllib.request.urlopen(
                            url.rstrip("/") + "/debug/traces",
                            timeout=2.0) as resp:
                        payload = _json.loads(resp.read().decode())
                except Exception:  # noqa: BLE001 - shard may be down
                    cp_metrics.swallowed("dashboard",
                                         "shard trace fetch")
                    continue
                span_lists.append(payload.get("spans") or [])
                slow.extend(payload.get("slow") or [])
        return tracing.merge_spans(*span_lists), slow

    @app.route("/api/traces")
    def list_traces(req):
        """Slow-trace index: tail-sampled root traces across every
        shard, slowest first, with span counts and the processes each
        trace crossed."""
        from kubeflow_rm_tpu.controlplane import tracing
        spans, slow = _merged_spans()
        by_trace: dict[str, list] = {}
        for s in spans:
            by_trace.setdefault(s["trace_id"], []).append(s)
        slow_index = []
        seen = set()
        for t in sorted(slow, key=lambda t: -(t.get("duration_ms") or 0)):
            if t["trace_id"] in seen:
                continue
            seen.add(t["trace_id"])
            merged = tracing.merge_spans(
                t.get("spans") or [], by_trace.get(t["trace_id"], []))
            slow_index.append({
                "trace_id": t["trace_id"],
                "duration_ms": t.get("duration_ms"),
                "spans": len(merged),
                "processes": sorted({s.get("process") or ""
                                     for s in merged}),
            })
        return {"enabled": tracing.enabled(),
                "traces": len(by_trace),
                "slow": slow_index}

    @app.route("/api/traces/<trace_id>")
    def get_trace(req, trace_id):
        """One whole trace — spans merged across shards — plus its
        critical path (the ordered blocking chain with per-hop self
        time; self_ms sums to the root span's duration)."""
        from kubeflow_rm_tpu.controlplane import tracing
        spans, slow = _merged_spans()
        mine = [s for s in spans if s["trace_id"] == trace_id]
        for t in slow:
            if t["trace_id"] == trace_id:
                mine = tracing.merge_spans(mine, t.get("spans") or [])
        if not mine:
            from werkzeug.exceptions import NotFound as HTTPNotFound
            raise HTTPNotFound(f"no spans for trace {trace_id!r}")
        mine.sort(key=lambda s: s["start"])
        return {"trace_id": trace_id,
                "spans": mine,
                "critical_path": tracing.critical_path(mine)}

    # ---- api_workgroup.ts surface -----------------------------------
    @app.route("/api/workgroup/exists")
    def workgroup_exists(req):
        user = app.username(req)
        owned = [p for p in api.list(PROFILE_KIND)
                 if deep_get(p, "spec", "owner", "name") == user]
        member_ns = _member_namespaces(api, user)
        return {
            "hasAuth": True,
            "user": user,
            "hasWorkgroup": bool(owned) or bool(member_ns),
            "registrationFlowAllowed": True,
        }

    @app.route("/api/workgroup/create", methods=("POST",))
    def workgroup_create(req):
        user = app.username(req)
        body = json_body(req)
        name = body.get("namespace")
        if not name:
            raise BadRequest("'namespace' is a required body field")
        api.create(make_profile(name, user))
        return {"message": f"Profile {name} created."}

    @app.route("/api/workgroup/env-info")
    def env_info(req):
        user = app.username(req)
        namespaces = _member_namespaces(api, user)
        slice_types = sorted({
            t.accelerator_type
            for node in api.list("Node")
            for t in [_node_slice_type(node)] if t
        })
        return {
            "user": user,
            "platform": {"kubeflowVersion": "tpu-native",
                         "provider": "gke", "providerName": "gke"},
            "namespaces": [
                {"namespace": ns, "role": role, "user": user}
                for ns, role in namespaces
            ],
            "isClusterAdmin": api.access_review(user, "*", "*"),
            "tpuSliceTypes": slice_types,
        }

    @app.route("/api/workgroup/get-all-namespaces")
    def get_all_namespaces(req):
        user = app.username(req)
        if not api.access_review(user, "*", "*"):
            from werkzeug.exceptions import Forbidden
            raise Forbidden("cluster admin required")
        out = []
        for ns in api.list("Namespace"):
            owner = (ns["metadata"].get("annotations") or {}).get(
                OWNER_ANNOTATION)
            out.append({"namespace": ns["metadata"]["name"],
                        "owner": owner})
        return {"namespaces": out}

    @app.route("/api/workgroup/get-contributors/<namespace>")
    def get_contributors(req, namespace):
        from kubeflow_rm_tpu.controlplane.webapps.kfam import (
            ROLE_ANNOTATION, USER_ANNOTATION,
        )
        out = []
        for rb in api.list("RoleBinding", namespace):
            ann = rb["metadata"].get("annotations") or {}
            if USER_ANNOTATION in ann:
                out.append({"user": ann[USER_ANNOTATION],
                            "role": ann.get(ROLE_ANNOTATION)})
        return {"contributors": out}

    # ---- the SPA (replaces centraldashboard/public + the Angular
    # frontends — VERDICT r2 missing #1) ------------------------------
    import mimetypes
    from pathlib import Path

    from werkzeug.wrappers import Response

    static_dir = Path(__file__).parent / "static"

    def _serve_static(filename: str) -> Response:
        path = (static_dir / filename).resolve()
        if not path.is_relative_to(static_dir.resolve()) \
                or not path.is_file():
            from werkzeug.exceptions import NotFound as HTTPNotFound
            raise HTTPNotFound(f"no static file {filename}")
        ctype = mimetypes.guess_type(path.name)[0] or "text/plain"
        return Response(path.read_bytes(), mimetype=ctype)

    @app.route("/", no_auth=True, no_csrf=True)
    def index(req):
        """The SPA shell; sets the CSRF double-submit cookie the way
        the reference index does (crud_backend/csrf.py)."""
        resp = _serve_static("index.html")
        app.set_csrf_cookie(resp)
        return resp

    @app.route("/static/<path:filename>", no_auth=True, no_csrf=True)
    def static_file(req, filename):
        return _serve_static(filename)

    return app


def _member_namespaces(api: APIServer, user: str | None):
    """(namespace, role) pairs where the user holds a binding — the
    dashboard's namespace selector contents."""
    out = []
    for ns in api.list("Namespace"):
        ns_name = ns["metadata"]["name"]
        owner = (ns["metadata"].get("annotations") or {}).get(
            OWNER_ANNOTATION)
        if owner == user:
            out.append((ns_name, "owner"))
            continue
        for rb in api.list("RoleBinding", ns_name):
            if any(s.get("name") == user
                   for s in rb.get("subjects") or []):
                role = deep_get(rb, "roleRef", "name", default="")
                out.append((ns_name, "contributor"
                            if "admin" not in role else "owner"))
                break
    return out


def _node_slice_type(node: dict):
    labels = node["metadata"].get("labels") or {}
    accel = labels.get(tpu_api.NODE_LABEL_ACCELERATOR)
    topo = labels.get(tpu_api.NODE_LABEL_TOPOLOGY)
    if accel and topo:
        return tpu_api.by_node_labels(accel, topo)
    return None
