/* Dashboard SPA: hash-routed vanilla ES module.
 *
 * Views consume the web-app backends exactly where the gateway mounts
 * them (manifests path routing / webapps.gateway):
 *   /api/...              central dashboard
 *   /jupyter/api/...      jupyter web app (spawner)
 *   /volumes/api/...      volumes web app
 *   /tensorboards/api/... tensorboards web app
 *   /kfam/kfam/v1/...     access management
 * CSRF: double-submit — echo the XSRF-TOKEN cookie in X-XSRF-TOKEN on
 * unsafe methods (crud_backend contract, webapps/core.py).
 */

const $ = (sel, el = document) => el.querySelector(sel);
const view = $("#view");

// ---- api client ------------------------------------------------------

function csrfToken() {
  const m = document.cookie.match(/(?:^|;\s*)XSRF-TOKEN=([^;]+)/);
  return m ? decodeURIComponent(m[1]) : "";
}

async function api(method, url, body) {
  const opts = { method, headers: {} };
  if (!["GET", "HEAD"].includes(method)) {
    opts.headers["X-XSRF-TOKEN"] = csrfToken();
  }
  if (body !== undefined) {
    opts.headers["Content-Type"] = "application/json";
    opts.body = JSON.stringify(body);
  }
  const resp = await fetch(url, opts);
  let data = {};
  try { data = await resp.json(); } catch { /* non-JSON error body */ }
  if (!resp.ok || data.success === false) {
    throw new Error(data.log || `${method} ${url}: HTTP ${resp.status}`);
  }
  return data;
}

const get = (url) => api("GET", url);
const post = (url, body) => api("POST", url, body);
const patch = (url, body) => api("PATCH", url, body);
const del = (url) => api("DELETE", url);

// ---- shared state ----------------------------------------------------

const state = {
  namespace: localStorage.getItem("ns") || null,
  namespaces: [],
  user: null,
};

let toastTimer = null;
function toast(msg, isError = false) {
  const t = $("#toast");
  t.textContent = msg;
  t.className = isError ? "error" : "";
  t.hidden = false;
  clearTimeout(toastTimer);
  toastTimer = setTimeout(() => { t.hidden = true; }, 4000);
}

function esc(s) {
  // attribute-safe escaping: quotes must be covered because esc() is
  // interpolated into double-quoted HTML attributes (title=, data-*)
  return String(s == null ? "" : s)
    .replace(/&/g, "&amp;")
    .replace(/</g, "&lt;")
    .replace(/>/g, "&gt;")
    .replace(/"/g, "&quot;")
    .replace(/'/g, "&#39;");
}

function age(ts) {
  if (!ts) return "—";
  const s = (Date.now() - new Date(ts).getTime()) / 1000;
  if (s < 90) return `${Math.max(1, Math.round(s))}s`;
  if (s < 5400) return `${Math.round(s / 60)}m`;
  if (s < 129600) return `${Math.round(s / 3600)}h`;
  return `${Math.round(s / 86400)}d`;
}

function statusCell(st) {
  const phase = (st && st.phase) || "waiting";
  const msg = (st && st.message) || "";
  return `<span class="status" title="${esc(msg)}">
    <span class="dot ${esc(phase)}"></span>${esc(phase)}</span>`;
}

// ---- resource-table controls: sort + filter --------------------------
// the reference's shared Angular resource-table ships column sorting
// and a quick text filter; same semantics here, shared by the
// notebooks / volumes / tensorboards list views. Views call
// tableControls(card, columns) once after rendering their skeleton,
// then pipe fetched items through .apply() on every render.

function qty(s) {
  // kubernetes quantity -> number, so Size columns sort by magnitude
  // (lexicographic order would put 10Gi before 5Gi)
  const m = /^([0-9.]+)([KMGTPE]i?|[kmun])?$/.exec(String(s || "").trim());
  if (!m) return 0;
  const mult = { k: 1e3, K: 1e3, M: 1e6, G: 1e9, T: 1e12, P: 1e15,
    E: 1e18, m: 1e-3, u: 1e-6, n: 1e-9,
    Ki: 2 ** 10, Mi: 2 ** 20, Gi: 2 ** 30, Ti: 2 ** 40,
    Pi: 2 ** 50, Ei: 2 ** 60 }[m[2]] || 1;
  return parseFloat(m[1]) * mult;
}

function tableControls(card, columns) {
  // columns: key -> accessor, or key -> {text, sort}. The text
  // accessor MUST return what the cell displays (the filter matches
  // against it); sort may differ (e.g. qty() for Size columns).
  const cols = {};
  for (const [k, v] of Object.entries(columns)) {
    cols[k] = typeof v === "function" ? { text: v, sort: v }
      : { text: v.text, sort: v.sort || v.text };
  }
  const tc = { sortKey: null, dir: 1, q: "", onchange: null };
  const input = document.createElement("input");
  input.type = "search";
  input.placeholder = "filter…";
  input.className = "table-filter";
  card.querySelector("table").before(input);
  input.addEventListener("input", () => {
    tc.q = input.value.toLowerCase();
    if (tc.onchange) tc.onchange();
  });
  const thead = card.querySelector("thead");
  thead.addEventListener("click", (ev) => {
    const th = ev.target.closest("th[data-sort]");
    if (!th) return;
    const key = th.dataset.sort;
    if (tc.sortKey === key) tc.dir = -tc.dir;
    else { tc.sortKey = key; tc.dir = 1; }
    for (const h of thead.querySelectorAll("th[data-sort]")) {
      h.textContent = h.textContent.replace(/ [▲▼]$/, "");
      if (h.dataset.sort === tc.sortKey) {
        h.textContent += tc.dir > 0 ? " ▲" : " ▼";
      }
    }
    if (tc.onchange) tc.onchange();
  });
  tc.apply = (items) => {
    let out = items;
    if (tc.q) {
      out = out.filter((it) => Object.values(cols).some((c) =>
        String(c.text(it) ?? "").toLowerCase().includes(tc.q)));
    }
    if (tc.sortKey) {
      const acc = cols[tc.sortKey].sort;
      out = [...out].sort((a, b) => {
        const va = acc(a) ?? "", vb = acc(b) ?? "";
        return (va > vb ? 1 : va < vb ? -1 : 0) * tc.dir;
      });
    }
    return out;
  };
  return tc;
}

// ---- router ----------------------------------------------------------

const routes = [];
function route(pattern, render) { routes.push({ pattern, render }); }

let activeTimers = [];
function every(ms, fn) { activeTimers.push(setInterval(fn, ms)); }

async function navigate() {
  activeTimers.forEach(clearInterval);
  activeTimers = [];
  const hash = location.hash.replace(/^#/, "") || "/home";
  for (const a of document.querySelectorAll("#nav a")) {
    a.classList.toggle("active", hash.startsWith(a.hash.replace(/^#/, "")));
  }
  for (const { pattern, render } of routes) {
    const m = hash.match(pattern);
    if (m) {
      try {
        await render(...m.slice(1));
      } catch (e) {
        view.innerHTML = `<div class="card">${esc(e.message)}</div>`;
      }
      return;
    }
  }
  location.hash = "#/home";
}

// ---- boot: namespaces ------------------------------------------------

async function loadNamespaces() {
  const data = await get("/jupyter/api/namespaces");
  state.user = data.user;
  state.namespaces = data.namespaces;
  if (!state.namespace || !data.namespaces.includes(state.namespace)) {
    state.namespace = data.namespaces.find((n) => !n.startsWith("kube")) ||
      data.namespaces[0];
  }
  const sel = $("#ns-select");
  sel.innerHTML = state.namespaces
    .map((n) => `<option ${n === state.namespace ? "selected" : ""}>${esc(n)}</option>`)
    .join("");
  sel.onchange = () => {
    state.namespace = sel.value;
    localStorage.setItem("ns", sel.value);
    navigate();
  };
  $("#whoami").textContent = data.user || "";
}

// ---- home ------------------------------------------------------------

// One single-series line panel: 2px line, recessive grid, crosshair +
// tooltip on hover, optional dashed reference line with a direct
// label, and a <details> data table for the no-color/screen-reader
// path. One y-axis per panel — two measures of different scale get
// two panels, never a dual axis.
function lineChart(el, pts, { value, refValue, refLabel, unit }) {
  const W = 520, H = 120, PX = 34, PY = 10;
  const xs = pts.map((p) => p.t);
  const ys = pts.map(value);
  const ref = refValue ? refValue(pts[pts.length - 1]) : null;
  const yMax = Math.max(1, ...ys.filter((v) => v != null),
                        ref ?? 0) * 1.1;
  const x0 = xs[0], x1 = xs[xs.length - 1] || x0 + 1;
  const sx = (t) => PX + (W - PX - 6) *
    (x1 === x0 ? 1 : (t - x0) / (x1 - x0));
  const sy = (v) => H - PY - (H - 2 * PY) * (v / yMax);
  const path = pts
    .map((p, i) => `${i ? "L" : "M"}${sx(p.t).toFixed(1)},` +
                   `${sy(value(p) || 0).toFixed(1)}`)
    .join(" ");
  const gridY = [0.5, 1].map((f) => {
    const v = yMax * f / 1.1;
    return `<line class="grid" x1="${PX}" x2="${W - 6}"
        y1="${sy(v)}" y2="${sy(v)}"></line>
      <text class="tick" x="${PX - 4}" y="${sy(v) + 3}">` +
      `${Math.round(v)}</text>`;
  }).join("");
  const refLine = ref == null ? "" :
    `<line class="ref" x1="${PX}" x2="${W - 6}" y1="${sy(ref)}"
        y2="${sy(ref)}"></line>
     <text class="ref-label" x="${W - 8}" y="${sy(ref) - 3}">` +
     `${esc(refLabel)} ${Math.round(ref)}</text>`;
  // a one-point series has no line extent: draw the point itself so
  // a just-booted dashboard shows data, not a blank panel
  const seed = pts.length === 1
    ? `<circle class="seed" cx="${sx(xs[0])}" cy="${sy(ys[0] || 0)}"
         r="3.5"></circle>` : "";
  el.innerHTML = `
    <svg viewBox="0 0 ${W} ${H}" class="tschart" role="img">
      ${gridY}${refLine}
      <path class="series" d="${path}"></path>${seed}
      <line class="xhair" y1="${PY}" y2="${H - PY}" hidden></line>
      <circle class="dot" r="3.5" hidden></circle>
    </svg>
    <div class="tooltip" hidden></div>
    <details class="chart-data"><summary>data</summary>
      <table><tbody>${pts.slice(-12).map((p) =>
        `<tr><td>${new Date(p.t * 1e3).toLocaleTimeString()}</td>` +
        `<td>${value(p) ?? "–"} ${esc(unit)}</td></tr>`).join("")}
      </tbody></table></details>`;
  const svg = el.querySelector("svg");
  const tip = el.querySelector(".tooltip");
  const xhair = el.querySelector(".xhair");
  const dot = el.querySelector(".dot");
  svg.addEventListener("mousemove", (ev) => {
    const r = svg.getBoundingClientRect();
    const t = x0 + (x1 - x0) *
      ((ev.clientX - r.left) / r.width * W - PX) / (W - PX - 6);
    let best = pts[0];
    for (const p of pts) {
      if (Math.abs(p.t - t) < Math.abs(best.t - t)) best = p;
    }
    const cx = sx(best.t), cy = sy(value(best) || 0);
    xhair.setAttribute("x1", cx); xhair.setAttribute("x2", cx);
    xhair.hidden = false;
    dot.setAttribute("cx", cx); dot.setAttribute("cy", cy);
    dot.hidden = false;
    tip.hidden = false;
    tip.textContent = `${new Date(best.t * 1e3).toLocaleTimeString()}` +
      ` · ${value(best) ?? "–"} ${unit}`;
    tip.style.left = `${Math.min(cx / W * 100, 70)}%`;
  });
  svg.addEventListener("mouseleave", () => {
    tip.hidden = true; xhair.hidden = true; dot.hidden = true;
  });
}

route(/^\/home$/, async () => {
  const ns = state.namespace;
  const [links, metrics, activities] = await Promise.all([
    get("/api/dashboard-links"),
    get("/api/metrics"),
    get(`/api/activities/${ns}`).catch(() => ({ activities: [] })),
  ]);
  const m = metrics.metrics || {};
  view.innerHTML = `
    <div class="card">
      <h2>TPU fleet</h2>
      <p class="sub">Live accelerator inventory</p>
      <div class="row">
        <span class="pill">${esc(m.nodes ?? "–")} TPU nodes</span>
        <span class="pill">${esc(m.chips_capacity ?? "–")} chips capacity</span>
        <span class="pill">${esc(m.chips_requested ?? "–")} chips in use</span>
        <span class="pill">${esc(m.notebooks_running ?? "–")} notebooks running</span>
      </div>
      <div class="charts">
        <div class="chart-panel">
          <h3>TPU chips in use</h3>
          <div id="chart-chips" class="chart"></div>
        </div>
        <div class="chart-panel">
          <h3>Notebooks running</h3>
          <div id="chart-notebooks" class="chart"></div>
        </div>
      </div>
    </div>
    <div class="card quick-links">
      <h2>Quick shortcuts</h2>
      ${(links.links?.quickLinks || [])
        .map((l) => `<a href="#/notebooks/new">${esc(l.desc)}</a>`)
        .join("") || '<a href="#/notebooks/new">Create a new Notebook server</a>'}
    </div>
    <div class="card">
      <h2>Recent activity <span class="pill">${esc(ns)}</span></h2>
      <table><tbody id="act"></tbody></table>
    </div>`;
  try {
    const hist = await get("/api/metrics/history");
    const pts = hist.series || [];
    if (pts.length) {
      lineChart($("#chart-chips"), pts, {
        value: (p) => p.chips_used,
        refValue: (p) => p.chips_capacity, refLabel: "capacity",
        unit: "chips",
      });
      lineChart($("#chart-notebooks"), pts, {
        value: (p) => p.notebooks_running, unit: "notebooks",
      });
    }
  } catch { /* charts are progressive enhancement */ }
  $("#act").innerHTML = (activities.activities || [])
    .slice(0, 12)
    .map((e) => `<tr>
        <td>${esc(e.type)}</td><td>${esc(e.reason)}</td>
        <td>${esc(e.involvedObject?.kind)}/${esc(e.involvedObject?.name)}</td>
        <td>${esc(e.message)}</td>
        <td>${age(e.lastTimestamp)}</td></tr>`)
    .join("") || `<tr><td class="empty">No recent events</td></tr>`;
});

// ---- notebooks table -------------------------------------------------

route(/^\/notebooks$/, async () => {
  const ns = state.namespace;
  view.innerHTML = `
    <div class="card">
      <div class="row" style="justify-content: space-between">
        <div><h2>Notebook servers</h2>
          <p class="sub">TPU slices in <b>${esc(ns)}</b></p></div>
        <button class="primary" id="new-nb">+ New Notebook</button>
      </div>
      <table>
        <thead><tr><th data-sort="status">Status</th>
          <th data-sort="name">Name</th><th data-sort="image">Image</th>
          <th data-sort="tpu">TPU slice</th><th data-sort="age">Age</th>
          <th></th></tr></thead>
        <tbody id="nb-rows"></tbody>
      </table>
    </div>`;
  $("#new-nb").onclick = () => { location.hash = "#/notebooks/new"; };

  const tpuText = (nb) => nb.tpu
    ? `${nb.tpu.acceleratorType} · ${nb.tpu.chips} chips / ${nb.tpu.hosts} hosts`
    : "none";
  const tc = tableControls(view.querySelector(".card"), {
    status: (nb) => nb.status?.phase || "",
    name: (nb) => nb.name,
    image: (nb) => (nb.image || "").split("/").pop(),
    tpu: tpuText,
    age: { text: (nb) => age(nb.age), sort: (nb) => nb.age || "" },
  });
  let items = [];
  tc.onchange = () => render();

  async function refresh() {
    const data = await get(`/jupyter/api/namespaces/${ns}/notebooks`);
    items = data.notebooks;
    render();
  }

  function render() {
    const rows = tc.apply(items).map((nb) => {
      const stopped = nb.status?.phase === "stopped";
      const tpu = tpuText(nb);
      return `<tr class="clickable" data-name="${esc(nb.name)}">
        <td>${statusCell(nb.status)}</td>
        <td><b>${esc(nb.name)}</b></td>
        <td title="${esc(nb.image)}">${esc((nb.image || "").split("/").pop())}</td>
        <td>${esc(tpu)}</td>
        <td>${age(nb.age)}</td>
        <td class="actions">
          <a class="btn" data-act="connect"
             href="/notebook/${esc(ns)}/${esc(nb.name)}/"
             target="_blank" ${nb.status?.phase !== "ready" ? "hidden" : ""}>Connect</a>
          <button data-act="${stopped ? "start" : "stop"}">${stopped ? "Start" : "Stop"}</button>
          <button data-act="delete" class="danger">Delete</button>
        </td></tr>`;
    });
    $("#nb-rows").innerHTML = rows.join("") ||
      `<tr><td colspan="6" class="empty">No notebooks yet — create one.</td></tr>`;
  }

  $("#nb-rows").onclick = async (ev) => {
    const row = ev.target.closest("tr[data-name]");
    if (!row) return;
    const name = row.dataset.name;
    const act = ev.target.dataset.act;
    if (act === "connect") return; // the <a> handles it
    try {
      if (act === "stop") {
        await patch(`/jupyter/api/namespaces/${ns}/notebooks/${name}`, { stopped: true });
        toast(`Stopping ${name}`);
      } else if (act === "start") {
        await patch(`/jupyter/api/namespaces/${ns}/notebooks/${name}`, { stopped: false });
        toast(`Starting ${name}`);
      } else if (act === "delete") {
        if (!confirm(`Delete notebook ${name}?`)) return;
        await del(`/jupyter/api/namespaces/${ns}/notebooks/${name}`);
        toast(`Deleted ${name}`);
      } else {
        location.hash = `#/notebooks/${name}`;
        return;
      }
      await refresh();
    } catch (e) { toast(e.message, true); }
  };

  await refresh();
  every(3000, () => refresh().catch(() => {}));
});

// ---- spawner form ----------------------------------------------------

route(/^\/notebooks\/new$/, async () => {
  const ns = state.namespace;
  const [cfgData, tpuData, pdData, pvcData] = await Promise.all([
    get("/jupyter/api/config"),
    get("/jupyter/api/tpus"),
    get(`/jupyter/api/namespaces/${ns}/poddefaults`).catch(() => ({ poddefaults: [] })),
    get(`/jupyter/api/namespaces/${ns}/pvcs`).catch(() => ({ pvcs: [] })),
  ]);
  const cfg = cfgData.config || {};
  const poddefaults = pdData.poddefaults || [];
  const existingPvcs = (pvcData.pvcs || []).map((p) => p.metadata.name);
  const field = (k) => cfg[k] || {};
  const ro = (k) => (field(k).readOnly ? "disabled" : "");
  // per-server-type image field (backend set_image contract)
  const imageFieldFor = (st) => ({
    "group-one": "imageGroupOne",
    "group-two": "imageGroupTwo",
  }[st] || "image");
  const imageOpts = field("image").options || [];
  const tpus = tpuData.tpus || [];

  view.innerHTML = `
    <div class="card">
      <h2>New notebook server</h2>
      <p class="sub">Namespace <b>${esc(ns)}</b> — chips, hosts and node
        selectors derive from the slice preset server-side.</p>
      <form id="spawn">
        <div class="field">
          <label for="f-name">Name</label>
          <input type="text" id="f-name" required
                 pattern="[a-z0-9]([-a-z0-9]*[a-z0-9])?"
                 placeholder="my-notebook">
        </div>
        <div class="grid2">
          <div class="field">
            <label for="f-image">Image</label>
            <select id="f-image" ${ro("image")}>
              ${imageOpts.map((o) => `<option ${o === field("image").value ? "selected" : ""}>${esc(o)}</option>`).join("")}
            </select>
            ${field("image").readOnly ? '<p class="hint">Pinned by your admin</p>' : ""}
          </div>
          <div class="field">
            <label for="f-servertype">Server type</label>
            <select id="f-servertype" ${ro("serverType")}>
              ${["jupyter", "group-one", "group-two"].map((st) =>
                `<option ${field("serverType").value === st ? "selected" : ""}>${st}</option>`).join("")}
            </select>
            <p class="hint">jupyter · group-one = VSCode · group-two = RStudio</p>
          </div>
          <div class="field">
            <label for="f-cpu">CPU</label>
            <input type="text" id="f-cpu" value="${esc(field("cpu").value || "4")}" ${ro("cpu")}>
          </div>
          <div class="field">
            <label for="f-memory">Memory</label>
            <input type="text" id="f-memory" value="${esc(field("memory").value || "16Gi")}" ${ro("memory")}>
          </div>
        </div>
        <div class="field">
          <label>TPU slice</label>
          <div class="slice-picker" id="f-tpus">
            <span class="slice-chip selected" data-accel="none">none</span>
            ${tpus.map((t) => `<span class="slice-chip" data-accel="${esc(t.acceleratorType)}"
                title="topology ${esc(t.topology)}">${esc(t.acceleratorType)}
                · ${esc(t.chips)} chips / ${esc(t.hosts)} hosts</span>`).join("")}
          </div>
          <p class="hint">Only slice types present in the cluster inventory are offered.</p>
          <div class="row" id="f-multislice" hidden>
            <label for="f-numslices">Slices (DCN-joined)</label>
            <input type="number" id="f-numslices" value="1" min="1"
                   max="16" style="width: 5em">
          </div>
        </div>
        <div class="field">
          <label><input type="checkbox" id="f-workspace" checked>
            Create a workspace volume (5Gi, mounted at /home/jovyan)</label>
        </div>
        <details class="field">
          <summary>Advanced options</summary>
          ${poddefaults.length ? `
          <div class="field">
            <label>Configurations (PodDefaults)</label>
            ${poddefaults.map((pd) => {
              const key = Object.keys(pd.label || {})[0];
              return key ? `<label class="inline">
                <input type="checkbox" class="f-poddefault"
                       value="${esc(key)}"> ${esc(pd.desc)}</label>` : "";
            }).join("")}
          </div>` : ""}
          <div class="field">
            <label for="f-datavols">Data volumes</label>
            <div id="f-datavols"></div>
            <button type="button" class="btn" id="f-addvol">+ Attach volume</button>
            <p class="hint">Mount an existing PVC or create a new one per row.</p>
          </div>
          <div class="grid2">
            <div class="field">
              <label for="f-tolerations">Tolerations</label>
              <select id="f-tolerations" ${ro("tolerationGroup")}>
                ${(field("tolerationGroup").options || [{ groupKey: "none", displayName: "No toleration" }])
                  .map((g) => `<option value="${esc(g.groupKey)}"
                    ${g.groupKey === field("tolerationGroup").value ? "selected" : ""}>
                    ${esc(g.displayName || g.groupKey)}</option>`).join("")}
              </select>
            </div>
            <div class="field">
              <label for="f-affinity">Affinity</label>
              <select id="f-affinity" ${ro("affinityConfig")}>
                <option value="none">none</option>
                ${(field("affinityConfig").options || [])
                  .map((a) => `<option value="${esc(a.configKey)}"
                    ${a.configKey === field("affinityConfig").value ? "selected" : ""}>
                    ${esc(a.displayName || a.configKey)}</option>`).join("")}
              </select>
            </div>
          </div>
          <div class="field">
            <label for="f-env">Environment variables (KEY=VALUE, one per line)</label>
            <textarea id="f-env" rows="3" placeholder="HF_HOME=/home/jovyan/.cache"></textarea>
          </div>
          <div class="field">
            <label><input type="checkbox" id="f-shm"
              ${field("shm").value === false ? "" : "checked"} ${ro("shm")}>
              Mount /dev/shm (Memory-backed)</label>
          </div>
        </details>
        <div class="row">
          <button type="submit" class="primary">Launch</button>
          <a class="btn" href="#/notebooks">Cancel</a>
        </div>
      </form>
    </div>`;

  // data-volume rows: existing-PVC picker or new-PVC spec
  const volRows = [];
  $("#f-addvol").onclick = () => {
    const idx = volRows.length;
    const row = document.createElement("div");
    row.className = "row volrow";
    row.innerHTML = `
      <select class="v-src">
        <option value="">new volume…</option>
        ${existingPvcs.map((p) => `<option>${esc(p)}</option>`).join("")}
      </select>
      <input class="v-name" placeholder="name" value="{notebook-name}-vol-${idx}">
      <input class="v-size" placeholder="size" value="5Gi" size="5">
      <input class="v-mount" placeholder="mount" value="/home/jovyan/data-${idx}">
      <button type="button" class="btn v-del">✕</button>`;
    const sync = () => {
      const isNew = !row.querySelector(".v-src").value;
      row.querySelector(".v-name").hidden = !isNew;
      row.querySelector(".v-size").hidden = !isNew;
    };
    row.querySelector(".v-src").onchange = sync;
    row.querySelector(".v-del").onclick = () => {
      volRows.splice(volRows.indexOf(row), 1);
      row.remove();
    };
    $("#f-datavols").appendChild(row);
    volRows.push(row);
    sync();
  };

  // server type drives which image list the dropdown offers
  $("#f-servertype").onchange = () => {
    const f = field(imageFieldFor($("#f-servertype").value));
    $("#f-image").innerHTML = (f.options || [])
      .map((o) => `<option ${o === f.value ? "selected" : ""}>${esc(o)}</option>`)
      .join("");
  };

  let accel = "none";
  $("#f-tpus").onclick = (ev) => {
    const chip = ev.target.closest(".slice-chip");
    if (!chip) return;
    accel = chip.dataset.accel;
    for (const c of document.querySelectorAll(".slice-chip")) {
      c.classList.toggle("selected", c === chip);
    }
    $("#f-multislice").hidden = accel === "none";
  };

  $("#spawn").onsubmit = async (ev) => {
    ev.preventDefault();
    const name = $("#f-name").value.trim();
    const serverType = $("#f-servertype").value;
    const environment = {};
    for (const line of $("#f-env").value.split("\n")) {
      const m = line.match(/^\s*([^=\s]+)\s*=\s*(.*)$/);
      if (m) environment[m[1]] = m[2];
    }
    const datavols = volRows.map((row) => {
      const src = row.querySelector(".v-src").value;
      const mount = row.querySelector(".v-mount").value;
      if (src) {
        return { mount, existingSource: {
          persistentVolumeClaim: { claimName: src } } };
      }
      return { mount, newPvc: {
        metadata: { name: row.querySelector(".v-name").value },
        spec: {
          resources: { requests: {
            storage: row.querySelector(".v-size").value } },
          accessModes: ["ReadWriteOnce"],
        } } };
    });
    const body = {
      name,
      [imageFieldFor(serverType)]: $("#f-image").value,
      imagePullPolicy: "IfNotPresent",
      serverType,
      cpu: $("#f-cpu").value,
      memory: $("#f-memory").value,
      tpu: accel === "none" ? null : {
        acceleratorType: accel,
        numSlices: parseInt($("#f-numslices").value, 10) || 1,
      },
      tolerationGroup: $("#f-tolerations").value,
      affinityConfig: $("#f-affinity").value,
      configurations: [...document.querySelectorAll(".f-poddefault:checked")]
        .map((el) => el.value),
      shm: $("#f-shm").checked,
      environment,
      datavols,
    };
    if ($("#f-workspace").checked) {
      body.workspace = {
        mount: "/home/jovyan",
        newPvc: {
          metadata: { name: "{notebook-name}-workspace" },
          spec: {
            resources: { requests: { storage: "5Gi" } },
            accessModes: ["ReadWriteOnce"],
          },
        },
      };
    }
    try {
      await post(`/jupyter/api/namespaces/${ns}/notebooks`, body);
      toast(`Notebook ${name} created`);
      location.hash = "#/notebooks";
    } catch (e) { toast(e.message, true); }
  };
});

// ---- notebook detail: status ladder, events, per-ordinal logs --------

route(/^\/notebooks\/([a-z0-9][-a-z0-9]*)$/, async (name) => {
  const ns = state.namespace;

  view.innerHTML = `
    <div class="card">
      <div class="row" style="justify-content: space-between">
        <h2>${esc(name)} <span id="d-status"></span></h2>
        <a class="btn" href="#/notebooks">← Back</a>
      </div>
      <dl class="kv" id="d-kv"></dl>
    </div>
    <div class="card">
      <h2>Slice pods</h2>
      <p class="sub">One pod per TPU host; click to inspect its logs.</p>
      <div class="tabs" id="d-pods"></div>
      <div class="logbox" id="d-logs">select a pod</div>
    </div>
    <div class="card">
      <h2>Events</h2>
      <table><thead><tr><th>Type</th><th>Reason</th><th>Message</th>
        <th>Age</th></tr></thead><tbody id="d-events"></tbody></table>
    </div>`;

  let currentPod = null;

  async function refreshDetail() {
    const data = await get(`/jupyter/api/namespaces/${ns}/notebooks/${name}`);
    const nb = data.notebook;
    $("#d-status").innerHTML = statusCell(nb.processed_status);
    const tpu = nb.spec?.tpu || {};
    $("#d-kv").innerHTML = `
      <dt>Image</dt><dd>${esc(nb.spec?.template?.spec?.containers?.[0]?.image)}</dd>
      <dt>TPU slice</dt><dd>${esc(tpu.acceleratorType || "none")}</dd>
      <dt>Ready / desired hosts</dt>
      <dd>${esc(nb.status?.readyReplicas ?? 0)} / ${esc(nb.status?.desiredReplicas ?? 0)}</dd>
      <dt>Conditions</dt>
      <dd>${(nb.status?.conditions || []).map((c) => `${esc(c.type)}=${esc(c.status)}`).join(", ") || "—"}</dd>
      <dt>Connect</dt>
      <dd><a href="/notebook/${esc(ns)}/${esc(name)}/" target="_blank">/notebook/${esc(ns)}/${esc(name)}/</a></dd>`;
  }

  async function refreshPods() {
    const data = await get(`/jupyter/api/namespaces/${ns}/notebooks/${name}/pods`);
    $("#d-pods").innerHTML = data.pods
      .map((p, i) => `<button data-pod="${i}" class="${p.name === currentPod ? "active" : ""}">
         ${esc(p.name)} · ${esc(p.phase || "Pending")}</button>`)
      .join("") || '<span class="empty">no pods yet</span>';
    if (!currentPod && data.pods.length) {
      currentPod = data.pods[0].name;
      await refreshLogs();
    }
  }

  async function refreshLogs() {
    if (!currentPod) return;
    const ordinal = currentPod.split("-").pop();
    const data = await get(
      `/jupyter/api/namespaces/${ns}/notebooks/${name}/pods/${ordinal}/logs`);
    $("#d-logs").textContent = data.logs.join("\n") || "(no output yet)";
  }

  async function refreshEvents() {
    const data = await get(`/jupyter/api/namespaces/${ns}/notebooks/${name}/events`);
    $("#d-events").innerHTML = data.events
      .map((e) => `<tr><td>${esc(e.type)}</td><td>${esc(e.reason)}</td>
           <td>${esc(e.message)}</td><td>${age(e.lastTimestamp)}</td></tr>`)
      .join("") || `<tr><td colspan="4" class="empty">No events</td></tr>`;
  }

  $("#d-pods").onclick = async (ev) => {
    const b = ev.target.closest("button[data-pod]");
    if (!b) return;
    currentPod = b.textContent.trim().split(" ")[0].replace(/·.*/, "").trim();
    for (const x of document.querySelectorAll("#d-pods button")) {
      x.classList.toggle("active", x === b);
    }
    await refreshLogs();
  };

  await Promise.all([refreshDetail(), refreshPods(), refreshEvents()]);
  every(3000, () => Promise.all(
    [refreshDetail(), refreshPods(), refreshLogs(), refreshEvents()],
  ).catch(() => {}));
});

// ---- volumes ---------------------------------------------------------

route(/^\/volumes$/, async () => {
  const ns = state.namespace;
  view.innerHTML = `
    <div class="card">
      <h2>Volumes</h2>
      <p class="sub">PersistentVolumeClaims in <b>${esc(ns)}</b></p>
      <table>
        <thead><tr><th data-sort="name">Name</th>
          <th data-sort="size">Size</th><th data-sort="access">Access</th>
          <th data-sort="usedby">Used by</th><th>Viewer</th><th></th>
        </tr></thead>
        <tbody id="pvc-rows"></tbody>
      </table>
    </div>`;

  const tc = tableControls(view.querySelector(".card"), {
    name: (r) => r.pvc.metadata.name,
    size: { text: (r) => r.pvc.spec?.resources?.requests?.storage || "",
            sort: (r) => qty(r.pvc.spec?.resources?.requests?.storage) },
    access: (r) => (r.pvc.spec?.accessModes || []).join(","),
    usedby: (r) => r.inUseBy.join(", ") || "—",
  });
  let items = [];
  tc.onchange = () => render();

  async function refresh() {
    const data = await get(`/volumes/api/namespaces/${ns}/pvcs`);
    items = data.pvcs;
    render();
  }

  function render() {
    $("#pvc-rows").innerHTML = tc.apply(items)
      .map((row) => {
        const pvc = row.pvc;
        const name = pvc.metadata.name;
        return `<tr data-name="${esc(name)}">
          <td><b>${esc(name)}</b></td>
          <td>${esc(pvc.spec?.resources?.requests?.storage)}</td>
          <td>${esc((pvc.spec?.accessModes || []).join(","))}</td>
          <td>${esc(row.inUseBy.join(", ") || "—")}</td>
          <td>${row.viewer ? esc(row.viewer) : "—"}</td>
          <td class="actions">
            <button data-act="browse">${row.viewer ? "Close browser" : "Browse"}</button>
            <button data-act="delete" class="danger"
              ${row.inUseBy.length ? "disabled title='in use'" : ""}>Delete</button>
          </td></tr>`;
      })
      .join("") || `<tr><td colspan="6" class="empty">No volumes</td></tr>`;
  }

  $("#pvc-rows").onclick = async (ev) => {
    const row = ev.target.closest("tr[data-name]");
    const act = ev.target.dataset.act;
    if (!row || !act) return;
    const name = row.dataset.name;
    try {
      if (act === "browse") {
        const hasViewer = ev.target.textContent.includes("Close");
        if (hasViewer) {
          await del(`/volumes/api/namespaces/${ns}/viewers/${name}`);
          toast("Viewer deleted");
        } else {
          await post(`/volumes/api/namespaces/${ns}/viewers/${name}`);
          toast("Viewer starting — it appears in the table when ready");
        }
      } else if (act === "delete") {
        if (!confirm(`Delete PVC ${name}?`)) return;
        await del(`/volumes/api/namespaces/${ns}/pvcs/${name}`);
        toast(`Deleted ${name}`);
      }
      await refresh();
    } catch (e) { toast(e.message, true); }
  };

  await refresh();
  every(4000, () => refresh().catch(() => {}));
});

// ---- tensorboards ----------------------------------------------------

route(/^\/tensorboards$/, async () => {
  const ns = state.namespace;
  view.innerHTML = `
    <div class="card">
      <h2>Tensorboards</h2>
      <p class="sub">Serving from PVC or GCS log dirs in <b>${esc(ns)}</b></p>
      <table>
        <thead><tr><th data-sort="status">Status</th>
          <th data-sort="name">Name</th><th data-sort="logspath">Logspath</th>
          <th data-sort="age">Age</th><th></th></tr></thead>
        <tbody id="tb-rows"></tbody>
      </table>
    </div>
    <div class="card">
      <h2>New tensorboard</h2>
      <form id="tb-form" class="row">
        <input type="text" id="tb-name" placeholder="name" required
               pattern="[a-z0-9]([-a-z0-9]*[a-z0-9])?">
        <input type="text" id="tb-logspath" required
               placeholder="pvc://my-pvc/logs or gs://bucket/dir">
        <button type="submit" class="primary">Create</button>
      </form>
    </div>`;

  const tc = tableControls(view.querySelector(".card"), {
    status: (tb) => tb.status?.phase || "",
    name: (tb) => tb.name,
    logspath: (tb) => tb.logspath || "",
    age: { text: (tb) => age(tb.age), sort: (tb) => tb.age || "" },
  });
  let items = [];
  tc.onchange = () => render();

  async function refresh() {
    const data = await get(`/tensorboards/api/namespaces/${ns}/tensorboards`);
    items = data.tensorboards;
    render();
  }

  function render() {
    $("#tb-rows").innerHTML = tc.apply(items)
      .map((tb) => `<tr data-name="${esc(tb.name)}">
          <td>${statusCell(tb.status)}</td>
          <td><b>${esc(tb.name)}</b></td>
          <td>${esc(tb.logspath)}</td>
          <td>${age(tb.age)}</td>
          <td class="actions">
            <button data-act="delete" class="danger">Delete</button>
          </td></tr>`)
      .join("") || `<tr><td colspan="5" class="empty">No tensorboards</td></tr>`;
  }

  $("#tb-rows").onclick = async (ev) => {
    const row = ev.target.closest("tr[data-name]");
    if (!row || ev.target.dataset.act !== "delete") return;
    try {
      await del(`/tensorboards/api/namespaces/${ns}/tensorboards/${row.dataset.name}`);
      toast("Deleted");
      await refresh();
    } catch (e) { toast(e.message, true); }
  };

  $("#tb-form").onsubmit = async (ev) => {
    ev.preventDefault();
    try {
      await post(`/tensorboards/api/namespaces/${ns}/tensorboards`, {
        name: $("#tb-name").value.trim(),
        logspath: $("#tb-logspath").value.trim(),
      });
      toast("Tensorboard created");
      await refresh();
    } catch (e) { toast(e.message, true); }
  };

  await refresh();
  every(4000, () => refresh().catch(() => {}));
});

// ---- members (KFAM) --------------------------------------------------

route(/^\/members$/, async () => {
  const ns = state.namespace;
  view.innerHTML = `
    <div class="card">
      <h2>Contributors <span class="pill">${esc(ns)}</span></h2>
      <table>
        <thead><tr><th>User</th><th>Role</th><th></th></tr></thead>
        <tbody id="mb-rows"></tbody>
      </table>
    </div>
    <div class="card">
      <h2>Add contributor</h2>
      <form id="mb-form" class="row">
        <input type="text" id="mb-user" placeholder="user@example.com" required>
        <select id="mb-role"><option>edit</option><option>view</option></select>
        <button type="submit" class="primary">Add</button>
      </form>
    </div>`;

  async function refresh() {
    const data = await get(`/kfam/kfam/v1/bindings?namespace=${ns}`);
    $("#mb-rows").innerHTML = (data.bindings || [])
      .map((b) => `<tr data-user="${esc(b.user?.name)}" data-role="${esc(b.roleRef?.name)}">
          <td>${esc(b.user?.name)}</td>
          <td>${esc(b.roleRef?.name)}</td>
          <td class="actions"><button data-act="remove" class="danger">Remove</button></td>
        </tr>`)
      .join("") || `<tr><td colspan="3" class="empty">No contributors</td></tr>`;
  }

  $("#mb-rows").onclick = async (ev) => {
    const row = ev.target.closest("tr[data-user]");
    if (!row || ev.target.dataset.act !== "remove") return;
    try {
      await api("DELETE", "/kfam/kfam/v1/bindings", {
        user: { kind: "User", name: row.dataset.user },
        referredNamespace: ns,
        roleRef: { kind: "ClusterRole", name: row.dataset.role },
      });
      toast("Contributor removed");
      await refresh();
    } catch (e) { toast(e.message, true); }
  };

  $("#mb-form").onsubmit = async (ev) => {
    ev.preventDefault();
    try {
      await post("/kfam/kfam/v1/bindings", {
        user: { kind: "User", name: $("#mb-user").value.trim() },
        referredNamespace: ns,
        roleRef: { kind: "ClusterRole",
                   name: $("#mb-role").value === "view" ? "view" : "edit" },
      });
      toast("Contributor added");
      await refresh();
    } catch (e) { toast(e.message, true); }
  };

  await refresh();
});

// ---- boot ------------------------------------------------------------

window.addEventListener("hashchange", navigate);
loadNamespaces()
  .then(navigate)
  .catch((e) => { view.innerHTML = `<div class="card">${esc(e.message)}</div>`; });
