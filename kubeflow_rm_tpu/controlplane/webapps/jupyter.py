"""Jupyter web app backend — the TPU-slice spawner.

Behavioral mirror of the reference JWA backend
(``crud-web-apps/jupyter/backend``): the spawner-config contract with
``{value, readOnly, options}`` enforced server-side (``form.py:15-59``),
form→Notebook-CR assembly (``form.py:74-299``,
``routes/post.py:12-75``), start/stop via the stop annotation, the
status ladder, and accelerator discovery — where the reference
intersects node capacity keys with configured GPU vendor limitsKeys
(``routes/get.py:101-126``), ``/api/tpus`` intersects the config's
slice presets with the cluster's live TPU node inventory, so the
picker only offers obtainable slices.

TPU differences by design:
- one ``tpu.acceleratorType`` field replaces {vendor, num}: chips,
  hosts, nodeSelectors, and rendezvous env are derived downstream
  (controller + webhook), never chosen by the user;
- ``/dev/shm`` stays (reference ``form.py:264-276``) for host-local
  torch dataloaders, but TPU collectives ride ICI — no NCCL.
"""

from __future__ import annotations

import copy
import threading

import yaml
from werkzeug.exceptions import BadRequest, NotFound

from kubeflow_rm_tpu.controlplane.api import notebook as nb_api
from kubeflow_rm_tpu.controlplane.api import tpu as tpu_api
from kubeflow_rm_tpu.controlplane.api.meta import (
    annotations_of, deep_get, fast_deepcopy, set_annotation,
)
from kubeflow_rm_tpu.controlplane.apiserver import APIServer
from kubeflow_rm_tpu.controlplane import suspend as suspend_mod
from kubeflow_rm_tpu.controlplane.webapps import status as status_mod
from kubeflow_rm_tpu.controlplane.webapps.core import WebApp, json_body
from kubeflow_rm_tpu.controlplane.webapps.readiness import ReadinessHub
from kubeflow_rm_tpu.analysis.lockgraph import make_lock

DEFAULT_CONFIG = __file__.rsplit("/", 1)[0] + "/spawner_ui_config.yaml"


def load_spawner_config(path: str | None = None) -> dict:
    with open(path or DEFAULT_CONFIG) as f:
        return yaml.safe_load(f)["spawnerFormDefaults"]


def get_form_value(body: dict, defaults: dict, body_field: str,
                   defaults_field: str | None = None, optional: bool = False):
    """readOnly-aware form value resolution (reference form.py:15-59)."""
    defaults_field = defaults_field or body_field
    user_value = body.get(body_field)
    if defaults_field not in defaults:
        return user_value
    entry = defaults[defaults_field]
    if entry.get("readOnly", False):
        if body_field in body:
            raise BadRequest(
                f"'{body_field}' is readonly but a value was provided: "
                f"{user_value}")
        return entry["value"]
    if user_value is None:
        if not optional:
            raise BadRequest(f"No value provided for: {body_field}")
        return None
    return user_value


# --- form setters (reference form.py:74-299, TPU-adapted) -------------

def _container(nb: dict) -> dict:
    return nb["spec"]["template"]["spec"]["containers"][0]


def set_image(nb: dict, body: dict, defaults: dict) -> None:
    """Image resolution per server type (reference form.py set_image):
    jupyter reads ``image``, group-one ``imageGroupOne`` (codeserver),
    group-two ``imageGroupTwo`` (rstudio); ``customImage`` overrides
    any of them subject to the base field's readOnly."""
    server_type = body.get("serverType") or deep_get(
        defaults, "serverType", "value", default="jupyter")
    group_field = {"group-one": "imageGroupOne",
                   "group-two": "imageGroupTwo"}.get(server_type, "image")
    if body.get("customImage"):
        image = get_form_value(body, defaults, "customImage", group_field)
    else:
        image = get_form_value(body, defaults, group_field)
    _container(nb)["image"] = image.strip()
    policy = get_form_value(body, defaults, "imagePullPolicy")
    _container(nb)["imagePullPolicy"] = policy


def set_server_type(nb: dict, body: dict, defaults: dict) -> None:
    valid = ("jupyter", "group-one", "group-two")
    server_type = get_form_value(body, defaults, "serverType") or "jupyter"
    if server_type not in valid:
        raise BadRequest(f"'{server_type}' is not a valid server type")
    set_annotation(nb, nb_api.SERVER_TYPE_ANNOTATION, server_type)
    if server_type in ("group-one", "group-two"):
        set_annotation(nb, nb_api.REWRITE_URI_ANNOTATION, "/")


def _reject_nan(value: str, what: str) -> None:
    if value and "nan" in value.lower():
        raise BadRequest(f"Invalid value for {what}: {value}")


def set_cpu(nb: dict, body: dict, defaults: dict) -> None:
    cpu = get_form_value(body, defaults, "cpu")
    _reject_nan(cpu, "cpu")
    limit = get_form_value(body, defaults, "cpuLimit", optional=True)
    _reject_nan(limit or "", "cpu limit")
    factor = defaults.get("cpu", {}).get("limitFactor", "none")
    if not limit and factor != "none":
        limit = str(round(float(cpu) * float(factor), 1))
    res = _container(nb).setdefault("resources", {})
    res.setdefault("requests", {})["cpu"] = cpu
    if limit:
        if float(limit) < float(cpu):
            raise BadRequest("CPU limit must be greater than the request")
        res.setdefault("limits", {})["cpu"] = limit


def set_memory(nb: dict, body: dict, defaults: dict) -> None:
    memory = get_form_value(body, defaults, "memory")
    _reject_nan(memory, "memory")
    limit = get_form_value(body, defaults, "memoryLimit", optional=True)
    _reject_nan(limit or "", "memory limit")
    factor = defaults.get("memory", {}).get("limitFactor", "none")
    if not limit and factor != "none":
        limit = str(round(float(memory.replace("Gi", "")) * float(factor),
                          1)) + "Gi"
    res = _container(nb).setdefault("resources", {})
    res.setdefault("requests", {})["memory"] = memory
    if limit:
        if float(limit.replace("Gi", "")) < float(memory.replace("Gi", "")):
            raise BadRequest("Memory limit must be greater than the request")
        res.setdefault("limits", {})["memory"] = limit


def set_tpu(nb: dict, body: dict, defaults: dict) -> None:
    """The reference's set_notebook_gpus seam (form.py:226-250), TPU
    shape: a single acceleratorType names the whole slice."""
    tpu = get_form_value(body, defaults, "tpu")
    if not tpu:
        return
    accel = tpu.get("acceleratorType", "none")
    if accel == "none":
        return
    try:
        topo = tpu_api.lookup(accel)
    except tpu_api.UnknownAcceleratorType as e:
        raise BadRequest(str(e))
    allowed = defaults.get("tpu", {}).get("options")
    if allowed and accel not in allowed:
        raise BadRequest(
            f"acceleratorType {accel!r} is not offered by this "
            f"deployment's spawner config")
    nb["spec"]["tpu"] = {"acceleratorType": topo.accelerator_type}
    # multislice: N ICI slices joined over DCN (MEGASCALE_* rendezvous
    # comes from the webhook; the controller renders hosts x N pods)
    num_slices = tpu.get("numSlices", 1)
    if (not isinstance(num_slices, int) or num_slices < 1
            or num_slices > nb_api.MAX_SLICES):
        raise BadRequest(
            f"tpu.numSlices must be an int in "
            f"[1, {nb_api.MAX_SLICES}], got {num_slices!r}")
    if num_slices > 1:
        nb["spec"]["tpu"]["numSlices"] = num_slices


def set_tolerations(nb: dict, body: dict, defaults: dict) -> None:
    key = get_form_value(body, defaults, "tolerationGroup")
    if key == "none":
        return
    for group in defaults.get("tolerationGroup", {}).get("options", []):
        if group.get("groupKey") == key:
            spec = nb["spec"]["template"]["spec"]
            spec.setdefault("tolerations", []).extend(group["tolerations"])
            return
    raise BadRequest(f"No Toleration Group with key {key!r} in the config")


def set_affinity(nb: dict, body: dict, defaults: dict) -> None:
    key = get_form_value(body, defaults, "affinityConfig")
    if key == "none":
        return
    for cfg in defaults.get("affinityConfig", {}).get("options", []):
        if cfg.get("configKey") == key:
            nb["spec"]["template"]["spec"]["affinity"] = cfg["affinity"]
            return
    raise BadRequest(f"No Affinity Config with key {key!r} in the config")


def set_configurations(nb: dict, body: dict, defaults: dict) -> None:
    labels = get_form_value(body, defaults, "configurations")
    if not isinstance(labels, list):
        raise BadRequest(f"Labels for PodDefaults are not list: {labels}")
    for label in labels:
        nb["metadata"].setdefault("labels", {})[label] = "true"


def set_shm(nb: dict, body: dict, defaults: dict) -> None:
    if not get_form_value(body, defaults, "shm"):
        return
    spec = nb["spec"]["template"]["spec"]
    spec.setdefault("volumes", []).append(
        {"name": "dshm", "emptyDir": {"medium": "Memory"}})
    _container(nb).setdefault("volumeMounts", []).append(
        {"mountPath": "/dev/shm", "name": "dshm"})


def set_environment(nb: dict, body: dict, defaults: dict) -> None:
    env = get_form_value(body, defaults, "environment") or {}
    if isinstance(env, str):
        import json
        env = json.loads(env) if env else {}
    _container(nb).setdefault("env", []).extend(
        {"name": k, "value": str(v)} for k, v in env.items())


def _mount_volume(ns: str, nb: dict, vol: dict) -> dict | None:
    """Phase 1 of a workspace/data volume: fold the mount into the
    template WITHOUT side effects; returns the PVC object to create
    (phase 2) for newPvc volumes. Split so the PodDefault dry-run can
    validate the FULL pod shape (mounts included) before any PVC
    exists — a rejected spawn must leave nothing behind."""
    mount = vol.get("mount")
    if not mount:
        raise BadRequest("volume requires a 'mount' path")
    pvc_to_create = None
    if "newPvc" in vol:
        pvc = copy.deepcopy(vol["newPvc"])
        name = deep_get(pvc, "metadata", "name", default="") or ""
        name = name.replace("{notebook-name}", nb["metadata"]["name"])
        pvc.setdefault("metadata", {})["name"] = name
        pvc["metadata"]["namespace"] = ns
        pvc.setdefault("apiVersion", "v1")
        pvc.setdefault("kind", "PersistentVolumeClaim")
        pvc_to_create = pvc
        claim = name
    elif "existingSource" in vol:
        claim = deep_get(vol, "existingSource", "persistentVolumeClaim",
                         "claimName")
        if not claim:
            raise BadRequest("existingSource requires a PVC claimName")
    else:
        raise BadRequest("volume must specify newPvc or existingSource")
    vol_name = claim
    spec = nb["spec"]["template"]["spec"]
    spec.setdefault("volumes", []).append(
        {"name": vol_name, "persistentVolumeClaim": {"claimName": claim}})
    _container(nb).setdefault("volumeMounts", []).append(
        {"mountPath": mount, "name": vol_name})
    return pvc_to_create


# --- the app ----------------------------------------------------------

def _dry_run_poddefault_merge(api, namespace: str, nb: dict) -> None:
    """Run the worker-pod shape the controller will render through the
    PodDefault merge engine WITHOUT persisting anything; an atomic
    conflict rejection becomes a spawn-time 400 (dry-run admission,
    the reference's post.py:51-57 dry-run create)."""
    from kubeflow_rm_tpu.controlplane.apiserver import AdmissionDenied
    from kubeflow_rm_tpu.controlplane.webhook.poddefault import (
        PodDefaultWebhook,
    )
    from kubeflow_rm_tpu.controlplane.api.meta import deep_get

    pod = {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {
            "name": f"{nb['metadata']['name']}-0",
            "namespace": namespace,
            "labels": dict(nb["metadata"].get("labels") or {}),
            "annotations": dict(
                deep_get(nb, "spec", "template", "metadata",
                         "annotations", default={}) or {}),
        },
        "spec": fast_deepcopy(
            deep_get(nb, "spec", "template", "spec", default={})),
    }
    try:
        PodDefaultWebhook(api)("CREATE", pod, None)
    except AdmissionDenied as e:
        raise BadRequest(str(e)) from e


def create_app(api: APIServer, *, config_path: str | None = None,
               disable_auth: bool = False, prefix: str = "", **app_kwargs) -> WebApp:
    app = WebApp("jupyter", api, prefix=prefix, disable_auth=disable_auth, **app_kwargs)
    defaults = load_spawner_config(config_path)

    # readiness hub is built lazily: the in-memory backend spawns a
    # dispatch thread per watcher, and most app instances (tests,
    # short-lived tools) never take a readiness long-poll
    _hub_lock = make_lock("jupyter.hub_registry")
    _hub_box: list[ReadinessHub] = []

    def _hub() -> ReadinessHub:
        with _hub_lock:
            if not _hub_box:
                _hub_box.append(ReadinessHub(api))
            return _hub_box[0]

    @app.route("/api/config")
    def get_config(req):
        return {"config": defaults}

    @app.route("/api/namespaces")
    def get_namespaces(req):
        app.ensure_authorized(req, "list", "namespaces")
        return {"namespaces": [n["metadata"]["name"]
                               for n in api.list("Namespace")]}

    @app.route("/api/tpus")
    def get_tpus(req):
        """Slice types that are both configured and present in the
        node inventory (generalizes /api/gpus, routes/get.py:101-126)."""
        offered = [o for o in defaults.get("tpu", {}).get("options", [])
                   if o != "none"]
        live = set()
        for node in api.list("Node"):
            labels = node["metadata"].get("labels") or {}
            accel = labels.get(tpu_api.NODE_LABEL_ACCELERATOR)
            topo = labels.get(tpu_api.NODE_LABEL_TOPOLOGY)
            if accel and topo:
                t = tpu_api.by_node_labels(accel, topo)
                if t:
                    live.add(t.accelerator_type)
        available = [o for o in offered if o in live]
        return {"tpus": [
            {"acceleratorType": a,
             "chips": tpu_api.lookup(a).chips,
             "hosts": tpu_api.lookup(a).hosts,
             "topology": tpu_api.lookup(a).topology}
            for a in available]}

    @app.route("/api/namespaces/<namespace>/notebooks")
    def list_notebooks(req, namespace):
        app.ensure_authorized(req, "list", "notebooks", namespace)
        out = []
        for nb in api.list(nb_api.KIND, namespace):
            st = status_mod.process_status(nb, api.events_for(nb))
            out.append(_summarize(nb, st))
        return {"notebooks": out}

    @app.route("/api/namespaces/<namespace>/notebooks/<name>")
    def get_notebook(req, namespace, name):
        app.ensure_authorized(req, "get", "notebooks", namespace)
        nb = api.get(nb_api.KIND, name, namespace)
        nb["processed_status"] = status_mod.process_status(
            nb, api.events_for(nb)).to_dict()
        return {"notebook": nb}

    @app.route("/api/namespaces/<namespace>/notebooks/<name>/readiness")
    def get_notebook_readiness(req, namespace, name):
        """Long-poll readiness: block until the notebook's
        resourceVersion moves past ``knownVersion`` (or
        ``timeoutSeconds`` elapses), woken by the watch stream through
        the ReadinessHub — the push path that replaces the SPA's and
        conformance client's fixed-interval status polling. Clients
        loop: pass the last observed resourceVersion back in and each
        request returns at watch latency, not poll-tick latency."""
        app.ensure_authorized(req, "get", "notebooks", namespace)
        raw = req.args.get("timeoutSeconds", "30")
        try:
            timeout = float(raw)
        except ValueError:
            raise BadRequest(f"timeoutSeconds must be a number, "
                             f"got {raw!r}")
        timeout = max(0.0, min(timeout, 120.0))
        known = req.args.get("knownVersion", "")

        # an incoming readiness poll IS demand: transparently resume a
        # suspended notebook before blocking (wake=false opts out for
        # passive dashboards that must not un-park what they observe)
        if req.args.get("wake", "true") != "false":
            cur = api.try_get(nb_api.KIND, name, namespace)
            if cur is not None and \
                    nb_api.SUSPEND_ANNOTATION in annotations_of(cur):
                suspend_mod.request_resume(api, cur,
                                           source="readiness request")

        def fetch():
            return api.try_get(nb_api.KIND, name, namespace)

        def moved(nb):
            if nb is None:
                # a deletion is a change worth reporting — but with no
                # baseline ("" = first subscribe) keep waiting for the
                # notebook to appear
                return known != ""
            rv = deep_get(nb, "metadata", "resourceVersion", default="")
            return known == "" or str(rv) != known

        nb, changed = _hub().wait(namespace, name, timeout, fetch, moved)
        if nb is None:
            raise NotFound(f"notebook {name} in namespace {namespace} "
                           f"not found")
        nb["processed_status"] = status_mod.process_status(
            nb, api.events_for(nb)).to_dict()
        desired = deep_get(nb, "status", "desiredReplicas",
                           default=nb_api.total_hosts(nb))
        ready_n = deep_get(nb, "status", "readyReplicas", default=0)
        return {"notebook": nb, "changed": changed,
                "ready": bool(desired) and ready_n >= desired}

    @app.route("/api/namespaces/<namespace>/notebooks/<name>/events")
    def get_notebook_events(req, namespace, name):
        app.ensure_authorized(req, "get", "notebooks", namespace)
        nb = api.get(nb_api.KIND, name, namespace)
        return {"events": api.events_for(nb)}

    @app.route("/api/namespaces/<namespace>/notebooks/<name>/pods")
    def get_notebook_pods(req, namespace, name):
        """Per-host view of the slice: one pod per ordinal, with phase
        — the ref lists a single server pod
        (jupyter/backend/apps/common/routes/get.py); a TPU slice has
        `hosts` of them."""
        app.ensure_authorized(req, "get", "notebooks", namespace)
        nb = api.get(nb_api.KIND, name, namespace)
        pods = sorted(
            (p for p in api.list("Pod", namespace)
             if (p["metadata"].get("labels") or {}).get(
                 nb_api.NOTEBOOK_NAME_LABEL) == name),
            key=lambda p: p["metadata"]["name"])
        return {"pods": [
            {"name": p["metadata"]["name"],
             "phase": deep_get(p, "status", "phase"),
             "nodeName": deep_get(p, "spec", "nodeName")}
            for p in pods]}

    @app.route(
        "/api/namespaces/<namespace>/notebooks/<name>/pods/<ordinal>/logs")
    def get_notebook_pod_logs(req, namespace, name, ordinal):
        """Container logs for one slice host (pod ordinal) — the
        debugging surface for a hung multi-host rendezvous. Ref:
        jupyter/backend/apps/common/routes/get.py `get_pod_logs`."""
        app.ensure_authorized(req, "get", "notebooks", namespace)
        api.get(nb_api.KIND, name, namespace)  # 404 on unknown notebook
        try:
            ordinal = int(ordinal)
        except ValueError:
            raise BadRequest(f"pod ordinal must be an integer, "
                             f"got {ordinal!r}")
        raw = req.args.get("tailLines")
        try:
            tail = int(raw) if raw is not None else None
        except ValueError:
            raise BadRequest(f"tailLines must be an integer, got {raw!r}")
        pod_name = f"{name}-{ordinal}"
        # The pod must belong to THIS notebook: a name-prefix match alone
        # would let notebook 'a' read pods of notebook 'a-b'.
        pod = api.try_get("Pod", pod_name, namespace)
        if pod is None or (pod["metadata"].get("labels") or {}).get(
                nb_api.NOTEBOOK_NAME_LABEL) != name:
            raise NotFound(f"pod {pod_name} of notebook {name} not found")
        # kube semantics delegated to pod_logs: 0 -> nothing, <0 -> 4xx
        text = api.pod_logs(namespace, pod_name, tail_lines=tail)
        return {"logs": text.splitlines()}

    @app.route("/api/namespaces/<namespace>/notebooks", methods=("POST",))
    def post_notebook(req, namespace):
        app.ensure_authorized(req, "create", "notebooks", namespace)
        body = json_body(req)
        if "name" not in body:
            raise BadRequest("'name' is a required body field")
        user = app.username(req) or "anonymous@kubeflow.org"

        nb = nb_api.make_notebook(body["name"], namespace)
        nb["metadata"].setdefault("labels", {})
        nb["metadata"].setdefault("annotations", {})
        nb["spec"]["template"]["spec"]["serviceAccountName"] = \
            "default-editor"
        set_annotation(nb, "notebooks.kubeflow.org/creator", user)

        set_image(nb, body, defaults)
        set_server_type(nb, body, defaults)
        set_cpu(nb, body, defaults)
        set_memory(nb, body, defaults)
        set_tpu(nb, body, defaults)
        set_tolerations(nb, body, defaults)
        set_affinity(nb, body, defaults)
        set_configurations(nb, body, defaults)
        set_shm(nb, body, defaults)
        set_environment(nb, body, defaults)
        cls = get_form_value(body, defaults, "priorityClassName",
                             optional=True)
        if cls:
            nb["spec"]["priorityClassName"] = cls

        vols = list(get_form_value(body, defaults, "datavols", "dataVolumes")
                    or [])
        workspace = get_form_value(body, defaults, "workspace",
                                   "workspaceVolume", optional=True)
        if workspace:
            vols.insert(0, workspace)

        # fold volume mounts into the template FIRST (no side
        # effects), dry-run the PodDefault merge the pods will go
        # through (the reference dry-run-creates before the real
        # create — post.py:51-57), and only then create PVCs: a
        # conflicting configuration or mountPath gets a 400 AT SPAWN,
        # leaving nothing behind
        pvcs = [pvc for vol in vols
                for pvc in [_mount_volume(namespace, nb, vol)] if pvc]
        _dry_run_poddefault_merge(api, namespace, nb)
        for pvc in pvcs:
            api.create(pvc)

        api.create(nb)
        return {"message": "Notebook created successfully."}

    @app.route("/api/namespaces/<namespace>/notebooks/<name>",
               methods=("PATCH",))
    def patch_notebook(req, namespace, name):
        app.ensure_authorized(req, "update", "notebooks", namespace)
        body = json_body(req)
        nb = api.get(nb_api.KIND, name, namespace)
        if "stopped" in body:
            ann = annotations_of(nb)
            if body["stopped"]:
                set_annotation(nb, nb_api.STOP_ANNOTATION,
                               api.clock().isoformat())
            else:
                ann.pop(nb_api.STOP_ANNOTATION, None)
            api.update(nb)
        if "suspended" in body:
            # the API arm of the lifecycle: true parks the slice
            # through the same checkpoint-then-drain path the idle
            # suspender uses; false is an explicit resume request
            if body["suspended"]:
                suspend_mod.initiate_suspend(api, nb, reason="api")
            else:
                suspend_mod.request_resume(api, nb, source="api")
        return {"message": "Notebook updated successfully."}

    @app.route("/api/namespaces/<namespace>/notebooks/<name>",
               methods=("DELETE",))
    def delete_notebook(req, namespace, name):
        app.ensure_authorized(req, "delete", "notebooks", namespace)
        api.delete(nb_api.KIND, name, namespace)
        return {"message": "Notebook deleted successfully."}

    @app.route("/api/namespaces/<namespace>/pvcs")
    def list_pvcs(req, namespace):
        app.ensure_authorized(req, "list", "persistentvolumeclaims",
                              namespace)
        return {"pvcs": api.list("PersistentVolumeClaim", namespace)}

    @app.route("/api/namespaces/<namespace>/poddefaults")
    def list_poddefaults(req, namespace):
        app.ensure_authorized(req, "list", "poddefaults", namespace)
        pds = api.list("PodDefault", namespace)
        return {"poddefaults": [
            {"label": deep_get(p, "spec", "selector", "matchLabels",
                               default={}),
             "desc": deep_get(p, "spec", "desc",
                              default=p["metadata"]["name"]),
             "name": p["metadata"]["name"]}
            for p in pds]}

    return app


def _summarize(nb: dict, st) -> dict:
    topo = nb_api.tpu_spec(nb)
    container = deep_get(nb, "spec", "template", "spec", "containers", 0,
                         default={})
    return {
        "name": nb["metadata"]["name"],
        "namespace": nb["metadata"]["namespace"],
        "image": container.get("image"),
        "serverType": annotations_of(nb).get(nb_api.SERVER_TYPE_ANNOTATION),
        "tpu": ({"acceleratorType": topo.accelerator_type,
                 "chips": topo.chips, "hosts": topo.hosts}
                if topo else None),
        "status": st.to_dict(),
        "age": nb["metadata"].get("creationTimestamp"),
    }
