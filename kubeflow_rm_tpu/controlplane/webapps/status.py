"""Notebook status derivation — the UI's status ladder.

Mirrors the reference's ``process_status``
(``crud-web-apps/jupyter/backend/apps/common/status.py:9-60``): a
Notebook is reported as one of [ready | waiting | warning |
terminating | stopped], derived in priority order from the stop
annotation, deletionTimestamp, readyReplicas vs the slice's host
count, containerState, conditions, and finally warning Events. The
TPU difference: readiness is *slice* readiness — a v5p-16 notebook is
"waiting" until BOTH hosts are Ready, because a partially-up slice
cannot run a jax program.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass

from kubeflow_rm_tpu.controlplane.api import notebook as nb_api
from kubeflow_rm_tpu.controlplane.api.meta import annotations_of, deep_get

PHASE_READY = "ready"
PHASE_WAITING = "waiting"
PHASE_WARNING = "warning"
PHASE_TERMINATING = "terminating"
PHASE_STOPPED = "stopped"
PHASE_SUSPENDED = "suspended"


@dataclass(frozen=True)
class Status:
    phase: str
    message: str

    def to_dict(self) -> dict:
        return asdict(self)


def process_status(notebook: dict, events: list[dict] | None = None) -> Status:
    ann = annotations_of(notebook)

    if notebook["metadata"].get("deletionTimestamp"):
        return Status(PHASE_TERMINATING, "Deleting this Notebook.")

    if nb_api.SUSPEND_ANNOTATION in ann:
        # suspended ≠ stopped: the chips went back to the pool, but any
        # incoming request (this UI included) transparently resumes it
        if deep_get(notebook, "status", "readyReplicas", default=0):
            return Status(PHASE_WAITING, "Suspending this Notebook.")
        return Status(PHASE_SUSPENDED,
                      "Notebook is suspended; its TPU slice was released. "
                      "It will resume automatically on the next request.")
    if nb_api.RESUME_REQUESTED_ANNOTATION in ann:
        return Status(PHASE_WAITING, "Resuming this Notebook.")

    if nb_api.STOP_ANNOTATION in ann:
        # mirrors get_stopped_status: a stopped CR with replicas still
        # up is "stopping"; fully drained is "stopped"
        if deep_get(notebook, "status", "readyReplicas", default=0):
            return Status(PHASE_WAITING, "Stopping this Notebook.")
        return Status(PHASE_STOPPED, "No Pods are currently running for "
                                     "this Notebook.")

    topo = nb_api.tpu_spec(notebook)
    want = nb_api.total_hosts(notebook)
    ready = deep_get(notebook, "status", "readyReplicas", default=0)
    if ready >= want:
        return Status(PHASE_READY, "Running.")

    # waiting on containers: surface the container state if one exists
    cstate = deep_get(notebook, "status", "containerState", default={}) or {}
    if "waiting" in cstate:
        reason = deep_get(cstate, "waiting", "reason", default="")
        phase = PHASE_WARNING if reason in (
            "ImagePullBackOff", "CrashLoopBackOff", "ErrImagePull",
        ) else PHASE_WAITING
        return Status(phase, f"Container is waiting: {reason}.")

    # scan warning events for scheduling errors (get_status_from_events)
    for ev in reversed(events or []):
        if ev.get("type") == "Warning":
            return Status(PHASE_WARNING, ev.get("message", ev.get("reason",
                                                                  "")))

    if topo and topo.multihost and ready:
        return Status(PHASE_WAITING,
                      f"Slice is starting: {ready}/{want} hosts ready.")
    return Status(PHASE_WAITING, "Starting this Notebook.")
