"""Shared web-app kit — the ``crud_backend`` library of this framework.

The reference factors authn/authz/CSRF/probes/error envelopes into a
Flask library every web app builds on
(``crud-web-apps/common/backend/kubeflow/kubeflow/crud_backend/__init__.py:16-35``).
This is the same factoring on bare werkzeug (no Flask in the TPU
image), talking to the in-memory apiserver through the identical verb
surface a kubernetes client would offer:

- **authn** (``authn.py:12-67``): identity arrives as a trusted
  ``kubeflow-userid`` header stamped by the mesh's auth proxy; the
  optional prefix (``:``) is stripped. Routes opt out with
  ``no_auth=True``; ``disable_auth`` handles dev mode.
- **authz** (``authz.py:101-133``): every mutating/list route declares
  the k8s verb+resource it performs; the app submits an access review
  to the apiserver (SubjectAccessReview equivalent) and 403s with the
  reference's message shape.
- **CSRF** (``csrf.py``): double-submit cookie — index sets a random
  ``XSRF-TOKEN`` cookie, unsafe methods must echo it in
  ``X-XSRF-TOKEN``; GET/HEAD/OPTIONS/TRACE are exempt.
- **probes** (``probes.py``): ``/healthz`` + ``/readyz``.
- **envelopes** (``api/utils.py:7-30``): ``{"status", "success",
  "user", <data_field>}`` on success, ``{"success": False, "log",
  "status", "user"}`` on failure — the Angular frontends key on these.
"""

from __future__ import annotations

import json
import logging
import secrets
import traceback
from typing import Any, Callable

from werkzeug.exceptions import (
    BadRequest, Forbidden, HTTPException, Unauthorized,
)
from werkzeug.routing import Map, Rule
from werkzeug.wrappers import Request, Response

from kubeflow_rm_tpu.controlplane.apiserver import (
    AdmissionDenied, AlreadyExists, APIServer, Invalid, NotFound,
)
from kubeflow_rm_tpu.controlplane import tracing

log = logging.getLogger("kubeflow_rm_tpu.webapps")

USER_HEADER = "kubeflow-userid"
USER_PREFIX = ":"
CSRF_COOKIE = "XSRF-TOKEN"
CSRF_HEADER = "X-XSRF-TOKEN"
SAFE_METHODS = ("GET", "HEAD", "OPTIONS", "TRACE")


class WebApp:
    """A WSGI app with the crud_backend request pipeline.

    Handlers take ``(req, **url_params)`` and return a dict (JSON
    envelope added), a Response, or ``(dict, status)``.
    """

    def __init__(self, name: str, api: APIServer, *, prefix: str = "",
                 disable_auth: bool = False, secure_cookies: bool = True,
                 user_header: str = USER_HEADER,
                 user_prefix: str = USER_PREFIX,
                 authz_cache_ttl: float | None = None):
        self.name = name
        self.api = api
        self.prefix = prefix.rstrip("/")
        self.disable_auth = disable_auth
        self.secure_cookies = secure_cookies
        self.user_header = user_header
        self.user_prefix = user_prefix
        # SubjectAccessReview decision cache: kube-apiserver's webhook
        # authorizer caches decisions (allow 5 min / deny 30 s by
        # default); a short symmetric TTL here keeps a polling SPA from
        # turning every status refresh into a live SAR round-trip.
        # Env override KFRM_AUTHZ_CACHE_TTL; 0 disables (tests that
        # flip RBAC mid-flight want instant effect).
        if authz_cache_ttl is None:
            import os
            authz_cache_ttl = float(
                os.environ.get("KFRM_AUTHZ_CACHE_TTL", "2.0"))
        self.authz_cache_ttl = authz_cache_ttl
        self._authz_cache: dict[tuple, tuple[bool, float]] = {}
        self._map = Map()
        self._handlers: dict[str, Callable] = {}
        self._no_auth: set[str] = set()
        self._no_csrf: set[str] = set()
        self.route("/healthz", no_auth=True, no_csrf=True)(_healthz)
        self.route("/readyz", no_auth=True, no_csrf=True)(_healthz)
        self.route("/metrics", no_auth=True, no_csrf=True)(_metrics)

    # ---- routing -----------------------------------------------------
    def route(self, rule: str, methods=("GET",), *, no_auth: bool = False,
              no_csrf: bool = False):
        def deco(fn):
            endpoint = f"{fn.__module__}.{fn.__qualname__}:{rule}"
            self._map.add(Rule(self.prefix + rule, endpoint=endpoint,
                               methods=list(methods)))
            self._handlers[endpoint] = fn
            if no_auth:
                self._no_auth.add(endpoint)
            if no_csrf:
                self._no_csrf.add(endpoint)
            return fn
        return deco

    # ---- identity ----------------------------------------------------
    def username(self, req: Request) -> str | None:
        raw = req.headers.get(self.user_header)
        if raw is None:
            return None
        if raw.startswith(self.user_prefix):
            raw = raw[len(self.user_prefix):]
        return raw

    def ensure_authorized(self, req: Request, verb: str, resource: str,
                          namespace: str | None = None) -> None:
        if self.disable_auth:
            return
        user = self.username(req)
        if user is None:
            raise Unauthorized("No user credentials were found!")
        if not self._access_review_cached(user, verb, resource,
                                          namespace):
            msg = f"User '{user}' is not authorized to {verb} {resource}"
            if namespace is not None:
                msg += f" in namespace '{namespace}'"
            raise Forbidden(msg)

    def _access_review_cached(self, user: str, verb: str, resource: str,
                              namespace: str | None) -> bool:
        if self.authz_cache_ttl <= 0:
            return self.api.access_review(user, verb, resource, namespace)
        import time
        key = (user, verb, resource, namespace)
        hit = self._authz_cache.get(key)
        now = time.monotonic()
        if hit is not None and hit[1] > now:
            return hit[0]
        allowed = self.api.access_review(user, verb, resource, namespace)
        self._authz_cache[key] = (allowed, now + self.authz_cache_ttl)
        if len(self._authz_cache) > 4096:  # bound a hostile user sweep
            # snapshot first: other werkzeug threads insert concurrently
            self._authz_cache = {k: v for k, v in
                                 list(self._authz_cache.items())
                                 if v[1] > now}
        return allowed

    # ---- envelopes ---------------------------------------------------
    def success(self, req: Request, data_field: str | None = None,
                data: Any = None, status: int = 200) -> Response:
        body = {"status": status, "success": True,
                "user": self.username(req)}
        if data_field is not None:
            body[data_field] = data
        return _json_response(body, status)

    def failed(self, req: Request, msg: str, status: int) -> Response:
        body = {"success": False, "log": msg, "status": status,
                "user": self.username(req)}
        return _json_response(body, status)

    # ---- CSRF --------------------------------------------------------
    def set_csrf_cookie(self, resp: Response) -> None:
        resp.set_cookie(CSRF_COOKIE, secrets.token_urlsafe(32),
                        samesite="Strict", httponly=False,
                        secure=self.secure_cookies,
                        path=self.prefix or "/")
        resp.headers["Cache-Control"] = \
            "no-cache, no-store, must-revalidate, max-age=0"

    def _check_csrf(self, req: Request) -> None:
        if req.method in SAFE_METHODS:
            return
        cookie = req.cookies.get(CSRF_COOKIE)
        if cookie is None:
            raise Forbidden(f"Could not find CSRF cookie {CSRF_COOKIE} in "
                            "the request.")
        header = req.headers.get(CSRF_HEADER)
        if header is None:
            raise Forbidden("Could not detect CSRF protection header "
                            f"{CSRF_HEADER}.")
        if header != cookie:
            raise Forbidden("CSRF check failed. Token in cookie "
                            f"{CSRF_COOKIE} doesn't match token in header "
                            f"{CSRF_HEADER}.")

    # ---- WSGI --------------------------------------------------------
    def __call__(self, environ, start_response):
        # server-span boundary for context-bearing requests: a client
        # that sends ``traceparent`` (the conformance harness around a
        # notebook POST) gets the whole handler — auth, CSRF, apiserver
        # writes, downstream kube calls — recorded as one server hop of
        # ITS trace. Header-less traffic takes the plain path.
        if tracing.enabled():
            parent = tracing.parse_traceparent(
                environ.get("HTTP_TRACEPARENT"))
            if parent is not None:
                with tracing.start_span(
                        f"{environ.get('REQUEST_METHOD', 'GET')} "
                        f"{environ.get('PATH_INFO', '/')}",
                        kind="server", parent=parent,
                        attrs={"component": self.name}):
                    return self._call_inner(environ, start_response)
        return self._call_inner(environ, start_response)

    def _call_inner(self, environ, start_response):
        req = Request(environ)
        try:
            endpoint, args = self._map.bind_to_environ(environ).match()
            if not self.disable_auth and endpoint not in self._no_auth:
                if self.username(req) is None:
                    raise Unauthorized("No user detected.")
            if endpoint not in self._no_csrf:
                self._check_csrf(req)
            rv = self._handlers[endpoint](req, **args)
            resp = self._to_response(req, rv)
        except HTTPException as e:
            resp = self.failed(req, e.description, e.code)
        except NotFound as e:
            resp = self.failed(
                req, "The requested resource could not be found in the "
                f"API Server: {e}", 404)
        except (AlreadyExists,) as e:
            resp = self.failed(req, str(e), 409)
        except (Invalid, AdmissionDenied) as e:
            resp = self.failed(req, str(e), 422)
        except Exception as e:
            log.error("unhandled exception on %s: %s\n%s", req.path, e,
                      traceback.format_exc())
            resp = self.failed(req, "An error occured in the backend.", 500)
        return resp(environ, start_response)

    def _to_response(self, req: Request, rv) -> Response:
        if isinstance(rv, Response):
            return rv
        if isinstance(rv, tuple):
            body, status = rv
            return _json_response(body, status)
        if rv is None:
            return self.success(req)
        if isinstance(rv, dict):
            if "success" not in rv:
                rv = {"status": 200, "success": True,
                      "user": self.username(req), **rv}
            return _json_response(rv, rv.get("status", 200))
        raise TypeError(f"handler returned {type(rv)}")

    # ---- testing -----------------------------------------------------
    def test_client(self, user: str | None = "user@example.com"):
        """A werkzeug client with identity + CSRF pre-wired, the way
        Istio's auth proxy and the SPA would present them."""
        from werkzeug.test import Client
        client = Client(self)
        headers = []
        if user is not None:
            headers.append((self.user_header, self.user_prefix + user))
        token = secrets.token_urlsafe(16)
        import inspect
        params = list(inspect.signature(client.set_cookie).parameters)
        if params and params[0] == "server_name":
            # werkzeug < 2.3 leads with the cookie domain
            client.set_cookie("localhost", CSRF_COOKIE, token,
                              path=self.prefix or "/")
        else:
            client.set_cookie(CSRF_COOKIE, token,
                              path=self.prefix or "/")
        headers.append((CSRF_HEADER, token))
        return _ClientProxy(client, headers)


class _ClientProxy:
    """Adds standing headers to every request of a werkzeug Client."""

    def __init__(self, client, headers):
        self._client = client
        self._headers = headers

    def open(self, *args, **kwargs):
        headers = list(kwargs.pop("headers", []) or [])
        merged = {k: v for k, v in self._headers}
        for k, v in headers:
            merged[k] = v
        kwargs["headers"] = list(merged.items())
        return self._client.open(*args, **kwargs)

    def get(self, *a, **kw):
        return self.open(*a, method="GET", **kw)

    def post(self, *a, **kw):
        return self.open(*a, method="POST", **kw)

    def patch(self, *a, **kw):
        return self.open(*a, method="PATCH", **kw)

    def delete(self, *a, **kw):
        return self.open(*a, method="DELETE", **kw)


def _json_response(body: dict, status: int = 200) -> Response:
    return Response(json.dumps(body), status=status,
                    mimetype="application/json")


def _healthz(req: Request):
    return {"status": 200, "success": True, "alive": True}


def _metrics(req: Request):
    """Prometheus exposition (the reference serves :8080/metrics from
    every controller — pkg/metrics/metrics.go, kfam/monitoring.go)."""
    from kubeflow_rm_tpu.controlplane import metrics
    return Response(metrics.scrape(), mimetype="text/plain")


def json_body(req: Request) -> dict:
    try:
        return json.loads(req.get_data(as_text=True) or "{}")
    except json.JSONDecodeError as e:
        raise BadRequest(f"bad JSON body: {e}")
