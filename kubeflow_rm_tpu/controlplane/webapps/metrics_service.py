"""Pluggable cluster-metrics backends for the dashboard.

The reference abstracts its chart data source behind a factory that
picks Prometheus or Stackdriver at boot
(``centraldashboard/app/metrics_service_factory.ts``,
``prometheus_metrics_service.ts``, ``stackdriver_metrics_service.ts``).
Same shape here, with backends that fit the TPU platform:

- ``inventory`` (default): compute fleet numbers straight from the
  apiserver's Node/Pod/Notebook objects — zero extra infrastructure,
  always available.
- ``prometheus``: scrape a Prometheus text exposition endpoint (the
  controller manager's ``/metrics``, or a real Prometheus federate
  URL via ``KFRM_PROMETHEUS_URL``) and read the platform's own gauges
  (``controlplane/metrics.py``).

Both return the same ``snapshot()`` dict, and ``MetricsHistory`` rings
snapshots for the dashboard's utilization-over-time charts (the
reference's ``resource-chart.js`` backs onto interval queries; here
the history lives in-process).
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Protocol

from kubeflow_rm_tpu.controlplane.api import tpu as tpu_api
from kubeflow_rm_tpu.controlplane.api.meta import deep_get, parse_quantity
from kubeflow_rm_tpu.controlplane import metrics as cp_metrics
from kubeflow_rm_tpu.analysis.lockgraph import make_lock


class MetricsService(Protocol):
    def snapshot(self) -> dict: ...


def _controlplane_section(api=None) -> dict:
    """HA runtime health for the dashboard pills: who holds the
    controller-manager lease (from the store) plus the in-process
    workqueue/leadership gauges (``controlplane/metrics.py``)."""
    from kubeflow_rm_tpu.controlplane import metrics as cp_metrics
    from kubeflow_rm_tpu.controlplane import scheduler as cp_scheduler
    # free/fragmentation gauges are computed on stats() — bring them
    # current so the pills reflect the live cache, not the last bind
    cp_scheduler.refresh_gauges()
    leader, transitions = None, None
    if api is not None:
        try:
            from kubeflow_rm_tpu.controlplane.ha.leases import (
                DEFAULT_LEASE_NAME,
            )
            lease = api.try_get("Lease", DEFAULT_LEASE_NAME, "kubeflow")
        except Exception:  # noqa: BLE001 - lease kind may not exist
            cp_metrics.swallowed("metrics_service", "lease read")
            lease = None
        if lease:
            spec = lease.get("spec") or {}
            leader = spec.get("holderIdentity") or None
            transitions = spec.get("leaseTransitions")
    # informer-cache health: the api's shared ObjectStore when it has
    # one (KubeAPIServer.cache / CachedAPI.store), else in-process
    # gauge sums — works for both backends
    cache_stats = None
    store = getattr(api, "cache", None) or getattr(api, "store", None)
    if store is not None and hasattr(store, "stats"):
        try:
            cache_stats = store.stats()
        except Exception:  # noqa: BLE001 - pills are best-effort
            cp_metrics.swallowed("metrics_service", "cache stats")
            cache_stats = None
    return {
        "leader": leader,
        "lease_transitions": transitions,
        "is_leader": cp_metrics.registry_value("leader_is_leader"),
        "workqueue_depth": cp_metrics.registry_value("workqueue_depth"),
        "workqueue_requeues": cp_metrics.registry_value(
            "workqueue_requeues_total"),
        "retries_exhausted": cp_metrics.registry_value(
            "workqueue_retries_exhausted_total"),
        "cache": {
            "objects": cache_stats["objects"] if cache_stats else None,
            "synced_kinds": (cache_stats["synced_kinds"]
                             if cache_stats else
                             cp_metrics.registry_value(
                                 "informer_synced_kinds")),
            "events_applied": (cache_stats["events_applied"]
                               if cache_stats else None),
            "last_event_t": (cache_stats["last_event_t"]
                             if cache_stats else
                             cp_metrics.registry_value(
                                 "informer_last_event_timestamp_seconds")),
            "hits": cp_metrics.registry_value(
                "cache_reads_total", {"result": "hit"}),
            "misses": cp_metrics.registry_value(
                "cache_reads_total", {"result": "miss"}),
            "suppressed_writes": cp_metrics.registry_value(
                "cache_suppressed_writes_total"),
            "conflict_fastpath": cp_metrics.registry_value(
                "cache_conflict_fastpath_total"),
        },
        # async watch-fanout health (apiserver per-watcher dispatch
        # queues): sustained depth or overflows mean a consumer can't
        # keep up with the event rate and is being forced to relist
        "fanout": {
            "queue_depth": cp_metrics.registry_value(
                "watch_fanout_queue_depth"),
            "overflows": cp_metrics.registry_value(
                "watch_fanout_overflows_total"),
            "delivered": cp_metrics.registry_value(
                "watch_fanout_delivered_total"),
            "dispatch_lag_s": cp_metrics.registry_value(
                "watch_fanout_dispatch_lag_seconds"),
        },
        # batched write path: where reconcile milliseconds go — render
        # vs child writes vs status vs event re-emission, summed across
        # controllers (per-controller split lives in /metrics)
        "reconcile_phases": {
            p: {
                "count": cp_metrics.registry_value(
                    "reconcile_phase_duration_seconds_count",
                    {"phase": p}),
                "seconds": cp_metrics.registry_value(
                    "reconcile_phase_duration_seconds_sum",
                    {"phase": p}),
            }
            for p in ("render", "child_writes", "status", "events")
        },
        # incremental scheduler: gang-bind latency split by outcome,
        # plus cache health (assumed pods should drain to 0 at idle;
        # rebuilds beyond the initial prime mean fanout overflow)
        "scheduler": {
            "bound": {
                "count": cp_metrics.registry_value(
                    "schedule_latency_seconds_count",
                    {"result": "bound"}),
                "seconds": cp_metrics.registry_value(
                    "schedule_latency_seconds_sum",
                    {"result": "bound"}),
            },
            "unschedulable": {
                "count": cp_metrics.registry_value(
                    "schedule_latency_seconds_count",
                    {"result": "unschedulable"}),
                "seconds": cp_metrics.registry_value(
                    "schedule_latency_seconds_sum",
                    {"result": "unschedulable"}),
            },
            "assumed_pods": cp_metrics.registry_value(
                "scheduler_assumed_pods"),
            "cache_events": cp_metrics.registry_value(
                "scheduler_cache_events_total"),
            "cache_rebuilds": cp_metrics.registry_value(
                "scheduler_cache_rebuilds_total"),
            # bin-packing health: stranded = free - largest_free_gang
            # (chips no single gang can use at the current spread)
            "free_chips": cp_metrics.registry_value(
                "scheduler_free_chips"),
            "largest_free_gang": cp_metrics.registry_value(
                "scheduler_largest_free_gang_chips"),
            "fragmentation": cp_metrics.registry_value(
                "scheduler_fragmentation"),
        },
        # oversubscription lifecycle: suspensions by reason, resumes
        # with state restored, preemption victims, per-phase latency
        "suspend": {
            "suspended": cp_metrics.registry_value(
                "notebook_suspend_total"),
            "resumed": cp_metrics.registry_value(
                "notebook_resume_total"),
            "preempted": cp_metrics.registry_value(
                "notebook_preempt_total"),
            "phase_seconds": {
                p: {
                    "count": cp_metrics.registry_value(
                        "suspend_resume_phase_seconds_count",
                        {"phase": p}),
                    "seconds": cp_metrics.registry_value(
                        "suspend_resume_phase_seconds_sum",
                        {"phase": p}),
                }
                for p in ("drain", "rebind", "restore")
            },
        },
        # multi-role gang jobs (TPUJob): live gangs, per-role
        # readiness (summed across roles here; split by label in the
        # /metrics exposition), phase-transition churn
        "jobs": {
            "running": cp_metrics.registry_value("tpujob_running"),
            "ready_pods": cp_metrics.registry_value(
                "tpujob_ready_pods"),
            "phase_transitions": cp_metrics.registry_value(
                "tpujob_phase_transitions_total"),
        },
        # durable sharded control plane: WAL group-commit and snapshot
        # health plus ring membership. shard is THIS process's identity
        # ("" = unsharded); counters sum across shard labels when a
        # single registry hosts several (in-thread test stacks)
        "persistence": {
            "shard": cp_metrics.shard_label() or None,
            "ring_members": cp_metrics.registry_value(
                "shard_ring_members"),
            "wal_fsyncs": cp_metrics.registry_value(
                "wal_fsync_seconds_count"),
            "wal_fsync_s": cp_metrics.registry_value(
                "wal_fsync_seconds_sum"),
            "wal_bytes": cp_metrics.registry_value("wal_bytes_total"),
            "snapshots": cp_metrics.registry_value(
                "snapshot_duration_seconds_count"),
            "snapshot_s": cp_metrics.registry_value(
                "snapshot_duration_seconds_sum"),
        },
        # push readiness: long-polls currently parked on the hub and
        # the event-arrival -> waiter-observation latency that replaced
        # the clients' fixed-interval status polling
        "readiness": {
            "waiters": cp_metrics.registry_value("readiness_waiters"),
            "wakes": cp_metrics.registry_value(
                "readiness_wake_to_observe_seconds_count"),
            "wake_to_observe_s": cp_metrics.registry_value(
                "readiness_wake_to_observe_seconds_sum"),
        },
        # continuous-batching serving gateway: slot utilization, queue
        # pressure, and SLO enforcement (per-tenant split lives in the
        # labelled /metrics exposition)
        "serving": {
            "queue_depth": cp_metrics.registry_value(
                "serving_queue_depth"),
            "active_slots": cp_metrics.registry_value(
                "serving_active_slots"),
            "slot_capacity": cp_metrics.registry_value(
                "serving_slot_capacity"),
            "batch_occupancy": cp_metrics.registry_value(
                "serving_batch_occupancy"),
            "requests_ok": cp_metrics.registry_value(
                "serving_requests_total", {"result": "ok"}),
            "requests_shed": cp_metrics.registry_value(
                "serving_requests_total", {"result": "shed"}),
            "shed": cp_metrics.registry_value("serving_shed_total"),
            "generated_tokens": cp_metrics.registry_value(
                "serving_generated_tokens_total"),
            "request_latency": {
                "count": cp_metrics.registry_value(
                    "serving_request_latency_seconds_count"),
                "seconds": cp_metrics.registry_value(
                    "serving_request_latency_seconds_sum"),
            },
            # paged-KV fleet (r13): per-class backlog, shared-prefix
            # cache effectiveness, block headroom, replica states
            "class_queue_depth": {
                c: cp_metrics.registry_value(
                    "serving_class_queue_depth", {"slo_class": c})
                for c in ("interactive", "batch", "best_effort")
            },
            "prefix_hit_ratio": cp_metrics.registry_value(
                "serving_prefix_hit_ratio"),
            "free_block_fraction": cp_metrics.registry_value(
                "serving_free_block_fraction"),
            "migrations": cp_metrics.registry_value(
                "serving_migrations_total"),
            "fleet_replicas": {
                s: cp_metrics.registry_value(
                    "serving_fleet_replicas", {"state": s})
                for s in ("ready", "draining", "dead")
            },
        },
        # error accounting: intentionally-absorbed exceptions (KFRM005
        # counts them instead of letting them vanish); per-module split
        # lives in the labelled /metrics exposition, and the
        # swallowed-errors SLO pages on a sustained nonzero rate
        "errors": {
            "swallowed": cp_metrics.registry_value(
                "swallowed_errors_total"),
        },
    }


class InventoryMetricsService:
    """Fleet numbers from the store: per-accelerator-type chip
    allocatable/used plus the summary counters the SPA pills show."""

    def __init__(self, api):
        self.api = api

    def snapshot(self) -> dict:
        api = self.api
        scan = getattr(api, "scan", api.list)  # read-only references
        per_type: dict[str, dict] = {}
        used_by_node: dict[str, float] = {}
        for pod in scan("Pod"):
            node = deep_get(pod, "spec", "nodeName")
            if not node:
                continue
            chips = 0.0
            for c in deep_get(pod, "spec", "containers",
                              default=[]) or []:
                amt = deep_get(c, "resources", "limits",
                               tpu_api.GOOGLE_TPU_RESOURCE)
                if amt is not None:
                    chips += parse_quantity(amt)
            if chips:
                used_by_node[node] = used_by_node.get(node, 0.0) + chips
        nodes = 0
        for node in scan("Node"):
            labels = node["metadata"].get("labels") or {}
            accel = labels.get(tpu_api.NODE_LABEL_ACCELERATOR)
            if not accel:
                continue
            nodes += 1
            alloc = parse_quantity(deep_get(
                node, "status", "allocatable",
                tpu_api.GOOGLE_TPU_RESOURCE, default=0))
            entry = per_type.setdefault(
                accel, {"allocatable": 0.0, "used": 0.0, "nodes": 0})
            entry["allocatable"] += alloc
            entry["used"] += used_by_node.get(
                node["metadata"]["name"], 0.0)
            entry["nodes"] += 1
        running = 0
        for nb in scan("Notebook"):
            if (nb.get("status") or {}).get("readyReplicas"):
                running += 1
        return {
            "tpu": per_type,
            "metrics": {
                "nodes": nodes,
                "chips_capacity": sum(e["allocatable"]
                                      for e in per_type.values()),
                "chips_requested": sum(e["used"]
                                       for e in per_type.values()),
                "notebooks_running": running,
            },
            "controlplane": _controlplane_section(api),
        }


class PrometheusMetricsService:
    """Scrape the platform's own gauges from a Prometheus text
    endpoint. Per-accelerator breakdown isn't available from the flat
    gauges, so ``tpu`` is empty — the reference's Prometheus service
    similarly serves only the aggregate chart queries."""

    def __init__(self, url: str, timeout_s: float = 3.0):
        self.url = url
        self.timeout_s = timeout_s

    def _scrape(self) -> dict[str, float]:
        import urllib.request
        out: dict[str, float] = {}
        with urllib.request.urlopen(self.url,
                                    timeout=self.timeout_s) as resp:
            for raw in resp.read().decode().splitlines():
                line = raw.strip()
                if not line or line.startswith("#"):
                    continue
                # exposition: `name value` or `name{labels} value` —
                # federate appends a timestamp, and label VALUES may
                # contain spaces, so split after the closing brace
                if "}" in line:
                    head, _, rest = line.partition("}")
                    name = head.split("{", 1)[0].strip()
                    fields = rest.split()
                else:
                    fields = line.split()
                    name = fields[0] if fields else ""
                    fields = fields[1:]
                if not name or not fields:
                    continue
                try:
                    out[name] = out.get(name, 0.0) + float(fields[0])
                except ValueError:
                    continue
        return out

    def snapshot(self) -> dict:
        g = self._scrape()
        return {
            "tpu": {},
            "metrics": {
                "nodes": None,
                "chips_capacity": None,
                "chips_requested": g.get("tpu_chips_requested"),
                "notebooks_running": g.get("notebook_running"),
            },
            "controlplane": {
                "leader": None,  # identity label lost in the flat sum
                "lease_transitions": None,
                "is_leader": g.get("leader_is_leader"),
                "workqueue_depth": g.get("workqueue_depth"),
                "workqueue_requeues": g.get("workqueue_requeues_total"),
                "retries_exhausted": g.get(
                    "workqueue_retries_exhausted_total"),
                "cache": {
                    "objects": None,  # not exported as a flat gauge
                    "synced_kinds": g.get("informer_synced_kinds"),
                    "events_applied": g.get("informer_events_total"),
                    "last_event_t": g.get(
                        "informer_last_event_timestamp_seconds"),
                    # hit/miss labels are summed by the flat scrape, so
                    # only the combined read count survives here
                    "hits": g.get("cache_reads_total"),
                    "misses": None,
                    "suppressed_writes": g.get(
                        "cache_suppressed_writes_total"),
                    "conflict_fastpath": g.get(
                        "cache_conflict_fastpath_total"),
                },
                "fanout": {
                    "queue_depth": g.get("watch_fanout_queue_depth"),
                    "overflows": g.get("watch_fanout_overflows_total"),
                    "delivered": g.get("watch_fanout_delivered_total"),
                    "dispatch_lag_s": g.get(
                        "watch_fanout_dispatch_lag_seconds"),
                },
                # phase labels are summed by the flat scrape, so only
                # the all-phase totals survive here
                "reconcile_phases": {
                    "count": g.get(
                        "reconcile_phase_duration_seconds_count"),
                    "seconds": g.get(
                        "reconcile_phase_duration_seconds_sum"),
                },
                # result labels (bound/unschedulable) are summed by
                # the flat scrape — only combined attempt totals here
                "scheduler": {
                    "attempts": g.get("schedule_latency_seconds_count"),
                    "seconds": g.get("schedule_latency_seconds_sum"),
                    "assumed_pods": g.get("scheduler_assumed_pods"),
                    "cache_events": g.get(
                        "scheduler_cache_events_total"),
                    "cache_rebuilds": g.get(
                        "scheduler_cache_rebuilds_total"),
                    "free_chips": g.get("scheduler_free_chips"),
                    "largest_free_gang": g.get(
                        "scheduler_largest_free_gang_chips"),
                    "fragmentation": g.get("scheduler_fragmentation"),
                },
                # reason/phase labels summed by the flat scrape
                "suspend": {
                    "suspended": g.get("notebook_suspend_total"),
                    "resumed": g.get("notebook_resume_total"),
                    "preempted": g.get("notebook_preempt_total"),
                    "phase_seconds": {
                        "count": g.get(
                            "suspend_resume_phase_seconds_count"),
                        "seconds": g.get(
                            "suspend_resume_phase_seconds_sum"),
                    },
                },
                # role/phase labels summed by the flat scrape
                "jobs": {
                    "running": g.get("tpujob_running"),
                    "ready_pods": g.get("tpujob_ready_pods"),
                    "phase_transitions": g.get(
                        "tpujob_phase_transitions_total"),
                },
                # shard labels summed by the flat scrape: fleet-wide
                # WAL/snapshot totals (per-shard split needs the
                # labelled exposition, not this backend)
                "persistence": {
                    "shard": None,
                    "ring_members": g.get("shard_ring_members"),
                    "wal_fsyncs": g.get("wal_fsync_seconds_count"),
                    "wal_fsync_s": g.get("wal_fsync_seconds_sum"),
                    "wal_bytes": g.get("wal_bytes_total"),
                    "snapshots": g.get(
                        "snapshot_duration_seconds_count"),
                    "snapshot_s": g.get(
                        "snapshot_duration_seconds_sum"),
                },
                "readiness": {
                    "waiters": g.get("readiness_waiters"),
                    "wakes": g.get(
                        "readiness_wake_to_observe_seconds_count"),
                    "wake_to_observe_s": g.get(
                        "readiness_wake_to_observe_seconds_sum"),
                },
                # tenant/result/reason labels summed by the flat scrape
                "serving": {
                    "queue_depth": g.get("serving_queue_depth"),
                    "active_slots": g.get("serving_active_slots"),
                    "slot_capacity": g.get("serving_slot_capacity"),
                    "batch_occupancy": g.get("serving_batch_occupancy"),
                    "requests_ok": None,
                    "requests_shed": None,
                    "shed": g.get("serving_shed_total"),
                    "generated_tokens": g.get(
                        "serving_generated_tokens_total"),
                    "request_latency": {
                        "count": g.get(
                            "serving_request_latency_seconds_count"),
                        "seconds": g.get(
                            "serving_request_latency_seconds_sum"),
                    },
                },
                # module labels summed by the flat scrape
                "errors": {
                    "swallowed": g.get("swallowed_errors_total"),
                },
            },
        }


def make_metrics_service(api, backend: str | None = None,
                         prometheus_url: str | None = None
                         ) -> MetricsService:
    """The factory (``metrics_service_factory.ts`` equivalent).
    Backend from the arg or ``KFRM_METRICS_BACKEND``; unknown names
    raise so a typo can't silently fall back."""
    backend = backend or os.environ.get("KFRM_METRICS_BACKEND",
                                        "inventory")
    if backend == "inventory":
        return InventoryMetricsService(api)
    if backend == "prometheus":
        url = prometheus_url or os.environ.get("KFRM_PROMETHEUS_URL")
        if not url:
            raise ValueError(
                "prometheus metrics backend needs KFRM_PROMETHEUS_URL")
        return PrometheusMetricsService(url)
    raise ValueError(f"unknown metrics backend {backend!r} "
                     "(inventory|prometheus)")


class MetricsHistory:
    """Ring buffer of timestamped snapshots behind the dashboard's
    utilization-over-time charts. Samples on a daemon thread every
    ``interval_s`` (0 = only on demand); ``series()`` also takes a
    fresh sample when the last one is stale, so a just-opened
    dashboard always has a current point."""

    def __init__(self, service: MetricsService, *,
                 interval_s: float = 10.0, capacity: int = 720):
        self.service = service
        self.interval_s = interval_s
        self._ring: collections.deque = collections.deque(
            maxlen=capacity)
        self._lock = make_lock("metrics_service.sampler")
        self._stop = threading.Event()
        self._thread_started = False
        self._thread_lock = make_lock("metrics_service.sampler_thread")
        # seed one point synchronously so a just-booted dashboard has
        # a current sample; the polling thread starts LAZILY on the
        # first history read, so apps that never chart never pay for
        # (or leak) a sampler thread
        try:
            self.sample()
        except Exception:  # noqa: BLE001 - charts are best-effort
            cp_metrics.swallowed("metrics_service", "seed sample")

    def _ensure_thread(self):
        if self.interval_s <= 0 or self._thread_started:
            return
        with self._thread_lock:
            if not self._thread_started:
                threading.Thread(target=self._loop,
                                 daemon=True).start()
                self._thread_started = True

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.sample()
            except Exception:  # noqa: BLE001 - keep sampling
                cp_metrics.swallowed("metrics_service", "sampler tick")

    def stop(self):
        self._stop.set()

    def sample(self) -> dict:
        snap = self.service.snapshot()
        m = snap.get("metrics") or {}
        point = {"t": time.time(),
                 "chips_used": m.get("chips_requested"),
                 "chips_capacity": m.get("chips_capacity"),
                 "notebooks_running": m.get("notebooks_running")}
        with self._lock:
            self._ring.append(point)
        return point

    def series(self, max_points: int = 360) -> list[dict]:
        self._ensure_thread()
        with self._lock:
            fresh = (not self._ring or
                     time.time() - self._ring[-1]["t"] >
                     max(self.interval_s, 1.0))
        if fresh:
            self.sample()
        with self._lock:
            pts = list(self._ring)
        return pts[-max_points:]
