"""Push-based readiness hub for the web apps.

The conformance client (and the SPA) used to discover ``slice Ready``
by polling the notebook status on a fixed 50ms tick — so observed
readiness was quantized to the poll interval and every waiting client
cost a status GET per tick. The hub inverts that: it subscribes ONCE
to the backend's watch stream (``add_watcher`` on the in-memory
apiserver's async fanout, or the kube adapter's watch threads) and
wakes blocked readiness long-polls the moment a Notebook event lands.

Wakeups are edge-triggered on a PER-KEY sequence number, kube
wait.Until-style: the waiter snapshots its key's sequence *before*
reading the object (no lost-wakeup window), re-checks its predicate
on every bump, and falls back to a coarse 1s guard tick so a wedged
watch degrades to slow rather than hung. Keying the condition by
``(namespace, name)`` keeps a 20-way storm from thundering-herd
waking every parked long-poll on every sibling's event — only the
event's own waiters (and, on a TOO_OLD overflow, everyone) pay a
wakeup.

``_on_event`` does O(1) work under per-key locks — a slow or
disconnected long-poll client can never back-pressure the apiserver's
write path (the async fanout channel absorbs it; see
test_watch_fanout).
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from kubeflow_rm_tpu.controlplane import metrics, tracing
from kubeflow_rm_tpu.analysis.lockgraph import make_condition, make_lock

# a wedged watch degrades to this guard tick instead of hanging waiters
_GUARD_TICK_S = 1.0


class _KeyState:
    """One waited-on notebook: its condition, edge counter, and the
    perf_counter() of its last event (feeds the wake-to-observe
    histogram: hub-arrival -> waiter-observation)."""

    __slots__ = ("cond", "seq", "event_t", "waiters")

    def __init__(self) -> None:
        self.cond = make_condition("readiness.key")
        self.seq = 0
        self.event_t: float | None = None
        self.waiters = 0


class ReadinessHub:
    """Fan-in point between the watch stream and readiness long-polls."""

    def __init__(self, api) -> None:
        self._lock = make_lock("readiness.registry")  # key registry
        self._keys: dict[tuple[str, str], _KeyState] = {}
        backend = getattr(api, "api", api)
        backend.add_watcher(self._on_event, name="readiness-hub")

    def _state(self, key: tuple[str, str]) -> _KeyState | None:
        with self._lock:
            return self._keys.get(key)

    def _register(self, key: tuple[str, str]) -> _KeyState:
        # waiter-count changes happen under the registry lock so a new
        # waiter can never receive a state a leaving waiter is retiring
        with self._lock:
            st = self._keys.get(key)
            if st is None:
                st = self._keys[key] = _KeyState()
            with st.cond:
                st.waiters += 1
            return st

    def _deregister(self, key: tuple[str, str], st: _KeyState) -> None:
        with self._lock:
            with st.cond:
                st.waiters -= 1
                if st.waiters == 0 and self._keys.get(key) is st:
                    del self._keys[key]

    # -- watch side ----------------------------------------------------
    def _on_event(self, etype: str, obj: dict, old=None) -> None:
        if etype == "TOO_OLD":
            # overflow sentinel: state unknown — wake every waiter so
            # each re-fetches and re-evaluates its predicate
            with self._lock:
                states = list(self._keys.values())
            for st in states:
                with st.cond:
                    st.seq += 1
                    st.cond.notify_all()
            return
        if obj.get("kind") != "Notebook":
            return
        md = obj.get("metadata") or {}
        key = (md.get("namespace") or "", md.get("name") or "")
        st = self._state(key)
        if st is None:
            return  # nobody is waiting on this notebook
        now = time.perf_counter()
        with st.cond:
            st.seq += 1
            # DELETED still stamps: waiters observing the delete get a
            # wake-to-observe sample like any other edge
            st.event_t = now
            st.cond.notify_all()

    # -- waiter side ---------------------------------------------------
    def wait(self, namespace: str, name: str, timeout_s: float,
             fetch: Callable[[], dict | None],
             satisfied: Callable[[dict | None], bool]):
        """Block until ``satisfied(fetch())`` or ``timeout_s`` elapses.

        Returns ``(obj, changed)`` where ``obj`` is the last fetched
        state and ``changed`` says whether the predicate was met.
        """
        # the readiness wake is the LAST hop of a provision trace: the
        # span covers park -> watch-event wake -> predicate satisfied,
        # so critical-path attribution separates "waiting on the
        # controller" from handler overhead
        with tracing.start_span_if_active(
                "readiness.wait",
                attrs={"namespace": namespace, "name": name}) as sp:
            obj, changed = self._wait_inner(namespace, name, timeout_s,
                                            fetch, satisfied)
            sp.set_attr("satisfied", changed)
            return obj, changed

    def _wait_inner(self, namespace: str, name: str, timeout_s: float,
                    fetch: Callable[[], dict | None],
                    satisfied: Callable[[dict | None], bool]):
        deadline = time.monotonic() + max(0.0, timeout_s)
        key = (namespace, name)
        t_start = time.perf_counter()
        waited = False
        st = self._register(key)
        metrics.READINESS_WAITERS.inc()
        try:
            while True:
                # snapshot the sequence BEFORE fetching: an event that
                # lands during the fetch bumps it and skips the wait
                with st.cond:
                    seq = st.seq
                obj = fetch()
                if satisfied(obj):
                    if waited:
                        with st.cond:
                            evt = st.event_t
                        if evt is not None and evt >= t_start:
                            metrics.READINESS_WAKE_TO_OBSERVE_SECONDS \
                                .observe(max(0.0,
                                             time.perf_counter() - evt))
                    return obj, True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return obj, False
                with st.cond:
                    if st.seq == seq:
                        st.cond.wait(min(remaining, _GUARD_TICK_S))
                waited = True
        finally:
            metrics.READINESS_WAITERS.dec()
            self._deregister(key, st)
