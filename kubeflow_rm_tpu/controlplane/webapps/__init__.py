"""Web apps (SURVEY.md §2.2–§2.3 layer L5): werkzeug backends over the
apiserver — jupyter (spawner), volumes, tensorboards, KFAM, dashboard —
all built on the shared ``core.WebApp`` pipeline (authn/authz/CSRF/
probes/envelopes), the crud_backend equivalent."""

from kubeflow_rm_tpu.controlplane.webapps.core import WebApp

__all__ = ["WebApp"]
