"""Single-origin gateway over every web app — the role the Istio
gateway + VirtualService path routes play in-cluster
(``deploy/manifests._webapp_virtualservice``) and the reference
dashboard's Express proxy plays in dev
(``centraldashboard/app/server.ts:56-91``).

``make_gateway`` mounts:

    /                     central dashboard API + SPA shell + static
    /jupyter/...          jupyter web app (spawner)
    /volumes/...          volumes web app
    /tensorboards/...     tensorboards web app
    /kfam/...             access management

Used by the ``dashboard`` process entrypoint, the wallclock conformance
stack, and browser e2e runs. ``dev_user`` plays the mesh auth proxy:
it stamps the trusted identity header on every request, which is how a
browser (that has no Istio sidecar in front of it) gets an identity in
dev/e2e — NEVER set it behind a real proxy.
"""

from __future__ import annotations

from werkzeug.middleware.dispatcher import DispatcherMiddleware

from kubeflow_rm_tpu.controlplane.webapps import (
    dashboard as dashboard_mod,
    jupyter as jupyter_mod,
    kfam as kfam_mod,
    tensorboards as tensorboards_mod,
    volumes as volumes_mod,
)
from kubeflow_rm_tpu.controlplane.webapps.core import USER_HEADER, USER_PREFIX


def make_gateway(api, *, dev_user: str | None = None,
                 secure_cookies: bool = True):
    """One WSGI app path-routing every web app off a shared backend."""
    kw = dict(secure_cookies=secure_cookies)
    gw = DispatcherMiddleware(
        dashboard_mod.create_app(api, **kw),
        {
            "/jupyter": jupyter_mod.create_app(api, **kw),
            "/volumes": volumes_mod.create_app(api, **kw),
            "/tensorboards": tensorboards_mod.create_app(api, **kw),
            "/kfam": kfam_mod.create_app(api, **kw),
        },
    )
    if dev_user is None:
        return gw

    header_key = "HTTP_" + USER_HEADER.upper().replace("-", "_")

    def with_identity(environ, start_response):
        # Overwrite unconditionally: dev_user pins the identity, so a
        # client-supplied header must not be able to impersonate others.
        environ[header_key] = USER_PREFIX + dev_user
        return gw(environ, start_response)

    return with_identity
