"""Tensorboards web app backend.

Behavioral mirror of the reference TWA
(``crud-web-apps/tensorboards/backend/app/routes``): Tensorboard CR
CRUD keyed on ``{name, logspath}`` (``post.py:14-38`` requires both),
with the same ``pvc://`` / ``gs://`` logspath vocabulary the
tensorboard controller consumes. GCS paths need no secret here —
workload identity on default-editor covers them (the TPU-native
replacement for the reference's ``user-gcp-sa`` secret mount).
"""

from __future__ import annotations

from werkzeug.exceptions import BadRequest

from kubeflow_rm_tpu.controlplane.api.meta import deep_get
from kubeflow_rm_tpu.controlplane.apiserver import APIServer
from kubeflow_rm_tpu.controlplane.controllers.tensorboard import (
    KIND, make_tensorboard, parse_logspath,
)
from kubeflow_rm_tpu.controlplane.webapps.core import WebApp, json_body


def create_app(api: APIServer, *, disable_auth: bool = False,
               prefix: str = "", **app_kwargs) -> WebApp:
    app = WebApp("tensorboards", api, prefix=prefix,
                 disable_auth=disable_auth, **app_kwargs)

    @app.route("/api/namespaces/<namespace>/tensorboards")
    def list_tensorboards(req, namespace):
        app.ensure_authorized(req, "list", "tensorboards", namespace)
        out = []
        for tb in api.list(KIND, namespace):
            ready = deep_get(tb, "status", "readyReplicas", default=0)
            out.append({
                "name": tb["metadata"]["name"],
                "namespace": namespace,
                "logspath": deep_get(tb, "spec", "logspath"),
                "status": {"phase": "ready" if ready else "waiting"},
                "age": tb["metadata"].get("creationTimestamp"),
            })
        return {"tensorboards": out}

    @app.route("/api/namespaces/<namespace>/tensorboards",
               methods=("POST",))
    def post_tensorboard(req, namespace):
        app.ensure_authorized(req, "create", "tensorboards", namespace)
        body = json_body(req)
        for field in ("name", "logspath"):
            if field not in body:
                raise BadRequest(f"'{field}' is a required body field")
        scheme, _, _ = parse_logspath(body["logspath"])
        if scheme == "raw":
            raise BadRequest(
                "logspath must be a pvc:// or gs:// URI, got "
                f"{body['logspath']!r}")
        api.create(make_tensorboard(body["name"], namespace,
                                    body["logspath"]))
        return {"message": "Tensorboard created successfully."}

    @app.route("/api/namespaces/<namespace>/tensorboards/<name>",
               methods=("DELETE",))
    def delete_tensorboard(req, namespace, name):
        app.ensure_authorized(req, "delete", "tensorboards", namespace)
        api.delete(KIND, name, namespace)
        return {"message": "Tensorboard deleted successfully."}

    return app
