"""Volumes web app backend — PVC CRUD + PVCViewer lifecycle.

Behavioral mirror of the reference VWA
(``crud-web-apps/volumes/backend``): PVC list/create/delete with
in-use detection (a PVC mounted by a pod can't be deleted from the
UI), plus the file-browser viewer flow — the backend stamps a
templated PVCViewer CR per PVC (``apps/common/viewer.py:16-49``
substitutes ``$NAME``/``$PVC_NAME``/``$NAMESPACE`` into a viewer-spec
mounted from a ConfigMap; here the template is injectable the same
way) and the pvcviewer controller renders it.
"""

from __future__ import annotations

import copy
from string import Template

from werkzeug.exceptions import BadRequest, Conflict

from kubeflow_rm_tpu.controlplane.api.meta import deep_get
from kubeflow_rm_tpu.controlplane.apiserver import APIServer
from kubeflow_rm_tpu.controlplane.controllers.pvcviewer import (
    API_VERSION as VIEWER_API_VERSION, KIND as VIEWER_KIND,
)
from kubeflow_rm_tpu.controlplane.webapps.core import WebApp, json_body

# default viewer spec (the reference ships this as a ConfigMap mounted
# at /etc/config/viewer-spec.yaml)
DEFAULT_VIEWER_SPEC = {"pvc": "$PVC_NAME"}


def create_app(api: APIServer, *, viewer_spec: dict | None = None,
               disable_auth: bool = False, prefix: str = "", **app_kwargs) -> WebApp:
    app = WebApp("volumes", api, prefix=prefix, disable_auth=disable_auth, **app_kwargs)
    spec_template = viewer_spec or DEFAULT_VIEWER_SPEC

    @app.route("/api/namespaces/<namespace>/pvcs")
    def list_pvcs(req, namespace):
        app.ensure_authorized(req, "list", "persistentvolumeclaims",
                              namespace)
        pods = api.list("Pod", namespace)
        out = []
        for pvc in api.list("PersistentVolumeClaim", namespace):
            name = pvc["metadata"]["name"]
            mounted_by = [
                p["metadata"]["name"] for p in pods
                if any(deep_get(v, "persistentVolumeClaim", "claimName")
                       == name
                       for v in deep_get(p, "spec", "volumes",
                                         default=[]) or [])
            ]
            viewer = api.try_get(VIEWER_KIND, name, namespace)
            out.append({
                "pvc": pvc,
                "inUseBy": mounted_by,
                "viewer": (deep_get(viewer, "status", "phase",
                                    default="ready")
                           if viewer else None),
            })
        return {"pvcs": out}

    @app.route("/api/namespaces/<namespace>/pvcs", methods=("POST",))
    def post_pvc(req, namespace):
        app.ensure_authorized(req, "create", "persistentvolumeclaims",
                              namespace)
        body = json_body(req)
        pvc = body.get("pvc") or {}
        if not deep_get(pvc, "metadata", "name"):
            raise BadRequest("'pvc.metadata.name' is required")
        pvc.setdefault("apiVersion", "v1")
        pvc.setdefault("kind", "PersistentVolumeClaim")
        pvc["metadata"]["namespace"] = namespace
        api.create(pvc)
        return {"message": "PVC created successfully."}

    @app.route("/api/namespaces/<namespace>/pvcs/<name>",
               methods=("DELETE",))
    def delete_pvc(req, namespace, name):
        app.ensure_authorized(req, "delete", "persistentvolumeclaims",
                              namespace)
        # the PVC's own viewer goes first (its filebrowser pod mounts
        # the PVC and must not count as an external user)
        if api.try_get(VIEWER_KIND, name, namespace):
            api.delete(VIEWER_KIND, name, namespace)
        pods = api.list("Pod", namespace)
        users = [p["metadata"]["name"] for p in pods
                 if any(deep_get(v, "persistentVolumeClaim", "claimName")
                        == name
                        for v in deep_get(p, "spec", "volumes",
                                          default=[]) or [])]
        if users:
            raise Conflict(f"PVC {name} is in use by pods: {users}")
        api.delete("PersistentVolumeClaim", name, namespace)
        return {"message": "PVC deleted successfully."}

    @app.route("/api/namespaces/<namespace>/viewers/<pvc>",
               methods=("POST",))
    def post_viewer(req, namespace, pvc):
        app.ensure_authorized(req, "create", "pvcviewers", namespace)
        api.get("PersistentVolumeClaim", pvc, namespace)  # 404 if absent
        spec = _substitute(copy.deepcopy(spec_template),
                           {"NAME": pvc, "PVC_NAME": pvc,
                            "NAMESPACE": namespace})
        api.create({
            "apiVersion": VIEWER_API_VERSION,
            "kind": VIEWER_KIND,
            "metadata": {"name": pvc, "namespace": namespace},
            "spec": spec,
        })
        return {"message": "PVCViewer created successfully."}

    @app.route("/api/namespaces/<namespace>/viewers/<pvc>",
               methods=("DELETE",))
    def delete_viewer(req, namespace, pvc):
        app.ensure_authorized(req, "delete", "pvcviewers", namespace)
        api.delete(VIEWER_KIND, pvc, namespace)
        return {"message": "PVCViewer deleted successfully."}

    return app


def _substitute(node, variables: dict):
    """Recursive $VAR substitution (viewer.py:16-49 equivalent)."""
    if isinstance(node, str):
        return Template(node).safe_substitute(variables)
    if isinstance(node, list):
        return [_substitute(x, variables) for x in node]
    if isinstance(node, dict):
        return {k: _substitute(v, variables) for k, v in node.items()}
    return node
