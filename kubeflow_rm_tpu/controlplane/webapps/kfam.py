"""Access management (KFAM) REST service.

Behavioral mirror of the reference's Go KFAM
(``access-management/kfam/routers.go:32-90``): the dashboard's
profile/contributor management API. Endpoints:

- ``/kfam/v1/bindings`` GET/POST/DELETE — contributor management: a
  binding ``{user, referredNamespace, roleRef}`` becomes a RoleBinding
  (role mapped through admin/edit/view → kubeflow-* —
  ``bindings.go:33-40``) plus an Istio AuthorizationPolicy admitting
  that user's identity header through the gateway
  (``bindings.go:79-157``).
- ``/kfam/v1/profiles`` POST / ``/kfam/v1/profiles/<name>`` DELETE —
  registration flow (``api_default.go:134-156``).
- ``/kfam/v1/role/clusteradmin`` GET — admin check backed by the
  apiserver's access review (the reference submits a
  SubjectAccessReview — ``api_default.go:104-132``).
"""

from __future__ import annotations

import functools
import re

from werkzeug.exceptions import BadRequest, Forbidden

from kubeflow_rm_tpu.controlplane.api.meta import deep_get, make_object
from kubeflow_rm_tpu.controlplane.api.profile import make_profile
from kubeflow_rm_tpu.controlplane.apiserver import APIServer
from kubeflow_rm_tpu.controlplane.metrics import KFAM_REQUESTS_TOTAL
from kubeflow_rm_tpu.controlplane.webapps.core import (
    USER_HEADER, USER_PREFIX, WebApp, json_body,
)

USER_ANNOTATION = "user"
ROLE_ANNOTATION = "role"

ROLE_MAP = {"admin": "kubeflow-admin", "edit": "kubeflow-edit",
            "view": "kubeflow-view"}


def _counted(action: str):
    """Per-action success/error counters, the reference's KFAM
    prometheus surface (``kfam/monitoring.go:46-77``); scraped from
    this app's ``/metrics`` like every control-plane process.

    Counts requests that REACH the handler — in-handler authz denials
    land in the ``error`` bucket, while gateway-level rejections
    (missing identity header, CSRF) happen before dispatch and are not
    KFAM actions, the same boundary the reference has behind its
    mesh's auth filter."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*a, **kw):
            try:
                out = fn(*a, **kw)
            except Exception:
                KFAM_REQUESTS_TOTAL.labels(action, "error").inc()
                raise
            KFAM_REQUESTS_TOTAL.labels(action, "success").inc()
            return out
        return wrapper
    return deco


def binding_name(user: str, role: str) -> str:
    safe = re.sub(r"[^a-z0-9]", "-", user.lower())
    return f"user-{safe}-clusterrole-{ROLE_MAP[role]}"


def create_app(api: APIServer, *, disable_auth: bool = False,
               prefix: str = "", **app_kwargs) -> WebApp:
    app = WebApp("kfam", api, prefix=prefix, disable_auth=disable_auth, **app_kwargs)

    @app.route("/kfam/v1/bindings")
    @_counted("read_bindings")
    def get_bindings(req):
        ns_filter = req.args.get("namespace")
        user_filter = req.args.get("user")
        role_filter = req.args.get("role")
        out = []
        if ns_filter:
            # explicit namespace: hard 403 if the caller may not read
            # its role grants (ADVICE r2: was world-readable)
            app.ensure_authorized(req, "list", "rolebindings", ns_filter)
            namespaces = [ns_filter]
        else:
            # cluster-wide listing: silently scope to namespaces the
            # caller may read, mirroring the reference's per-namespace
            # SubjectAccessReview filtering
            caller = app.username(req)
            namespaces = [
                n["metadata"]["name"] for n in api.list("Namespace")
                if app.disable_auth or api.access_review(
                    caller, "list", "rolebindings",
                    n["metadata"]["name"])
            ]
        for ns in namespaces:
            for rb in api.list("RoleBinding", ns):
                ann = rb["metadata"].get("annotations") or {}
                if USER_ANNOTATION not in ann:
                    continue  # not a KFAM-managed binding
                role = ann.get(ROLE_ANNOTATION)
                if user_filter and ann[USER_ANNOTATION] != user_filter:
                    continue
                if role_filter and role != role_filter:
                    continue
                out.append({
                    "user": {"kind": "User",
                             "name": ann[USER_ANNOTATION]},
                    "referredNamespace": ns,
                    "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                                "kind": "ClusterRole", "name": role},
                })
        return {"bindings": out}

    @app.route("/kfam/v1/bindings", methods=("POST",))
    @_counted("create_binding")
    def post_binding(req):
        b = _parse_binding(json_body(req))
        ns, user, role = b
        app.ensure_authorized(req, "create", "rolebindings", ns)
        name = binding_name(user, role)
        rb = make_object("rbac.authorization.k8s.io/v1", "RoleBinding",
                         name, ns,
                         annotations={USER_ANNOTATION: user,
                                      ROLE_ANNOTATION: role})
        rb["roleRef"] = {"apiGroup": "rbac.authorization.k8s.io",
                         "kind": "ClusterRole", "name": ROLE_MAP[role]}
        rb["subjects"] = [{"kind": "User", "name": user,
                           "apiGroup": "rbac.authorization.k8s.io"}]
        api.create(rb)

        authz = make_object("security.istio.io/v1beta1",
                            "AuthorizationPolicy", name, ns,
                            annotations={USER_ANNOTATION: user,
                                         ROLE_ANNOTATION: role})
        authz["spec"] = {"rules": [{
            "when": [{
                "key": f"request.headers[{USER_HEADER}]",
                "values": [USER_PREFIX + user],
            }],
        }]}
        api.create(authz)
        return {"message": "Binding created successfully."}

    @app.route("/kfam/v1/bindings", methods=("DELETE",))
    @_counted("delete_binding")
    def delete_binding(req):
        ns, user, role = _parse_binding(json_body(req))
        app.ensure_authorized(req, "delete", "rolebindings", ns)
        name = binding_name(user, role)
        api.delete("RoleBinding", name, ns)
        if api.try_get("AuthorizationPolicy", name, ns):
            api.delete("AuthorizationPolicy", name, ns)
        return {"message": "Binding deleted successfully."}

    @app.route("/kfam/v1/profiles")
    @_counted("read_profiles")
    def get_profiles(req):
        profiles = api.list("Profile")
        if app.disable_auth:
            return {"profiles": profiles}
        caller = app.username(req)
        if api.access_review(caller, "list", "profiles"):
            return {"profiles": profiles}  # cluster admin sees all
        # everyone else: own profiles + namespaces they contribute to
        visible = []
        for p in profiles:
            name = p["metadata"]["name"]
            if deep_get(p, "spec", "owner", "name") == caller or \
                    api.access_review(caller, "get", "profiles", name):
                visible.append(p)
        return {"profiles": visible}

    @app.route("/kfam/v1/profiles", methods=("POST",))
    @_counted("create_profile")
    def post_profile(req):
        body = json_body(req)
        name = deep_get(body, "metadata", "name")
        owner = deep_get(body, "spec", "owner", "name")
        if not name or not owner:
            raise BadRequest("profile requires metadata.name and "
                             "spec.owner.name")
        # self-registration (the dashboard workgroup flow) is always
        # allowed; creating a profile for SOMEONE ELSE requires real
        # create-profiles RBAC (ADVICE r2: was unauthenticated)
        caller = app.username(req)
        if not app.disable_auth and owner != caller and \
                not api.access_review(caller, "create", "profiles"):
            raise Forbidden(
                f"User '{caller}' may not create a profile owned by "
                f"'{owner}'")
        api.create(make_profile(name, owner))
        return {"message": "Profile created successfully."}

    @app.route("/kfam/v1/profiles/<name>", methods=("DELETE",))
    @_counted("delete_profile")
    def delete_profile(req, name):
        profile = api.get("Profile", name)
        user = app.username(req)
        owner = deep_get(profile, "spec", "owner", "name")
        if not app.disable_auth and user not in (owner,) and \
                not api.access_review(user, "delete", "profiles"):
            raise Forbidden(f"User '{user}' may not delete profile "
                            f"'{name}' owned by '{owner}'")
        api.delete("Profile", name)
        return {"message": "Profile deleted successfully."}

    @app.route("/kfam/v1/role/clusteradmin")
    @_counted("read_clusteradmin")
    def get_clusteradmin(req):
        user = req.args.get("user") or app.username(req)
        is_admin = api.access_review(user, "*", "*")
        return {"clusteradmin": bool(is_admin)}

    return app


def _parse_binding(body: dict) -> tuple[str, str, str]:
    user = deep_get(body, "user", "name")
    ns = body.get("referredNamespace")
    role = deep_get(body, "roleRef", "name")
    if not (user and ns and role):
        raise BadRequest("binding requires user.name, referredNamespace "
                         "and roleRef.name")
    if role not in ROLE_MAP:
        raise BadRequest(f"roleRef.name must be one of {sorted(ROLE_MAP)}")
    return ns, user, role
