"""End-to-end distributed tracing for the control plane.

Every observability signal before this module was an *aggregate* —
histograms and ``PhaseRecorder`` percentiles can say provision p95 is
458 ms but not why ONE notebook took 2 s. This module adds the causal
layer: W3C-traceparent-style contexts (trace_id / span_id / parent_id)
carried across every boundary a request crosses:

- **threads**: a thread-local current span; ``start_span`` parents new
  spans on it automatically.
- **HTTP hops**: clients inject a ``traceparent`` header
  (``deploy/kubeclient.py``), servers extract it and open a server
  span (``deploy/restserver.py``, ``webapps/core.py``) — cross-shard
  hops through ``ShardedKubeAPIServer`` stay one trace.
- **async causality**: writes stamp the live context into the object's
  ``tpu.kubeflow.org/trace`` annotation, the controller runtime lifts
  it off watch events into workqueue items, and the reconcile opens a
  child span — the POST that created a Notebook parents the reconcile
  that runs 50 ms later on another thread (or another process).

Spans land in a per-process ``SpanCollector``: a bounded ring (recent
spans, lock held only for an append) plus tail-sampled *slow-trace*
retention — when a ROOT span ends slower than the retention threshold
the whole trace is copied aside before ring eviction can shred it, so
the interesting exemplars survive a storm. ``critical_path`` reduces a
trace's span tree to the ordered blocking chain with per-hop
self-time; the segments partition the root interval, so self-times sum
to the root's wallclock by construction.

Tracing is OFF by default and the disabled path is near-zero cost:
``start_span`` returns a shared no-op context manager after one
boolean check, and propagation call sites gate on ``enabled()``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from kubeflow_rm_tpu.analysis.lockgraph import make_lock

#: annotation key carrying a serialized context across async hops
TRACE_ANNOTATION = "tpu.kubeflow.org/trace"
#: HTTP header (W3C trace-context). Version 00, sampled flag 01.
TRACE_HEADER = "traceparent"

_tls = threading.local()


# ---------------------------------------------------------------------------
# ids — os.urandom is ~100ns and needs no seeding discipline across
# the spawn'd shard processes (a shared PRNG state would collide)
# ---------------------------------------------------------------------------

def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


# ---------------------------------------------------------------------------
# span + context
# ---------------------------------------------------------------------------

class SpanContext:
    """Just enough identity to parent a remote/async child."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    def __repr__(self):  # pragma: no cover - debug aid
        return f"SpanContext({self.to_traceparent()})"


class Span:
    """One timed operation. ``start``/``end`` are epoch seconds
    (``time.time()``) so spans from different PROCESSES on the same
    host order correctly — perf_counter bases diverge across spawn."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "kind",
                 "start", "end", "attrs", "process")

    def __init__(self, name: str, *, trace_id: str, span_id: str,
                 parent_id: str | None, kind: str = "internal",
                 start: float | None = None,
                 attrs: dict | None = None, process: str = ""):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.kind = kind
        self.start = time.time() if start is None else start
        self.end: float | None = None
        self.attrs = attrs or {}
        self.process = process

    # context-ish surface so callers can parent on a live span
    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def duration_ms(self) -> float | None:
        if self.end is None:
            return None
        return (self.end - self.start) * 1e3

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "kind": self.kind,
            "start": self.start,
            "end": self.end,
            "duration_ms": None if self.end is None
            else round((self.end - self.start) * 1e3, 3),
            "process": self.process,
            "attrs": self.attrs,
        }


def parse_traceparent(header: str | None) -> SpanContext | None:
    """``00-<32 hex>-<16 hex>-<2 hex>`` → SpanContext, else None.
    Tolerant: malformed headers are dropped, never raised on — a bad
    client must not 500 the apiserver."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    _, trace_id, span_id, _ = parts
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    return SpanContext(trace_id, span_id)


# ---------------------------------------------------------------------------
# collector: bounded ring + tail-sampled slow-trace retention
# ---------------------------------------------------------------------------

class SpanCollector:
    """Per-process span sink.

    ``add`` appends to a bounded ring under a lock held only for the
    append (deque.append is O(1); eviction is implicit). When a ROOT
    span (no parent) finishes slower than ``slow_threshold_s`` — the
    tail-sampling decision, made when the outcome is KNOWN — the whole
    trace is copied into the slow store, itself bounded to the
    ``slow_keep`` slowest traces so a storm cannot grow it unbounded.
    """

    def __init__(self, capacity: int = 8192, *,
                 slow_threshold_s: float = 0.25, slow_keep: int = 32):
        self._lock = make_lock("tracing.collector")
        self._ring: deque[Span] = deque(maxlen=capacity)
        self.slow_threshold_s = slow_threshold_s
        self.slow_keep = slow_keep
        # trace_id -> (root_duration_s, [span dicts])
        self._slow: dict[str, tuple[float, list[dict]]] = {}
        self.dropped = 0
        self.added = 0

    def add(self, span: Span) -> None:
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(span)
            self.added += 1
            if (span.parent_id is None and span.end is not None
                    and span.end - span.start >= self.slow_threshold_s):
                self._retain_slow_locked(span)

    def _retain_slow_locked(self, root: Span) -> None:
        dur = root.end - root.start
        if len(self._slow) >= self.slow_keep:
            fastest = min(self._slow, key=lambda t: self._slow[t][0])
            if self._slow[fastest][0] >= dur:
                return  # slower traces already retained; drop this one
            del self._slow[fastest]
        spans = [s.to_dict() for s in self._ring
                 if s.trace_id == root.trace_id]
        self._slow[root.trace_id] = (dur, spans)

    # -- export --------------------------------------------------------
    def spans(self) -> list[dict]:
        """Every span currently held: ring ∪ slow store (deduped)."""
        with self._lock:
            out = {(s.trace_id, s.span_id): s.to_dict()
                   for s in self._ring}
            for _, spans in self._slow.values():
                for d in spans:
                    out.setdefault((d["trace_id"], d["span_id"]), d)
        return list(out.values())

    def traces(self) -> dict[str, list[dict]]:
        grouped: dict[str, list[dict]] = {}
        for d in self.spans():
            grouped.setdefault(d["trace_id"], []).append(d)
        for spans in grouped.values():
            spans.sort(key=lambda d: d["start"])
        return grouped

    def get_trace(self, trace_id: str) -> list[dict]:
        return sorted(
            (d for d in self.spans() if d["trace_id"] == trace_id),
            key=lambda d: d["start"])

    def slow_traces(self) -> list[dict]:
        """Tail-retained exemplars, slowest first."""
        with self._lock:
            items = sorted(self._slow.items(),
                           key=lambda kv: kv[1][0], reverse=True)
        return [{"trace_id": tid, "duration_ms": round(dur * 1e3, 3),
                 "spans": spans} for tid, (dur, spans) in items]

    def export_json(self) -> str:
        return json.dumps({"spans": self.spans(),
                           "slow": self.slow_traces(),
                           "added": self.added,
                           "dropped": self.dropped})

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._slow.clear()
            self.dropped = 0
            self.added = 0


_collector = SpanCollector()
_process_name = ""


def collector() -> SpanCollector:
    return _collector


def set_process(name: str) -> None:
    """Tag every span this process emits (shard name); feeds the
    cross-process view in merged traces."""
    global _process_name
    _process_name = name


def process_name() -> str:
    return _process_name


# ---------------------------------------------------------------------------
# enable switch + thread-local current span
# ---------------------------------------------------------------------------

_enabled = False


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def current_span() -> Span | None:
    return getattr(_tls, "span", None)


def current_context() -> SpanContext | None:
    """Context of the live span, for injection into headers or
    annotations; None when tracing is off or no span is open."""
    if not _enabled:
        return None
    span = getattr(_tls, "span", None)
    return span.context() if span is not None else None


def current_traceparent() -> str | None:
    ctx = current_context()
    return ctx.to_traceparent() if ctx is not None else None


class _NullCtx:
    """The disabled fast path: one shared instance, no allocation."""

    __slots__ = ()

    def __enter__(self):
        return _NULL_SPAN

    def __exit__(self, *exc):
        return False


class _NullSpan:
    __slots__ = ()

    def set_attr(self, key, value):
        pass

    def context(self):
        return None

    def to_traceparent(self):
        return None


_NULL_SPAN = _NullSpan()
_NULL_CTX = _NullCtx()


class _SpanCtx:
    """Context manager for one live span: pushes itself as the
    thread-local current on enter, restores + collects on exit."""

    __slots__ = ("span", "_prev")

    def __init__(self, span: Span):
        self.span = span
        self._prev = None

    def __enter__(self) -> Span:
        self._prev = getattr(_tls, "span", None)
        _tls.span = self.span
        return self.span

    def __exit__(self, exc_type, exc, tb):
        _tls.span = self._prev
        self.span.end = time.time()
        if exc_type is not None:
            self.span.attrs["error"] = f"{exc_type.__name__}: {exc}"
        _collector.add(self.span)
        return False


def start_span(name: str, *, kind: str = "internal",
               parent: SpanContext | Span | str | None = None,
               root: bool = False, attrs: dict | None = None):
    """Open a span as a context manager.

    ``parent`` overrides the thread-local current span: pass a
    SpanContext (remote hop), a Span, or a raw traceparent string
    (annotation payload). ``root=True`` forces a fresh trace even if a
    current span exists. Disabled tracing returns a shared no-op after
    a single boolean check.
    """
    if not _enabled:
        return _NULL_CTX
    if isinstance(parent, str):
        parent = parse_traceparent(parent)
    if parent is None and not root:
        parent = getattr(_tls, "span", None)
    if parent is not None and not root:
        trace_id = parent.trace_id
        parent_id = parent.span_id
    else:
        trace_id = new_trace_id()
        parent_id = None
    span = Span(name, trace_id=trace_id, span_id=new_span_id(),
                parent_id=parent_id, kind=kind, attrs=attrs,
                process=_process_name)
    return _SpanCtx(span)


def start_span_if_active(name: str, *, kind: str = "internal",
                         attrs: dict | None = None):
    """Child span only when a trace is already in flight on this
    thread — internal hops (admission, reconcile phases, scheduling)
    use this so background work with no causal origin doesn't mint
    orphan root traces."""
    if not _enabled or getattr(_tls, "span", None) is None:
        return _NULL_CTX
    return start_span(name, kind=kind, attrs=attrs)


def record_span(name: str, *, start: float, end: float,
                parent: SpanContext | Span | str | None = None,
                kind: str = "internal",
                attrs: dict | None = None) -> SpanContext | None:
    """Retroactively record a span whose interval was measured
    elsewhere (e.g. the serving drain thread stamping submit→done on
    completion). Returns the new span's context for chaining."""
    if not _enabled:
        return None
    if isinstance(parent, str):
        parent = parse_traceparent(parent)
    if parent is not None:
        trace_id, parent_id = parent.trace_id, parent.span_id
    else:
        trace_id, parent_id = new_trace_id(), None
    span = Span(name, trace_id=trace_id, span_id=new_span_id(),
                parent_id=parent_id, kind=kind, start=start,
                attrs=attrs, process=_process_name)
    span.end = end
    _collector.add(span)
    return span.context()


class attach:
    """Adopt a remote context as the thread-local current WITHOUT
    opening a span — the workqueue worker uses this so annotation
    stamping inside the reconcile inherits the right trace even before
    the reconcile span opens."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx: SpanContext | str | None):
        if isinstance(ctx, str):
            ctx = parse_traceparent(ctx)
        self._ctx = ctx
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_tls, "span", None)
        if self._ctx is not None:
            # a context is not a Span; wrap it in an uncollected stub
            # that only exists to parent children
            _tls.span = Span("(attached)", trace_id=self._ctx.trace_id,
                             span_id=self._ctx.span_id, parent_id=None)
        return self._ctx

    def __exit__(self, *exc):
        _tls.span = self._prev
        return False


# ---------------------------------------------------------------------------
# annotation plumbing (async causality across watch/workqueue hops)
# ---------------------------------------------------------------------------

def stamp(obj: dict) -> None:
    """Write the live context into ``metadata.annotations`` of an
    object about to be persisted, so watch consumers can resume the
    trace. No-op when tracing is off or no span is open."""
    if not _enabled:
        return
    tp = current_traceparent()
    if tp is None:
        return
    md = obj.setdefault("metadata", {})
    ann = md.get("annotations")
    if ann is None:
        ann = md["annotations"] = {}
    # first cause wins: an object stamped at creation keeps that
    # context for life — later writers extend the SAME trace via their
    # own spans, they don't rewrite history
    ann.setdefault(TRACE_ANNOTATION, tp)


def context_of(obj: dict | None) -> SpanContext | None:
    """Read a stamped context back off an object (watch event)."""
    if not obj:
        return None
    ann = (obj.get("metadata") or {}).get("annotations") or {}
    return parse_traceparent(ann.get(TRACE_ANNOTATION))


# ---------------------------------------------------------------------------
# critical path
# ---------------------------------------------------------------------------

def critical_path(spans: list[dict]) -> list[dict]:
    """Reduce one trace's spans to the ordered blocking chain.

    Walks the span tree backwards from the root's end: at each cursor
    position the blocking span is the deepest descendant still running;
    the gap back to that child's start is charged to the parent as
    SELF time. Segments partition the root interval exactly (children
    are clipped to their parent), so ``sum(self_ms) == root duration``
    — the property the conformance artifact asserts against measured
    wallclock.

    Returns hops ordered by first appearance on the path:
    ``{name, span_id, process, kind, self_ms, start, end}``.
    """
    closed = [dict(s) for s in spans if s.get("end") is not None]
    if not closed:
        return []
    by_id = {s["span_id"]: s for s in closed}
    children: dict[str, list[dict]] = {}
    roots = []
    for s in closed:
        pid = s.get("parent_id")
        if pid and pid in by_id:
            children.setdefault(pid, []).append(s)
        else:
            roots.append(s)
    root = min(roots, key=lambda s: s["start"])

    # (span, seg_start, seg_end) self-time segments, collected by a
    # backwards walk; recursion depth = tree depth (dozens, not 1e4)
    segments: list[tuple[dict, float, float]] = []

    def walk(span: dict, start_cut: float, end_cut: float) -> None:
        cursor = end_cut
        kids = [c for c in children.get(span["span_id"], [])
                if c["start"] < end_cut and c["end"] > start_cut]
        while kids and cursor > start_cut:
            live = [c for c in kids if c["start"] < cursor]
            if not live:
                break
            # the child whose (clipped) end reaches closest to cursor
            # is what the parent was blocked on
            c = max(live, key=lambda c: min(c["end"], cursor))
            c_end = min(c["end"], cursor)
            c_start = max(c["start"], start_cut)
            if c_end < cursor:
                segments.append((span, c_end, cursor))
            walk(c, c_start, c_end)
            cursor = c_start
            kids.remove(c)
        if cursor > start_cut:
            segments.append((span, start_cut, cursor))

    walk(root, root["start"], root["end"])

    # aggregate per span, ordered by earliest segment on the path
    agg: dict[str, dict] = {}
    for span, s0, s1 in segments:
        hop = agg.get(span["span_id"])
        if hop is None:
            hop = agg[span["span_id"]] = {
                "name": span["name"],
                "span_id": span["span_id"],
                "process": span.get("process", ""),
                "kind": span.get("kind", "internal"),
                "self_ms": 0.0,
                "start": span["start"],
                "end": span["end"],
                "_first": s0,
            }
        hop["self_ms"] += (s1 - s0) * 1e3
        hop["_first"] = min(hop["_first"], s0)
    hops = sorted(agg.values(), key=lambda h: h["_first"])
    for h in hops:
        del h["_first"]
        h["self_ms"] = round(h["self_ms"], 3)
    return hops


def merge_spans(*span_lists: list[dict]) -> list[dict]:
    """Union span lists from several collectors (processes), deduped
    on (trace_id, span_id) — the cross-shard merge primitive."""
    out: dict[tuple[str, str], dict] = {}
    for spans in span_lists:
        for d in spans or []:
            out.setdefault((d["trace_id"], d["span_id"]), d)
    return sorted(out.values(), key=lambda d: d["start"])
