"""Prometheus metrics for the control plane.

Mirrors the reference's collector set
(``notebook-controller/pkg/metrics/metrics.go:13-99`` and
``profile-controller/controllers/monitoring.go:30-43``) on a dedicated
registry so tests can scrape and reset it hermetically.
"""

from __future__ import annotations

import logging

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

REGISTRY = CollectorRegistry()

NOTEBOOK_RUNNING = Gauge(
    "notebook_running",
    "Current number of notebooks with at least one ready replica",
    registry=REGISTRY,
)
NOTEBOOK_CREATE_TOTAL = Counter(
    "notebook_create_total",
    "Total notebook StatefulSets created",
    registry=REGISTRY,
)
NOTEBOOK_CREATE_FAILED_TOTAL = Counter(
    "notebook_create_failed_total",
    "Total notebook StatefulSet creations that failed",
    registry=REGISTRY,
)
NOTEBOOK_CULL_TOTAL = Counter(
    "notebook_cull_total",
    "Total notebooks culled for idleness",
    registry=REGISTRY,
)
PROFILE_CREATE_TOTAL = Counter(
    "profile_create_total",
    "Total profiles reconciled into namespaces",
    registry=REGISTRY,
)
RECONCILE_ERRORS_TOTAL = Counter(
    "reconcile_errors_total",
    "Total reconcile errors across controllers",
    ["controller"],
    registry=REGISTRY,
)
KFAM_REQUESTS_TOTAL = Counter(
    "kfam_requests_total",
    "KFAM API requests by action and result "
    "(ref access-management/kfam/monitoring.go:46-77)",
    ["action", "result"],
    registry=REGISTRY,
)
TPU_CHIPS_REQUESTED = Gauge(
    "tpu_chips_requested",
    "TPU chips currently requested by scheduled notebook pods",
    registry=REGISTRY,
)

# ---- HA runtime (controlplane/ha): leader election + workqueues ------
LEADER_IS_LEADER = Gauge(
    "leader_is_leader",
    "1 while this identity holds the controller-manager lease "
    "(controller-runtime's leader_election_master_status)",
    ["identity"],
    registry=REGISTRY,
)
WORKQUEUE_DEPTH = Gauge(
    "workqueue_depth",
    "Items waiting in a controller's work queue",
    ["name"],
    registry=REGISTRY,
)
WORKQUEUE_NAMESPACE_DEPTH = Gauge(
    "workqueue_namespace_depth",
    "Pending work-queue items broken down by the namespace they "
    "reconcile — the hot-namespace signal the shard autoscaler's "
    "carve-off reads; drained namespaces are zeroed, not dropped, so "
    "federated last-value sums never hold stale depth",
    ["name", "namespace"],
    registry=REGISTRY,
)
WORKQUEUE_ADDS_TOTAL = Counter(
    "workqueue_adds_total",
    "Total items added to a controller's work queue (pre-dedup)",
    ["name"],
    registry=REGISTRY,
)
WORKQUEUE_REQUEUES_TOTAL = Counter(
    "workqueue_requeues_total",
    "Total rate-limited (backoff) requeues per work queue",
    ["name"],
    registry=REGISTRY,
)
WORKQUEUE_RETRIES_EXHAUSTED_TOTAL = Counter(
    "workqueue_retries_exhausted_total",
    "Items dropped after exhausting their retry budget",
    ["name"],
    registry=REGISTRY,
)
WORKQUEUE_QUEUE_SECONDS = Histogram(
    "workqueue_queue_duration_seconds",
    "Time items spend waiting in a work queue before hand-out",
    ["name"],
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0),
    registry=REGISTRY,
)

# ---- informer cache (controlplane/cache): reads, suppression ---------
CACHE_READS_TOTAL = Counter(
    "cache_reads_total",
    "Read verbs against the CachedAPI by verb and whether the shared "
    "informer store served them (hit) or they fell through (miss)",
    ["verb", "result"],
    registry=REGISTRY,
)
CACHE_SUPPRESSED_WRITES_TOTAL = Counter(
    "cache_suppressed_writes_total",
    "Writes dropped by no-op suppression (desired object semantically "
    "equal to the cached current one after normalization)",
    ["verb"],
    registry=REGISTRY,
)
CACHE_CONFLICT_FASTPATH_TOTAL = Counter(
    "cache_conflict_fastpath_total",
    "Conflict resolutions attempted from the cache: noop (write already "
    "reflected in latest), rebased (disjoint three-way rebase retried), "
    "fallthrough (re-raised for the caller's retry loop)",
    ["result"],
    registry=REGISTRY,
)
INFORMER_EVENTS_TOTAL = Counter(
    "informer_events_total",
    "Watch events folded into the shared informer store, per kind",
    ["kind"],
    registry=REGISTRY,
)
INFORMER_SYNCED_KINDS = Gauge(
    "informer_synced_kinds",
    "Kinds whose initial list completed (serving reads from memory)",
    registry=REGISTRY,
)
INFORMER_LAST_EVENT_TIMESTAMP = Gauge(
    "informer_last_event_timestamp_seconds",
    "Wall time the informer last folded an event in (staleness proxy)",
    registry=REGISTRY,
)

# ---- watch fanout (apiserver async dispatch): queue health -----------
WATCH_FANOUT_QUEUE_DEPTH = Gauge(
    "watch_fanout_queue_depth",
    "Events waiting in a watcher's fanout queue (kube-apiserver's "
    "apiserver_watch_cache_events_dispatched analogue, per consumer)",
    ["watcher"],
    registry=REGISTRY,
)
WATCH_FANOUT_OVERFLOWS_TOTAL = Counter(
    "watch_fanout_overflows_total",
    "Times a watcher's bounded queue overflowed and was collapsed to a "
    "TOO_OLD sentinel forcing that watcher to relist (410 Gone analogue)",
    ["watcher"],
    registry=REGISTRY,
)
WATCH_FANOUT_DELIVERED_TOTAL = Counter(
    "watch_fanout_delivered_total",
    "Events delivered to a watcher callback by its dispatch thread",
    ["watcher"],
    registry=REGISTRY,
)
WATCH_FANOUT_DISPATCH_LAG = Gauge(
    "watch_fanout_dispatch_lag_seconds",
    "Enqueue-to-delivery latency of the most recent event per watcher",
    ["watcher"],
    registry=REGISTRY,
)

# ---- batched write path (runtime fan-out + bulk create) --------------
RECONCILE_PHASE_SECONDS = Histogram(
    "reconcile_phase_duration_seconds",
    "Per-reconcile phase timing (render / child_writes / status / "
    "events) — attributes the provisioning write chain per controller",
    ["controller", "phase"],
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0),
    registry=REGISTRY,
)
BULK_CREATE_BATCHES_TOTAL = Counter(
    "bulk_create_batches_total",
    "create_many batches accepted by the apiserver, per kind",
    ["kind"],
    registry=REGISTRY,
)
BULK_CREATE_OBJECTS_TOTAL = Counter(
    "bulk_create_objects_total",
    "Objects submitted through create_many by kind and per-item result",
    ["kind", "result"],
    registry=REGISTRY,
)

# ---- incremental scheduler + push readiness --------------------------
SCHEDULE_LATENCY_SECONDS = Histogram(
    "schedule_latency_seconds",
    "Gang-bind latency per scheduling attempt: node selection + "
    "capacity check + assume, over the incremental usage cache "
    "(kube-scheduler's scheduling_attempt_duration_seconds analogue); "
    "result=bound|unschedulable",
    ["result"],
    buckets=(0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0,
             5.0),
    registry=REGISTRY,
)
SCHEDULER_ASSUMED_PODS = Gauge(
    "scheduler_assumed_pods",
    "Pods assumed (bound in the usage cache, bind write not yet "
    "confirmed by its watch event) — kube-scheduler's assumed-pod set",
    registry=REGISTRY,
)
SCHEDULER_CACHE_EVENTS_TOTAL = Counter(
    "scheduler_cache_events_total",
    "Pod/Node watch events folded into the scheduler's usage cache "
    "(the O(Δ) accounting replacing the per-reconcile full Pod scan)",
    ["kind"],
    registry=REGISTRY,
)
SCHEDULER_CACHE_REBUILDS_TOTAL = Counter(
    "scheduler_cache_rebuilds_total",
    "Full usage-cache rebuilds from a fresh snapshot (initial prime + "
    "TOO_OLD relists)",
    registry=REGISTRY,
)
READINESS_WAKE_TO_OBSERVE_SECONDS = Histogram(
    "readiness_wake_to_observe_seconds",
    "Watch-event arrival at the web app's readiness hub to a blocked "
    "readiness long-poll observing the change — the push-path latency "
    "that replaces the client's fixed-interval status polling",
    buckets=(0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0),
    registry=REGISTRY,
)
READINESS_WAITERS = Gauge(
    "readiness_waiters",
    "Readiness long-polls currently blocked on the hub",
    registry=REGISTRY,
)

# ---- oversubscription: suspend/resume lifecycle + preemption ---------
SCHEDULER_FREE_CHIPS = Gauge(
    "scheduler_free_chips",
    "Unclaimed TPU chips across the tracked node fleet (capacity minus "
    "charged usage in the scheduler cache)",
    registry=REGISTRY,
)
SCHEDULER_LARGEST_FREE_GANG = Gauge(
    "scheduler_largest_free_gang_chips",
    "Largest slice placeable as one gang of identical hosts given the "
    "current free-chip distribution (ParvaGPU's largest allocatable "
    "unit) — free_chips minus this is stranded capacity",
    registry=REGISTRY,
)
SCHEDULER_FRAGMENTATION = Gauge(
    "scheduler_fragmentation",
    "Bin-packing fragmentation gauge: 1 - largest_free_gang/free_chips "
    "(0 = all free capacity gang-placeable, 1 = fully stranded)",
    registry=REGISTRY,
)
SCHEDULER_FREE_HBM_GIB = Gauge(
    "scheduler_free_hbm_gib",
    "Unclaimed predicted-HBM (GiB) across the tracked node fleet — the "
    "second gang-packing axis under --hbm-packing; unlike chips this "
    "axis is never overcommitted, so free approaching 0 is the true "
    "admission ceiling for declared workloads",
    registry=REGISTRY,
)
NOTEBOOK_SUSPEND_TOTAL = Counter(
    "notebook_suspend_total",
    "Notebooks driven to Suspended, by reason (idle | preempted | api)",
    ["reason"],
    registry=REGISTRY,
)
NOTEBOOK_RESUME_TOTAL = Counter(
    "notebook_resume_total",
    "Suspended notebooks resumed back to Running with state restored",
    registry=REGISTRY,
)
NOTEBOOK_PREEMPT_TOTAL = Counter(
    "notebook_preempt_total",
    "Victim slices suspended by the preemptive gang-bind path so a "
    "higher-priority slice could bind all-or-nothing",
    registry=REGISTRY,
)
SUSPEND_RESUME_SECONDS = Histogram(
    "suspend_resume_phase_seconds",
    "Suspend/resume lifecycle latency per phase: drain (suspend "
    "decision -> slice fully scaled to zero), rebind (resume request "
    "-> slice ready again), restore (state-store restore call)",
    ["phase"],
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0, 120.0),
    registry=REGISTRY,
)


# ---- multi-role gang jobs (TPUJob): actor-learner workloads ----------
TPUJOB_RUNNING = Gauge(
    "tpujob_running",
    "TPUJobs whose whole heterogeneous gang (every role) is Running",
    registry=REGISTRY,
)
TPUJOB_READY_PODS = Gauge(
    "tpujob_ready_pods",
    "Ready gang pods across all TPUJobs, by role name (learner slice "
    "hosts vs CPU actors)",
    ["role"],
    registry=REGISTRY,
)
TPUJOB_PHASE_TRANSITIONS_TOTAL = Counter(
    "tpujob_phase_transitions_total",
    "TPUJob phase-ladder transitions (Pending -> Provisioning -> "
    "Running -> Succeeded/Failed, plus Suspended), by entered phase",
    ["phase"],
    registry=REGISTRY,
)


# ---- sharded control plane: durable WAL + snapshot + ring ------------
# Every gauge below carries a ``shard`` label: each shard runs in its
# own process with its own registry, so the label is what lets a
# fleet-level scrape (or the /api/metrics facade aggregating shard
# scrapes) tell the per-shard series apart.
WAL_FSYNC_SECONDS = Histogram(
    "wal_fsync_seconds",
    "Group-commit flush latency: buffered frames written + fsynced in "
    "one batch (etcd's wal_fsync_duration_seconds analogue)",
    ["shard"],
    buckets=(0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0),
    registry=REGISTRY,
)
WAL_BYTES_TOTAL = Counter(
    "wal_bytes",
    "Bytes appended to the write-ahead log (CRC frame headers included)",
    ["shard"],
    registry=REGISTRY,
)
SNAPSHOT_DURATION_SECONDS = Histogram(
    "snapshot_duration_seconds",
    "Compacting-snapshot write latency: cut under the write lock, "
    "serialize, fsync, rename, drop compacted WAL segments",
    ["shard"],
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0),
    registry=REGISTRY,
)
SHARD_RING_MEMBERS = Gauge(
    "shard_ring_members",
    "Shards on the consistent-hash ring this process routes to (router) "
    "or participates in (shard worker)",
    ["shard"],
    registry=REGISTRY,
)

# ---- serving gateway: continuous batching + tenant SLO enforcement --
SERVING_QUEUE_DEPTH = Gauge(
    "serving_queue_depth",
    "Requests admitted by the gateway but not yet holding a decode "
    "slot (the engine's internal admission queue)",
    registry=REGISTRY,
)
SERVING_ACTIVE_SLOTS = Gauge(
    "serving_active_slots",
    "Decode slots currently mid-generation in the continuous-batching "
    "engine (capacity is serving_slot_capacity)",
    registry=REGISTRY,
)
SERVING_SLOT_CAPACITY = Gauge(
    "serving_slot_capacity",
    "Total decode slots in the engine's KV pool",
    registry=REGISTRY,
)
SERVING_BATCH_OCCUPANCY = Gauge(
    "serving_batch_occupancy",
    "Mean fraction of decode slots doing useful work per decode step "
    "since boot — the utilization win continuous batching exists for",
    registry=REGISTRY,
)
SERVING_REQUESTS_TOTAL = Counter(
    "serving_requests_total",
    "Gateway requests by tenant and result (ok | shed | error)",
    ["tenant", "result"],
    registry=REGISTRY,
)
SERVING_SHED_TOTAL = Counter(
    "serving_shed_total",
    "Requests shed before touching the engine, by tenant and reason "
    "(rate | tokens | queue | slo)",
    ["tenant", "reason"],
    registry=REGISTRY,
)
SERVING_REQUEST_LATENCY_SECONDS = Histogram(
    "serving_request_latency_seconds",
    "End-to-end request latency (admission to last token) per tenant",
    ["tenant"],
    buckets=(0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
             30.0),
    registry=REGISTRY,
)
SERVING_GENERATED_TOKENS_TOTAL = Counter(
    "serving_generated_tokens_total",
    "Tokens decoded and returned, per tenant (the token-budget meter)",
    ["tenant"],
    registry=REGISTRY,
)
SERVING_CLASS_QUEUE_DEPTH = Gauge(
    "serving_class_queue_depth",
    "Engine admission-queue depth per SLO class (interactive | batch | "
    "best_effort) — the weighted-round-robin backlog each class drains "
    "from at token boundaries",
    ["slo_class"],
    registry=REGISTRY,
)
SERVING_PREFIX_HIT_RATIO = Gauge(
    "serving_prefix_hit_ratio",
    "Fraction of prompt tokens served from the shared-prefix block "
    "cache instead of prefilled (cumulative since boot)",
    registry=REGISTRY,
)
SERVING_PREFIX_MISS_RATIO = Gauge(
    "serving_prefix_miss_ratio",
    "1 - serving_prefix_hit_ratio, set only once prompts have flowed — "
    "the burn signal for the prefix-hit-collapse SLO (sustained ~1.0 "
    "under prefix-heavy traffic means the cache stopped working)",
    registry=REGISTRY,
)
SERVING_FREE_BLOCK_FRACTION = Gauge(
    "serving_free_block_fraction",
    "Fraction of the paged-KV pool's usable blocks free or evictable "
    "right now — sustained ~0 predicts admission OOM rejections",
    registry=REGISTRY,
)
SERVING_MIGRATIONS_TOTAL = Counter(
    "serving_migrations",
    "In-flight requests re-routed to another replica after their "
    "original replica drained or died (resumed, not failed)",
    registry=REGISTRY,
)
SERVING_FLEET_REPLICAS = Gauge(
    "serving_fleet_replicas",
    "Serving-fleet replicas by state (ready | draining | dead)",
    ["state"],
    registry=REGISTRY,
)
SERVING_STORE_HIT_RATIO = Gauge(
    "serving_store_hit_ratio",
    "Fraction of GlobalBlockStore lookups that found a chain "
    "(cumulative since boot) — the fleet-wide prefix economy's "
    "effectiveness across replica deaths and rebalancing",
    registry=REGISTRY,
)
SERVING_STORE_MISS_RATIO = Gauge(
    "serving_store_miss_ratio",
    "1 - serving_store_hit_ratio, set only once lookups have flowed — "
    "the burn signal for the store-hit-collapse SLO (sustained ~1.0 "
    "under steady traffic means the global prefix tier stopped "
    "absorbing re-prefills)",
    registry=REGISTRY,
)
SERVING_STORE_CHAINS = Gauge(
    "serving_store_chains",
    "Chains currently resident in the GlobalBlockStore",
    registry=REGISTRY,
)
SERVING_STORE_BYTES = Gauge(
    "serving_store_bytes",
    "Bytes of chain payload resident in the GlobalBlockStore (LRU "
    "evicts ref-0 chains past the byte budget)",
    registry=REGISTRY,
)
SERVING_STORE_PROMOTED_TOTAL = Counter(
    "serving_store_promoted_chains",
    "Hot ref-0 chains promoted into the GlobalBlockStore at local "
    "eviction time instead of dying with the replica's pool",
    registry=REGISTRY,
)
SERVING_CHAIN_HANDOFF_SECONDS = Histogram(
    "serving_chain_handoff_seconds",
    "Prefill-tier handoff latency: route to a prefill replica, "
    "prefill the prompt, export the chain, publish it to the global "
    "store — the added cost a disaggregated request pays before its "
    "decode replica installs the chain",
    registry=REGISTRY,
)
SERVING_TIER_OCCUPANCY = Gauge(
    "serving_tier_occupancy",
    "Mean busy fraction per serving tier (prefill: active prefill "
    "fraction of READY prefill replicas' queue+work; decode: active "
    "slot fraction of READY decode replicas)",
    ["tier"],
    registry=REGISTRY,
)

# ---- observability loop: provision SLI + watchdog-visible deaths -----
PROVISION_LATENCY_SECONDS = Histogram(
    "provision_latency_seconds",
    "Notebook provision latency observed in-platform: CR "
    "creationTimestamp to the status mirror first seeing readyReplicas "
    "reach desired — the SLI behind the provision-p50 SLO (the "
    "conformance harness measures the same edge from the client side)",
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
             120.0),
    registry=REGISTRY,
)
SHARD_DEATHS_TOTAL = Counter(
    "shard_deaths",
    "Shard worker processes the ShardRunner watchdog observed dead and "
    "respawned, by shard — feeds the shard-deaths critical SLO so a "
    "respawn is an *alert*, not just a log line. A deliberate "
    "scale-down (elastic merge) does NOT count here — the runner's "
    "intentional-shutdown handshake excludes it",
    ["shard"],
    registry=REGISTRY,
)

# ---- elastic shard layer (split / merge / autoscale) ------------------
SHARD_SPLITS_TOTAL = Counter(
    "shard_splits_total",
    "Completed live shard splits (new member admitted to the ring "
    "after snapshot + WAL tail-replay handoff)",
    registry=REGISTRY,
)
SHARD_MERGES_TOTAL = Counter(
    "shard_merges_total",
    "Completed live shard merges (member retired from the ring after "
    "its key-range was handed to the survivors)",
    registry=REGISTRY,
)
SHARD_HANDOFF_SECONDS = Histogram(
    "shard_handoff_seconds",
    "End-to-end live handoff duration by kind (split | merge | "
    "migrate): donor snapshot, bulk copy, tail-replay to "
    "under-threshold lag, fence, final drain, ring flip",
    ["kind"],
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0),
    registry=REGISTRY,
)
SHARD_HANDOFF_OBJECTS = Counter(
    "shard_handoff_objects_total",
    "Objects copied to a recipient shard during handoffs, by phase "
    "(bulk | tail) — the tail share is the live-traffic cost a "
    "split pays",
    ["phase"],
    registry=REGISTRY,
)
SHARD_HANDOFF_REPLAY_LAG = Gauge(
    "shard_handoff_replay_lag",
    "WAL records still to tail-replay in the in-flight handoff "
    "(0 when none is running) — the convergence signal the fence "
    "waits on",
    registry=REGISTRY,
)
SHARD_AUTOSCALE_DECISIONS_TOTAL = Counter(
    "shard_autoscale_decisions_total",
    "Autoscaler verdicts by decision (split | merge | hold | "
    "cooldown) — sustained queue depth or SLO burn scales out, "
    "sustained idle merges back",
    ["decision"],
    registry=REGISTRY,
)

# ---- chaos engine + replicated kernels + migration -------------------
CHAOS_FAULTS_INJECTED_TOTAL = Counter(
    "chaos_faults_injected_total",
    "Faults injected by the seeded chaos engine, by fault kind — the "
    "attribution counter every chaos-matrix artifact asserts against "
    "(each injection also lands in the plan ledger and, when a flight "
    "recorder is attached, a chaos_<fault> incident bundle)",
    ["fault"],
    registry=REGISTRY,
)
PREEMPT_SKIPPED_TOTAL = Counter(
    "preempt_skipped_total",
    "try_preempt opportunities that could not be served, by reason "
    "(oversubscribe_off | not_notebook_owner | legacy_scan | "
    "no_viable_victims) — makes the TPUJob-vs-TPUJob preemption gap "
    "(ROADMAP item 5) a visible counter instead of a silent skip",
    ["reason"],
    registry=REGISTRY,
)
NOTEBOOK_FAILOVER_TOTAL = Counter(
    "notebook_failover_total",
    "Active-replica deaths that promoted a warm standby via "
    "demand-resume (NotebookOS replicated-kernel failover)",
    registry=REGISTRY,
)
NOTEBOOK_FAILOVER_SECONDS = Histogram(
    "notebook_failover_seconds",
    "Active-replica death detection to the promoted standby fully "
    "ready (state restored, chips re-bound through gang_bind) — the "
    "latency that must beat cold provisioning by >=10x",
    buckets=(0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
             30.0),
    registry=REGISTRY,
)
NOTEBOOK_MIGRATION_TOTAL = Counter(
    "notebook_migration_total",
    "Live migrations (checkpoint -> drain -> re-bind on different "
    "nodes) by trigger (api | fragmentation)",
    ["trigger"],
    registry=REGISTRY,
)

# ---- chip harvesting (r20): serving on idle notebook chips ----------
HARVESTED_CHIPS = Gauge(
    "harvested_chips",
    "TPU chips currently on loan to the serving fleet under harvest "
    "leases (charges marked harvested=true in the scheduler cache) — "
    "capacity a notebook resume reclaims instantly",
    registry=REGISTRY,
)
HARVEST_GRANTS_TOTAL = Counter(
    "harvest_grants_total",
    "Harvest leases granted: an idle/suspended notebook's slice "
    "checkpointed, drained, and re-bound as a serving replica gang",
    registry=REGISTRY,
)
HARVEST_RECLAIMS_TOTAL = Counter(
    "harvest_reclaims_total",
    "Harvest leases reclaimed, by trigger (resume | preempt | "
    "idle_giveback | chaos) — resume means a notebook demanded its "
    "chips back and outranked serving",
    ["trigger"],
    registry=REGISTRY,
)
HARVEST_RECLAIM_SECONDS = Histogram(
    "harvest_reclaim_seconds",
    "Demand-resume reclaim latency: resume request observed to the "
    "harvested replica drained and its lease released — must fit "
    "inside the r15 failover SLO (notebook_failover_seconds envelope)",
    buckets=(0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
             30.0),
    registry=REGISTRY,
)
DECLARED_HBM_DRIFT_RATIO = Gauge(
    "declared_hbm_drift_ratio",
    "Worst relative divergence between a workload's observed on-chip "
    "HBM peak and its webhook-priced declared peak "
    "(|observed - declared| / declared, max over tracked workloads) — "
    "sustained > 0.2 trips the warn-only declared-hbm-drift SLO",
    registry=REGISTRY,
)

# ---- compute-path fleet SLIs (jaxcheck probes, per tenant) -----------
JIT_RECOMPILES_TOTAL = Counter(
    "jit_recompiles",
    "New (shape, dtype, static-arg) signatures observed by the "
    "jaxcheck recompile sentinel, per tenant — a sustained rate means "
    "some notebook is feeding dynamic shapes into jit and burning its "
    "slice on XLA compiles instead of steps (feeds the "
    "recompile-storm RateSLO)",
    ["tenant"],
    registry=REGISTRY,
)
IMPLICIT_HOSTSYNCS_TOTAL = Counter(
    "implicit_hostsyncs",
    "Implicit device->host transfers (bool()/.item()/np.asarray on "
    "device arrays) witnessed by the jaxcheck hostsync probe inside "
    "instrumented regions, per tenant — each one stalls the TPU "
    "pipeline for a host round-trip (feeds the hostsync-storm RateSLO)",
    ["tenant"],
    registry=REGISTRY,
)

# ---- error accounting: no silent except Exception (KFRM005) ----------
SWALLOWED_ERRORS_TOTAL = Counter(
    "swallowed_errors",
    "Exceptions intentionally absorbed on best-effort paths, by module "
    "— every `except Exception:` in the tree either re-raises, logs, "
    "or feeds this counter via metrics.swallowed() (the KFRM005 lint "
    "rule enforces it). A rising rate on one module is the early-"
    "warning signal that a 'best effort' path is failing constantly.",
    ["module"],
    registry=REGISTRY,
)

_swallow_log = logging.getLogger("kfrm.swallowed")


def swallowed(module: str, context: str = "") -> None:
    """Account for an intentionally-absorbed exception. Call from
    inside an ``except`` block: increments
    ``swallowed_errors_total{module}`` and debug-logs the traceback so
    the error is countable in production and visible under -v debug."""
    SWALLOWED_ERRORS_TOTAL.labels(module=module).inc()
    _swallow_log.debug("swallowed in %s%s", module,
                       f" ({context})" if context else "", exc_info=True)


# the shard identity this process reports under — "" outside sharded
# deployments so single-process metrics stay label-stable
_SHARD = ""


def set_shard(name: str) -> None:
    """Tag this process's per-shard metric series (shard worker boot /
    router construction call this once)."""
    global _SHARD
    _SHARD = name


def shard_label() -> str:
    return _SHARD


def registry_value(sample_name: str,
                   labels: dict[str, str] | None = None) -> float:
    """Sum the current value of all samples named ``sample_name``
    (optionally filtered by labels) — how the dashboard's inventory
    backend reads in-process HA gauges without scraping itself."""
    total = 0.0
    for family in REGISTRY.collect():
        for sample in family.samples:
            if sample.name != sample_name:
                continue
            if labels and any(sample.labels.get(k) != v
                              for k, v in labels.items()):
                continue
            total += sample.value
    return total


def scrape() -> bytes:
    """Prometheus exposition text for the control-plane registry."""
    return generate_latest(REGISTRY)
