"""Prometheus metrics for the control plane.

Mirrors the reference's collector set
(``notebook-controller/pkg/metrics/metrics.go:13-99`` and
``profile-controller/controllers/monitoring.go:30-43``) on a dedicated
registry so tests can scrape and reset it hermetically.
"""

from __future__ import annotations

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    generate_latest,
)

REGISTRY = CollectorRegistry()

NOTEBOOK_RUNNING = Gauge(
    "notebook_running",
    "Current number of notebooks with at least one ready replica",
    registry=REGISTRY,
)
NOTEBOOK_CREATE_TOTAL = Counter(
    "notebook_create_total",
    "Total notebook StatefulSets created",
    registry=REGISTRY,
)
NOTEBOOK_CREATE_FAILED_TOTAL = Counter(
    "notebook_create_failed_total",
    "Total notebook StatefulSet creations that failed",
    registry=REGISTRY,
)
NOTEBOOK_CULL_TOTAL = Counter(
    "notebook_cull_total",
    "Total notebooks culled for idleness",
    registry=REGISTRY,
)
PROFILE_CREATE_TOTAL = Counter(
    "profile_create_total",
    "Total profiles reconciled into namespaces",
    registry=REGISTRY,
)
RECONCILE_ERRORS_TOTAL = Counter(
    "reconcile_errors_total",
    "Total reconcile errors across controllers",
    ["controller"],
    registry=REGISTRY,
)
KFAM_REQUESTS_TOTAL = Counter(
    "kfam_requests_total",
    "KFAM API requests by action and result "
    "(ref access-management/kfam/monitoring.go:46-77)",
    ["action", "result"],
    registry=REGISTRY,
)
TPU_CHIPS_REQUESTED = Gauge(
    "tpu_chips_requested",
    "TPU chips currently requested by scheduled notebook pods",
    registry=REGISTRY,
)


def scrape() -> bytes:
    """Prometheus exposition text for the control-plane registry."""
    return generate_latest(REGISTRY)
