"""TPUJob reconciler: one heterogeneous gang → one StatefulSet per role.

The first controller whose children are heterogeneous: a TPUJob's
ordered role groups each materialise as a StatefulSet (named
``{job}-{role}``) plus a headless Service, but the *scheduling* unit is
the whole gang — the StatefulSet controller recognises the gang labels
this reconciler stamps and binds every role's pods in ONE mixed-resource
``gang_bind`` transaction (see ``controllers/statefulset.py``). This
reconciler owns:

- rendering: role STS + headless Service per role, gang labels
  (``JOB_NAME_LABEL``/``JOB_ROLE_LABEL``) and the gang-wide
  ``JOB_ROLES_ANNOTATION`` on every pod template — the whole contract
  the webhook's role-aware rendezvous injection reads;
- the single job phase ladder
  Pending→Provisioning→Running→Succeeded/Failed (plus Suspended),
  mirrored into ``status`` with per-role ready counts;
- whole-gang suspend/resume: the shared Notebook suspend annotations
  park EVERY role to zero replicas at once, the drain stamp lands only
  after the last gang pod is gone (and the scheduler charges for both
  resources are released), and demand-resume scales every role back in
  the same render — no half-gang ever runs;
- pod/STS Warning re-emission onto the CR (users see FailedScheduling
  for the gang on the job itself).
"""

from __future__ import annotations

from kubeflow_rm_tpu.controlplane import metrics, scheduler
from kubeflow_rm_tpu.controlplane.api import notebook as nb_api
from kubeflow_rm_tpu.controlplane.api import tpu as tpu_api
from kubeflow_rm_tpu.controlplane.api import tpujob as tj_api
from kubeflow_rm_tpu.controlplane.api.meta import (
    annotations_of,
    deep_get,
    fast_deepcopy,
    name_of,
)
from kubeflow_rm_tpu.controlplane.apiserver import APIServer, NotFound
from kubeflow_rm_tpu.controlplane.webhook.admission_pricer import (
    is_admission_rejected,
    slice_topology_of,
)
from kubeflow_rm_tpu.controlplane.runtime import (
    Controller,
    Request,
    copy_service_fields,
    copy_statefulset_fields,
    map_by_label,
    map_to_owner,
    phase_observer,
    reconcile_children,
)
from kubeflow_rm_tpu.utils.profiling import PhaseRecorder

COORDINATOR_PORT = 8476


class TPUJobController(Controller):
    kind = tj_api.KIND

    def __init__(self):
        self.phases = PhaseRecorder()
        self._observe = phase_observer("tpujob", self.phases)

    def watches(self):
        return (
            ("StatefulSet", map_to_owner(tj_api.KIND)),
            ("Pod", map_by_label(tj_api.JOB_NAME_LABEL)),
        )

    def reconcile(self, api: APIServer, req: Request):
        try:
            job = api.get(tj_api.KIND, req.name, req.namespace)
        except NotFound:
            return None  # children follow via GC

        roles = tj_api.roles(job)
        with self._observe("render"):
            children = []
            for role in roles:
                children.append((self._generate_role_sts(job, role),
                                 copy_statefulset_fields))
                children.append((self._generate_role_service(job, role),
                                 copy_service_fields))
        with self._observe("child_writes"):
            reconcile_children(api, job, children)
        with self._observe("suspend"):
            job = self._reconcile_suspend(api, job, roles)
        with self._observe("status"):
            self._mirror_status(api, job, roles)
        with self._observe("events"):
            self._reemit_child_events(api, job, roles)
        return None

    # -- rendering -----------------------------------------------------
    def _generate_role_sts(self, job: dict, role: dict) -> dict:
        job_name = name_of(job)
        ns = job["metadata"]["namespace"]
        sts_name = tj_api.role_sts_name(job_name, role["name"])
        acc = tj_api.role_accelerator(role)
        pods = tj_api.role_pods(role)
        # priced admission: a rejected declaration parks the WHOLE gang
        # — no pod of any role renders until the declaration reprices
        parked = (tj_api.is_stopped(job) or tj_api.is_suspended(job)
                  or is_admission_rejected(job))

        template = fast_deepcopy(role.get("template") or {})
        pod_spec = template.get("spec") or {}
        containers = pod_spec.setdefault("containers", [])
        if not containers:
            containers.append({
                "name": role["name"],
                "image": deep_get(job, "spec", "image",
                                  default=tj_api.DEFAULT_IMAGE),
            })

        pod_labels = dict(job["metadata"].get("labels") or {})
        pod_labels.update({
            "statefulset": sts_name,
            tj_api.JOB_NAME_LABEL: job_name,
            tj_api.JOB_ROLE_LABEL: role["name"],
        })
        pod_annotations = dict(
            deep_get(template, "metadata", "annotations", default={})
            or {})
        pod_annotations[tj_api.JOB_ROLES_ANNOTATION] = \
            tj_api.roles_annotation_value(job)

        if acc:
            topo = tpu_api.lookup(acc)
            pod_labels[nb_api.TPU_ACCELERATOR_LABEL] = acc
            nslices = int(role.get("replicas", 1))
            if nslices > 1:
                pod_labels[nb_api.TPU_NUM_SLICES_LABEL] = str(nslices)
            limits = containers[0].setdefault("resources", {}) \
                .setdefault("limits", {})
            limits[tpu_api.GOOGLE_TPU_RESOURCE] = str(topo.chips_per_host)
            sel = pod_spec.setdefault("nodeSelector", {})
            sel[tpu_api.NODE_LABEL_ACCELERATOR] = topo.gke_accelerator
            sel[tpu_api.NODE_LABEL_TOPOLOGY] = topo.topology
            # priced admission: the declared workload lives on the
            # learner slice — fan its predicted HBM/FLOPs per-pod onto
            # that role only (CPU actors carry no HBM charge)
            priced_topo = slice_topology_of(job)
            if priced_topo and acc == priced_topo.accelerator_type:
                job_ann = annotations_of(job)
                pred = job_ann.get(tpu_api.PREDICTED_HBM_ANNOTATION)
                if pred:
                    try:
                        pod_annotations[
                            tpu_api.PREDICTED_HBM_ANNOTATION] = \
                            f"{float(pred) / topo.hosts:.4f}"
                    except (TypeError, ValueError):
                        pass
                pred = job_ann.get(tpu_api.PREDICTED_FLOPS_ANNOTATION)
                if pred:
                    try:
                        pod_annotations[
                            tpu_api.PREDICTED_FLOPS_ANNOTATION] = \
                            f"{float(pred) / topo.hosts:.6g}"
                    except (TypeError, ValueError):
                        pass
        cpu = role.get("cpu")
        if cpu is not None:
            requests = containers[0].setdefault("resources", {}) \
                .setdefault("requests", {})
            requests[scheduler.CPU_RESOURCE] = str(cpu)

        return {
            "apiVersion": "apps/v1",
            "kind": "StatefulSet",
            "metadata": {
                "name": sts_name,
                "namespace": ns,
                "labels": {tj_api.JOB_NAME_LABEL: job_name,
                           tj_api.JOB_ROLE_LABEL: role["name"]},
            },
            "spec": {
                "replicas": 0 if parked else pods,
                "serviceName": sts_name,
                # a gang needs all its workers together — never ordered
                "podManagementPolicy": "Parallel",
                "selector": {"matchLabels": {"statefulset": sts_name}},
                "template": {
                    "metadata": {"labels": pod_labels,
                                 "annotations": pod_annotations},
                    "spec": pod_spec,
                },
            },
        }

    def _generate_role_service(self, job: dict, role: dict) -> dict:
        job_name = name_of(job)
        ns = job["metadata"]["namespace"]
        sts_name = tj_api.role_sts_name(job_name, role["name"])
        return {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": sts_name, "namespace": ns,
                         "labels": {tj_api.JOB_NAME_LABEL: job_name}},
            "spec": {
                "type": "ClusterIP",
                "clusterIP": "None",
                "selector": {"statefulset": sts_name},
                "ports": [{"name": "jax-coordinator",
                           "port": COORDINATOR_PORT,
                           "targetPort": COORDINATOR_PORT,
                           "protocol": "TCP"}],
            },
        }

    # -- suspend / resume ----------------------------------------------
    def _reconcile_suspend(self, api: APIServer, job: dict,
                           roles: list[dict]) -> dict:
        """Drain/resume bookkeeping for the whole gang. The render
        above already parked every role at zero replicas; here we stamp
        the drain only once the LAST gang pod is gone (and free the
        scheduler's dual-resource charges), and finish a resume only
        once EVERY role is ready again — the no-half-gang invariant."""
        from kubeflow_rm_tpu.controlplane import suspend as suspend_mod

        ann = annotations_of(job)
        name, ns = name_of(job), job["metadata"]["namespace"]
        gang_pods = api.list(
            "Pod", ns,
            {"matchLabels": {tj_api.JOB_NAME_LABEL: name}})
        if nb_api.SUSPEND_ANNOTATION in ann \
                and nb_api.RESUME_REQUESTED_ANNOTATION in ann:
            # demand resume: un-park the gang (the SuspendController
            # does this for Notebooks; TPUJobs own their whole-gang
            # cycle). The next reconcile renders full replicas for
            # EVERY role at once; the completion branch below pops the
            # cycle annotations only when all of them are ready.
            job["metadata"]["annotations"].pop(
                nb_api.SUSPEND_ANNOTATION, None)
            job = api.update(job)
            api.record_event(job, "Normal", "Resuming",
                             "resume requested; re-ganging every role")
        elif nb_api.SUSPEND_ANNOTATION in ann \
                and nb_api.SUSPEND_DRAINED_ANNOTATION not in ann:
            if gang_pods:
                return job  # pods still terminating; events re-trigger
            sched = scheduler.cache_for(api)
            for role in roles:
                sts_name = tj_api.role_sts_name(name, role["name"])
                for i in range(tj_api.role_pods(role)):
                    sched.release((ns, f"{sts_name}-{i}"))
            job["metadata"].setdefault("annotations", {})[
                nb_api.SUSPEND_DRAINED_ANNOTATION] = \
                api.clock().isoformat()
            job = api.update(job)
            api.record_event(
                job, "Normal", "Suspended",
                f"gang drained ({tj_api.total_pods(job)} pods across "
                f"{len(roles)} roles); chips and cpu released")
            # freed capacity may unblock queued gangs right now
            suspend_mod.kick_pending_pods(api, now=api.clock())
        elif nb_api.SUSPEND_ANNOTATION not in ann \
                and nb_api.RESUME_REQUESTED_ANNOTATION in ann:
            ready, total = self._gang_readiness(api, job, roles)
            if total and ready == total:
                md_ann = job["metadata"].setdefault("annotations", {})
                for key in (nb_api.RESUME_REQUESTED_ANNOTATION,
                            nb_api.SUSPEND_DRAINED_ANNOTATION,
                            nb_api.SUSPEND_REASON_ANNOTATION,
                            nb_api.SUSPEND_CHECKPOINT_ANNOTATION):
                    md_ann.pop(key, None)
                job = api.update(job)
                api.record_event(
                    job, "Normal", "Resumed",
                    f"gang restored atomically: {ready}/{total} pods "
                    "across every role")
        return job

    def _gang_readiness(self, api: APIServer, job: dict,
                        roles: list[dict]) -> tuple[int, int]:
        name, ns = name_of(job), job["metadata"]["namespace"]
        ready = total = 0
        for role in roles:
            sts = api.try_get(
                "StatefulSet", tj_api.role_sts_name(name, role["name"]),
                ns)
            ready += deep_get(sts, "status", "readyReplicas",
                              default=0) if sts else 0
            total += tj_api.role_pods(role)
        return ready, total

    # -- status --------------------------------------------------------
    def _mirror_status(self, api: APIServer, job: dict,
                       roles: list[dict]) -> None:
        name, ns = name_of(job), job["metadata"]["namespace"]
        ann = annotations_of(job)
        role_status: dict = {}
        ready = total = 0
        for role in roles:
            sts = api.try_get(
                "StatefulSet", tj_api.role_sts_name(name, role["name"]),
                ns)
            r = deep_get(sts, "status", "readyReplicas",
                         default=0) if sts else 0
            t = tj_api.role_pods(role)
            role_status[role["name"]] = {"ready": r, "total": t}
            ready += r
            total += t
        gang_pods = api.list(
            "Pod", ns, {"matchLabels": {tj_api.JOB_NAME_LABEL: name}})
        phase = self._phase(ann, gang_pods, ready, total)
        status = {"phase": phase, "readyPods": ready,
                  "totalPods": total, "roles": role_status}
        # status.admission is webhook-owned: carry it through the
        # mirror so the replace-style status write doesn't wipe it
        # (the webhook would re-stamp it and reconcile never quiesces)
        adm = deep_get(job, "status", "admission")
        if adm is not None:
            status["admission"] = adm
        prev_phase = deep_get(job, "status", "phase")
        if deep_get(job, "status") != status:
            job["status"] = status
            api.update_status(job)
        if phase != prev_phase:
            metrics.TPUJOB_PHASE_TRANSITIONS_TOTAL.labels(
                phase=phase).inc()
            api.record_event(job, "Normal", phase,
                             f"job phase: {prev_phase or 'none'} → "
                             f"{phase} ({ready}/{total} pods ready)")
        self._refresh_gauges(api)

    @staticmethod
    def _phase(ann: dict, gang_pods: list[dict], ready: int,
               total: int) -> str:
        if nb_api.SUSPEND_ANNOTATION in ann \
                and nb_api.SUSPEND_DRAINED_ANNOTATION in ann:
            return tj_api.SUSPENDED_PHASE
        pod_phases = [deep_get(p, "status", "phase")
                      for p in gang_pods]
        if pod_phases and any(p == "Failed" for p in pod_phases):
            return tj_api.FAILED_PHASE
        if pod_phases and len(pod_phases) >= total \
                and all(p == "Succeeded" for p in pod_phases):
            return tj_api.SUCCEEDED_PHASE
        if total and ready == total:
            return tj_api.RUNNING_PHASE
        if gang_pods:
            return tj_api.PROVISIONING_PHASE
        return tj_api.PENDING_PHASE

    def _refresh_gauges(self, api: APIServer) -> None:
        # cluster-wide recompute (scan: read-only references) so the
        # gauges survive any single job's deletion
        running = 0
        per_role: dict[str, int] = {}
        for job in getattr(api, "scan", api.list)(tj_api.KIND):
            if deep_get(job, "status", "phase") == tj_api.RUNNING_PHASE:
                running += 1
            for rname, rs in (deep_get(job, "status", "roles",
                                       default={}) or {}).items():
                per_role[rname] = per_role.get(rname, 0) \
                    + int(rs.get("ready", 0))
        metrics.TPUJOB_RUNNING.set(running)
        for rname, n in per_role.items():
            metrics.TPUJOB_READY_PODS.labels(role=rname).set(n)

    # -- event re-emission ---------------------------------------------
    def _reemit_child_events(self, api: APIServer, job: dict,
                             roles: list[dict]) -> None:
        name, ns = name_of(job), job["metadata"]["namespace"]
        already = {(e.get("reason"), e.get("message"))
                   for e in api.events_for(job)}

        def reemit(ev, source):
            if ev.get("type") != "Warning":
                return
            sig = (ev.get("reason"), f"[{source}] {ev.get('message')}")
            if sig in already:
                return
            already.add(sig)
            api.record_event(job, "Warning", sig[0], sig[1])

        for pod in api.list(
                "Pod", ns,
                {"matchLabels": {tj_api.JOB_NAME_LABEL: name}}):
            for ev in api.events_for(pod):
                reemit(ev, f"pod {name_of(pod)}")
        for role in roles:
            sts_name = tj_api.role_sts_name(name, role["name"])
            sts = api.try_get("StatefulSet", sts_name, ns)
            if sts is not None:
                for ev in api.events_for(sts):
                    reemit(ev, f"sts {sts_name}")
