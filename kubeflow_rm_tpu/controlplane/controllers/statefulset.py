"""StatefulSet → Pods: the fake kubelet + scheduler for tests.

The reference gets these semantics from a real cluster (envtest stops at
the apiserver, so its suites never see Pods; the pvcviewer suite
fabricates them by hand — ``pvcviewer-controller/controllers/test_utils.go:21-128``).
This controller goes one step further than envtest: it realizes a
StatefulSet into ordinal Pods, runs them through the admission chain
(where the TPU webhook injects rendezvous env), schedules them onto
Nodes by nodeSelector + ``google.com/tpu`` capacity, and mirrors a
Running/Ready status — or leaves them Pending with a FailedScheduling
event, which is what the slice-health machinery watches for.

This is test infrastructure with production semantics: every behavior
here (ordinal naming, subdomain DNS, Parallel management, capacity
gating) is exactly what GKE does to a real TPU-slice StatefulSet.
"""

from __future__ import annotations

import copy
import json

from kubeflow_rm_tpu.controlplane.api.meta import (
    fast_deepcopy,
    deep_get,
    labels_of,
    matches_selector,
    name_of,
    namespace_of,
    parse_quantity,
    set_controller_reference,
)
from kubeflow_rm_tpu.controlplane.api.tpu import GOOGLE_TPU_RESOURCE
from kubeflow_rm_tpu.controlplane.api import tpujob as tj_api
from kubeflow_rm_tpu.controlplane.apiserver import (
    AdmissionDenied, APIServer, NotFound, is_status,
)
from kubeflow_rm_tpu.controlplane import chaos, runtime, scheduler
from kubeflow_rm_tpu.controlplane.runtime import (
    Controller, Request, map_all_in_namespace, map_to_owner,
    phase_observer,
)
from kubeflow_rm_tpu.controlplane.scheduler import (
    TERMINAL_PHASES, VIRTUAL_NODE,
)

POD_NAME_LABEL = "statefulset.kubernetes.io/pod-name"


def make_tpu_node(name: str, accelerator_type: str) -> dict:
    """A Node carrying one TPU host's worth of chips + GKE labels."""
    from kubeflow_rm_tpu.controlplane.api import tpu as tpu_api

    topo = tpu_api.lookup(accelerator_type)
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {
            "name": name,
            "labels": {
                tpu_api.NODE_LABEL_ACCELERATOR: topo.gke_accelerator,
                tpu_api.NODE_LABEL_TOPOLOGY: topo.topology,
            },
        },
        "status": {
            "capacity": {
                GOOGLE_TPU_RESOURCE: str(topo.chips_per_host),
                tpu_api.GOOGLE_TPU_HBM_RESOURCE:
                    str(topo.hbm_gib_per_host),
                "cpu": "96",
                "memory": "384Gi",
            },
            "allocatable": {
                GOOGLE_TPU_RESOURCE: str(topo.chips_per_host),
                tpu_api.GOOGLE_TPU_HBM_RESOURCE:
                    str(topo.hbm_gib_per_host),
                "cpu": "96",
                "memory": "384Gi",
            },
        },
    }


class StatefulSetController(Controller):
    kind = "StatefulSet"

    def __init__(self, auto_ready: bool = True,
                 virtual_node_fallback: bool | None = None):
        # auto_ready=False leaves scheduled pods un-Ready so tests can
        # exercise status ladders and slice-health timing.
        # virtual_node_fallback: place selector-less CPU pods on a
        # synthetic node when no Node inventory exists. None (default)
        # resolves per-backend: allowed against the hermetic in-memory
        # APIServer, refused against a KubeAPIServer — there an empty
        # node list is a real "no nodes at all" condition that must
        # surface as FailedScheduling, not be papered over.
        self.auto_ready = auto_ready
        self.virtual_node_fallback = virtual_node_fallback
        self._observe = phase_observer(self.kind.lower())

    def watches(self):
        # ResourceQuota: a raised quota must requeue every STS in its
        # namespace immediately — a quota-rejected slice used to wait
        # out a 30s poll before admission.
        # Gang pods additionally fan out to EVERY role STS of their
        # TPUJob — the gang's binder must wake when a sibling role's
        # pods appear, and pod-create events only map to their owner
        return (("Pod", map_to_owner("StatefulSet")),
                ("Pod", _map_gang_pod),
                ("ResourceQuota", map_all_in_namespace("StatefulSet")))

    def reconcile(self, api: APIServer, req: Request):
        try:
            sts = api.get(self.kind, req.name, req.namespace)
        except NotFound:
            return None  # pods are GC'd via ownerReferences
        with self._observe("render"):
            replicas = deep_get(sts, "spec", "replicas", default=1)
            ns = req.namespace

            scan = getattr(api, "scan", api.list)  # read-only fast path
            existing = {
                name_of(p): p for p in scan("Pod", ns)
                if any(r.get("uid") == sts["metadata"]["uid"]
                       for r in p["metadata"].get("ownerReferences", []))
            }

            # scale down: remove pods at ordinals >= replicas
            for pname, pod in existing.items():
                ordinal = _ordinal(pname, req.name)
                if ordinal is None or ordinal >= replicas:
                    api.delete("Pod", pname, ns)

            missing = [i for i in range(replicas)
                       if f"{req.name}-{i}" not in existing]

        # slice admission is all-or-nothing: pre-check EVERY missing pod
        # against namespace quota before creating any. Creating ordinals
        # until one is denied would either leave a rump slice holding
        # chips while the jax rendezvous waits forever, or (if torn
        # down) free the quota and retry in an endless create/teardown
        # loop. Reject whole, once, with an event.
        if missing and not self._missing_pods_fit_quota(api, sts, missing):
            msg = (f"namespace quota cannot admit all {replicas} hosts "
                   "of the slice; rejecting whole (slice admission is "
                   "all-or-nothing)")
            if not any(e["reason"] == "SliceAdmissionFailed"
                       and e["message"] == msg
                       for e in api.events_for(sts)):
                api.record_event(sts, "Warning", "SliceAdmissionFailed",
                                 msg)
            missing = []
            # no timed poll: the ResourceQuota watch (watches() above)
            # requeues this STS the moment the quota is raised

        # scale up: create missing ordinals (Parallel policy: all at once)
        with self._observe("child_writes"):
            if missing:
                self._create_missing(api, sts, missing)
            self._schedule_and_run(api, sts)
        self._maybe_chaos_pod_kill(api, sts)
        with self._observe("status"):
            self._mirror_status(api, sts)
            from kubeflow_rm_tpu.controlplane import metrics
            if scheduler.legacy_scan():
                metrics.TPU_CHIPS_REQUESTED.set(sum(
                    _pod_tpu_request(p)
                    for p in getattr(api, "scan", api.list)("Pod")
                    if deep_get(p, "spec", "nodeName")
                    and deep_get(p, "status", "phase")
                    not in TERMINAL_PHASES))
            else:
                # O(nodes) from the usage cache, not an O(pods) scan
                metrics.TPU_CHIPS_REQUESTED.set(
                    scheduler.cache_for(api).total_used())
        return None

    def _create_missing(self, api: APIServer, sts: dict,
                        missing: list[int]) -> None:
        pods = []
        for i in missing:
            pod = self._render_pod(sts, i)
            set_controller_reference(sts, pod)
            pods.append(pod)
        create_many = getattr(api, "create_many", None)
        if (create_many is not None and len(pods) > 1
                and not runtime.serial_writes()):
            # whole slice in one verb: one lock acquisition, one rv
            # range, one coalesced watch emit; admission runs per-pod
            # inside the batch, failures come back as Status items
            for pod, res in zip(pods, create_many(pods)):
                if is_status(res):
                    api.record_event(
                        sts, "Warning", "FailedCreate",
                        f"create Pod {name_of(pod)} failed: "
                        f"{res.get('message')}")
            return
        for pod in pods:
            try:
                api.create(pod)
            except AdmissionDenied as e:
                # backstop for admission races the pre-check can't see
                api.record_event(
                    sts, "Warning", "FailedCreate",
                    f"create Pod {name_of(pod)} failed: {e}")
                break  # quota: further ordinals would fail identically

    def _missing_pods_fit_quota(self, api: APIServer, sts: dict,
                                missing: list[int]) -> bool:
        """Would creating every missing ordinal clear the namespace's
        ResourceQuotas? Mirrors the apiserver's per-pod enforcement
        (``apiserver._enforce_quota``) summed over the whole batch."""
        # KubeAPIServer has no client-side toggle: quota admission is
        # the server's job there, this pre-check stays advisory
        if not getattr(api, "quota_enforcement", True):
            return True
        ns = namespace_of(sts)
        scan = getattr(api, "scan", api.list)
        quotas = scan("ResourceQuota", ns)
        if not quotas:
            return True
        template_pod = self._render_pod(sts, 0)
        live = [p for p in scan("Pod", ns)
                if not p["metadata"].get("deletionTimestamp")]
        for quota in quotas:
            hard = deep_get(quota, "spec", "hard", default={}) or {}
            for resource, limit in hard.items():
                limit_v = parse_quantity(limit)
                if resource == "pods":
                    if len(live) + len(missing) > limit_v:
                        return False
                    continue
                # mirror _enforce_quota exactly: "limits.X" charges
                # limits only; everything else charges requests
                # defaulting to limits
                rname, rkind = resource, "requests"
                if rname.startswith("requests."):
                    rname = rname[len("requests."):]
                elif rname.startswith("limits."):
                    rname = rname[len("limits."):]
                    rkind = "limits"
                per_pod = _pod_resource_request(template_pod, rname, rkind)
                if not per_pod:
                    continue
                used = sum(_pod_resource_request(p, rname, rkind)
                           for p in live)
                if used + per_pod * len(missing) > limit_v:
                    return False
        return True

    # -- pod rendering -------------------------------------------------
    def _render_pod(self, sts: dict, ordinal: int) -> dict:
        name = f"{name_of(sts)}-{ordinal}"
        tmpl = fast_deepcopy(deep_get(sts, "spec", "template", default={}))
        labels = dict(tmpl.get("metadata", {}).get("labels") or {})
        labels[POD_NAME_LABEL] = name
        pod = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": name,
                "namespace": namespace_of(sts),
                "labels": labels,
                "annotations": dict(
                    tmpl.get("metadata", {}).get("annotations") or {}),
            },
            "spec": fast_deepcopy(tmpl.get("spec") or {}),
        }
        pod["spec"]["hostname"] = name
        svc = deep_get(sts, "spec", "serviceName")
        if svc:
            pod["spec"]["subdomain"] = svc
        return pod

    # -- scheduling + status (the fake kubelet) ------------------------
    #: legacy arm only: scheduling there is a read-compute-write over
    #: SHARED node capacity, serialized whole under one global lock.
    #: The default path runs assume/bind against the incremental usage
    #: cache in ``controlplane/scheduler.py`` — per-node locks, no
    #: global serialization, no per-reconcile Pod scan.
    _bind_lock = __import__("threading").Lock()

    def _schedule_and_run(self, api: APIServer, sts: dict) -> None:
        if _gang_of(sts) is not None:
            # multi-role gangs always take the cached assume/bind path:
            # mixed-resource all-or-nothing placement needs the dual
            # (chips, cpu) accounting the legacy scan never had
            self._schedule_and_run_cached(api, sts)
            return
        if scheduler.legacy_scan():
            with self._bind_lock:
                self._schedule_and_run_locked(api, sts)
            return
        self._schedule_and_run_cached(api, sts)

    def _owned_pods(self, api: APIServer, sts: dict) -> list[dict]:
        # this STS's pods ARE mutated by the kubelet half -> copies
        return [p for p in api.list("Pod", namespace_of(sts))
                if any(r.get("uid") == sts["metadata"]["uid"]
                       for r in p["metadata"].get("ownerReferences", []))]

    def _allow_virtual(self, api: APIServer) -> bool:
        return (self.virtual_node_fallback
                if self.virtual_node_fallback is not None
                # unwrap a CachedAPI: the backend decides — hermetic
                # in-memory yes, real cluster no
                else isinstance(getattr(api, "api", api), APIServer))

    @staticmethod
    def _exclude_nodes(sts: dict) -> set[str] | None:
        """Live migration: the notebook controller mirrors the CR's
        migrate-exclude annotation onto the STS; the re-bind must avoid
        those nodes or the "migration" would land right back where it
        drained from."""
        from kubeflow_rm_tpu.controlplane.api import notebook as nb_api
        raw = (sts["metadata"].get("annotations") or {}).get(
            nb_api.MIGRATE_EXCLUDE_ANNOTATION)
        if not raw:
            return None
        try:
            nodes = json.loads(raw)
        except ValueError:
            return None
        return {str(n) for n in nodes} if isinstance(nodes, list) \
            else None

    def _mark_unschedulable(self, api: APIServer, pod: dict,
                            message: str | None = None) -> None:
        if deep_get(pod, "status", "phase") != "Pending":
            pod["status"] = {"phase": "Pending"}
            api.update_status(pod)
        if not any(e["reason"] == "FailedScheduling"
                   for e in api.events_for(pod)):
            api.record_event(
                pod, "Warning", "FailedScheduling",
                message or ("no node matches TPU nodeSelector with free "
                            f"{GOOGLE_TPU_RESOURCE} capacity"))

    def _schedule_and_run_cached(self, api: APIServer, sts: dict) -> None:
        """Assume/bind over the incremental usage cache: the whole
        slice gang-binds all-or-nothing, each bind charged to the cache
        before its write and confirmed with the write's rv (or
        forgotten on failure) — concurrent reconciles can't over-commit
        a node no matter how far the watch stream lags."""
        sched = scheduler.cache_for(api)
        unbound = []
        for pod in sorted(self._owned_pods(api, sts), key=name_of):
            if deep_get(pod, "spec", "nodeName"):
                # pre-pinned (RWO node affinity) or already scheduled:
                # the kubelet half still owes it a Running status
                if (self.auto_ready
                        and deep_get(pod, "status", "phase")
                        not in ("Running",) + TERMINAL_PHASES):
                    # terminal pods stay terminal — recovery is the
                    # slice-health controller's whole-slice decision,
                    # and a real kubelet never resurrects a Failed or
                    # Succeeded pod
                    self.mark_running(api, pod)
                continue
            unbound.append(pod)
        gang = _gang_of(sts)
        if gang is not None:
            self._schedule_gang(api, sts, gang, sched)
            return
        if not unbound:
            # active-defrag arm: a settled reconcile is the cheap place
            # to ask "is the pool fragmented enough to compact now?" —
            # flag-gated no-op by default
            from kubeflow_rm_tpu.controlplane import suspend
            if suspend.active_defrag():
                suspend.maybe_active_defrag(
                    api, sched, allow_virtual=self._allow_virtual(api))
            return
        allow_virtual = self._allow_virtual(api)
        exclude = self._exclude_nodes(sts)
        plan = sched.gang_bind(unbound, allow_virtual=allow_virtual,
                               exclude_nodes=exclude)
        if plan is None:
            # priority preemption: suspend strictly lower-priority
            # victim slices and retry the gang in this same reconcile
            from kubeflow_rm_tpu.controlplane import suspend
            plan = suspend.try_preempt(api, sts, unbound, sched,
                                       allow_virtual=allow_virtual)
        if plan is None:
            # fragmentation-triggered live migration: when free chips
            # would seat the gang but sit stranded across nodes, move a
            # small victim out of the way (no-op unless enabled)
            from kubeflow_rm_tpu.controlplane import suspend
            suspend.try_compact_migration(api, sts, unbound, sched,
                                          allow_virtual=allow_virtual)
            for pod in unbound:
                self._mark_unschedulable(api, pod)
            return
        for pod in unbound:
            key = (namespace_of(pod), name_of(pod))
            pod["spec"]["nodeName"] = plan[key]
            try:
                live = api.update(pod)
            except Exception:
                # bind write lost (conflict/deleted): release the
                # assumed charge; the retried reconcile re-plans
                sched.forget(key)
                raise
            sched.confirm(key, deep_get(
                live, "metadata", "resourceVersion", default=0))
            if self.auto_ready:
                self.mark_running(api, pod, live=live)

    def _schedule_gang(self, api: APIServer, sts: dict,
                       gang: tuple[str, list[dict]], sched) -> None:
        """Bind a TPUJob's WHOLE heterogeneous gang — every role's pods
        across every role StatefulSet — in one mixed-resource assume
        transaction. Exactly one STS acts as the binder (the first
        role's — deterministic, so two role reconciles never race a
        bind for the same pod); the others only run the kubelet half.
        Binding waits until every role has materialised its pods: a
        half-created gang is never partially placed."""
        job, roles = gang
        ns = namespace_of(sts)
        if name_of(sts) != tj_api.role_sts_name(
                job, roles[0].get("name", "")):
            return  # not the binder; _map_gang_pod keeps it requeued
        expected = sum(int(r.get("pods") or 0) for r in roles)
        gang_pods = [
            p for p in api.list(
                "Pod", ns,
                {"matchLabels": {tj_api.JOB_NAME_LABEL: job}})
            if deep_get(p, "status", "phase") not in TERMINAL_PHASES
        ]
        if len(gang_pods) < expected:
            return  # sibling roles still creating; their events requeue
        unbound = sorted(
            [p for p in gang_pods
             if not deep_get(p, "spec", "nodeName")], key=name_of)
        if not unbound:
            return
        plan = sched.gang_bind(unbound,
                               allow_virtual=self._allow_virtual(api))
        if plan is None:
            msg = (f"gang of {expected} pods ({len(roles)} roles) does "
                   "not fit: needs chip AND cpu headroom on matching "
                   "nodes; nothing was placed (all-or-nothing)")
            for pod in unbound:
                self._mark_unschedulable(api, pod, message=msg)
            return
        for pod in unbound:
            key = (namespace_of(pod), name_of(pod))
            pod["spec"]["nodeName"] = plan[key]
            try:
                live = api.update(pod)
            except Exception:
                sched.forget(key)
                raise
            sched.confirm(key, deep_get(
                live, "metadata", "resourceVersion", default=0))
            if self.auto_ready:
                self.mark_running(api, pod, live=live)

    def _schedule_and_run_locked(self, api: APIServer, sts: dict) -> None:
        scan = getattr(api, "scan", api.list)
        nodes = scan("Node")
        pods = self._owned_pods(api, sts)

        # chips already committed per node; terminal pods hold none (a
        # Failed host must free its chips for the replacement slice,
        # not leak them until the Pod object is deleted)
        used: dict[str, float] = {}
        for p in scan("Pod"):
            node = deep_get(p, "spec", "nodeName")
            if node and deep_get(p, "status", "phase") \
                    not in TERMINAL_PHASES:
                used[node] = used.get(node, 0.0) + _pod_tpu_request(p)

        for pod in sorted(pods, key=name_of):
            if deep_get(pod, "spec", "nodeName"):
                # pre-pinned (RWO node affinity) or already scheduled:
                # the kubelet half still owes it a Running status
                if (self.auto_ready
                        and deep_get(pod, "status", "phase")
                        not in ("Running",) + TERMINAL_PHASES):
                    self.mark_running(api, pod)
                continue
            node = self._pick_node(api, pod, nodes, used)
            if node is None:
                self._mark_unschedulable(api, pod)
                continue
            used[name_of(node)] = used.get(name_of(node), 0.0) + \
                _pod_tpu_request(pod)
            pod["spec"]["nodeName"] = name_of(node)
            api.update(pod)
            if self.auto_ready:
                self.mark_running(api, pod)

    def _maybe_chaos_pod_kill(self, api: APIServer, sts: dict) -> None:
        """Seeded kubelet pod-kill: one chaos opportunity per reconcile
        of an STS with Running pods. The victim goes to phase=Failed —
        exactly what a real kubelet reports for an OOM-killed or
        node-lost container — so the platform's own recovery ladders
        (slice health restart, replica failover) do the healing."""
        if chaos.active() is None:
            return
        running = [p for p in self._owned_pods(api, sts)
                   if deep_get(p, "status", "phase") == "Running"]
        site = f"{namespace_of(sts)}/{name_of(sts)}"
        victim = chaos.pod_kill_victim(site,
                                       [name_of(p) for p in running])
        if victim is None:
            return
        pod = next(p for p in running if name_of(p) == victim)
        pod["status"]["phase"] = "Failed"
        pod["status"]["conditions"] = [
            {"type": "Ready", "status": "False"}]
        try:
            api.update_status(pod)
            api.record_event(pod, "Warning", "ChaosKilled",
                             "chaos: injected kubelet pod kill")
        except NotFound:
            pass  # raced a delete; the kill is moot

    def mark_running(self, api: APIServer, pod: dict,
                     live: dict | None = None) -> None:
        # a caller holding the pod's freshly-written state (the bind
        # update's return) passes it as ``live`` to skip the re-read
        pod = live if live is not None else api.get(
            "Pod", name_of(pod), namespace_of(pod))
        containers = deep_get(pod, "spec", "containers", default=[]) or []
        pod["status"] = {
            "phase": "Running",
            "podIP": "10.0.0.1",
            "conditions": [
                {"type": "Ready", "status": "True"},
                {"type": "PodScheduled", "status": "True"},
            ],
            "containerStatuses": [
                {
                    "name": c["name"],
                    "ready": True,
                    "restartCount": 0,
                    "state": {"running": {"startedAt":
                                          api.clock().isoformat()}},
                }
                for c in containers
            ],
        }
        api.update_status(pod)
        # synthesize the container boot transcript (what `kubectl logs`
        # would show): per-ordinal debugging of a multi-host slice is a
        # first-class JWA feature here
        env = {e.get("name"): e.get("value")
               for c in containers for e in (c.get("env") or [])}
        ns, name = namespace_of(pod), name_of(pod)
        now = api.clock().isoformat()
        for c in containers:
            api.append_pod_log(
                ns, name, f"{now} pulled image {c.get('image')}")
        api.append_pod_log(ns, name, f"{now} s6: services started")
        if "TPU_WORKER_ID" in env:
            api.append_pod_log(
                ns, name,
                f"{now} worker-agent: TPU_WORKER_ID={env['TPU_WORKER_ID']} "
                f"hostnames={env.get('TPU_WORKER_HOSTNAMES', '')} "
                "joining jax.distributed")

    def _pick_node(self, api: APIServer, pod: dict, nodes: list[dict],
                   used: dict[str, float]):
        selector = deep_get(pod, "spec", "nodeSelector", default={}) or {}
        need = _pod_tpu_request(pod)
        for node in nodes:
            if selector and not matches_selector(
                    labels_of(node), {"matchLabels": selector}):
                continue
            if need:
                cap = parse_quantity(deep_get(
                    node, "status", "allocatable", GOOGLE_TPU_RESOURCE,
                    default=0))
                if used.get(name_of(node), 0.0) + need > cap:
                    continue
            return node
        if self._allow_virtual(api) and not selector and not need:
            # plain CPU pod: runnable even in a test with no Node inventory
            return {"metadata": {"name": VIRTUAL_NODE}}
        return None

    def _mirror_status(self, api: APIServer, sts: dict) -> None:
        ns = namespace_of(sts)
        pods = [p for p in getattr(api, "scan", api.list)("Pod", ns)
                if any(r.get("uid") == sts["metadata"]["uid"]
                       for r in p["metadata"].get("ownerReferences", []))]
        ready = sum(
            1 for p in pods
            if any(c.get("type") == "Ready" and c.get("status") == "True"
                   for c in deep_get(p, "status", "conditions",
                                     default=[]) or [])
        )
        status = {"replicas": len(pods), "readyReplicas": ready}
        if deep_get(sts, "status") != status:
            sts["status"] = status
            api.update_status(sts)


class DeploymentController(StatefulSetController):
    """Deployment → Pods: same fake kubelet, Deployment semantics
    (no ordinal identity guarantees needed at this fidelity; status
    mirrors readyReplicas/availableReplicas)."""

    kind = "Deployment"

    def watches(self):
        return (("Pod", map_to_owner("Deployment")),)

    def _mirror_status(self, api: APIServer, deploy: dict) -> None:
        ns = namespace_of(deploy)
        pods = [p for p in getattr(api, "scan", api.list)("Pod", ns)
                if any(r.get("uid") == deploy["metadata"]["uid"]
                       for r in p["metadata"].get("ownerReferences", []))]
        ready = sum(
            1 for p in pods
            if any(c.get("type") == "Ready" and c.get("status") == "True"
                   for c in deep_get(p, "status", "conditions",
                                     default=[]) or [])
        )
        status = {"replicas": len(pods), "readyReplicas": ready,
                  "availableReplicas": ready}
        if deep_get(deploy, "status") != status:
            deploy["status"] = status
            api.update_status(deploy)


def _gang_of(sts: dict) -> tuple[str, list[dict]] | None:
    """(job_name, roles) when this STS is one role of a TPUJob gang —
    read off the pod template's gang label + roles annotation, so the
    binder needs no TPUJob CR round-trip."""
    tmpl_md = deep_get(sts, "spec", "template", "metadata",
                       default={}) or {}
    job = (tmpl_md.get("labels") or {}).get(tj_api.JOB_NAME_LABEL)
    if not job:
        return None
    roles = tj_api.parse_roles_annotation({"metadata": tmpl_md})
    if not roles:
        return None
    return job, roles


def _map_gang_pod(obj: dict) -> list[Request]:
    """Fan a gang pod's events out to every role STS of its TPUJob —
    the binder (first role's STS) must reconcile when ANY role's pods
    change, and plain ownership mapping only reaches one role."""
    job = labels_of(obj).get(tj_api.JOB_NAME_LABEL)
    if not job:
        return []
    roles = tj_api.parse_roles_annotation(obj) or []
    ns = namespace_of(obj)
    return [Request(ns, tj_api.role_sts_name(job, r["name"]))
            for r in roles if r.get("name")]


def _ordinal(pod_name: str, sts_name: str) -> int | None:
    prefix = sts_name + "-"
    if not pod_name.startswith(prefix):
        return None
    try:
        return int(pod_name[len(prefix):])
    except ValueError:
        return None


def _pod_tpu_request(pod: dict) -> float:
    return _pod_resource_request(pod, GOOGLE_TPU_RESOURCE)


def _pod_resource_request(pod: dict, resource: str,
                          kind: str = "requests") -> float:
    """kind='requests': requests defaulting to limits (the kube quota
    convention); kind='limits': limits only — matches
    ``apiserver._enforce_quota`` so pre-checks and admission agree."""
    total = 0.0
    for c in deep_get(pod, "spec", "containers", default=[]) or []:
        if kind == "limits":
            amount = deep_get(c, "resources", "limits", resource)
        else:
            amount = deep_get(c, "resources", "requests", resource)
            if amount is None:
                amount = deep_get(c, "resources", "limits", resource)
        if amount is not None:
            total += parse_quantity(amount)
    return total
