"""Idle culling: probe kernel activity, stop idle slices whole.

Mirrors the reference culler
(``notebook-controller/pkg/culler/culler.go`` +
``controllers/culling_controller.go:85-169``): each check period, probe
the notebook's Jupyter server for ``/api/kernels`` and
``/api/terminals`` activity, maintain the
``notebooks.kubeflow.org/last-activity`` annotation (newest activity
wins — ``culler.go:242-262``), and set the stop annotation once idle
longer than CULL_IDLE_TIME (``NotebookNeedsCulling`` ``:404-419``).

Slice-aware by construction: activity is only observable on worker 0
(JupyterLab runs there; peers run the worker agent), but the stop
annotation drives the StatefulSet to zero replicas, so one idle
notebook releases ALL hosts of the slice at once — idleness on a
v5p-128 costs 16 hosts. Like the reference (ENABLE_CULLING,
``main.go:111-123``), culling is opt-in: pass
``enable_culling=True`` to ``make_control_plane``.

The probe is injected (``probe_fn(notebook, pod0) -> {"kernels": [...],
"terminals": [...]} | None``) so tests — and deployments with
nonstandard servers — control it; the default implementation does the
same HTTP GET against the worker-0 service DNS the reference does
(``culler.go:155-180``).
"""

from __future__ import annotations

import datetime
from typing import Callable

from kubeflow_rm_tpu.controlplane import metrics
from kubeflow_rm_tpu.controlplane.api import notebook as nb_api
from kubeflow_rm_tpu.controlplane.api.meta import (
    annotations_of,
    deep_get,
    set_annotation,
)
from kubeflow_rm_tpu.controlplane.apiserver import APIServer, NotFound
from kubeflow_rm_tpu.controlplane.runtime import Controller, Request

DEFAULT_CULL_IDLE_TIME_MIN = 1440.0   # culler.go:26
DEFAULT_CHECK_PERIOD_MIN = 1.0        # culler.go:27


import logging

log = logging.getLogger("kubeflow_rm_tpu.culling")


def default_probe(notebook: dict, pod0: dict | None,
                  base_url: str | None = None):
    """HTTP probe of worker 0's Jupyter REST API (culler.go:155-180).

    ``base_url`` overrides the in-cluster service DNS (tests, port
    forwards). Per-endpoint failures are logged with their reason — an
    auth-broken or misconfigured probe must be debuggable from the
    controller log, not silently identical to an idle server
    (culler.go:155-221 logs per-endpoint warnings the same way)."""
    import json
    import urllib.request

    ns = notebook["metadata"]["namespace"]
    name = notebook["metadata"]["name"]
    url = base_url or (
        f"http://{name}.{ns}.svc.cluster.local/notebook/{ns}/{name}/api")
    # per-endpoint failure handling: a server with terminals disabled
    # 404s /api/terminals but still reports busy kernels — discarding
    # the kernel answer would cull an actively-used notebook
    out = {}
    for kind in ("kernels", "terminals"):
        try:
            with urllib.request.urlopen(f"{url}/{kind}", timeout=5) as r:
                out[kind] = json.load(r)
        except Exception as e:
            log.warning("probe %s/%s: GET %s/%s failed: %r",
                        ns, name, url, kind, e)
    return out or None  # both unreachable: no activity info this period


class CullingController(Controller):
    kind = nb_api.KIND

    def __init__(self,
                 cull_idle_minutes: float = DEFAULT_CULL_IDLE_TIME_MIN,
                 check_period_minutes: float = DEFAULT_CHECK_PERIOD_MIN,
                 probe_fn: Callable | None = None):
        self.cull_idle = datetime.timedelta(minutes=cull_idle_minutes)
        self.check_period = datetime.timedelta(minutes=check_period_minutes)
        self.probe_fn = probe_fn or default_probe

    def reconcile(self, api: APIServer, req: Request):
        try:
            notebook = api.get(nb_api.KIND, req.name, req.namespace)
        except NotFound:
            return None
        ann = annotations_of(notebook)
        if nb_api.STOP_ANNOTATION in ann:
            return None  # already stopped: nothing to cull
        if ann.get(nb_api.CULLING_EXCLUDE_ANNOTATION) == "true":
            return None
        if nb_api.SUSPEND_ANNOTATION in ann:
            return None  # suspended: chips already released, nothing to cull
        if nb_api.is_pinned(notebook):
            return None  # pinned: holds its slice for the notebook's lifetime
        requeue = self.check_period.total_seconds()

        pod0 = api.try_get("Pod", f"{req.name}-0", req.namespace)
        if pod0 is None or deep_get(pod0, "status", "phase") != "Running":
            # not running: nothing to probe, nothing to cull
            # (culling_controller.go:103-128 skips pod-absent notebooks)
            return requeue
        activity = self.probe_fn(notebook, pod0)
        now = api.clock()
        if activity is None:
            # a running pod whose probe is entirely unreachable is a
            # misconfiguration signal (auth proxy, NetworkPolicy), not
            # just an idle server — surface it once per incarnation
            already = any(e.get("reason") == "CullingProbeFailed"
                          for e in api.events_for(notebook))
            if not already:
                api.record_event(
                    notebook, "Warning", "CullingProbeFailed",
                    "worker-0 activity probe unreachable; idleness is "
                    "being measured from the last known activity only")

        # activity cannot predate the current incarnation: a restarted
        # slice starts its idle clock at worker-0's start time, so a
        # stale last-activity from before a cull can't re-cull instantly
        started = deep_get(pod0, "status", "containerStatuses", 0, "state",
                           "running", "startedAt")

        if activity is not None:
            last = self._newest_activity(activity, now)
            if last is not None:
                current = ann.get(nb_api.LAST_ACTIVITY_ANNOTATION)
                if current is None or last.isoformat() > current:
                    set_annotation(notebook, nb_api.LAST_ACTIVITY_ANNOTATION,
                                   last.isoformat())
                    notebook = api.update(notebook)
                    ann = annotations_of(notebook)

        last_str = ann.get(nb_api.LAST_ACTIVITY_ANNOTATION)
        if last_str is None:
            # no recorded activity yet: start the idle clock now
            set_annotation(notebook, nb_api.LAST_ACTIVITY_ANNOTATION,
                           now.isoformat())
            api.update(notebook)
            return requeue

        last_activity = datetime.datetime.fromisoformat(last_str)
        if started:
            start_t = datetime.datetime.fromisoformat(
                started.replace("Z", "+00:00"))
            if start_t > last_activity:
                last_activity = start_t
        if now - last_activity >= self.cull_idle:
            set_annotation(notebook, nb_api.STOP_ANNOTATION, now.isoformat())
            api.update(notebook)
            api.record_event(
                notebook, "Normal", "Culling",
                f"idle since {last_str}; stopping the slice "
                f"(threshold {self.cull_idle})")
            metrics.NOTEBOOK_CULL_TOTAL.inc()
            return None
        return requeue

    def _newest_activity(self, activity: dict, now: datetime.datetime):
        """Newest last_activity across kernels+terminals; a busy kernel
        counts as activity *now* (culler.go:223-262)."""
        newest = None
        for kind in ("kernels", "terminals"):
            for item in activity.get(kind) or []:
                if item.get("execution_state") == "busy":
                    return now
                ts = item.get("last_activity")
                if ts:
                    t = datetime.datetime.fromisoformat(
                        ts.replace("Z", "+00:00"))
                    if newest is None or t > newest:
                        newest = t
        return newest
