"""Slice health: failure detection + whole-slice restart.

The reference's recovery story is level-triggered reconciliation of a
single pod (SURVEY.md §5: "Elasticity is only replicas 0↔1"). A TPU
slice changes the failure calculus: an SPMD program spans every host,
so ONE failed/preempted worker wedges the other N−1 — they hold chips,
the jax collectives block, and nothing recovers until all N pods
restart together. This controller supplies the missing semantic:

- a Failed pod (OOM-kill, preemption, node drain) in a multi-host
  slice ⇒ delete EVERY pod of the slice at once; the StatefulSet
  controller re-creates all ordinals in parallel and the workers
  re-rendezvous from a clean state;
- a vanished pod (count < hosts while peers still run) ⇒ same
  whole-slice restart — a rump slice is never left holding chips;
- single-host notebooks keep the reference behavior: delete just the
  failed pod and let it come back.

Events (``SliceRestart``) make the restart visible in the UI's
activity feed, the way the reference re-emits scheduling failures
(``notebook_controller.go:94-123``).
"""

from __future__ import annotations

from kubeflow_rm_tpu.controlplane.api import notebook as nb_api
from kubeflow_rm_tpu.controlplane.api.meta import deep_get, name_of
from kubeflow_rm_tpu.controlplane.apiserver import APIServer, NotFound
from kubeflow_rm_tpu.controlplane.runtime import Controller, Request


def _map_pod_to_notebook(pod: dict):
    label = (pod["metadata"].get("labels") or {}).get(
        nb_api.NOTEBOOK_NAME_LABEL)
    if not label:
        return []
    return [Request(pod["metadata"].get("namespace"), label)]


class SliceHealthController(Controller):
    kind = nb_api.KIND

    def watches(self):
        return (("Pod", _map_pod_to_notebook),)

    def reconcile(self, api: APIServer, req: Request):
        try:
            nb = api.get(self.kind, req.name, req.namespace)
        except NotFound:
            return None
        ann = nb["metadata"].get("annotations") or {}
        if nb_api.STOP_ANNOTATION in ann:
            return None  # stopped/culled: drained pods are expected
        if (nb_api.SUSPEND_ANNOTATION in ann
                or nb_api.RESUME_REQUESTED_ANNOTATION in ann):
            return None  # suspend/resume drains on purpose mid-flight
        if nb_api.replicas_of(nb) > 1:
            # replicated kernels: the failover controller owns recovery
            # (promote a warm standby), not a cold in-place restart
            return None

        # a multislice job is ONE gang: any slice's failure restarts all
        hosts = nb_api.total_hosts(nb)
        # scan(): phase/labels are only read here; deletes go through
        # the verb surface by name
        pods = [
            p for p in getattr(api, "scan", api.list)("Pod", req.namespace)
            if (p["metadata"].get("labels") or {}).get(
                nb_api.NOTEBOOK_NAME_LABEL) == req.name
            and not p["metadata"].get("deletionTimestamp")
        ]
        failed = [p for p in pods
                  if deep_get(p, "status", "phase") == "Failed"]
        running = [p for p in pods
                   if deep_get(p, "status", "phase") == "Running"]

        if hosts == 1:
            # reference behavior: recycle just the failed pod
            for p in failed:
                api.delete("Pod", name_of(p), req.namespace)
            return None

        unhealthy = bool(failed) or (running and len(pods) < hosts)
        if not unhealthy:
            return None

        reason = (f"{len(failed)} failed pod(s)" if failed else
                  f"only {len(pods)}/{hosts} pods present")
        api.record_event(
            nb, "Warning", "SliceRestart",
            f"TPU slice unhealthy ({reason}); restarting all {hosts} "
            "hosts — a slice recovers whole or not at all")
        # tear down by ORDINAL NAME, not by "pods currently visible":
        # this controller reads through an informer cache, and during a
        # churn the cache can momentarily show a partial slice — a
        # visibility-based sweep would then leave survivors, breaking
        # the whole-or-not-at-all guarantee (deletes of already-gone
        # ordinals are NotFound no-ops)
        for i in range(hosts):
            try:
                api.delete("Pod", f"{req.name}-{i}", req.namespace)
            except NotFound:
                pass
        return None
