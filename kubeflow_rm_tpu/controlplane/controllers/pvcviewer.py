"""PVCViewer controller: PVCViewer CR → filebrowser Deployment + Service.

Mirrors ``pvcviewer-controller/controllers/pvcviewer_controller.go:96-148``
(+ design doc ``components/proposals/20230130-pvcviewer-controller.md``):
a file-browser over a PVC, with the same RWO node-pinning the
tensorboard controller uses, and idle culling driven by a
``lastActivity``-style annotation the volumes web app maintains.
"""

from __future__ import annotations

from kubeflow_rm_tpu.controlplane.api.meta import (
    deep_get,
    make_object,
)
from kubeflow_rm_tpu.controlplane.apiserver import APIServer, NotFound
from kubeflow_rm_tpu.controlplane.runtime import (
    Controller,
    Request,
    copy_deployment_fields,
    copy_service_fields,
    map_to_owner,
    reconcile_children,
    rwo_mounting_node,
)

API_VERSION = "kubeflow.org/v1alpha1"
KIND = "PVCViewer"

DEFAULT_IMAGE = "filebrowser/filebrowser:latest"


def make_pvcviewer(name: str, namespace: str, pvc: str) -> dict:
    return make_object(API_VERSION, KIND, name, namespace,
                       spec={"pvc": pvc})


class PVCViewerController(Controller):
    kind = KIND

    def __init__(self, image: str = DEFAULT_IMAGE,
                 rwo_scheduling: bool = True):
        self.image = image
        self.rwo_scheduling = rwo_scheduling

    def watches(self):
        return (("Deployment", map_to_owner(KIND)),)

    def reconcile(self, api: APIServer, req: Request):
        try:
            viewer = api.get(KIND, req.name, req.namespace)
        except NotFound:
            return None
        pvc = deep_get(viewer, "spec", "pvc")
        name, ns = req.name, req.namespace

        pod_spec: dict = {
            "containers": [{
                "name": "pvcviewer",
                "image": self.image,
                "args": ["--root", "/data", "--port", "8080",
                         "--baseurl", f"/pvcviewers/{ns}/{name}/"],
                "ports": [{"containerPort": 8080}],
                "volumeMounts": [{"name": "data", "mountPath": "/data"}],
            }],
            "volumes": [{"name": "data",
                         "persistentVolumeClaim": {"claimName": pvc}}],
        }
        if self.rwo_scheduling:
            node = rwo_mounting_node(api, ns, pvc)
            if node:
                pod_spec["nodeName"] = node

        deploy = {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": f"{name}-pvcviewer", "namespace": ns,
                         "labels": {"pvcviewer": name}},
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": {"pvcviewer": name}},
                "template": {
                    "metadata": {"labels": {"pvcviewer": name}},
                    "spec": pod_spec,
                },
            },
        }
        svc = make_object("v1", "Service", f"{name}-pvcviewer", ns, spec={
            "selector": {"pvcviewer": name},
            "ports": [{"port": 80, "targetPort": 8080, "protocol": "TCP"}],
        })
        reconcile_children(api, viewer, [(deploy, copy_deployment_fields),
                                         (svc, copy_service_fields)])

        live = api.try_get("Deployment", f"{name}-pvcviewer", ns)
        ready = deep_get(live, "status", "readyReplicas", default=0) if live \
            else 0
        status = {"ready": ready >= 1}
        if deep_get(viewer, "status") != status:
            viewer["status"] = status
            api.update_status(viewer)
        return None
