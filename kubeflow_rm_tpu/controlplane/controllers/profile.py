"""Profile reconciler: Profile CR → namespace + RBAC + TPU-chip quota.

Mirrors ``profile-controller/controllers/profile_controller.go:105-335``:
namespace with owner annotation, ``default-editor``/``default-viewer``
ServiceAccounts, an admin RoleBinding for the owner, and a
``kf-resource-quota`` ResourceQuota created/updated iff
``spec.resourceQuotaSpec.hard`` is set and deleted when unset
(``:252-281``) — with ``google.com/tpu`` as a first-class quota
resource, enforced by the apiserver's quota admission on every pod of a
slice. Plugins follow the reference's interface (``:77-84``); the GCP
Workload Identity plugin replaces the AWS-first ordering.
"""

from __future__ import annotations

from kubeflow_rm_tpu.controlplane import metrics
from kubeflow_rm_tpu.controlplane.api import profile as profile_api
from kubeflow_rm_tpu.controlplane.api.meta import (
    deep_get,
    make_object,
    set_controller_reference,
)
from kubeflow_rm_tpu.controlplane.apiserver import (
    AlreadyExists, APIServer, NotFound,
)
from kubeflow_rm_tpu.controlplane.runtime import (
    Controller,
    Request,
    copy_simple_spec,
    reconcile_child,
)


class ProfilePlugin:
    """Plugin contract (ref ``profile_controller.go:77-84``)."""

    kind: str = ""

    def apply(self, api: APIServer, profile: dict, spec: dict) -> None:
        raise NotImplementedError

    def revoke(self, api: APIServer, profile: dict, spec: dict) -> None:
        pass


class GcpWorkloadIdentityPlugin(ProfilePlugin):
    """Binds the namespace's default-editor SA to a GCP service account
    via Workload Identity annotation — the TPU-native first-class plugin
    (ref ``plugin_workload_identity.go``; checkpoints and tensorboard
    logs live in GCS)."""

    kind = "WorkloadIdentity"

    def apply(self, api: APIServer, profile: dict, spec: dict) -> None:
        ns = profile["metadata"]["name"]
        sa = api.try_get("ServiceAccount", profile_api.DEFAULT_EDITOR, ns)
        if sa is None:
            return
        gsa = spec.get("gcpServiceAccount")
        if not gsa:
            return
        ann = sa["metadata"].setdefault("annotations", {})
        if ann.get("iam.gke.io/gcp-service-account") != gsa:
            ann["iam.gke.io/gcp-service-account"] = gsa
            api.update(sa)


PLUGINS: dict[str, ProfilePlugin] = {
    p.kind: p for p in (GcpWorkloadIdentityPlugin(),)
}


class ProfileController(Controller):
    kind = profile_api.KIND

    def reconcile(self, api: APIServer, req: Request):
        try:
            profile = api.get(profile_api.KIND, req.name)
        except NotFound:
            return None  # namespace + children go via GC (ownerReferences)
        name = req.name
        owner = deep_get(profile, "spec", "owner", "name", default="")

        ns = api.try_get("Namespace", name)
        if ns is None:
            ns = {
                "apiVersion": "v1",
                "kind": "Namespace",
                "metadata": {
                    "name": name,
                    "annotations": {profile_api.OWNER_ANNOTATION: owner},
                    "labels": {
                        "app.kubernetes.io/part-of": "kubeflow-profile",
                        "katib.kubeflow.org/metrics-collector-injection":
                            "enabled",
                    },
                },
            }
            set_controller_reference(profile, ns)
            try:
                api.create(ns)
            except AlreadyExists:
                pass
            metrics.PROFILE_CREATE_TOTAL.inc()

        for sa_name in (profile_api.DEFAULT_EDITOR,
                        profile_api.DEFAULT_VIEWER):
            sa = make_object("v1", "ServiceAccount", sa_name, name)
            reconcile_child(api, profile, sa, copy_simple_spec)

        admin_binding = make_object(
            "rbac.authorization.k8s.io/v1", "RoleBinding",
            "namespaceAdmin", name)
        admin_binding["roleRef"] = {
            "apiGroup": "rbac.authorization.k8s.io",
            "kind": "ClusterRole", "name": "kubeflow-admin",
        }
        admin_binding["subjects"] = [
            {"kind": "User", "name": owner,
             "apiGroup": "rbac.authorization.k8s.io"},
        ]
        reconcile_child(api, profile, admin_binding, copy_simple_spec)

        for sa_name, role in ((profile_api.DEFAULT_EDITOR, "kubeflow-edit"),
                              (profile_api.DEFAULT_VIEWER, "kubeflow-view")):
            rb = make_object("rbac.authorization.k8s.io/v1", "RoleBinding",
                             sa_name, name)
            rb["roleRef"] = {"apiGroup": "rbac.authorization.k8s.io",
                             "kind": "ClusterRole", "name": role}
            rb["subjects"] = [{"kind": "ServiceAccount", "name": sa_name,
                               "namespace": name}]
            reconcile_child(api, profile, rb, copy_simple_spec)

        # ResourceQuota: present iff spec.resourceQuotaSpec.hard (ref :252-281)
        hard = deep_get(profile, "spec", "resourceQuotaSpec", "hard")
        existing = api.try_get("ResourceQuota", profile_api.QUOTA_NAME, name)
        if hard:
            quota = make_object("v1", "ResourceQuota",
                                profile_api.QUOTA_NAME, name,
                                spec={"hard": dict(hard)})
            reconcile_child(api, profile, quota, copy_simple_spec)
        elif existing is not None:
            api.delete("ResourceQuota", profile_api.QUOTA_NAME, name)

        for plugin_spec in deep_get(profile, "spec", "plugins",
                                    default=[]) or []:
            plugin = PLUGINS.get(plugin_spec.get("kind", ""))
            if plugin:
                plugin.apply(api, profile, plugin_spec.get("spec", {}))
        return None
