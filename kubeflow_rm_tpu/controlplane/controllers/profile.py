"""Profile reconciler: Profile CR → namespace + RBAC + TPU-chip quota.

Mirrors ``profile-controller/controllers/profile_controller.go:105-335``:
namespace with owner annotation and ``istio-injection: enabled`` label
(``:126-172``, re-applied to pre-existing namespaces as ``:181`` does),
the owner ``ns-owner-access-istio`` AuthorizationPolicy (``:419-557``),
``default-editor``/``default-viewer`` ServiceAccounts, an admin
RoleBinding for the owner, and a ``kf-resource-quota`` ResourceQuota
created/updated iff ``spec.resourceQuotaSpec.hard`` is set and deleted
when unset (``:252-281``) — with ``google.com/tpu`` as a first-class
quota resource, enforced by the apiserver's quota admission on every
pod of a slice. Plugins follow the reference's interface (``:77-84``);
the GCP Workload Identity plugin replaces the AWS-first ordering. A
``profile-finalizer`` gates deletion on ``plugin.revoke`` so external
grants (Workload Identity bindings) are cleaned up (``:297-331``).
"""

from __future__ import annotations

from kubeflow_rm_tpu.controlplane import metrics
from kubeflow_rm_tpu.controlplane.api import profile as profile_api
from kubeflow_rm_tpu.controlplane.api.meta import (
    deep_get,
    make_object,
    set_controller_reference,
)
from kubeflow_rm_tpu.controlplane.apiserver import (
    AlreadyExists, APIServer, NotFound,
)
from kubeflow_rm_tpu.controlplane.runtime import (
    Controller,
    Request,
    copy_simple_spec,
    reconcile_child,
)
from kubeflow_rm_tpu.controlplane.webapps.core import (
    USER_HEADER,
    USER_PREFIX,
)


#: ref profile_controller.go:57
FINALIZER = "profile-finalizer"
#: ref profile_controller.go:51
OWNER_POLICY_NAME = "ns-owner-access-istio"
#: ref profile_controller.go:71,132
ISTIO_INJECTION_LABEL = "istio-injection"

# mesh principals admitted by the owner policy; the reference reads
# these from env with the same defaults (profile_controller.go:420-430)
NOTEBOOK_CONTROLLER_PRINCIPAL = (
    "cluster.local/ns/kubeflow/sa/notebook-controller-service-account")
INGRESS_GATEWAY_PRINCIPAL = (
    "cluster.local/ns/istio-system/sa/istio-ingressgateway-service-account")


class ProfilePlugin:
    """Plugin contract (ref ``profile_controller.go:77-84``)."""

    kind: str = ""

    def apply(self, api: APIServer, profile: dict, spec: dict) -> None:
        raise NotImplementedError

    def revoke(self, api: APIServer, profile: dict, spec: dict) -> None:
        pass


class GcpWorkloadIdentityPlugin(ProfilePlugin):
    """Binds the namespace's default-editor SA to a GCP service account
    via Workload Identity annotation — the TPU-native first-class plugin
    (ref ``plugin_workload_identity.go``; checkpoints and tensorboard
    logs live in GCS)."""

    kind = "WorkloadIdentity"

    ANNOTATION = "iam.gke.io/gcp-service-account"

    def apply(self, api: APIServer, profile: dict, spec: dict) -> None:
        ns = profile["metadata"]["name"]
        sa = api.try_get("ServiceAccount", profile_api.DEFAULT_EDITOR, ns)
        if sa is None:
            return
        gsa = spec.get("gcpServiceAccount")
        if not gsa:
            return
        ann = sa["metadata"].setdefault("annotations", {})
        if ann.get(self.ANNOTATION) != gsa:
            ann[self.ANNOTATION] = gsa
            api.update(sa)

    def revoke(self, api: APIServer, profile: dict, spec: dict) -> None:
        """Remove the Workload Identity grant — the external state the
        finalizer exists to clean up (ref ``plugin_workload_identity.go``
        revoke path / ``profile_controller.go:311-321``)."""
        ns = profile["metadata"]["name"]
        sa = api.try_get("ServiceAccount", profile_api.DEFAULT_EDITOR, ns)
        if sa is None:
            return
        ann = sa["metadata"].get("annotations") or {}
        if self.ANNOTATION in ann:
            del ann[self.ANNOTATION]
            api.update(sa)


PLUGINS: dict[str, ProfilePlugin] = {
    p.kind: p for p in (GcpWorkloadIdentityPlugin(),)
}


class ProfileController(Controller):
    kind = profile_api.KIND

    def reconcile(self, api: APIServer, req: Request):
        try:
            profile = api.get(profile_api.KIND, req.name)
        except NotFound:
            return None  # namespace + children go via GC (ownerReferences)
        name = req.name
        owner = deep_get(profile, "spec", "owner", "name", default="")

        # Deletion: revoke every plugin's external grants, then release
        # the finalizer so the apiserver finalizes the object
        # (ref profile_controller.go:297-331).
        if profile["metadata"].get("deletionTimestamp"):
            if FINALIZER in (profile["metadata"].get("finalizers") or []):
                for plugin_spec in deep_get(profile, "spec", "plugins",
                                            default=[]) or []:
                    plugin = PLUGINS.get(plugin_spec.get("kind", ""))
                    if plugin:
                        plugin.revoke(api, profile,
                                      plugin_spec.get("spec", {}))
                profile["metadata"]["finalizers"] = [
                    f for f in profile["metadata"]["finalizers"]
                    if f != FINALIZER]
                api.update(profile)
            return None

        if FINALIZER not in (profile["metadata"].get("finalizers") or []):
            profile["metadata"].setdefault("finalizers", []).append(FINALIZER)
            api.update(profile)

        # Every pod in the profile namespace gets an Istio sidecar by
        # default, and the labels are re-asserted on a pre-existing
        # namespace too (ref :126-172 and :181).
        ns_labels = {
            "app.kubernetes.io/part-of": "kubeflow-profile",
            "katib.kubeflow.org/metrics-collector-injection": "enabled",
            ISTIO_INJECTION_LABEL: "enabled",
        }
        ns = api.try_get("Namespace", name)
        if ns is None:
            ns = {
                "apiVersion": "v1",
                "kind": "Namespace",
                "metadata": {
                    "name": name,
                    "annotations": {profile_api.OWNER_ANNOTATION: owner},
                    "labels": dict(ns_labels),
                },
            }
            set_controller_reference(profile, ns)
            try:
                api.create(ns)
            except AlreadyExists:
                pass
            metrics.PROFILE_CREATE_TOTAL.inc()
        else:
            labels = ns["metadata"].setdefault("labels", {})
            if any(labels.get(k) != v for k, v in ns_labels.items()):
                labels.update(ns_labels)
                api.update(ns)

        for sa_name in (profile_api.DEFAULT_EDITOR,
                        profile_api.DEFAULT_VIEWER):
            sa = make_object("v1", "ServiceAccount", sa_name, name)
            reconcile_child(api, profile, sa, copy_simple_spec)

        admin_binding = make_object(
            "rbac.authorization.k8s.io/v1", "RoleBinding",
            "namespaceAdmin", name)
        admin_binding["roleRef"] = {
            "apiGroup": "rbac.authorization.k8s.io",
            "kind": "ClusterRole", "name": "kubeflow-admin",
        }
        admin_binding["subjects"] = [
            {"kind": "User", "name": owner,
             "apiGroup": "rbac.authorization.k8s.io"},
        ]
        reconcile_child(api, profile, admin_binding, copy_simple_spec)

        for sa_name, role in ((profile_api.DEFAULT_EDITOR, "kubeflow-edit"),
                              (profile_api.DEFAULT_VIEWER, "kubeflow-view")):
            rb = make_object("rbac.authorization.k8s.io/v1", "RoleBinding",
                             sa_name, name)
            rb["roleRef"] = {"apiGroup": "rbac.authorization.k8s.io",
                             "kind": "ClusterRole", "name": role}
            rb["subjects"] = [{"kind": "ServiceAccount", "name": sa_name,
                               "namespace": name}]
            reconcile_child(api, profile, rb, copy_simple_spec)

        # Owner AuthorizationPolicy: the profile owner reaches every
        # workload in their namespace through the mesh — without it the
        # owner's own traffic is unauthorized to their notebooks
        # (ref profile_controller.go:419-557). KFAM writes the matching
        # per-contributor policies (webapps/kfam.py).
        authz = make_object(
            "security.istio.io/v1beta1", "AuthorizationPolicy",
            OWNER_POLICY_NAME, name,
            annotations={"user": owner, "role": "admin"})
        authz["spec"] = {
            "action": "ALLOW",
            "rules": [
                {   # the owner, arriving through the ingress gateway
                    "when": [{
                        "key": f"request.headers[{USER_HEADER}]",
                        "values": [USER_PREFIX + owner],
                    }],
                    "from": [{"source": {
                        "principals": [INGRESS_GATEWAY_PRINCIPAL]}}],
                },
                {   # workloads in the namespace reach each other (the
                    # slice's rendezvous + worker-to-worker traffic)
                    "when": [{"key": "source.namespace",
                              "values": [name]}],
                },
                {   # probe paths stay open for platform health checks
                    "to": [{"operation": {"paths": [
                        "/healthz", "/metrics", "/wait-for-drain"]}}],
                },
                {   # the culler probes kernel activity on every server
                    "from": [{"source": {"principals": [
                        NOTEBOOK_CONTROLLER_PRINCIPAL]}}],
                    "to": [{"operation": {"methods": ["GET"],
                                          "paths": ["*/api/kernels"]}}],
                },
            ],
        }
        reconcile_child(api, profile, authz, copy_simple_spec)

        # ResourceQuota: present iff spec.resourceQuotaSpec.hard (ref :252-281)
        hard = deep_get(profile, "spec", "resourceQuotaSpec", "hard")
        existing = api.try_get("ResourceQuota", profile_api.QUOTA_NAME, name)
        if hard:
            quota = make_object("v1", "ResourceQuota",
                                profile_api.QUOTA_NAME, name,
                                spec={"hard": dict(hard)})
            reconcile_child(api, profile, quota, copy_simple_spec)
        elif existing is not None:
            api.delete("ResourceQuota", profile_api.QUOTA_NAME, name)

        for plugin_spec in deep_get(profile, "spec", "plugins",
                                    default=[]) or []:
            plugin = PLUGINS.get(plugin_spec.get("kind", ""))
            if plugin:
                plugin.apply(api, profile, plugin_spec.get("spec", {}))
        return None
