"""Tensorboard controller: Tensorboard CR → Deployment + Service.

Mirrors ``tensorboard-controller/controllers/tensorboard_controller.go``:
``spec.logspath`` is either ``pvc://name/subpath`` (mount the PVC,
``:178-232``) or ``gs://bucket/path`` (``:234-249``). The reference
mounts a ``user-gcp-sa`` secret for GCS; the TPU-native build relies on
GKE Workload Identity (the profile plugin annotates default-editor), so
the GCS branch sets the SA and no secret. RWO scheduling
(``RWO_PVC_SCHEDULING``, ``:207-232``): when the PVC is RWO and already
mounted by a running pod, pin the deployment to that pod's node so the
volume can attach.
"""

from __future__ import annotations

from kubeflow_rm_tpu.controlplane.api.meta import (
    deep_get,
    make_object,
    name_of,
)
from kubeflow_rm_tpu.controlplane.apiserver import APIServer, NotFound
from kubeflow_rm_tpu.controlplane.runtime import (
    Controller,
    Request,
    copy_deployment_fields,
    copy_service_fields,
    map_to_owner,
    reconcile_children,
    rwo_mounting_node,
)

API_VERSION = "tensorboard.kubeflow.org/v1alpha1"
KIND = "Tensorboard"

DEFAULT_IMAGE = "tensorflow/tensorflow:latest"  # env TENSORBOARD_IMAGE


def make_tensorboard(name: str, namespace: str, logspath: str) -> dict:
    return make_object(API_VERSION, KIND, name, namespace,
                       spec={"logspath": logspath})


def parse_logspath(path: str) -> tuple[str, str, str]:
    """→ (scheme, pvc_name_or_bucket, subpath)."""
    if path.startswith("pvc://"):
        rest = path[len("pvc://"):]
        pvc, _, sub = rest.partition("/")
        return ("pvc", pvc, sub)
    if path.startswith("gs://"):
        return ("gs", path, "")
    return ("raw", path, "")


class TensorboardController(Controller):
    kind = KIND

    def __init__(self, image: str = DEFAULT_IMAGE,
                 rwo_scheduling: bool = True):
        self.image = image
        self.rwo_scheduling = rwo_scheduling

    def watches(self):
        return (("Deployment", map_to_owner(KIND)),)

    def reconcile(self, api: APIServer, req: Request):
        try:
            tb = api.get(KIND, req.name, req.namespace)
        except NotFound:
            return None
        deploy = self._generate_deployment(api, tb)
        svc = make_object("v1", "Service", req.name, req.namespace, spec={
            "selector": {"app": req.name},
            "ports": [{"port": 80, "targetPort": 6006, "protocol": "TCP"}],
        })
        reconcile_children(api, tb, [(deploy, copy_deployment_fields),
                                     (svc, copy_service_fields)])

        live = api.try_get("Deployment", req.name, req.namespace)
        ready = deep_get(live, "status", "readyReplicas", default=0) if live \
            else 0
        status = {"readyReplicas": ready}
        if deep_get(tb, "status") != status:
            tb["status"] = status
            api.update_status(tb)
        return None

    def _generate_deployment(self, api: APIServer, tb: dict) -> dict:
        name, ns = name_of(tb), tb["metadata"]["namespace"]
        scheme, target, sub = parse_logspath(
            deep_get(tb, "spec", "logspath", default=""))
        container = {
            "name": "tensorboard",
            "image": self.image,
            "command": ["/usr/local/bin/tensorboard"],
            "args": ["--port", "6006", "--bind_all"],
            "ports": [{"containerPort": 6006}],
        }
        pod_spec: dict = {"containers": [container]}
        if scheme == "pvc":
            container["args"] += ["--logdir", f"/tensorboard_logs/{sub}"]
            container["volumeMounts"] = [
                {"name": "logs", "mountPath": "/tensorboard_logs"}]
            pod_spec["volumes"] = [
                {"name": "logs",
                 "persistentVolumeClaim": {"claimName": target}}]
            if self.rwo_scheduling:
                node = rwo_mounting_node(api, ns, target)
                if node:
                    pod_spec["nodeName"] = node
        elif scheme == "gs":
            container["args"] += ["--logdir", target]
            pod_spec["serviceAccountName"] = "default-editor"
        else:
            container["args"] += ["--logdir", target]

        return {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": name, "namespace": ns,
                         "labels": {"app": name}},
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": {"app": name}},
                "template": {
                    "metadata": {"labels": {"app": name}},
                    "spec": pod_spec,
                },
            },
        }
