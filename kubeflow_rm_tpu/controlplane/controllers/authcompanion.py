"""Auth companion controller — the odh-notebook-controller equivalent.

A second reconciler on the SAME Notebook CR (the reference runs the
kubeflow notebook-controller and the ODH companion side by side —
``odh-notebook-controller/controllers/notebook_controller.go:150-247``),
owning everything between the slice and the outside world:

- **OAuth sidecar machinery** (``notebook_oauth.go:49-266``): when the
  notebook opts in via the inject-oauth annotation, reconcile a
  ServiceAccount with an OAuth redirect reference, a ``{name}-tls``
  Service on the proxy port, a ``{name}-oauth-config`` Secret with a
  random cookie secret, and a TLS Route to the proxy. The sidecar
  container itself is injected by the webhook
  (``notebook_webhook.go:76-233`` — see ``webhook/notebook.py``).
- **Plain Route** (``notebook_route.go:34-146``): without OAuth, an
  edge Route straight to worker-0's UI Service.
- **NetworkPolicies** (``notebook_network.go:131-174``): ingress to
  the notebook port only from inside the namespace (+ gateway), and
  to the OAuth port from anywhere — a multi-host TPU addition closes
  the slice's rendezvous ports to everything except slice peers.
- **Pipeline RBAC** (``notebook_rbac.go:36-154``): RoleBinding letting
  the notebook's ServiceAccount drive the pipeline API, gated like
  ``SET_PIPELINE_RBAC``.
- **Trusted CA bundle** (``CreateNotebookCertConfigMap`` ``:254-357``):
  assemble a per-namespace ``workbench-trusted-ca-bundle`` ConfigMap
  from the cluster's ``odh-trusted-ca-bundle`` so every notebook
  trusts the org's CAs; the webhook mounts it into pods.
"""

from __future__ import annotations

import secrets

from kubeflow_rm_tpu.controlplane.api import notebook as nb_api
from kubeflow_rm_tpu.controlplane.api.meta import (
    annotations_of,
    make_object,
    set_controller_reference,
)
from kubeflow_rm_tpu.controlplane.apiserver import APIServer, NotFound
from kubeflow_rm_tpu.controlplane.runtime import (
    Controller, Request, reconcile_children,
)

OAUTH_INJECT_ANNOTATION = "notebooks.kubeflow.org/inject-oauth"
LOGOUT_URL_ANNOTATION = "notebooks.kubeflow.org/oauth-logout-url"

NOTEBOOK_PORT = 8888
OAUTH_PORT = 8443
OAUTH_PORT_NAME = "oauth-proxy"
OAUTH_SERVICE_PORT = 443

TRUSTED_CA_BUNDLE = "workbench-trusted-ca-bundle"
SOURCE_CA_BUNDLE = "odh-trusted-ca-bundle"
SOURCE_CA_NAMESPACE = "kubeflow"

PIPELINE_ROLE = "ds-pipeline-user-access"


def oauth_enabled(notebook: dict) -> bool:
    return annotations_of(notebook).get(OAUTH_INJECT_ANNOTATION) == "true"


class AuthCompanionController(Controller):
    kind = nb_api.KIND

    def __init__(self, *, set_pipeline_rbac: bool = True,
                 cluster_domain: str = "apps.example.com"):
        self.set_pipeline_rbac = set_pipeline_rbac
        self.cluster_domain = cluster_domain

    def reconcile(self, api: APIServer, req: Request):
        try:
            nb = api.get(self.kind, req.name, req.namespace)
        except NotFound:
            return None

        # the four groups touch disjoint objects (ordering matters only
        # WITHIN a group) — fan them out as callables
        groups = [
            lambda: self._reconcile_ca_bundle(api, nb),
            lambda: self._reconcile_network_policies(api, nb),
        ]
        if self.set_pipeline_rbac:
            groups.append(lambda: self._reconcile_pipeline_rbac(api, nb))
        if oauth_enabled(nb):
            groups.append(lambda: self._reconcile_oauth(api, nb))
        else:
            groups.append(lambda: self._reconcile_plain_route(api, nb))
        reconcile_children(api, nb, groups)
        return None

    # ---- OAuth machinery (notebook_oauth.go:49-266) ------------------
    def _reconcile_oauth(self, api: APIServer, nb: dict) -> None:
        name, ns = nb["metadata"]["name"], nb["metadata"]["namespace"]

        sa = make_object(
            "v1", "ServiceAccount", name, ns,
            annotations={
                "serviceaccounts.openshift.io/oauth-redirectreference."
                "first": '{"kind":"OAuthRedirectReference","apiVersion":'
                         '"v1","reference":{"kind":"Route","name":"%s"}}'
                         % name,
            })
        self._ensure(api, nb, sa)

        svc = make_object("v1", "Service", f"{name}-tls", ns,
                          annotations={
                              "service.beta.openshift.io/serving-cert-"
                              "secret-name": f"{name}-tls",
                          })
        svc["spec"] = {
            "ports": [{"name": OAUTH_PORT_NAME,
                       "port": OAUTH_SERVICE_PORT,
                       "targetPort": OAUTH_PORT_NAME,
                       "protocol": "TCP"}],
            "selector": {nb_api.NOTEBOOK_NAME_LABEL: name,
                         "statefulset.kubernetes.io/pod-name": f"{name}-0"},
        }
        self._ensure(api, nb, svc)

        if api.try_get("Secret", f"{name}-oauth-config", ns) is None:
            secret = make_object("v1", "Secret", f"{name}-oauth-config", ns)
            secret["type"] = "Opaque"
            secret["stringData"] = {
                "cookie_secret": secrets.token_urlsafe(32),
            }
            set_controller_reference(nb, secret)
            api.create(secret)

        route = make_object("route.openshift.io/v1", "Route", name, ns)
        route["spec"] = {
            "host": f"{name}-{ns}.{self.cluster_domain}",
            "to": {"kind": "Service", "name": f"{name}-tls",
                   "weight": 100},
            "port": {"targetPort": OAUTH_PORT_NAME},
            "tls": {"termination": "reencrypt",
                    "insecureEdgeTerminationPolicy": "Redirect"},
        }
        self._ensure(api, nb, route)

    def _reconcile_plain_route(self, api: APIServer, nb: dict) -> None:
        name, ns = nb["metadata"]["name"], nb["metadata"]["namespace"]
        route = make_object("route.openshift.io/v1", "Route", name, ns)
        route["spec"] = {
            "host": f"{name}-{ns}.{self.cluster_domain}",
            "to": {"kind": "Service", "name": name, "weight": 100},
            "port": {"targetPort": NOTEBOOK_PORT},
        }
        self._ensure(api, nb, route)

    # ---- NetworkPolicies (notebook_network.go:131-174 + TPU) ---------
    def _reconcile_network_policies(self, api: APIServer, nb: dict) -> None:
        name, ns = nb["metadata"]["name"], nb["metadata"]["namespace"]
        pod_sel = {"matchLabels": {nb_api.NOTEBOOK_NAME_LABEL: name}}

        ctrl_np = make_object("networking.k8s.io/v1", "NetworkPolicy",
                              f"{name}-ctrl-np", ns)
        ctrl_np["spec"] = {
            "podSelector": pod_sel,
            "policyTypes": ["Ingress"],
            "ingress": [{
                "ports": [{"protocol": "TCP", "port": NOTEBOOK_PORT}],
                "from": [{"namespaceSelector": {"matchLabels": {
                    "kubernetes.io/metadata.name": ns}}}],
            }],
        }
        self._ensure(api, nb, ctrl_np)

        if oauth_enabled(nb):
            oauth_np = make_object("networking.k8s.io/v1", "NetworkPolicy",
                                   f"{name}-oauth-np", ns)
            oauth_np["spec"] = {
                "podSelector": pod_sel,
                "policyTypes": ["Ingress"],
                "ingress": [{"ports": [{"protocol": "TCP",
                                        "port": OAUTH_PORT}]}],
            }
            self._ensure(api, nb, oauth_np)

        # TPU addition: slice-internal rendezvous ports (ICI bootstrap,
        # jax.distributed) reachable only from the slice's own pods
        topo = nb_api.tpu_spec(nb)
        if topo and topo.multihost:
            peer_np = make_object("networking.k8s.io/v1", "NetworkPolicy",
                                  f"{name}-slice-np", ns)
            peer_np["spec"] = {
                "podSelector": pod_sel,
                "policyTypes": ["Ingress"],
                "ingress": [{
                    "ports": [{"protocol": "TCP", "port": 8471},
                              {"protocol": "TCP", "port": 8476}],
                    "from": [{"podSelector": pod_sel}],
                }],
            }
            self._ensure(api, nb, peer_np)

    # ---- pipeline RBAC (notebook_rbac.go:36-154) ---------------------
    def _reconcile_pipeline_rbac(self, api: APIServer, nb: dict) -> None:
        name, ns = nb["metadata"]["name"], nb["metadata"]["namespace"]
        rb = make_object("rbac.authorization.k8s.io/v1", "RoleBinding",
                         f"elyra-pipelines-{name}", ns)
        rb["roleRef"] = {"apiGroup": "rbac.authorization.k8s.io",
                         "kind": "Role", "name": PIPELINE_ROLE}
        rb["subjects"] = [{"kind": "ServiceAccount", "name": name,
                           "namespace": ns}]
        self._ensure(api, nb, rb)

    # ---- trusted CA bundle (:254-357) --------------------------------
    def _reconcile_ca_bundle(self, api: APIServer, nb: dict) -> None:
        ns = nb["metadata"]["namespace"]
        source = api.try_get("ConfigMap", SOURCE_CA_BUNDLE,
                             SOURCE_CA_NAMESPACE)
        if source is None:
            return
        bundle = "".join(
            v for k, v in sorted((source.get("data") or {}).items())
            if k.endswith(".crt"))
        cm = make_object("v1", "ConfigMap", TRUSTED_CA_BUNDLE, ns,
                         labels={"config.openshift.io/inject-trusted-"
                                 "cabundle": "true"})
        cm["data"] = {"ca-bundle.crt": bundle}
        existing = api.try_get("ConfigMap", TRUSTED_CA_BUNDLE, ns)
        if existing is None:
            api.create(cm)
        elif existing.get("data") != cm["data"]:
            existing["data"] = cm["data"]
            api.update(existing)

    # ---- helper ------------------------------------------------------
    @staticmethod
    def _ensure(api: APIServer, owner: dict, obj: dict) -> None:
        """Create-or-repair a companion object.

        Diffs every field the companion controller owns — not just
        ``spec``: the ServiceAccount's oauth-redirectreference and the
        Service's serving-cert annotations live in metadata, and the
        RoleBinding's reconciled state is ``roleRef``/``subjects``;
        objects mutated there (or created without them) must be
        repaired too (ADVICE r2).
        """
        existing = api.try_get(obj["kind"], obj["metadata"]["name"],
                               obj["metadata"].get("namespace"))
        set_controller_reference(owner, obj)
        if existing is None:
            api.create(obj)
            return
        changed = False
        # adopt pre-existing objects: without the ownerReference, GC
        # would skip them on Notebook deletion and leak the companion
        # (a stale RoleBinding = a lingering access grant)
        want_refs = obj["metadata"].get("ownerReferences") or []
        if want_refs and not (
                existing["metadata"].get("ownerReferences") or []):
            existing["metadata"]["ownerReferences"] = want_refs
            changed = True
        want_ann = obj["metadata"].get("annotations") or {}
        have_ann = existing["metadata"].get("annotations") or {}
        # only repair annotations this controller set; foreign
        # annotations (kubectl applied-config, etc.) are left alone
        for k, v in want_ann.items():
            if have_ann.get(k) != v:
                existing["metadata"].setdefault(
                    "annotations", {})[k] = v
                changed = True
        for top in ("spec", "roleRef", "subjects", "rules", "data",
                    "stringData", "type"):
            if top in obj and existing.get(top) != obj[top]:
                existing[top] = obj[top]
                changed = True
        if changed:
            api.update(existing)
