"""Notebook reconciler: Notebook CR → TPU-slice StatefulSet + Services.

TPU-first rework of the reference core reconciler
(``notebook-controller/controllers/notebook_controller.go``):

- ``generateStatefulSet`` (ref ``:408-484``): here replicas =
  hosts-per-slice (the reference hardcodes replicas ∈ {0,1} — ``:409-412``),
  ``podManagementPolicy: Parallel`` for multihost slices (rendezvous
  needs all workers up together, not ordered), ``google.com/tpu`` chip
  limits and ``gke-tpu-*`` nodeSelectors rendered from ``spec.tpu``.
- two Services instead of one (ref ``generateService`` ``:486-513``): a
  ClusterIP service pinned to worker-0 (the Jupyter UI lives there) and
  a headless service over all workers (stable per-ordinal DNS — the
  rendezvous substrate the webhook's TPU_WORKER_HOSTNAMES points at).
- stop-annotation → replicas=0 (``:410-412``), whole slice at once: a
  TPU slice is all-or-nothing.
- status mirroring from pod ordinal 0 (ref ``updateNotebookStatus``
  ``:274-349``) plus slice-aware readyReplicas.
- pod-event re-emission onto the Notebook (ref ``:94-123,662-736``) so
  users see FailedScheduling (no free slice) on the CR itself.
"""

from __future__ import annotations

import copy

from kubeflow_rm_tpu.controlplane.api import notebook as nb_api
from kubeflow_rm_tpu.controlplane.api import tpu as tpu_api
from kubeflow_rm_tpu.controlplane.api.meta import (
    fast_deepcopy,
    annotations_of,
    deep_get,
    name_of,
)
from kubeflow_rm_tpu.controlplane.apiserver import APIServer, NotFound
from kubeflow_rm_tpu.controlplane.webhook.admission_pricer import (
    is_admission_rejected,
)
from kubeflow_rm_tpu.controlplane.runtime import (
    Controller,
    Request,
    copy_service_fields,
    copy_statefulset_fields,
    map_by_label,
    map_to_owner,
    phase_observer,
    reconcile_children,
)
from kubeflow_rm_tpu.controlplane import metrics
from kubeflow_rm_tpu.utils.profiling import PhaseRecorder

DEFAULT_CONTAINER_PORT = 8888
SERVICE_PORT = 80


def headless_name(notebook_name: str) -> str:
    return f"{notebook_name}-workers"


def standby_name(notebook_name: str) -> str:
    return f"{notebook_name}-standby"


# label carried by standby pods INSTEAD of NOTEBOOK_NAME_LABEL: the
# drain/slice-health/failover machinery counts gang pods by the
# notebook-name label, and a CPU standby must never be mistaken for a
# slice member
STANDBY_LABEL = "notebook-standby"


class NotebookController(Controller):
    kind = nb_api.KIND

    def __init__(self, use_istio: bool = True,
                 istio_gateway: str = "kubeflow/kubeflow-gateway"):
        # the reference gates VirtualService rendering on USE_ISTIO
        # (notebook_controller.go:519-533); here it is constructor
        # config like every other knob
        self.use_istio = use_istio
        self.istio_gateway = istio_gateway
        self.phases = PhaseRecorder()
        self._observe = phase_observer("notebook", self.phases)

    def watches(self):
        return (
            ("StatefulSet", map_to_owner(nb_api.KIND)),
            ("Pod", map_by_label(nb_api.NOTEBOOK_NAME_LABEL)),
            ("Event", _map_event_to_notebook),
        )

    def reconcile(self, api: APIServer, req: Request):
        try:
            notebook = api.get(nb_api.KIND, req.name, req.namespace)
        except NotFound:
            return None  # children follow via GC

        with self._observe("render"):
            topo = nb_api.tpu_spec(notebook)
            parked, deferring = self._parked_state(api, notebook)
            # predictive admission: a priced-rejected declaration never
            # renders pods — the OOM is refused BEFORE placement; the
            # webhook's status.admission carries the explanation and
            # the advisor rung that would lift the gate
            rejected = is_admission_rejected(notebook)
            sts = self._generate_statefulset(
                notebook, topo, parked=parked or deferring or rejected)
            children = [(sts, copy_statefulset_fields)]
            replicas = nb_api.replicas_of(notebook)
            if replicas > 1:
                children.append((
                    self._generate_standby_statefulset(notebook, replicas),
                    copy_statefulset_fields))
            children += [(svc, copy_service_fields)
                         for svc in self._generate_services(notebook, topo)]
            if self.use_istio:
                children.append((self._generate_virtualservice(notebook),
                                 _copy_virtualservice_fields))

        creating = api.try_get("StatefulSet", req.name, req.namespace) is None
        try:
            with self._observe("child_writes"):
                reconcile_children(api, notebook, children)
        except Exception:
            if creating:
                # the STS write itself may have landed before a sibling
                # failed — only count a failed *create* if it didn't
                if api.try_get("StatefulSet", req.name,
                               req.namespace) is None:
                    metrics.NOTEBOOK_CREATE_FAILED_TOTAL.inc()
                else:
                    metrics.NOTEBOOK_CREATE_TOTAL.inc()
            raise
        if creating:
            metrics.NOTEBOOK_CREATE_TOTAL.inc()
        if nb_api.replicas_of(notebook) <= 1:
            # replicas collapsed back to 1: retire the standby fleet
            standby = api.try_get("StatefulSet",
                                  standby_name(req.name), req.namespace)
            if standby is not None:
                api.delete("StatefulSet", standby_name(req.name),
                           req.namespace)

        with self._observe("status"):
            self._mirror_status(api, notebook, topo,
                                parked=parked, deferring=deferring)
        with self._observe("events"):
            self._reemit_pod_events(api, notebook)
        return None

    # -- rendering -----------------------------------------------------
    def _parked_state(self, api: APIServer,
                      notebook: dict) -> tuple[bool, bool]:
        """(parked, deferring): parked = user-stopped OR suspended
        (chips released to the pool) — renders to zero replicas; the
        difference is who brings them back (a user vs. any incoming
        request). deferring = the park was just lifted but the OLD
        epoch's pods are still draining: the slice stays at zero until
        they are gone, so a restart can never interleave fresh ordinals
        with half-drained ones (the slice-health controller would read
        that mix as a rump slice and churn-restart it)."""
        ann = annotations_of(notebook)
        parked = (nb_api.STOP_ANNOTATION in ann
                  or nb_api.SUSPEND_ANNOTATION in ann)
        deferring = False
        if not parked and deep_get(notebook, "status", "parked",
                                   default=False):
            name = name_of(notebook)
            ns = notebook["metadata"]["namespace"]
            owned = [
                p for p in getattr(api, "scan", api.list)("Pod", ns)
                if (p["metadata"].get("labels") or {}).get(
                    nb_api.NOTEBOOK_NAME_LABEL) == name
            ]
            deferring = bool(owned)
        return parked, deferring

    def _generate_statefulset(self, notebook: dict,
                              topo: tpu_api.SliceTopology | None, *,
                              parked: bool) -> dict:
        name = name_of(notebook)
        ns = notebook["metadata"]["namespace"]
        # multislice: one StatefulSet spans every slice (slice_id =
        # ordinal // hosts-per-slice); the webhook derives per-slice
        # rendezvous + MEGASCALE_* DCN env from the labels below
        hosts = nb_api.total_hosts(notebook)
        ann = annotations_of(notebook)
        replicas = 0 if parked else hosts

        pod_spec = fast_deepcopy(
            deep_get(notebook, "spec", "template", "spec", default={}))
        containers = pod_spec.get("containers") or []
        if containers:
            c0 = containers[0]
            env = c0.setdefault("env", [])
            _upsert_env(env, "NB_PREFIX", f"/notebook/{ns}/{name}")
        # CR labels flow onto the pods (ref notebook_controller.go:441-443)
        # — the hook PodDefault selectors match on (JWA "configurations"
        # writes label keys to the Notebook metadata); ours win on clash
        pod_labels = dict(notebook["metadata"].get("labels") or {})
        pod_labels.update({
            "statefulset": name,
            nb_api.NOTEBOOK_NAME_LABEL: name,
        })
        pod_annotations = {}
        if topo:
            pod_labels[nb_api.TPU_ACCELERATOR_LABEL] = topo.accelerator_type
            nslices = nb_api.num_slices(notebook)
            if nslices > 1:
                pod_labels[nb_api.TPU_NUM_SLICES_LABEL] = str(nslices)
            if containers:
                limits = containers[0].setdefault("resources", {}) \
                    .setdefault("limits", {})
                limits[tpu_api.GOOGLE_TPU_RESOURCE] = str(topo.chips_per_host)
            sel = pod_spec.setdefault("nodeSelector", {})
            sel[tpu_api.NODE_LABEL_ACCELERATOR] = topo.gke_accelerator
            if topo.multihost:
                # multi-host slices need the exact ICI topology
                sel[tpu_api.NODE_LABEL_TOPOLOGY] = topo.topology
            # single-host slices select on accelerator family only: a
            # v6e-1 kernel packs onto any free v6e host regardless of
            # the node pool's nominal topology, which is what lets the
            # scheduler bin-pack small kernels and the compaction
            # migrator defragment them

            # priced admission: fan the slice's predicted HBM/FLOPs
            # onto every host pod as its per-pod share — the scheduler
            # packs on these beside the chip count
            pred_hbm = ann.get(tpu_api.PREDICTED_HBM_ANNOTATION)
            if pred_hbm:
                try:
                    pod_annotations[tpu_api.PREDICTED_HBM_ANNOTATION] = \
                        f"{float(pred_hbm) / topo.hosts:.4f}"
                except (TypeError, ValueError):
                    pass
            pred_flops = ann.get(tpu_api.PREDICTED_FLOPS_ANNOTATION)
            if pred_flops:
                try:
                    pod_annotations[tpu_api.PREDICTED_FLOPS_ANNOTATION] = \
                        f"{float(pred_flops) / topo.hosts:.6g}"
                except (TypeError, ValueError):
                    pass

        sts_annotations: dict = {}
        if nb_api.MIGRATE_EXCLUDE_ANNOTATION in ann:
            # live migration: the re-bind must avoid the nodes the
            # slice just drained off; the STS controller reads this
            # through to gang_bind(exclude_nodes=...)
            sts_annotations[nb_api.MIGRATE_EXCLUDE_ANNOTATION] = \
                ann[nb_api.MIGRATE_EXCLUDE_ANNOTATION]

        return {
            "apiVersion": "apps/v1",
            "kind": "StatefulSet",
            "metadata": {
                "name": name,
                "namespace": ns,
                "labels": {nb_api.NOTEBOOK_NAME_LABEL: name},
                **({"annotations": sts_annotations}
                   if sts_annotations else {}),
            },
            "spec": {
                "replicas": replicas,
                "serviceName": headless_name(name),
                "podManagementPolicy": "Parallel" if hosts > 1
                                       else "OrderedReady",
                "selector": {"matchLabels": {"statefulset": name}},
                "template": {
                    "metadata": {"labels": pod_labels,
                                 "annotations": pod_annotations},
                    "spec": pod_spec,
                },
            },
        }

    def _generate_standby_statefulset(self, notebook: dict,
                                      replicas: int) -> dict:
        """R−1 parked CPU-only standby kernels (NotebookOS replication).

        Standbys hold NO chips: no TPU resource limits, no TPU node
        selector — they bind anywhere (or virtually) and stay warm
        purely through the checkpoint state store, which is what makes
        R−1 extra replicas nearly free. They deliberately do NOT carry
        ``NOTEBOOK_NAME_LABEL``: every gang-membership scan (drain
        completion, slice health, failover death detection) counts
        pods by that label, and a standby is not a slice member."""
        name = name_of(notebook)
        ns = notebook["metadata"]["namespace"]
        ann = annotations_of(notebook)
        sname = standby_name(name)
        pod_spec = fast_deepcopy(
            deep_get(notebook, "spec", "template", "spec", default={}))
        pod_spec.pop("nodeSelector", None)
        for c in pod_spec.get("containers") or []:
            limits = deep_get(c, "resources", "limits")
            if limits:
                limits.pop(tpu_api.GOOGLE_TPU_RESOURCE, None)
        containers = pod_spec.get("containers") or []
        if containers:
            env = containers[0].setdefault("env", [])
            _upsert_env(env, "NB_PREFIX", f"/notebook/{ns}/{name}")
            _upsert_env(env, "NB_STANDBY", "1")
        count = 0 if nb_api.STOP_ANNOTATION in ann else replicas - 1
        return {
            "apiVersion": "apps/v1",
            "kind": "StatefulSet",
            "metadata": {
                "name": sname,
                "namespace": ns,
                "labels": {STANDBY_LABEL: name},
            },
            "spec": {
                "replicas": count,
                "serviceName": headless_name(name),
                "podManagementPolicy": "Parallel",
                "selector": {"matchLabels": {"statefulset": sname}},
                "template": {
                    "metadata": {"labels": {
                        "statefulset": sname,
                        STANDBY_LABEL: name,
                    }},
                    "spec": pod_spec,
                },
            },
        }

    def _generate_services(self, notebook: dict,
                           topo: tpu_api.SliceTopology | None) -> list[dict]:
        name = name_of(notebook)
        ns = notebook["metadata"]["namespace"]
        # UI service: port 80 → 8888 on worker 0 only
        ui = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": name, "namespace": ns,
                         "labels": {nb_api.NOTEBOOK_NAME_LABEL: name}},
            "spec": {
                "type": "ClusterIP",
                "selector": {
                    "statefulset.kubernetes.io/pod-name": f"{name}-0"},
                "ports": [{
                    "name": "http-" + name,
                    "port": SERVICE_PORT,
                    "targetPort": DEFAULT_CONTAINER_PORT,
                    "protocol": "TCP",
                }],
            },
        }
        # headless worker service: stable DNS for every ordinal
        workers = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": headless_name(name), "namespace": ns,
                         "labels": {nb_api.NOTEBOOK_NAME_LABEL: name}},
            "spec": {
                "type": "ClusterIP",
                "clusterIP": "None",
                "selector": {"statefulset": name},
                "ports": [{"name": "jax-coordinator", "port": 8476,
                           "targetPort": 8476, "protocol": "TCP"}],
            },
        }
        return [ui, workers]

    def _generate_virtualservice(self, notebook: dict) -> dict:
        """Gateway route for the notebook UI (ref
        ``notebook_controller.go:519-619`` ``generateVirtualService``):
        prefix-match ``/notebook/<ns>/<name>/``, rewrite to the
        annotation's URI (default "/"), optional request headers from
        the headers annotation, destination = the worker-0 UI Service."""
        import json as _json

        name = name_of(notebook)
        ns = notebook["metadata"]["namespace"]
        ann = annotations_of(notebook)
        prefix = f"/notebook/{ns}/{name}/"
        rewrite = ann.get(nb_api.REWRITE_URI_ANNOTATION) or "/"
        http_route: dict = {
            "match": [{"uri": {"prefix": prefix}}],
            "rewrite": {"uri": rewrite},
            "route": [{"destination": {
                "host": f"{name}.{ns}.svc.cluster.local",
                "port": {"number": SERVICE_PORT},
            }}],
            "timeout": "300s",
        }
        raw_headers = ann.get(nb_api.HEADERS_ANNOTATION)
        if raw_headers:
            try:
                headers = _json.loads(raw_headers)
                if isinstance(headers, dict):
                    http_route["headers"] = {"request": {"set": headers}}
            except ValueError:
                pass  # malformed annotation: route without headers, as ref
        return {
            "apiVersion": "networking.istio.io/v1beta1",
            "kind": "VirtualService",
            "metadata": {"name": f"notebook-{ns}-{name}", "namespace": ns,
                         "labels": {nb_api.NOTEBOOK_NAME_LABEL: name}},
            "spec": {
                "hosts": ["*"],
                "gateways": [self.istio_gateway],
                "http": [http_route],
            },
        }

    # -- status --------------------------------------------------------
    def _mirror_status(self, api: APIServer, notebook: dict,
                       topo: tpu_api.SliceTopology | None, *,
                       parked: bool, deferring: bool) -> None:
        name, ns = name_of(notebook), notebook["metadata"]["namespace"]
        hosts = nb_api.total_hosts(notebook)
        sts = api.try_get("StatefulSet", name, ns)
        ready = deep_get(sts, "status", "readyReplicas", default=0) if sts \
            else 0
        ann = annotations_of(notebook)
        effective_parked = parked or deferring
        epoch = int(deep_get(notebook, "status", "restartEpoch",
                             default=0))
        prev_parked = bool(deep_get(notebook, "status", "parked",
                                    default=False))
        if prev_parked and not effective_parked:
            # the park fully lifted (old pods drained): this status
            # write starts a NEW epoch AND zeroes readyReplicas in the
            # same write — a watcher waiting on the restart must never
            # see the previous epoch's stale ready count
            epoch += 1
            ready = 0
        status: dict = {
            "readyReplicas": ready,
            "desiredReplicas": 0 if effective_parked else hosts,
            "parked": effective_parked,
            "restartEpoch": epoch,
        }
        if (nb_api.SUSPEND_ANNOTATION in ann
                and nb_api.SUSPEND_DRAINED_ANNOTATION in ann):
            status["phase"] = nb_api.SUSPENDED_PHASE
        replicas = nb_api.replicas_of(notebook)
        if replicas > 1:
            status["replicas"] = replicas
            active = ann.get(nb_api.ACTIVE_REPLICA_ANNOTATION)
            if active is not None:
                status["activeReplica"] = active
            raw_states = ann.get(nb_api.REPLICA_STATES_ANNOTATION)
            if raw_states:
                import json as _json
                try:
                    status["replicaStates"] = _json.loads(raw_states)
                except ValueError:
                    pass
        pod0 = api.try_get("Pod", f"{name}-0", ns)
        if pod0:
            cs = deep_get(pod0, "status", "containerStatuses", 0)
            if cs:
                status["containerState"] = cs.get("state", {})
            status["conditions"] = [
                {"type": c.get("type"), "status": c.get("status")}
                for c in deep_get(pod0, "status", "conditions",
                                  default=[]) or []
            ]
        # status.admission is webhook-owned: carry it through the
        # mirror or the replace-style status write would wipe it, the
        # webhook would re-stamp it, and the reconcile never quiesces
        adm = deep_get(notebook, "status", "admission")
        if adm is not None:
            status["admission"] = adm
        if deep_get(notebook, "status") != status:
            prev_ready = deep_get(notebook, "status", "readyReplicas",
                                  default=0)
            notebook["status"] = status
            api.update_status(notebook)
            if not parked and hosts > 0 and prev_ready < hosts <= ready:
                self._observe_provision_latency(api, notebook)
        metrics.NOTEBOOK_RUNNING.set(self._count_running(api))

    @staticmethod
    def _observe_provision_latency(api: APIServer, notebook: dict
                                   ) -> None:
        """First transition to fully-ready: record creationTimestamp ->
        now as the provision SLI (``provision_latency_seconds``). Uses
        the apiserver's clock so injected test clocks stay coherent."""
        import datetime
        try:
            created = deep_get(notebook, "metadata", "creationTimestamp")
            if not created:
                return
            clock = getattr(api, "clock", None)
            now = clock() if callable(clock) \
                else datetime.datetime.now(datetime.timezone.utc)
            then = datetime.datetime.fromisoformat(
                str(created).replace("Z", "+00:00"))
            if then.tzinfo is None and now.tzinfo is not None:
                then = then.replace(tzinfo=now.tzinfo)
            if now.tzinfo is None and then.tzinfo is not None:
                then = then.replace(tzinfo=None)
            elapsed = (now - then).total_seconds()
            if elapsed >= 0:
                metrics.PROVISION_LATENCY_SECONDS.observe(elapsed)
        except Exception:  # noqa: BLE001 - SLI capture is best-effort
            metrics.swallowed("notebook", "provision latency observe")

    def _count_running(self, api: APIServer) -> int:
        # scan(): read-only references — this gauge refresh runs at the
        # tail of EVERY notebook reconcile, and copying every Notebook
        # in the cluster for a counter was pure overhead
        n = 0
        for nb in getattr(api, "scan", api.list)(nb_api.KIND):
            if deep_get(nb, "status", "readyReplicas", default=0) >= 1:
                n += 1
        return n

    # -- event re-emission (ref :662-736) ------------------------------
    def _reemit_pod_events(self, api: APIServer, notebook: dict) -> None:
        """Surface Warning events of the notebook's Pods AND its
        StatefulSet onto the Notebook CR — the reference's watch
        predicate covers both (``isStsOrPodEvent``,
        ``notebook_controller.go:700-736``), and the STS is where
        slice-level failures land (SliceAdmissionFailed,
        FailedCreate)."""
        name, ns = name_of(notebook), notebook["metadata"]["namespace"]
        already = {
            (e.get("reason"), e.get("message"))
            for e in api.events_for(notebook)
        }

        def reemit(ev, source):
            if ev.get("type") != "Warning":
                return  # only surface problems, as the ref predicate does
            sig = (ev.get("reason"), f"[{source}] {ev.get('message')}")
            if sig in already:
                return
            already.add(sig)
            api.record_event(notebook, "Warning", sig[0], sig[1])

        pods = api.list("Pod", ns, {"matchLabels":
                                    {nb_api.NOTEBOOK_NAME_LABEL: name}})
        for pod in pods:
            for ev in api.events_for(pod):
                reemit(ev, f"pod {name_of(pod)}")
        sts = api.try_get("StatefulSet", name, ns)
        if sts is not None:
            for ev in api.events_for(sts):
                reemit(ev, f"sts {name}")


def _map_event_to_notebook(event_obj: dict):
    inv = event_obj.get("involvedObject") or {}
    if inv.get("kind") == "Pod" and inv.get("name"):
        # pod name {notebook}-{ordinal}
        base = inv["name"].rsplit("-", 1)[0]
        return [Request(inv.get("namespace"), base)]
    if inv.get("kind") == "StatefulSet" and inv.get("name"):
        # the notebook's STS shares its name
        return [Request(inv.get("namespace"), inv["name"])]
    return []


def _copy_virtualservice_fields(desired: dict, found: dict) -> bool:
    changed = False
    for field in ("labels", "annotations"):
        want = desired["metadata"].get(field) or {}
        if (found["metadata"].get(field) or {}) != want:
            found["metadata"][field] = dict(want)
            changed = True
    if found.get("spec") != desired.get("spec"):
        found["spec"] = fast_deepcopy(desired["spec"])
        changed = True
    return changed


def _upsert_env(env: list, name: str, value: str) -> None:
    for e in env:
        if e.get("name") == name:
            e["value"] = value
            return
    env.append({"name": name, "value": value})
