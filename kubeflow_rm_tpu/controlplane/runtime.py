"""Controller runtime: watches, work queues, level-triggered reconciling.

A thin, deterministic stand-in for controller-runtime: each Controller
reconciles one primary kind, may watch other kinds mapped back to
primary requests (the reference watches Pods and Events and maps them to
their parent Notebook — ``notebook_controller.go:739-787``), and the
Manager drains all queues to quiescence. ``requeue_after`` plus the
injected clock give the culler its periodic loop without wall-clock
sleeps.

Reconcilers must be idempotent and cheap — run_until_idle re-runs them
until nothing changes, which is exactly the level-triggered semantics
the reference relies on for failure recovery (SURVEY.md §5).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Iterable

from kubeflow_rm_tpu.controlplane.api.meta import (
    annotations_of,
    deep_get,
    labels_of,
    name_of,
    namespace_of,
)
from kubeflow_rm_tpu.controlplane.apiserver import APIServer, Conflict, NotFound
from kubeflow_rm_tpu.controlplane import tracing
from kubeflow_rm_tpu.analysis.lockgraph import make_lock


@dataclass(frozen=True, order=True)
class Request:
    namespace: str | None
    name: str


def _chaos_reconcile_sleep(controller: str) -> None:
    """Reconcile-span fault injection, delegated to the chaos engine:
    seeded ``FaultPlan`` stalls plus the legacy perf-ratchet env hook
    (``KFRM_CHAOS_RECONCILE_SLEEP_MS`` / ``_CONTROLLER``) both land
    inside the reconcile span so injected latency sits on the trace's
    critical path exactly where a real slow hop would. No-op unless a
    plan is installed or the env var is set."""
    from kubeflow_rm_tpu.controlplane import chaos
    chaos.maybe_stall(controller)


class Controller:
    """Subclass contract: set ``kind``, implement ``reconcile``."""

    kind: str = ""
    name: str = ""

    def reconcile(self, api: APIServer, req: Request) -> float | None:
        """Reconcile one object. Return seconds to requeue after, or
        None. Raise to retry with backoff."""
        raise NotImplementedError

    def watches(self) -> Iterable[tuple[str, Callable[[dict], list[Request]]]]:
        """Extra (kind, map_fn) watches; map_fn maps an event's object to
        primary requests."""
        return ()


def map_to_owner(owner_kind: str) -> Callable[[dict], list[Request]]:
    """Map a dependent object to its controller-owner of ``owner_kind``."""

    def fn(obj: dict) -> list[Request]:
        for ref in obj["metadata"].get("ownerReferences", []):
            if ref.get("kind") == owner_kind and ref.get("controller"):
                return [Request(namespace_of(obj), ref["name"])]
        return []

    return fn


def map_by_label(label: str) -> Callable[[dict], list[Request]]:
    def fn(obj: dict) -> list[Request]:
        v = labels_of(obj).get(label)
        return [Request(namespace_of(obj), v)] if v else []

    return fn


def map_all_in_namespace(kind: str):
    """Map an event to EVERY object of ``kind`` in the event object's
    namespace — for namespace-scoped admission inputs (ResourceQuota)
    whose change can unblock any primary in that namespace. Needs the
    manager's api handle to enumerate, so it's marked ``wants_api`` and
    ``Manager._on_event`` calls it as ``fn(api, obj)``."""

    def fn(api: APIServer, obj: dict) -> list[Request]:
        ns = namespace_of(obj)
        return [Request(namespace_of(o), name_of(o))
                for o in getattr(api, "scan", api.list)(kind, ns)]

    fn.wants_api = True
    return fn


class Manager:
    """Runs controllers against an APIServer until the system is idle."""

    MAX_RETRIES = 5
    # resourceVersion conflicts are EXPECTED under cached reads (the
    # informer lags writes by a watch event) and always resolve once
    # the cache catches up — give them a far larger budget than real
    # reconcile errors, as controller-runtime's rate limiter does
    MAX_CONFLICT_RETRIES = 40

    def __init__(self, api: APIServer):
        import threading
        self.api = api
        self.controllers: list[Controller] = []
        # one rate-limited work queue per controller (ha/workqueue.py):
        # dedup on enqueue, per-item backoff with jitter, max-retries
        # terminal path, per-controller concurrency caps
        self._queues: dict[str, "WorkQueue"] = {}
        # guards the errors list; each queue carries its own lock
        self._queue_lock = make_lock("runtime.queue")
        self.errors: list[tuple[str, Request, Exception]] = []
        # trace context riding the workqueue: items are deduped frozen
        # dataclasses, so causality travels in this side map keyed by
        # (controller, request) and is popped when the reconcile opens
        # its span. Bounded defensively — entries for requests that
        # never dequeue (terminal retry paths) must not accumulate.
        self._trace_ctx: dict[tuple[str, Request], str] = {}
        # run_forever blocks on this between drains; enqueue sets it so
        # watch events are served at HTTP latency, not poll latency
        self._wake = threading.Event()
        api.add_watcher(self._on_event, name="manager")

    def _queue_clock(self) -> float:
        # queues measure time on the apiserver's injected clock, so
        # requeue_after and backoff stay deterministic under test clocks
        return self.api.clock().timestamp()

    def add(self, controller: Controller) -> None:
        from kubeflow_rm_tpu.controlplane.ha.workqueue import WorkQueue
        if not controller.name:
            controller.name = type(controller).__name__
        self.controllers.append(controller)
        self._queues.setdefault(controller.name, WorkQueue(
            name=controller.name, clock=self._queue_clock,
            max_retries=self.MAX_RETRIES,
            max_conflict_retries=self.MAX_CONFLICT_RETRIES,
            max_concurrent=getattr(controller, "max_concurrent", None)))

    def enqueue(self, controller: Controller | str, req: Request, *,
                trace: str | None = None) -> None:
        name = controller if isinstance(controller, str) else controller.name
        if trace is not None:
            with self._queue_lock:
                if len(self._trace_ctx) > 4096:
                    self._trace_ctx.clear()  # defensive bound
                self._trace_ctx[(name, req)] = trace
        self._queues[name].add(req)
        self._wake.set()

    def enqueue_all(self) -> None:
        """Seed every controller's queue with all existing primaries
        (informer initial list; also the leader-promotion resync).
        ``scan`` — only names/namespaces are read, so the read-only
        reference contract holds and a cache-backed api serves the
        whole resync from memory with zero server round-trips."""
        for c in self.controllers:
            for obj in getattr(self.api, "scan", self.api.list)(c.kind):
                self.enqueue(c, Request(namespace_of(obj), name_of(obj)))

    def _on_event(self, event: str, obj: dict, old: dict | None) -> None:
        if event == "TOO_OLD":
            # this watcher's fanout queue overflowed and the dropped
            # window can't be replayed — resync every controller from
            # a fresh list (the informer's 410 relist runs first: it
            # registered its watcher before ours)
            self.enqueue_all()
            return
        # lift the stamped context off the event object so the async
        # hop (watch → queue → reconcile thread) stays one trace
        trace = None
        if tracing.enabled():
            ctx = tracing.context_of(obj)
            trace = ctx.to_traceparent() if ctx is not None else None
        for c in self.controllers:
            if obj["kind"] == c.kind:
                self.enqueue(c, Request(namespace_of(obj), name_of(obj)),
                             trace=trace)
            for kind, map_fn in c.watches():
                if obj["kind"] == kind:
                    reqs = (map_fn(self.api, obj)
                            if getattr(map_fn, "wants_api", False)
                            else map_fn(obj))
                    for req in reqs:
                        if req.name:
                            self.enqueue(c, req, trace=trace)

    def _reconcile_span(self, c: Controller, req: Request):
        """Span context for one reconcile, parented on the trace the
        workqueue item carried (consumed exactly once). No carried
        context → no span: a periodic resync reconcile is not part of
        any request's causal chain."""
        import contextlib
        if not tracing.enabled():
            return contextlib.nullcontext()
        with self._queue_lock:
            tp = self._trace_ctx.pop((c.name, req), None)
        if tp is None:
            return contextlib.nullcontext()
        return tracing.start_span(
            f"reconcile {c.name}", kind="consumer", parent=tp,
            attrs={"namespace": req.namespace or "", "name": req.name})

    def run_until_idle(self, max_iterations: int = 10_000) -> int:
        """Process queues until empty (timed requeues fire only when the
        injected clock passes them; backoff requeues are promoted
        immediately — deterministic drains keep the historical
        immediate-retry semantics). Returns reconcile count."""
        count = 0
        # async fanout barrier: events from the previous batch's writes
        # must land in the queues before we decide "idle" (the kube
        # adapter has no drain — its watch threads are real-time and
        # run_forever is the serving loop there)
        drain = getattr(self.api, "drain_watchers", None)
        for _ in range(max_iterations):
            if drain is not None:
                drain()
            batch = [(c, req) for c in self.controllers
                     for req in self._queues[c.name].pop_ready(
                         ignore_backoff=True)]
            if not batch:
                return count
            for c, req in batch:
                count += 1
                q = self._queues[c.name]
                try:
                    with self._reconcile_span(c, req):
                        _chaos_reconcile_sleep(c.name)
                        requeue_after = c.reconcile(self.api, req)
                    q.forget(req)
                    if requeue_after is not None:
                        q.add_after(req, requeue_after)
                except (Conflict,) as e:
                    self._retry(c, req, e)
                except NotFound:
                    pass  # object vanished; level-triggered — nothing to do
                except Exception as e:  # reconcile error: retry w/ backoff
                    self._retry(c, req, e)
                finally:
                    q.done(req)
        hot = {c.name: self._queues[c.name].snapshot()
               for c in self.controllers
               if self._queues[c.name].depth()}
        raise RuntimeError(
            f"manager did not quiesce in {max_iterations} iterations "
            f"(hot objects: {hot})"
        )

    def _poll_timeout(self, poll_interval_s: float) -> float:
        """Bound the inter-drain sleep by the earliest delayed item so
        backoff/timed requeues fire on time, not a poll late."""
        earliest = None
        for q in self._queues.values():
            due = q.next_due()
            if due is not None and (earliest is None or due < earliest):
                earliest = due
        if earliest is None:
            return poll_interval_s
        delta = earliest - self._queue_clock()
        return max(0.001, min(poll_interval_s, delta))

    def run_forever(self, stop=None, poll_interval_s: float = 1.0,
                    on_error: Callable | None = None,
                    workers: int = 1, elector=None,
                    resync_interval_s: float | None = None) -> None:
        """In-cluster serving loop: drain the queues whenever watch
        events (fanned into ``_on_event`` by the kube adapter's watch
        threads) or timed requeues produce work; sleep ``poll_interval_s``
        between drains. ``stop`` is a ``threading.Event``; reconcile
        errors that exhaust retries go to ``on_error`` (default: log).

        ``workers`` > 1 reconciles DIFFERENT objects concurrently on a
        thread pool while keeping the one-reconcile-per-key invariant
        (controller-runtime's MaxConcurrentReconciles). This is the
        20-way provisioning fix: a reconcile against a real apiserver
        is a chain of HTTP round-trips, and one serial drain thread
        turns N simultaneous spawns into an N× latency queue — the
        reference exposes --qps/--burst for exactly this path
        (notebook-controller/main.go:71-85).

        ``elector`` (ha.LeaderElector) gates reconciling on holding the
        lease: its loop runs on a daemon thread, watch events keep
        accumulating in the (deduped) queues while standing by, and on
        promotion the queues are resynced with ``enqueue_all`` — so a
        standby takes over within one lease duration with a warm cache
        and a complete work list.

        ``resync_interval_s`` (opt-in) periodically re-enqueues every
        primary — controller-runtime's SyncPeriod. Level-triggered
        reconcilers converge from any state, so a periodic resync heals
        whatever a lost watch event (network blip, chaos ``watch_drop``)
        left stale, bounding staleness by the interval."""
        import logging
        import threading
        import time as _time
        stop = stop or threading.Event()
        logger = logging.getLogger("kubeflow_rm_tpu.manager")

        last_resync = _time.monotonic()

        def maybe_resync():
            nonlocal last_resync
            if resync_interval_s is None:
                return
            if elector is not None and not elector.is_leader:
                return
            now = _time.monotonic()
            if now - last_resync >= resync_interval_s:
                last_resync = now
                self.enqueue_all()

        if elector is not None:
            def _on_promoted():
                self.enqueue_all()
                self._wake.set()
            elector.on_started_leading.append(_on_promoted)
            elector.on_stopped_leading.append(self._wake.set)
            threading.Thread(
                target=elector.run, args=(stop,), daemon=True,
                name=f"leader-elect-{elector.identity}").start()

        def report_errors():
            with self._queue_lock:
                errs, self.errors = self.errors, []
            for cname, req, err in errs:
                if on_error:
                    on_error(cname, req, err)
                else:
                    logger.error("%s %s gave up after retries: %s",
                                 cname, req, err)

        if workers <= 1:
            while not stop.is_set():
                self._wake.clear()
                if elector is not None and not elector.is_leader:
                    report_errors()
                    self._wake.wait(poll_interval_s)
                    continue
                maybe_resync()
                try:
                    self._drain_serial(stop, elector)
                except RuntimeError as e:
                    logger.error("manager drain failed: %s", e)
                report_errors()
                # woken immediately by enqueue; the timeout only bounds
                # how late a timed/backoff requeue (or stop) can fire
                self._wake.wait(self._poll_timeout(poll_interval_s))
            return

        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=workers,
                                thread_name_prefix="reconcile") as pool:
            while not stop.is_set():
                self._wake.clear()
                if elector is not None and not elector.is_leader:
                    report_errors()
                    self._wake.wait(poll_interval_s)
                    continue
                # brief dwell so an event burst (pod ADDED + MODIFIED +
                # STS MODIFIED from one spawn) coalesces into ONE
                # reconcile per key instead of one per event — the
                # work-queue rate limiter's job in controller-runtime
                if stop.wait(0.01):
                    break
                maybe_resync()
                for c in self.controllers:
                    for req in self._queues[c.name].pop_ready():
                        pool.submit(self._reconcile_one, c, req)
                report_errors()
                self._wake.wait(self._poll_timeout(poll_interval_s))

    def _drain_serial(self, stop, elector) -> int:
        """Serial run_forever drain: like run_until_idle but honoring
        backoff delays (real time passes between drains) and bailing
        out on stop/demotion."""
        count = 0
        for _ in range(10_000):
            if stop.is_set() or \
                    (elector is not None and not elector.is_leader):
                return count
            batch = [(c, req) for c in self.controllers
                     for req in self._queues[c.name].pop_ready()]
            if not batch:
                return count
            for c, req in batch:
                count += 1
                self._reconcile_one(c, req)
        raise RuntimeError("manager did not quiesce in 10000 iterations")

    def _reconcile_one(self, c: Controller, req: Request) -> None:
        """One reconcile with retry/requeue semantics (both the serial
        drain and the worker pool land here)."""
        import logging
        q = self._queues[c.name]
        try:
            try:
                with self._reconcile_span(c, req):
                    _chaos_reconcile_sleep(c.name)
                    requeue_after = c.reconcile(self.api, req)
                q.forget(req)
                if requeue_after is not None:
                    q.add_after(req, requeue_after)
            except Conflict as e:
                self._retry(c, req, e)
            except NotFound:
                pass  # object vanished; level-triggered
            except Exception as e:
                logging.getLogger("kubeflow_rm_tpu.manager").debug(
                    "%s %s: %s", c.name, req, e)
                self._retry(c, req, e)
        finally:
            # the key may have been re-enqueued mid-flight: the queue
            # returns it to pending; wake the dispatcher so it gets
            # picked up at HTTP latency
            q.done(req)
            self._wake.set()

    def _retry(self, c: Controller, req: Request, e: Exception) -> None:
        from kubeflow_rm_tpu.controlplane import metrics
        metrics.RECONCILE_ERRORS_TOTAL.labels(controller=c.name).inc()
        conflict = isinstance(e, Conflict)
        if self._queues[c.name].add_rate_limited(req, conflict=conflict):
            self._wake.set()
        else:
            with self._queue_lock:
                self.errors.append((c.name, req, e))


def rwo_mounting_node(api: APIServer, namespace: str,
                      pvc_name: str) -> str | None:
    """Node pinning for ReadWriteOnce PVCs: the node where a running pod
    already mounts the claim, or None (shared by the tensorboard and
    pvcviewer controllers — ref ``tensorboard_controller.go:207-232``)."""
    pvc = api.try_get("PersistentVolumeClaim", pvc_name, namespace)
    if pvc is None:
        return None
    modes = deep_get(pvc, "spec", "accessModes", default=[]) or []
    if "ReadWriteOnce" not in modes:
        return None
    for pod in api.list("Pod", namespace):
        node = deep_get(pod, "spec", "nodeName")
        if not node or deep_get(pod, "status", "phase") != "Running":
            continue
        for v in deep_get(pod, "spec", "volumes", default=[]) or []:
            if deep_get(v, "persistentVolumeClaim",
                        "claimName") == pvc_name:
                return node
    return None


# ---- reconcilehelper: create-or-update field-copy semantics ----------
# Mirrors components/common/reconcilehelper/util.go:18-219 — deliberately
# copy only the fields the controller owns, so we don't fight defaulters
# or status writers.

def reconcile_child(api: APIServer, owner: dict, desired: dict,
                    copy_fields: Callable[[dict, dict], bool]) -> dict:
    """Create ``desired`` (owned by ``owner``) if absent; else copy the
    controller-owned fields onto the found object and update when
    changed. Returns the live object."""
    from kubeflow_rm_tpu.controlplane.api.meta import set_controller_reference

    set_controller_reference(owner, desired)
    found = api.try_get(desired["kind"], name_of(desired),
                        namespace_of(desired))
    if found is None:
        return api.create(desired)
    if copy_fields(desired, found):
        return api.update(found)
    return found


# ---- parallel child fan-out ------------------------------------------
# A Notebook's StatefulSet, Services, and VirtualService have no mutual
# ordering — issuing them serially turns one reconcile into a string of
# HTTP round-trips (PROVISION_r08: cr_to_statefulset 204ms p50 under the
# 20-way storm). reconcile_children fans independent child writes onto a
# bounded shared pool; --serial-writes flips the module switch below to
# restore the serial arm for A/B runs.

_serial_writes = False
_child_pool = None
_child_pool_lock = None
_CHILD_POOL_WORKERS = 16
_CHILD_CONFLICT_RETRIES = 4


def set_serial_writes(enabled: bool) -> None:
    """Force the pre-batched write path: reconcile_children runs its
    children sequentially and controllers fall back to per-object
    creates (the ``--serial-writes`` conformance arm)."""
    global _serial_writes
    _serial_writes = bool(enabled)


def serial_writes() -> bool:
    return _serial_writes


def _shared_child_pool():
    global _child_pool, _child_pool_lock
    if _child_pool_lock is None:
        _child_pool_lock = make_lock("runtime.child_pool")
    with _child_pool_lock:
        if _child_pool is None:
            from concurrent.futures import ThreadPoolExecutor
            _child_pool = ThreadPoolExecutor(
                max_workers=_CHILD_POOL_WORKERS,
                thread_name_prefix="child-fanout")
        return _child_pool


def _run_child(api: APIServer, owner: dict, child) -> dict:
    """One child write with a per-child Conflict retry budget.
    reconcile_child re-reads via try_get on every attempt, so a retry
    sees the rv that beat us; a Conflict that survives the budget
    surfaces to the Manager's rate limiter like any serial write."""
    for attempt in range(_CHILD_CONFLICT_RETRIES + 1):
        try:
            if callable(child):
                return child()
            desired, copy_fields = child
            return reconcile_child(api, owner, desired, copy_fields)
        except Conflict:
            if attempt >= _CHILD_CONFLICT_RETRIES:
                raise


def reconcile_children(api: APIServer, owner: dict,
                       children: list) -> list:
    """Issue independent child writes concurrently on a bounded shared
    pool. Each child is either a ``(desired, copy_fields)`` pair routed
    through :func:`reconcile_child` or a zero-arg callable (for
    controllers with bespoke ensure logic). Conflicts retry per child
    before surfacing; every child runs to completion even when a
    sibling fails, then the first error (in input order) is raised —
    one bad child still fails the reconcile, but it can't strand its
    siblings half-written. Returns results in input order."""
    if not children:
        return []
    if _serial_writes or len(children) == 1:
        return [_run_child(api, owner, child) for child in children]
    pool = _shared_child_pool()
    futures = [pool.submit(_run_child, api, owner, child)
               for child in children[1:]]
    results: list = [None] * len(children)
    errors: list = [None] * len(children)
    # run the first child on the calling thread: the reconcile worker
    # contributes a hand instead of idling, and the fan-out makes
    # progress even with the shared pool saturated by sibling reconciles
    try:
        results[0] = _run_child(api, owner, children[0])
    except Exception as e:
        errors[0] = e
    for i, fut in enumerate(futures, start=1):
        try:
            results[i] = fut.result()
        except Exception as e:
            errors[i] = e
    for err in errors:
        if err is not None:
            raise err
    return results


def phase_observer(controller: str, recorder=None):
    """Per-reconcile phase timing: returns ``observe(phase)`` context
    managers feeding both the ``reconcile_phase_duration_seconds``
    histogram (label children bound once — the observer sits on the
    reconcile hot path) and an optional ``PhaseRecorder``."""
    import contextlib
    import time as _time

    from kubeflow_rm_tpu.controlplane import metrics
    bound: dict = {}

    @contextlib.contextmanager
    def observe(phase: str):
        hist = bound.get(phase)
        if hist is None:
            hist = bound.setdefault(
                phase, metrics.RECONCILE_PHASE_SECONDS.labels(
                    controller=controller, phase=phase))
        t0 = _time.perf_counter()
        try:
            # the same boundary also emits a trace span: reconcile
            # phases become hops of the request's causal chain (no-op
            # when tracing is off or no reconcile span is open)
            with tracing.start_span_if_active(f"{controller}.{phase}"):
                yield
        finally:
            dt = _time.perf_counter() - t0
            hist.observe(dt)
            if recorder is not None:
                recorder.record(phase, dt)

    return observe


def copy_statefulset_fields(desired: dict, found: dict) -> bool:
    """Replicas, labels, annotations, pod template (util.go:107-134)."""
    changed = False
    for field in ("labels", "annotations"):
        want = desired["metadata"].get(field) or {}
        if (found["metadata"].get(field) or {}) != want:
            found["metadata"][field] = dict(want)
            changed = True
    if deep_get(desired, "spec", "replicas") != deep_get(found, "spec",
                                                         "replicas"):
        found.setdefault("spec", {})["replicas"] = deep_get(
            desired, "spec", "replicas")
        changed = True
    if deep_get(desired, "spec", "template") != deep_get(found, "spec",
                                                         "template"):
        found["spec"]["template"] = deep_get(desired, "spec", "template")
        changed = True
    return changed


def copy_service_fields(desired: dict, found: dict) -> bool:
    """Selector + ports only; clusterIP etc. belong to the cluster
    (util.go:166-219)."""
    changed = False
    for field in ("labels", "annotations"):
        want = desired["metadata"].get(field) or {}
        if (found["metadata"].get(field) or {}) != want:
            found["metadata"][field] = dict(want)
            changed = True
    for key in ("selector", "ports", "clusterIP", "type"):
        want = deep_get(desired, "spec", key)
        if want is not None and deep_get(found, "spec", key) != want:
            found.setdefault("spec", {})[key] = want
            changed = True
    return changed


def copy_deployment_fields(desired: dict, found: dict) -> bool:
    return copy_statefulset_fields(desired, found)


def copy_simple_spec(desired: dict, found: dict) -> bool:
    """Whole-spec ownership (quota, RBAC, network policy objects)."""
    changed = False
    for field in ("labels", "annotations"):
        want = desired["metadata"].get(field) or {}
        if (found["metadata"].get(field) or {}) != want:
            found["metadata"][field] = dict(want)
            changed = True
    for top in ("spec", "rules", "roleRef", "subjects", "data"):
        if top in desired and found.get(top) != desired[top]:
            found[top] = desired[top]
            changed = True
    return changed


def stamp(obj: dict) -> str:
    """Debug stamp kind/ns/name."""
    return f"{obj['kind']}/{namespace_of(obj)}/{name_of(obj)}"


def is_stopped(obj: dict) -> bool:
    from kubeflow_rm_tpu.controlplane.api.notebook import STOP_ANNOTATION
    return STOP_ANNOTATION in annotations_of(obj)
