"""Lease-based leader election over the APIServer verb surface.

Reimplements client-go's ``leaderelection`` package (which the
reference turns on via ``--leader-elect``,
``notebook-controller/main.go:60-93``) against this repo's apiserver
contract, so the SAME elector runs over the in-memory ``APIServer``
(tests, e2e) and the kube REST adapter (in-cluster):

- the lock is a ``coordination.k8s.io/v1`` Lease object;
- the holder renews ``spec.renewTime`` every ``retry_period_s``;
- a candidate steals only once ``renewTime + leaseDurationSeconds`` has
  passed, bumping ``leaseTransitions``;
- every write is an rv-CAS (the update carries the observed
  resourceVersion; the apiserver 409s stale writers) — the fencing
  that makes split-brain impossible even when two candidates race the
  same expired lease.

The elector is deliberately crash-oriented: leadership is *not*
released on stop by default, so failover exercises the expiry path
(standby takes over within one lease duration), matching what a
SIGKILLed manager pod would look like.
"""

from __future__ import annotations

import datetime
import logging
import threading
from typing import Callable

from kubeflow_rm_tpu.analysis.lockgraph import make_lock
from kubeflow_rm_tpu.controlplane.apiserver import (
    AlreadyExists,
    Conflict,
    NotFound,
)

log = logging.getLogger("kubeflow_rm_tpu.leaderelection")

LEASE_API_VERSION = "coordination.k8s.io/v1"
DEFAULT_LEASE_NAME = "kubeflow-rm-tpu-controller-manager"


def make_lease(name: str, namespace: str, holder: str,
               duration_s: float, now: datetime.datetime) -> dict:
    iso = now.isoformat()
    return {
        "apiVersion": LEASE_API_VERSION,
        "kind": "Lease",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "holderIdentity": holder,
            "leaseDurationSeconds": int(duration_s),
            "acquireTime": iso,
            "renewTime": iso,
            "leaseTransitions": 0,
        },
    }


def _parse_time(value: str | None) -> datetime.datetime | None:
    if not value:
        return None
    try:
        return datetime.datetime.fromisoformat(value)
    except ValueError:
        return None


class LeaderElector:
    """One candidate's view of the election.

    ``run(stop)`` is the blocking loop; ``is_leader`` is what the
    Manager's serving loop gates on. Callbacks in
    ``on_started_leading`` / ``on_stopped_leading`` fire on
    transitions (the Manager resyncs its queues on promotion).
    """

    def __init__(self, api, identity: str, *,
                 lease_name: str = DEFAULT_LEASE_NAME,
                 namespace: str = "kubeflow",
                 lease_duration_s: float = 15.0,
                 renew_deadline_s: float = 10.0,
                 retry_period_s: float = 2.0,
                 clock: Callable[[], datetime.datetime] | None = None,
                 release_on_exit: bool = False):
        self.api = api
        self.identity = identity
        self.lease_name = lease_name
        self.namespace = namespace
        self.lease_duration_s = lease_duration_s
        self.renew_deadline_s = renew_deadline_s
        self.retry_period_s = retry_period_s
        self.release_on_exit = release_on_exit
        self._clock = clock or getattr(api, "clock", None) or (
            lambda: datetime.datetime.now(datetime.timezone.utc))
        self._lock = make_lock("leases.elector")
        self._leader = False
        self._last_renew: datetime.datetime | None = None
        self.on_started_leading: list[Callable[[], None]] = []
        self.on_stopped_leading: list[Callable[[], None]] = []

    @property
    def is_leader(self) -> bool:
        with self._lock:
            return self._leader

    # ---- protocol ----------------------------------------------------
    def _expired(self, spec: dict, now: datetime.datetime) -> bool:
        renew = _parse_time(spec.get("renewTime")) or \
            _parse_time(spec.get("acquireTime"))
        if renew is None:
            return True
        duration = float(spec.get("leaseDurationSeconds")
                         or self.lease_duration_s)
        return renew + datetime.timedelta(seconds=duration) <= now

    def try_acquire_or_renew(self) -> bool:
        """One election round. True iff this identity holds a fresh
        lease afterwards. Returns False on a definitive loss (another
        fresh holder, or losing a CAS race); raises only on transport
        errors, which ``run`` treats as transient."""
        now = self._clock()
        lease = self.api.try_get("Lease", self.lease_name,
                                 self.namespace)
        if lease is None:
            try:
                self.api.create(make_lease(
                    self.lease_name, self.namespace, self.identity,
                    self.lease_duration_s, now))
            except (AlreadyExists, Conflict):
                return False  # lost the creation race
            except NotFound:
                # the lease namespace doesn't exist yet (fresh cluster)
                self.api.ensure_namespace(self.namespace)
                return False
            return True
        spec = lease.setdefault("spec", {})
        holder = spec.get("holderIdentity")
        if holder == self.identity:
            spec["renewTime"] = now.isoformat()
        elif not holder or self._expired(spec, now):
            # empty holder = graceful release; expired = crashed holder
            spec["holderIdentity"] = self.identity
            spec["acquireTime"] = now.isoformat()
            spec["renewTime"] = now.isoformat()
            spec["leaseDurationSeconds"] = int(self.lease_duration_s)
            spec["leaseTransitions"] = \
                int(spec.get("leaseTransitions") or 0) + 1
        else:
            return False  # someone else holds a fresh lease
        try:
            # fencing: the update carries the resourceVersion observed
            # above; any concurrent writer bumped it, so this CAS loses
            # with a Conflict instead of clobbering the new holder
            self.api.update(lease)
        except (Conflict, NotFound):
            return False
        return True

    def release(self) -> None:
        """Clear holderIdentity (graceful shutdown): the next candidate
        acquires immediately instead of waiting out the lease."""
        try:
            lease = self.api.try_get("Lease", self.lease_name,
                                     self.namespace)
            if lease is None or \
                    lease.get("spec", {}).get("holderIdentity") != \
                    self.identity:
                return
            lease["spec"]["holderIdentity"] = ""
            self.api.update(lease)
        except Exception as e:
            log.debug("lease release failed (harmless): %s", e)

    # ---- loop --------------------------------------------------------
    def run(self, stop: threading.Event) -> None:
        """Blocking election loop: candidates retry every
        ``retry_period_s``; the holder renews on the same period and
        abdicates when the lease is definitively lost, or when renewal
        has not succeeded within ``renew_deadline_s`` (apiserver
        outage)."""
        while not stop.is_set():
            err = None
            try:
                ok = self.try_acquire_or_renew()
            except Exception as e:  # transport trouble: transient
                ok, err = False, e
            now = self._clock()
            if ok:
                self._set_leader(True, now)
            elif err is None:
                self._set_leader(False, now)
            else:
                log.warning("election round for %s failed: %s",
                            self.identity, err)
                with self._lock:
                    deadline_passed = (
                        self._leader and self._last_renew is not None
                        and (now - self._last_renew).total_seconds()
                        > self.renew_deadline_s)
                if deadline_passed:
                    self._set_leader(False, now)
            stop.wait(self.retry_period_s)
        if self.release_on_exit and self.is_leader:
            self.release()
        self._set_leader(False, self._clock())

    def _set_leader(self, value: bool,
                    now: datetime.datetime) -> None:
        with self._lock:
            was = self._leader
            self._leader = value
            if value:
                self._last_renew = now
        from kubeflow_rm_tpu.controlplane import metrics
        metrics.LEADER_IS_LEADER.labels(identity=self.identity).set(
            1.0 if value else 0.0)
        if value and not was:
            log.info("%s acquired leadership of %s/%s", self.identity,
                     self.namespace, self.lease_name)
            self._fire(self.on_started_leading)
        elif was and not value:
            log.info("%s lost leadership of %s/%s", self.identity,
                     self.namespace, self.lease_name)
            self._fire(self.on_stopped_leading)

    @staticmethod
    def _fire(callbacks: list[Callable[[], None]]) -> None:
        for cb in list(callbacks):
            try:
                cb()
            except Exception:
                log.exception("leadership callback failed")
