"""HA reconcile runtime: leader election + rate-limited work queues.

Two replicas of the controller manager must not double-reconcile. The
reference gets this from controller-runtime (lease-based leader
election, ``notebook-controller/main.go:60-93``) and from client-go's
rate-limited workqueue. This package provides both over the repo's own
APIServer verb surface:

- ``leases.py``: coordination.k8s.io/v1 Lease objects plus a
  ``LeaderElector`` implementing acquire/renew/steal with
  resourceVersion fencing. Only the elected leader's Manager
  reconciles; standbys keep their informers warm and take over within
  one lease duration of leader death.
- ``workqueue.py``: per-controller work queues with dedup on enqueue,
  per-item exponential backoff with jitter, a max-retries terminal
  path, and per-controller concurrency caps (MaxConcurrentReconciles).
"""

from kubeflow_rm_tpu.controlplane.ha.leases import (  # noqa: F401
    DEFAULT_LEASE_NAME,
    LeaderElector,
    make_lease,
)
from kubeflow_rm_tpu.controlplane.ha.workqueue import (  # noqa: F401
    ExponentialBackoff,
    WorkQueue,
)
