"""Rate-limited work queue: the Manager's dispatch substrate.

Mirrors client-go's workqueue semantics, which the reference's
controllers inherit through controller-runtime:

- **dedup on add**: an item queued twice before it is handed out is
  reconciled once (level-triggered — the queue stores *keys*, not
  events).
- **processing/dirty**: an item re-added while a worker holds it is
  not handed out again (one reconcile per key at a time); it is
  re-queued when the worker finishes, so no event is lost.
- **rate-limited requeue**: failed items come back with per-item
  exponential backoff plus jitter; conflicts (expected under cached
  reads) get their own, tighter backoff curve and a separate, larger
  budget — mirroring the Manager's historical dual retry counters.
- **terminal path**: an item that exhausts its budget is dropped and
  reported through ``on_terminal`` instead of spinning forever.
- **per-queue concurrency cap**: ``max_concurrent`` bounds how many
  items of one queue may be processing at once
  (MaxConcurrentReconciles).

Time is injected (``clock`` returning float seconds) so backoff is
deterministic under the apiserver's frozen test clocks; the Manager's
``run_until_idle`` drains with ``ignore_backoff=True`` so deterministic
tests keep their immediate-retry semantics while the serving loop
(``run_forever``) honors real backoff.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Hashable

from kubeflow_rm_tpu.controlplane import metrics
from kubeflow_rm_tpu.analysis.lockgraph import make_lock


class ExponentialBackoff:
    """Per-item exponential backoff with multiplicative jitter."""

    def __init__(self, base_delay_s: float = 0.005,
                 max_delay_s: float = 2.0, jitter: float = 0.25,
                 rng: random.Random | None = None):
        self.base_delay_s = base_delay_s
        self.max_delay_s = max_delay_s
        self.jitter = jitter
        self._rng = rng or random.Random()
        self._failures: dict[Hashable, int] = {}

    def failures(self, item: Hashable) -> int:
        return self._failures.get(item, 0)

    def next_delay(self, item: Hashable) -> float:
        """Record one more failure for ``item`` and return the delay
        before its next attempt."""
        n = self._failures.get(item, 0)
        self._failures[item] = n + 1
        delay = min(self.base_delay_s * (2 ** n), self.max_delay_s)
        if self.jitter:
            # jitter spreads a burst of same-cause failures (e.g. one
            # apiserver hiccup failing every in-flight reconcile) so
            # the retries don't land as a second synchronized burst
            delay *= 1.0 + self.jitter * self._rng.random()
        return delay

    def forget(self, item: Hashable) -> None:
        self._failures.pop(item, None)


class WorkQueue:
    """Deduplicating, delaying, rate-limited queue of hashable items."""

    def __init__(self, name: str = "", *,
                 clock: Callable[[], float] = time.monotonic,
                 backoff: ExponentialBackoff | None = None,
                 conflict_backoff: ExponentialBackoff | None = None,
                 max_retries: int = 5, max_conflict_retries: int = 40,
                 max_concurrent: int | None = None,
                 on_terminal: Callable[[Hashable], None] | None = None):
        self.name = name
        self._clock = clock
        self.backoff = backoff or ExponentialBackoff()
        # conflicts resolve as soon as the informer cache catches up
        # (milliseconds) — back off hard enough to stop the hot loop,
        # short enough not to add visible provision latency
        self.conflict_backoff = conflict_backoff or ExponentialBackoff(
            base_delay_s=0.002, max_delay_s=0.1)
        self.max_retries = max_retries
        self.max_conflict_retries = max_conflict_retries
        self.max_concurrent = max_concurrent
        self.on_terminal = on_terminal
        self._lock = make_lock("workqueue")
        self._pending: dict[Hashable, float] = {}  # item -> enqueue time
        self._processing: set[Hashable] = set()
        self._dirty: set[Hashable] = set()
        # (due_time, from_backoff, item); from_backoff entries may be
        # promoted early by a deterministic drain
        self._delayed: list[tuple[float, bool, Hashable]] = []
        # namespaces with a live workqueue_namespace_depth series —
        # a namespace that drains must be zeroed, not just dropped
        self._ns_exported: set[str] = set()

    # ---- adds --------------------------------------------------------
    def add(self, item: Hashable) -> None:
        with self._lock:
            self._add_locked(item)

    def _add_locked(self, item: Hashable) -> None:
        metrics.WORKQUEUE_ADDS_TOTAL.labels(name=self.name).inc()
        if item in self._processing:
            self._dirty.add(item)
            return
        if item in self._pending:
            return
        self._pending[item] = self._clock()
        self._set_depth()

    def add_after(self, item: Hashable, delay_s: float) -> None:
        """Schedule ``item`` for ``delay_s`` from now (requeue_after).
        These delays are part of controller semantics (the culler's
        period) and are never promoted early."""
        if delay_s <= 0:
            self.add(item)
            return
        with self._lock:
            self._delayed.append((self._clock() + delay_s, False, item))

    def add_rate_limited(self, item: Hashable, *,
                         conflict: bool = False) -> bool:
        """Requeue a failed item with backoff. Returns False when the
        retry budget is exhausted: the item is dropped, its counters
        reset, and ``on_terminal`` fires."""
        exhausted = False
        with self._lock:
            limiter = self.conflict_backoff if conflict else self.backoff
            cap = (self.max_conflict_retries if conflict
                   else self.max_retries)
            if limiter.failures(item) + 1 > cap:
                exhausted = True
                self.backoff.forget(item)
                self.conflict_backoff.forget(item)
                metrics.WORKQUEUE_RETRIES_EXHAUSTED_TOTAL.labels(
                    name=self.name).inc()
            else:
                delay = limiter.next_delay(item)
                metrics.WORKQUEUE_REQUEUES_TOTAL.labels(
                    name=self.name).inc()
                self._delayed.append((self._clock() + delay, True, item))
        if exhausted and self.on_terminal is not None:
            self.on_terminal(item)
        return not exhausted

    def forget(self, item: Hashable) -> None:
        """Reset the item's failure counters (call on success)."""
        with self._lock:
            self.backoff.forget(item)
            self.conflict_backoff.forget(item)

    # ---- hand-out ----------------------------------------------------
    def pop_ready(self, *, limit: int | None = None,
                  ignore_backoff: bool = False) -> list:
        """Promote due delayed items and hand out pending ones, marking
        them processing. ``ignore_backoff`` promotes backoff requeues
        regardless of their due time (deterministic drains)."""
        with self._lock:
            now = self._clock()
            if self._delayed:
                keep = []
                for due, from_backoff, item in self._delayed:
                    if due <= now or (ignore_backoff and from_backoff):
                        self._add_locked(item)
                    else:
                        keep.append((due, from_backoff, item))
                self._delayed = keep
            if self.max_concurrent is not None:
                slots = max(0, self.max_concurrent
                            - len(self._processing))
                limit = slots if limit is None else min(limit, slots)
            items = sorted(self._pending)
            if limit is not None:
                items = items[:limit]
            for item in items:
                queued_at = self._pending.pop(item)
                self._processing.add(item)
                metrics.WORKQUEUE_QUEUE_SECONDS.labels(
                    name=self.name).observe(max(0.0, now - queued_at))
            self._set_depth()
            return items

    def done(self, item: Hashable) -> bool:
        """Finish processing ``item``. Returns True when it was re-added
        mid-flight (dirty) and is pending again."""
        with self._lock:
            self._processing.discard(item)
            if item in self._dirty:
                self._dirty.discard(item)
                if item not in self._pending:
                    self._pending[item] = self._clock()
                    self._set_depth()
                return True
            return False

    # ---- introspection -----------------------------------------------
    def depth(self) -> int:
        with self._lock:
            return len(self._pending)

    def next_due(self) -> float | None:
        """Earliest due time among delayed items, or None."""
        with self._lock:
            if not self._delayed:
                return None
            return min(due for due, _, _ in self._delayed)

    def snapshot(self) -> list:
        with self._lock:
            return sorted(self._pending)

    def _set_depth(self) -> None:
        metrics.WORKQUEUE_DEPTH.labels(name=self.name).set(
            len(self._pending))
        # per-namespace breakdown: the shard autoscaler's carve-off
        # needs to see WHICH namespace a deep queue belongs to, not
        # just that the queue is deep
        by_ns: dict[str, int] = {}
        for item in self._pending:
            ns = getattr(item, "namespace", None)
            if ns:
                by_ns[ns] = by_ns.get(ns, 0) + 1
        for ns in self._ns_exported - set(by_ns):
            metrics.WORKQUEUE_NAMESPACE_DEPTH.labels(
                name=self.name, namespace=ns).set(0)
        for ns, n in by_ns.items():
            metrics.WORKQUEUE_NAMESPACE_DEPTH.labels(
                name=self.name, namespace=ns).set(n)
        self._ns_exported = set(by_ns)
