"""Process entrypoints — ``python -m kubeflow_rm_tpu.controlplane <cmd>``.

The reference ships one ``main.go`` per component (controller manager
``notebook-controller/main.go:58-148``, webhook server
``admission-webhook/main.go:755-773``, Flask ``entrypoint.py`` per web
app, Express for the dashboard). This module is all of them behind one
binary, the way kubebuilder projects expose subcommands:

    controller-manager   watch-driven reconcile loop (kube adapter)
    webhook-server       HTTPS AdmissionReview server
    jupyter-web-app      spawner backend          (WSGI, werkzeug)
    volumes-web-app      PVC + viewer backend
    tensorboards-web-app TB CR backend
    kfam                 access management REST
    dashboard            central dashboard API (+ SPA)
    crds                 print CRD YAML to stdout
    manifests            write the kustomize tree to a directory

Env (reference convention of env-var feature flags, SURVEY.md §5):
``KUBE_API_URL``/``KUBE_TOKEN``/``KUBE_CA_CERT`` override in-cluster
autodetection; ``ENABLE_CULLING``, ``CULL_IDLE_TIME``,
``IDLENESS_CHECK_PERIOD`` gate the culler; ``PORT`` overrides each
server's default port; ``WEBHOOK_TLS_CERT``/``WEBHOOK_TLS_KEY`` for the
admission server; ``DISABLE_AUTH=true`` for dev (reference ``DEV``).
HA/throughput knobs (reference --leader-elect/--qps/--burst,
notebook-controller/main.go:60-93): ``LEADER_ELECT=true`` gates
reconciling on a coordination.k8s.io Lease (``LEASE_NAMESPACE``,
``LEASE_DURATION``, ``LEASE_RENEW_DEADLINE``, ``LEASE_RETRY_PERIOD``);
``KUBE_CLIENT_QPS``/``KUBE_CLIENT_BURST`` throttle the kube client;
``RECONCILE_WORKERS`` sets reconcile parallelism; ``POD_NAME`` names
this replica's election identity.
"""

from __future__ import annotations

import logging
import os
import signal
import sys
import threading


def _env_flag(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    return default if v is None else v.lower() in ("1", "true", "yes")


def _identity() -> str:
    """This replica's election identity: pod name in-cluster, else
    hostname+pid (unique per process, stable for its lifetime)."""
    import socket
    return os.environ.get("POD_NAME") or \
        f"{socket.gethostname()}_{os.getpid()}"


def _kube_api(identity: str | None = None):
    from kubeflow_rm_tpu.controlplane.deploy.kubeclient import KubeAPIServer
    qps = os.environ.get("KUBE_CLIENT_QPS")
    burst = os.environ.get("KUBE_CLIENT_BURST")
    return KubeAPIServer(
        base_url=os.environ.get("KUBE_API_URL"),
        token=os.environ.get("KUBE_TOKEN"),
        ca_cert=os.environ.get("KUBE_CA_CERT", True),
        qps=float(qps) if qps else None,
        burst=int(burst) if burst else None,
        identity=identity,
    )


def _serve_wsgi(app, default_port: int) -> None:
    from werkzeug.serving import run_simple
    port = int(os.environ.get("PORT", default_port))
    run_simple("0.0.0.0", port, app, threaded=True)


def _webapp(module: str, default_port: int) -> None:
    import importlib
    mod = importlib.import_module(
        f"kubeflow_rm_tpu.controlplane.webapps.{module}")
    api = _kube_api()
    if _env_flag("WEBAPP_INFORMER_CACHE", True):
        # web-app list endpoints are read-dominated: run the same
        # informer watch loops the controller manager does so index
        # pages serve from memory instead of a live LIST per request
        from kubeflow_rm_tpu.controlplane import WATCHED_KINDS
        stop = threading.Event()
        for kind in WATCHED_KINDS:
            threading.Thread(
                target=api.watch_kind, args=(kind, None, stop),
                daemon=True, name=f"watch-{kind}").start()
        api.wait_for_sync(WATCHED_KINDS, timeout=30.0)
    app = mod.create_app(
        api, disable_auth=_env_flag("DISABLE_AUTH"),
        prefix=os.environ.get("APP_PREFIX", ""))
    _serve_wsgi(app, default_port)


def cmd_controller_manager() -> int:
    from kubeflow_rm_tpu.controlplane import (
        WATCHED_KINDS,
        make_cluster_manager,
    )
    identity = _identity()
    api = _kube_api(identity=identity)
    culler = {}
    if os.environ.get("CULL_IDLE_TIME"):  # minutes, reference name
        culler["cull_idle_minutes"] = float(os.environ["CULL_IDLE_TIME"])
    if os.environ.get("IDLENESS_CHECK_PERIOD"):
        culler["check_period_minutes"] = float(
            os.environ["IDLENESS_CHECK_PERIOD"])
    manager = make_cluster_manager(
        api, enable_culling=_env_flag("ENABLE_CULLING"),
        culler_config=culler or None)
    elector = None
    if _env_flag("LEADER_ELECT"):
        from kubeflow_rm_tpu.controlplane.ha.leases import LeaderElector
        elector = LeaderElector(
            api, identity,
            namespace=os.environ.get("LEASE_NAMESPACE", "kubeflow"),
            lease_duration_s=float(
                os.environ.get("LEASE_DURATION", "15")),
            renew_deadline_s=float(
                os.environ.get("LEASE_RENEW_DEADLINE", "10")),
            retry_period_s=float(
                os.environ.get("LEASE_RETRY_PERIOD", "2")),
            release_on_exit=True)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *a: stop.set())
    threads = [
        threading.Thread(target=api.watch_kind, args=(kind, None, stop),
                         daemon=True, name=f"watch-{kind}")
        for kind in WATCHED_KINDS
    ]
    for t in threads:
        t.start()
    # gate on the informers' initial lists so the seed resync (and
    # every reconcile it triggers) reads from memory instead of racing
    # the watch threads with live GETs. Best-effort: on timeout the
    # cache serves whatever synced and the rest falls through.
    if not api.wait_for_sync(WATCHED_KINDS, timeout=30.0):
        logging.getLogger("kubeflow_rm_tpu").warning(
            "informer cache not fully synced after 30s; unsynced kinds "
            "fall through to live reads")
    manager.enqueue_all()
    logging.getLogger("kubeflow_rm_tpu").info(
        "controller manager %s running (%d controllers, %d watches, "
        "leader_elect=%s)", identity, len(manager.controllers),
        len(threads), elector is not None)
    manager.run_forever(
        stop, workers=int(os.environ.get("RECONCILE_WORKERS", "1")),
        elector=elector)
    return 0


def cmd_webhook_server() -> int:
    from kubeflow_rm_tpu.controlplane.deploy.webhook_server import (
        WebhookServer,
        make_admission_handler,
    )
    api = _kube_api()
    server = WebhookServer(
        make_admission_handler(api),
        port=int(os.environ.get("PORT", 8443)),
        certfile=os.environ.get("WEBHOOK_TLS_CERT"),
        keyfile=os.environ.get("WEBHOOK_TLS_KEY"),
    )
    port = server.start()
    logging.getLogger("kubeflow_rm_tpu").info(
        "webhook server on :%d (%s)", port,
        "https" if server.certfile else "http")
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *a: stop.set())
    stop.wait()
    server.stop()
    return 0


def cmd_crds() -> int:
    from kubeflow_rm_tpu.controlplane.deploy.crds import (
        all_crds,
        render_yaml,
    )
    sys.stdout.write(render_yaml(all_crds()))
    return 0


def cmd_manifests(outdir: str | None = None) -> int:
    from kubeflow_rm_tpu.controlplane.deploy.manifests import write_tree
    write_tree(outdir or "manifests")
    return 0


def cmd_gateway() -> int:
    """All web apps + the SPA on one origin — the dev/e2e stand-in for
    the in-cluster gateway (VirtualService path routes). DEV_USER
    stamps the identity header the mesh auth proxy would."""
    from kubeflow_rm_tpu.controlplane.webapps.gateway import make_gateway
    app = make_gateway(
        _kube_api(),
        dev_user=os.environ.get("DEV_USER"),
        secure_cookies=_env_flag("SECURE_COOKIES", True),
    )
    _serve_wsgi(app, 8082)
    return 0


COMMANDS = {
    "controller-manager": cmd_controller_manager,
    "webhook-server": cmd_webhook_server,
    "jupyter-web-app": lambda: _webapp("jupyter", 5000),
    "volumes-web-app": lambda: _webapp("volumes", 5001),
    "tensorboards-web-app": lambda: _webapp("tensorboards", 5002),
    "kfam": lambda: _webapp("kfam", 8081),
    "dashboard": lambda: _webapp("dashboard", 8082),
    "gateway": cmd_gateway,
    "crds": cmd_crds,
}


def main(argv: list[str] | None = None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s | %(name)s | %(levelname)s | %(message)s")
    argv = sys.argv[1:] if argv is None else argv
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        print("commands:", ", ".join([*COMMANDS, "manifests"]))
        return 0 if argv else 2
    cmd, *rest = argv
    if cmd == "manifests":
        return cmd_manifests(rest[0] if rest else None)
    if cmd not in COMMANDS:
        print(f"unknown command {cmd!r}; known: "
              f"{', '.join([*COMMANDS, 'manifests'])}", file=sys.stderr)
        return 2
    return COMMANDS[cmd]() or 0


if __name__ == "__main__":
    sys.exit(main())
