"""controlplane — the TPU-native Kubeflow Notebooks platform.

The platform half of this repo (SURVEY.md §1 layers L2–L5): the
Notebook/Profile/PodDefault/Tensorboard/PVCViewer resource model, the
reconcilers that render TPU-slice StatefulSets, the mutating-webhook
merge engine with TPU rendezvous injection, per-namespace TPU-chip
quotas, idle culling, and the web-app backends. Runs against the
in-memory apiserver for tests and against a real cluster through the
same verb surface.

``make_control_plane()`` assembles the full stack the way the
reference's kustomize manifests assemble its deployments.
"""

from __future__ import annotations

from kubeflow_rm_tpu.controlplane.apiserver import APIServer


def make_control_plane(clock=None, *, auto_ready: bool = True,
                       enable_culling: bool = False,
                       culler_config=None, cache: bool = True,
                       global_lock: bool = False,
                       enable_suspend: bool = False,
                       suspend_config=None):
    """Build (api, manager) with every controller and webhook wired.

    ``clock`` is injectable for deterministic culling tests;
    ``auto_ready=False`` leaves scheduled pods un-Ready for status tests;
    ``cache=False`` runs the manager on the raw verb surface (the A/B
    baseline arm of ``spawn_conformance --no-cache``);
    ``global_lock=True`` restores the pre-r08 single-RLock apiserver
    with synchronous watch delivery (the ``--global-lock`` A/B arm);
    ``enable_suspend=True`` adds the suspend/resume lifecycle
    controller (``suspend_config`` → ``SuspendController`` kwargs, e.g.
    ``{"suspend_idle_minutes": 30}`` to park idle slices).
    """
    from kubeflow_rm_tpu.controlplane.api import notebook as nb_api
    from kubeflow_rm_tpu.controlplane.api import poddefault as pd_api
    from kubeflow_rm_tpu.controlplane.api import tpujob as tj_api
    from kubeflow_rm_tpu.controlplane.controllers.culling import (
        CullingController,
    )
    from kubeflow_rm_tpu.controlplane.controllers.notebook import (
        NotebookController,
    )
    from kubeflow_rm_tpu.controlplane.controllers.profile import (
        ProfileController,
    )
    from kubeflow_rm_tpu.controlplane.controllers.pvcviewer import (
        PVCViewerController,
    )
    from kubeflow_rm_tpu.controlplane.controllers.statefulset import (
        DeploymentController,
        StatefulSetController,
    )
    from kubeflow_rm_tpu.controlplane.controllers.tensorboard import (
        TensorboardController,
    )
    from kubeflow_rm_tpu.controlplane.runtime import Manager
    from kubeflow_rm_tpu.controlplane.webhook.notebook import (
        LockReleaseController,
        NotebookWebhook,
    )
    from kubeflow_rm_tpu.controlplane.webhook.poddefault import (
        PodDefaultWebhook,
    )
    from kubeflow_rm_tpu.controlplane.webhook.tpu_inject import (
        TpuInjectWebhook,
    )
    from kubeflow_rm_tpu.controlplane.webhook.admission_pricer import (
        AdmissionPricer,
    )

    api = APIServer(global_lock=global_lock,
                    **({"clock": clock} if clock else {}))
    api.register_validator(nb_api.KIND, nb_api.validate)
    api.register_validator(pd_api.KIND, pd_api.validate)
    api.register_validator(tj_api.KIND, tj_api.validate)

    # admission order: notebook webhook on Notebooks (the pricer runs
    # after it so a priced status survives the lock injection); for
    # pods, the PodDefault merge runs before TPU injection (injection
    # must see the final container set, sidecars included)
    NotebookWebhook(api).register()
    AdmissionPricer(api).register()
    PodDefaultWebhook(api).register()
    TpuInjectWebhook(api).register()

    from kubeflow_rm_tpu.controlplane.controllers.authcompanion import (
        AuthCompanionController,
    )
    from kubeflow_rm_tpu.controlplane.controllers.slicehealth import (
        SliceHealthController,
    )

    # the Manager (and through it every controller) reads through the
    # shared informer cache; the raw api is returned so tests and web
    # apps keep their direct handle on the backing store. The informer
    # registers its watcher BEFORE the Manager's, so the store is
    # already updated when a reconcile fires for an event.
    from kubeflow_rm_tpu.controlplane.cache import CachedAPI
    from kubeflow_rm_tpu.controlplane.controllers.tpujob import (
        TPUJobController,
    )
    manager = Manager(CachedAPI(api) if cache else api)
    manager.add(NotebookController())
    manager.add(TPUJobController())
    manager.add(LockReleaseController())
    manager.add(AuthCompanionController())
    manager.add(SliceHealthController())
    manager.add(StatefulSetController(auto_ready=auto_ready))
    manager.add(DeploymentController(auto_ready=auto_ready))
    manager.add(ProfileController())
    manager.add(TensorboardController())
    manager.add(PVCViewerController())
    if enable_culling:
        manager.add(CullingController(**(culler_config or {})))
    if enable_suspend:
        from kubeflow_rm_tpu.controlplane.suspend import (
            ReplicaFailoverController,
            SuspendController,
        )
        manager.add(SuspendController(**(suspend_config or {})))
        # replicated kernels ride the same suspend/resume primitive:
        # failover = demand-resume from the warm checkpoint, so the
        # controller ships (and shares a store) with the lifecycle
        manager.add(ReplicaFailoverController(
            store=(suspend_config or {}).get("store")))
    return api, manager


def make_cluster_manager(api, *, enable_culling: bool = True,
                         culler_config=None,
                         enable_suspend: bool = False,
                         suspend_config=None):
    """Controller wiring for a REAL cluster (``deploy.kubeclient``):
    same reconcilers as ``make_control_plane`` minus the pieces a real
    cluster provides itself — the StatefulSet/Deployment controllers
    (kube-controller-manager + kubelet) and the admission webhooks
    (served over HTTPS by ``deploy.webhook_server`` instead).

    Equivalent of the reference's manager processes
    (``notebook-controller/main.go:58-148`` + odh + profile + tb +
    pvcviewer managers, collapsed into one here).
    """
    from kubeflow_rm_tpu.controlplane.controllers.authcompanion import (
        AuthCompanionController,
    )
    from kubeflow_rm_tpu.controlplane.controllers.culling import (
        CullingController,
    )
    from kubeflow_rm_tpu.controlplane.controllers.notebook import (
        NotebookController,
    )
    from kubeflow_rm_tpu.controlplane.controllers.profile import (
        ProfileController,
    )
    from kubeflow_rm_tpu.controlplane.controllers.pvcviewer import (
        PVCViewerController,
    )
    from kubeflow_rm_tpu.controlplane.controllers.slicehealth import (
        SliceHealthController,
    )
    from kubeflow_rm_tpu.controlplane.controllers.tensorboard import (
        TensorboardController,
    )
    from kubeflow_rm_tpu.controlplane.runtime import Manager
    from kubeflow_rm_tpu.controlplane.webhook.notebook import (
        LockReleaseController,
    )

    from kubeflow_rm_tpu.controlplane.cache import CachedAPI
    from kubeflow_rm_tpu.controlplane.controllers.tpujob import (
        TPUJobController,
    )
    if not isinstance(api, CachedAPI):
        # against the kube adapter this adopts the adapter's informer-
        # fed ObjectStore (one cache, two consumers); reads stay
        # fall-through until the watch threads sync each kind
        api = CachedAPI(api)
    manager = Manager(api)
    manager.add(NotebookController())
    manager.add(TPUJobController())
    manager.add(LockReleaseController())
    manager.add(AuthCompanionController())
    manager.add(SliceHealthController())
    manager.add(ProfileController())
    manager.add(TensorboardController())
    manager.add(PVCViewerController())
    if enable_culling:
        manager.add(CullingController(**(culler_config or {})))
    if enable_suspend:
        from kubeflow_rm_tpu.controlplane.suspend import (
            ReplicaFailoverController,
            SuspendController,
        )
        manager.add(SuspendController(**(suspend_config or {})))
        manager.add(ReplicaFailoverController(
            store=(suspend_config or {}).get("store")))
    return manager


# kinds the cluster manager watches (one watch thread per kind)
WATCHED_KINDS = (
    "Notebook", "TPUJob", "Profile", "Tensorboard", "PVCViewer",
    "StatefulSet", "Deployment", "Service", "Pod", "Event",
    # owned satellite kinds: controller-runtime's Owns() starts an
    # informer per owned type, which is what lets the cached client
    # serve reconcile_child's try_get-before-create from memory —
    # without these, every satellite read is a live GET and the
    # 20-way spawn storm goes apiserver-bound
    "Secret", "ServiceAccount", "ConfigMap", "RoleBinding",
    "NetworkPolicy", "VirtualService", "Route", "ResourceQuota",
    "Namespace", "Node", "AuthorizationPolicy",
    "PersistentVolumeClaim", "PodDefault",
)
