"""Consistent-hash ring partitioning the control plane by namespace.

Deterministic across processes (md5, no seed): the router in the
client, every shard worker, and the conformance harness all compute
the same ``shard_for(namespace)`` with no coordination. Virtual nodes
smooth the partition (#vnodes ≫ #shards keeps the largest shard within
a few percent of fair share); a restarted shard rejoins under the same
name at the same position, so "retry-with-remap" on the client
resolves to the same shard once it is back.

Elastic membership (split/merge): ``with_member`` / ``without_member``
derive the NEXT ring from this one without mutating it — the handoff
coordinator computes the moved key-set against both rings, copies
state, and only then flips the router to the new ring, so routing is
never observed mid-rebuild. ``moved_keys`` is the range-ownership
delta that drives a handoff; consistent hashing bounds it to roughly
1/N of the keyspace per membership change.

Pins: an explicit ``key -> member`` override consulted before the
hash. Cross-shard notebook migration moves ONE namespace to a chosen
target (not where the hash puts it); the pin makes that routing
deterministic for every client that shares the pin map.

Weights: heterogeneous members carry per-member vnode counts
(``weights``, defaulting to ``vnodes``). A member with 2x the vnodes
owns ~2x the keyspace — how a big shard box takes a proportionally
bigger share. ``with_weight`` derives the re-weighted ring; because
every member's points are independent (``hash(f"{m}#{v}")``), raising
one member's weight only ADDS that member's points, so every moved
key moves TO it — and lowering it only moves keys FROM it. The
movement is minimal in the same sense as membership changes.

Partition key: a namespaced object's namespace; a cluster-scoped
object's NAME (Profile "alice" and Namespace "alice" hash identically,
keeping a profile, its namespace, and everything inside on one shard).
"""

from __future__ import annotations

import bisect
import hashlib

DEFAULT_VNODES = 64


def _hash(key: str) -> int:
    return int.from_bytes(
        hashlib.md5(key.encode()).digest()[:8], "big")


class HashRing:
    def __init__(self, members: list[str], *,
                 vnodes: int = DEFAULT_VNODES,
                 pins: dict[str, str] | None = None,
                 weights: dict[str, int] | None = None):
        if not members:
            raise ValueError("HashRing needs at least one member")
        self.members = sorted(members)
        self.vnodes = vnodes
        self.pins = dict(pins or {})
        # per-member vnode override; members absent here get `vnodes`
        self.weights = {m: int(n) for m, n in (weights or {}).items()
                        if m in self.members}
        for m, n in self.weights.items():
            if n < 1:
                raise ValueError(
                    f"weight for {m!r} must be >= 1, got {n}")
        for key, owner in self.pins.items():
            if owner not in self.members:
                raise ValueError(
                    f"pin {key!r} -> {owner!r}: not a ring member")
        self._points: list[int] = []
        self._owners: list[str] = []
        pairs = sorted(
            (_hash(f"{m}#{v}"), m)
            for m in self.members
            for v in range(self.weights.get(m, vnodes)))
        for point, owner in pairs:
            self._points.append(point)
            self._owners.append(owner)

    def shard_for(self, key: str | None) -> str:
        """The member owning ``key`` (a namespace, or a cluster-scoped
        object's name). ``None`` — e.g. a cluster-wide list — is the
        caller's cue to fan out, but routes deterministically here."""
        pinned = self.pins.get(key or "")
        if pinned is not None:
            return pinned
        i = bisect.bisect_right(self._points, _hash(key or "")) \
            % len(self._points)
        return self._owners[i]

    def hash_owner(self, key: str | None) -> str:
        """Where the hash alone puts ``key``, ignoring pins — a pin
        whose target matches this is redundant and can be dropped."""
        i = bisect.bisect_right(self._points, _hash(key or "")) \
            % len(self._points)
        return self._owners[i]

    def spread(self, keys) -> dict[str, list[str]]:
        """Group ``keys`` by owning member (routing bulk writes)."""
        out: dict[str, list[str]] = {m: [] for m in self.members}
        for k in keys:
            out[self.shard_for(k)].append(k)
        return out

    # ---- elastic membership ------------------------------------------
    def with_member(self, name: str) -> "HashRing":
        """The ring after a split admits ``name``. Pins survive (their
        targets are all still members)."""
        if name in self.members:
            raise ValueError(f"{name!r} already a ring member")
        return HashRing(self.members + [name], vnodes=self.vnodes,
                        pins=self.pins, weights=self.weights)

    def without_member(self, name: str,
                       drop_pins: bool = True) -> "HashRing":
        """The ring after a merge retires ``name``. Pins targeting the
        leaving member are dropped (their keys fall back to the hash
        and ride the merge handoff like any other key)."""
        if name not in self.members:
            raise ValueError(f"{name!r} not a ring member")
        rest = [m for m in self.members if m != name]
        if not rest:
            raise ValueError("cannot remove the last ring member")
        pins = {k: o for k, o in self.pins.items() if o != name}
        if not drop_pins and len(pins) != len(self.pins):
            raise ValueError(f"pins still target {name!r}")
        weights = {m: n for m, n in self.weights.items() if m != name}
        return HashRing(rest, vnodes=self.vnodes, pins=pins,
                        weights=weights)

    def with_weight(self, member: str, n_vnodes: int) -> "HashRing":
        """The ring with ``member`` carrying ``n_vnodes`` virtual
        nodes. Ownership shifts proportionally, and every moved key
        involves ``member`` (gains on raise, losses on lower) — other
        members never exchange keys with each other."""
        if member not in self.members:
            raise ValueError(f"{member!r} not a ring member")
        if n_vnodes < 1:
            raise ValueError(
                f"weight for {member!r} must be >= 1, got {n_vnodes}")
        weights = dict(self.weights)
        weights[member] = int(n_vnodes)
        return HashRing(self.members, vnodes=self.vnodes,
                        pins=self.pins, weights=weights)

    def weight_of(self, member: str) -> int:
        return self.weights.get(member, self.vnodes)

    def with_pin(self, key: str, member: str) -> "HashRing":
        """The ring with ``key`` explicitly owned by ``member``. A pin
        matching the hash owner is stored anyway — callers may drop it
        later via ``with_pin``'s inverse (``without_pin``)."""
        if member not in self.members:
            raise ValueError(f"{member!r} not a ring member")
        pins = dict(self.pins)
        pins[key] = member
        return HashRing(self.members, vnodes=self.vnodes, pins=pins,
                        weights=self.weights)

    def without_pin(self, key: str) -> "HashRing":
        pins = dict(self.pins)
        pins.pop(key, None)
        return HashRing(self.members, vnodes=self.vnodes, pins=pins,
                        weights=self.weights)

    def moved_keys(self, new: "HashRing", keys) -> dict[str, tuple]:
        """The ownership delta driving a handoff: key ->
        (old_owner, new_owner) for every key whose owner changes
        between ``self`` and ``new``."""
        out: dict[str, tuple] = {}
        for k in keys:
            a, b = self.shard_for(k), new.shard_for(k)
            if a != b:
                out[k] = (a, b)
        return out

    def __len__(self) -> int:
        return len(self.members)
