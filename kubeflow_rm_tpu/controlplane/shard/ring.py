"""Consistent-hash ring partitioning the control plane by namespace.

Deterministic across processes (md5, no seed): the router in the
client, every shard worker, and the conformance harness all compute
the same ``shard_for(namespace)`` with no coordination. Virtual nodes
smooth the partition (#vnodes ≫ #shards keeps the largest shard within
a few percent of fair share); membership is fixed for a deployment —
a restarted shard rejoins under the same name at the same position, so
"retry-with-remap" on the client resolves to the same shard once it is
back (remap matters when a deployment is later resized).

Partition key: a namespaced object's namespace; a cluster-scoped
object's NAME (Profile "alice" and Namespace "alice" hash identically,
keeping a profile, its namespace, and everything inside on one shard).
"""

from __future__ import annotations

import bisect
import hashlib

DEFAULT_VNODES = 64


def _hash(key: str) -> int:
    return int.from_bytes(
        hashlib.md5(key.encode()).digest()[:8], "big")


class HashRing:
    def __init__(self, members: list[str], *,
                 vnodes: int = DEFAULT_VNODES):
        if not members:
            raise ValueError("HashRing needs at least one member")
        self.members = sorted(members)
        self._points: list[int] = []
        self._owners: list[str] = []
        pairs = sorted(
            (_hash(f"{m}#{v}"), m)
            for m in self.members for v in range(vnodes))
        for point, owner in pairs:
            self._points.append(point)
            self._owners.append(owner)

    def shard_for(self, key: str | None) -> str:
        """The member owning ``key`` (a namespace, or a cluster-scoped
        object's name). ``None`` — e.g. a cluster-wide list — is the
        caller's cue to fan out, but routes deterministically here."""
        i = bisect.bisect_right(self._points, _hash(key or "")) \
            % len(self._points)
        return self._owners[i]

    def spread(self, keys) -> dict[str, list[str]]:
        """Group ``keys`` by owning member (routing bulk writes)."""
        out: dict[str, list[str]] = {m: [] for m in self.members}
        for k in keys:
            out[self.shard_for(k)].append(k)
        return out

    def __len__(self) -> int:
        return len(self.members)
