"""Horizontal sharding of the control plane.

The apiserver partitions by NAMESPACE under a consistent-hash ring
(``ring.py``): every object of a namespace — and the cluster-scoped
objects keyed by the same name, like a Profile and the Namespace it
owns — lives on exactly one shard, so single-shard semantics (rv
ordering, Conflict CAS, quota, admission) are preserved per object
with zero cross-shard coordination. ``worker.py`` is one shard's
process (apiserver + WAL + kubelet + REST + elected platform
manager); ``runner.py`` supervises N of them and respawns a killed
shard in place; the client-side router lives in
``deploy.kubeclient.ShardedKubeAPIServer``.
"""

from kubeflow_rm_tpu.controlplane.shard.ring import DEFAULT_VNODES, HashRing
from kubeflow_rm_tpu.controlplane.shard.runner import ShardRunner

__all__ = ["HashRing", "DEFAULT_VNODES", "ShardRunner"]
