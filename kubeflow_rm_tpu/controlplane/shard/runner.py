"""Shard supervisor: N worker processes + respawn-in-place.

``ShardRunner`` owns the deployment topology: it picks one fixed port
and one WAL directory per shard BEFORE anything starts (the ring and
every client derive from this map, and a respawned shard must rebind
the same port and replay the same WAL), spawns each worker via
``multiprocessing`` spawn (no forked locks/sockets from the parent),
health-waits on ``/healthz``, and supervises — a shard that dies
without being asked (or is SIGKILLed by the chaos test) is respawned
in place, where its boot path replays snapshot + WAL and rejoins the
ring at the same position.
"""

from __future__ import annotations

import logging
import multiprocessing
import os
import signal
import socket
import threading
import time
import urllib.request

from kubeflow_rm_tpu.controlplane.shard.worker import shard_worker_main
from kubeflow_rm_tpu.analysis.lockgraph import make_lock

log = logging.getLogger("kubeflow_rm_tpu.shard.runner")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ShardRunner:
    def __init__(self, n_shards: int, *, base_dir: str | None = None,
                 wal: bool = True, manager_workers: int = 8,
                 auto_ready: bool = True, hang_dump_s: float = 0.0,
                 supervise: bool = True, tracing: bool = False,
                 on_death=None):
        if n_shards < 1:
            raise ValueError("need at least one shard")
        self._ctx = multiprocessing.get_context("spawn")
        self._procs: dict[str, multiprocessing.process.BaseProcess] = {}
        self._cfgs: dict[str, dict] = {}
        # retired (merged-away) shards keep their cfg so the handoff
        # coordinator can still resolve ``wal_dir(name)`` post-merge
        self._retired: dict[str, dict] = {}
        # the intentional-shutdown handshake: names whose next exit is
        # a deliberate scale-down, NOT a death — the watchdog must not
        # count it, alert on it, or respawn it
        self._expected: set[str] = set()
        self._next_index = n_shards
        self._stopping = False
        self._lock = make_lock("shard.watchdog")
        self._supervise = supervise
        self._base_dir = base_dir
        self._wal = wal
        self._template = {
            "manager_workers": manager_workers,
            "auto_ready": auto_ready, "hang_dump_s": hang_dump_s,
            "tracing": tracing,
        }
        # flight-recorder hook: ``on_death(name, exitcode)`` fires from
        # the watchdog thread AFTER the respawn is issued, so the
        # callback (which may scrape /metrics, dump bundles, ...) never
        # delays recovery
        self._on_death = on_death
        for i in range(n_shards):
            self._make_cfg(f"shard-{i}")

    def _make_cfg(self, name: str) -> dict:
        wal_dir = None
        if self._wal:
            wal_dir = os.path.join(self._base_dir or ".", "wal", name)
            os.makedirs(wal_dir, exist_ok=True)
        cfg = {
            "name": name, "port": _free_port(), "wal_dir": wal_dir,
            # span collection in the worker: a respawned shard
            # re-reads this, so the tracing arm survives chaos kills
            **self._template,
        }
        self._cfgs[name] = cfg
        return cfg

    # ---- topology ----------------------------------------------------
    @property
    def names(self) -> list[str]:
        return list(self._cfgs)

    @property
    def urls(self) -> dict[str, str]:
        return {n: f"http://127.0.0.1:{c['port']}"
                for n, c in self._cfgs.items()}

    def wal_dir(self, name: str) -> str | None:
        cfg = self._cfgs.get(name) or self._retired[name]
        return cfg["wal_dir"]

    def liveness(self) -> dict[str, bool]:
        """Per-shard aliveness as the supervisor sees it — the flight
        recorder's ``shard_liveness`` section."""
        return {name: p.is_alive() for name, p in self._procs.items()}

    def set_on_death(self, fn) -> None:
        """Late-bind the watchdog death hook (the chaos harness builds
        its observer after the runner, which already owns the ports)."""
        self._on_death = fn

    # ---- lifecycle ---------------------------------------------------
    def start(self, timeout: float = 60.0) -> None:
        from kubeflow_rm_tpu.controlplane import metrics
        for name in self._cfgs:
            # materialise each shard's death counter at 0 now: a
            # counter born at its first increment has no 0 -> 1 delta,
            # so the shard-deaths burn rate could never see the death
            metrics.SHARD_DEATHS_TOTAL.labels(shard=name)
            self._spawn(name)
        self.wait_ready(timeout)
        if self._supervise:
            threading.Thread(target=self._watchdog, daemon=True,
                             name="shard-watchdog").start()

    def _spawn(self, name: str) -> None:
        p = self._ctx.Process(target=shard_worker_main,
                              args=(self._cfgs[name],),
                              name=name, daemon=True)
        p.start()
        self._procs[name] = p
        log.info("spawned %s pid=%d port=%d", name, p.pid,
                 self._cfgs[name]["port"])

    def wait_ready(self, timeout: float = 60.0,
                   names: list[str] | None = None) -> None:
        deadline = time.monotonic() + timeout
        for name in names or self.names:
            url = self.urls[name] + "/healthz"
            while True:
                try:
                    with urllib.request.urlopen(url, timeout=2) as r:
                        if r.status == 200:
                            break
                except OSError:
                    pass
                if time.monotonic() > deadline:
                    raise TimeoutError(f"{name} never became healthy "
                                       f"at {url}")
                time.sleep(0.05)

    def kill(self, name: str) -> int:
        """SIGKILL one shard (the chaos verb). The watchdog — or an
        explicit ``respawn`` — brings it back at the same port + WAL
        directory, which is the whole point: recovery is replay, not
        re-provisioning."""
        p = self._procs[name]
        pid = p.pid
        os.kill(pid, signal.SIGKILL)
        p.join(timeout=10)
        return pid

    def respawn(self, name: str, timeout: float = 60.0) -> None:
        with self._lock:
            p = self._procs.get(name)
            if p is not None and p.is_alive():
                return
            self._spawn(name)
        self.wait_ready(timeout, names=[name])

    # ---- elastic membership (split / merge) --------------------------
    def add_shard(self, name: str | None = None,
                  timeout: float = 60.0) -> str:
        """Spawn one NEW shard (the split recipient): fresh name, fresh
        port, fresh (empty) WAL directory. Health-waited; the caller
        copies state into it and flips the ring afterwards."""
        from kubeflow_rm_tpu.controlplane import metrics
        with self._lock:
            if name is None:
                name = f"shard-{self._next_index}"
                self._next_index += 1
            elif name in self._cfgs:
                raise ValueError(f"shard {name!r} already exists")
            if name in self._retired:
                # a re-admitted name must not replay its old store
                raise ValueError(f"shard {name!r} was retired; "
                                 "elastic names are never reused")
            cfg = self._make_cfg(name)
            metrics.SHARD_DEATHS_TOTAL.labels(shard=name)
            self._spawn(name)
        self.wait_ready(timeout, names=[name])
        log.info("elastic: added %s on port %d", name, cfg["port"])
        return name

    def remove_shard(self, name: str, timeout: float = 30.0) -> None:
        """Retire one shard DELIBERATELY (the merge donor, after its
        range has been handed off). The intentional-shutdown handshake:
        the name goes into ``_expected`` under the watchdog's own lock
        BEFORE the SIGTERM, so the watchdog never mistakes this exit
        for a death — no ``shard_deaths_total`` increment, no
        shard-death critical alert, no respawn. SIGTERM (not SIGKILL)
        lets the worker flush + close its WAL cleanly."""
        with self._lock:
            if name not in self._cfgs:
                raise KeyError(f"no shard {name!r}")
            self._expected.add(name)
            p = self._procs.pop(name, None)
            self._retired[name] = self._cfgs.pop(name)
        if p is not None and p.is_alive():
            p.terminate()
            p.join(timeout=timeout)
            if p.is_alive():
                p.kill()
                p.join(timeout=5)
        with self._lock:
            self._expected.discard(name)
        log.info("elastic: retired %s", name)

    def _watchdog(self) -> None:
        from kubeflow_rm_tpu.controlplane import chaos, metrics
        while not self._stopping:
            time.sleep(0.2)
            # seeded shard-SIGKILL: one chaos opportunity per watchdog
            # tick; the kill lands through the same ``kill`` verb the
            # explicit chaos test uses, and this very loop observes the
            # death and respawns in place
            alive = [n for n, p in self._procs.items()
                     if p.is_alive() and n not in self._expected]
            victim = chaos.shard_kill_victim(alive)
            if victim is not None and not self._stopping:
                log.warning("chaos: SIGKILLing %s", victim)
                try:
                    self.kill(victim)
                except (OSError, KeyError):
                    metrics.swallowed("shard.runner", "chaos kill")
            for name, p in list(self._procs.items()):
                if self._stopping or p.is_alive():
                    continue
                if name in self._expected:
                    # intentional-shutdown handshake: a deliberate
                    # scale-down in flight — not a death
                    continue
                exitcode = p.exitcode
                log.warning("%s exited (code %s); respawning in place",
                            name, exitcode)
                metrics.SHARD_DEATHS_TOTAL.labels(shard=name).inc()
                respawned = False
                with self._lock:
                    if not self._stopping and \
                            name in self._procs and \
                            not self._procs[name].is_alive():
                        self._spawn(name)
                        respawned = True
                if respawned and self._on_death is not None:
                    try:
                        self._on_death(name, exitcode)
                    except Exception:  # noqa: BLE001 - observer hook
                        # must never take the watchdog down with it
                        metrics.swallowed("shard.runner",
                                          "on_death hook")

    def stop(self) -> None:
        self._stopping = True
        for p in self._procs.values():
            if p.is_alive():
                p.terminate()
        for p in self._procs.values():
            p.join(timeout=10)
            if p.is_alive():
                p.kill()
                p.join(timeout=5)
