"""Elastic shard layer: live split/merge with WAL-replay handoff,
plus the SLO/queue-depth-driven shard autoscaler.

The ring has been fixed at boot since r11; this module makes it
elastic. A handoff moves a key-range between shard processes using the
SAME machinery crash recovery already trusts — snapshot + WAL
tail-replay — instead of inventing a second replication protocol:

    IDLE ──► SNAPSHOT   donor forces a compacting snapshot
                        (bounds the tail the copy must chase)
         ──► COPY       bulk-apply the moving range to the recipient
                        (read-only ``read_state`` on the donor's WAL
                        dir; the donor keeps serving)
         ──► TAIL       replay donor WAL records past the horizon,
                        pass by pass, until lag < threshold
         ──► FENCE      router holds writes whose key changes owner
                        (predicate fence: even namespaces CREATED now)
         ──► DRAIN      final tail passes until two consecutive reads
                        find nothing new (donor acks are WAL-durable
                        before the client sees them, so "nothing new
                        on disk" == "nothing in flight")
         ──► FLIP       ``router.set_topology`` swaps ring + clients +
                        watch loops in one assignment each; unfence —
                        every held write re-resolves to the NEW owner
         ──► CLEANUP    donor's stale copies deleted best-effort
                        (the router's ownership filter makes them
                        inert either way)

A **split** admits a fresh empty shard (every existing member donates
the slice of its range the new vnodes claim). A **merge** retires one
member (it donates everything it owns to the survivors) and then stops
it through the runner's intentional-shutdown handshake — deliberate
scale-down is not a death. A **pinned migration** moves one namespace
to a chosen shard (``HashRing`` pins), which is how r15's notebook
live-migration crosses shard boundaries.

Zero-loss argument: a client write is acked only after the donor's WAL
fsyncs it. Writes acked before the fence are on disk and carried by
TAIL/DRAIN; writes issued during the fence block client-side and land
on the recipient after FLIP; the donor cannot ack a fenced-range write
between DRAIN and FLIP because fenced clients never send one. The
``shard_split`` chaos arm SIGKILLs the donor between COPY and TAIL —
recovery is the watchdog's respawn plus more tail passes against the
same WAL, which is exactly the crash-recovery property r11 proved.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.error
import urllib.request

from kubeflow_rm_tpu.analysis.lockgraph import make_lock
from kubeflow_rm_tpu.controlplane import chaos, metrics
from kubeflow_rm_tpu.controlplane.apiserver import (
    CLUSTER_SCOPED_KINDS,
    AlreadyExists,
    APIError,
    Conflict,
    NotFound,
)
from kubeflow_rm_tpu.controlplane.deploy.kubeclient import (
    BROADCAST_KINDS,
    KubeAPIServer,
    _is_transient,
)
from kubeflow_rm_tpu.controlplane.persistence import (
    read_state,
    tail_records,
)
from kubeflow_rm_tpu.controlplane.persistence.snapshot import (
    load_latest_snapshot,
)
from kubeflow_rm_tpu.controlplane.shard.ring import HashRing

log = logging.getLogger("kubeflow_rm_tpu.shard.elastic")

#: kinds that never ride a handoff: per-process liveness state (each
#: worker's LeaderElector lease lives in its OWN store and dies with
#: the process) — moving one would hand a zombie lease to the recipient
LOCAL_KINDS = frozenset({"Lease"})

#: apply order for a bulk copy: containers before their contents, the
#: audit trail last (anything unlisted lands in the middle)
_KIND_ORDER = {"Namespace": 0, "Profile": 1, "ServiceAccount": 2,
               "RoleBinding": 3, "PodDefault": 4, "Notebook": 5,
               "TPUJob": 5, "Deployment": 6, "StatefulSet": 6,
               "Pod": 8, "Event": 9}


def partition_key(kind: str, name: str | None,
                  namespace: str | None) -> str:
    """The ring key of one object — mirrors the router's rule."""
    if kind in CLUSTER_SCOPED_KINDS:
        return name or ""
    return namespace or ""


class ElasticShardManager:
    """The split/merge/migrate coordinator. Runs in the harness (or
    deployment-controller) process next to the router; talks to donors
    via their WAL directories (read-only) and to recipients via
    per-shard kube clients. One handoff at a time."""

    def __init__(self, runner, router, *, observer=None,
                 lag_threshold: int = 4, max_tail_passes: int = 200,
                 drain_settle_s: float = 0.15,
                 identity: str = "elastic"):
        self.runner = runner
        self.router = router
        self.observer = observer
        self.lag_threshold = int(lag_threshold)
        self.max_tail_passes = int(max_tail_passes)
        self.drain_settle_s = float(drain_settle_s)
        self.identity = identity
        self._lock = make_lock("shard.elastic")
        self._clients: dict[str, KubeAPIServer] = {}
        #: timeline of completed operations (the conformance artifact's
        #: ``scale_events`` section)
        self.events: list[dict] = []
        self._t0 = time.monotonic()

    # ---- plumbing ----------------------------------------------------
    def _client(self, name: str) -> KubeAPIServer:
        cli = self._clients.get(name)
        if cli is None:
            cli = KubeAPIServer(self.runner.urls[name],
                                identity=self.identity,
                                cache_reads=False)
            self._clients[name] = cli
        return cli

    def _post(self, name: str, path: str, body: dict | None = None,
              timeout: float = 15.0) -> dict:
        def go():
            req = urllib.request.Request(
                self.runner.urls[name] + path,
                data=json.dumps(body or {}).encode(), method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return json.loads(r.read() or b"{}")
        return self._ride_out(go)

    def _ride_out(self, fn, window_s: float = 20.0):
        """Run ``fn`` retrying transient TRANSPORT failures for up to
        ``window_s``. A handoff peer may be mid-respawn after a chaos
        kill — connection-refused while it replays its WAL is part of
        the recovery story, not an error. API-level errors (conflict,
        not-found, validation) pass straight through untouched."""
        deadline = time.monotonic() + window_s
        while True:
            try:
                return fn()
            except APIError:
                raise
            except Exception as e:
                if isinstance(e, urllib.error.HTTPError) \
                        or not _is_transient(e) \
                        or time.monotonic() >= deadline:
                    raise  # server answered (or window exhausted)
                time.sleep(0.1)

    def _event(self, op: str, **detail) -> None:
        self.events.append({
            "t": round(time.monotonic() - self._t0, 3), "op": op,
            "members": list(self.router.ring.members), **detail})

    # ---- public verbs ------------------------------------------------
    def split(self, name: str | None = None, *,
              weight: int | None = None,
              dedicate: str | None = None) -> str:
        """Admit one new shard: spawn it empty, hand it the range the
        new ring assigns it, flip. Returns the new shard's name.

        ``weight`` caps the newcomer's vnode count (``with_weight``);
        ``dedicate`` pins one partition key to it (``with_pin``).
        Together they make a **carve-off**: weight=1 means the new
        shard claims almost none of the hash range, so the pinned
        namespace is effectively all it serves — a dedicated shard for
        a hot tenant instead of an even rebalance. One ring
        derivation, one handoff, one flip."""
        with self._lock:
            t0 = time.monotonic()
            new_name = self.runner.add_shard(name)
            new_ring = self.router.ring.with_member(new_name)
            if weight is not None:
                new_ring = new_ring.with_weight(new_name, weight)
            if dedicate is not None:
                new_ring = new_ring.with_pin(dedicate, new_name)
            stats = self._handoff(new_ring, op="split",
                                  fresh=new_name)
            metrics.SHARD_SPLITS_TOTAL.inc()
            metrics.SHARD_HANDOFF_SECONDS.labels(kind="split").observe(
                time.monotonic() - t0)
            if self.observer is not None:
                self.observer.tsdb.add_scrape(
                    new_name, self.runner.urls[new_name])
            if dedicate is not None:
                stats = dict(stats, dedicate=dedicate)
            self._event("split", shard=new_name, **stats)
            log.info("split: admitted %s (%s)", new_name, stats)
            return new_name

    def merge(self, victim: str | None = None) -> str:
        """Retire one shard: hand its whole range to the survivors,
        flip, then stop it via the intentional-shutdown handshake.
        Returns the retired shard's name."""
        with self._lock:
            members = self.router.ring.members
            if len(members) < 2:
                raise ValueError("cannot merge below one shard")
            if victim is None:
                victim = self._default_victim(members)
            t0 = time.monotonic()
            new_ring = self.router.ring.without_member(victim)
            stats = self._handoff(new_ring, op="merge",
                                  retiring=victim)
            # stop AFTER the flip: routing already ignores the victim,
            # and the handshake keeps the watchdog + shard-deaths SLO
            # quiet about this deliberate exit
            self.runner.remove_shard(victim)
            self._clients.pop(victim, None)
            if self.observer is not None:
                self.observer.tsdb.remove_scrape(victim)
            metrics.SHARD_MERGES_TOTAL.inc()
            metrics.SHARD_HANDOFF_SECONDS.labels(kind="merge").observe(
                time.monotonic() - t0)
            self._event("merge", shard=victim, **stats)
            log.info("merge: retired %s (%s)", victim, stats)
            return victim

    def migrate_namespace(self, key: str, target: str) -> bool:
        """Pin one partition key (a namespace, or a cluster-scoped
        name) to ``target`` and hand its objects over. Returns False
        when the key already lives there."""
        with self._lock:
            ring = self.router.ring
            if target not in ring.members:
                raise ValueError(f"{target!r} not a ring member")
            if ring.shard_for(key) == target:
                return False
            t0 = time.monotonic()
            if ring.hash_owner(key) == target:
                new_ring = ring.without_pin(key)  # hash already agrees
            else:
                new_ring = ring.with_pin(key, target)
            stats = self._handoff(new_ring, op="migrate")
            metrics.SHARD_HANDOFF_SECONDS.labels(
                kind="migrate").observe(time.monotonic() - t0)
            self._event("migrate", key=key, target=target, **stats)
            return True

    def migrate_notebook(self, namespace: str, name: str,
                         target: str) -> bool:
        """Cross-shard notebook live-migration, riding the handoff
        path: move the notebook's whole namespace (CR, StatefulSet,
        pods, checkpoint annotations) to ``target`` with a pinned
        handoff, then drive r15's ``initiate_migration`` THROUGH the
        router — which now routes the namespace to the target shard,
        whose suspend controller drains the stale placement (the old
        shard's node names mean nothing there) and re-gangs the slice
        on its own node pool with state restored."""
        moved = self.migrate_namespace(namespace, target)
        from kubeflow_rm_tpu.controlplane import suspend
        nb = self.router.try_get("Notebook", name, namespace)
        if nb is None:
            raise NotFound(f"Notebook {namespace}/{name} not found "
                           "after handoff")
        suspend.initiate_migration(self.router, nb,
                                   trigger="cross-shard")
        return moved

    # ---- the handoff core --------------------------------------------
    def _default_victim(self, members: list[str]) -> str:
        """Retire the youngest member (highest index): splits append
        shard-N, so scale-down unwinds scale-up."""
        def idx(m: str) -> tuple:
            tail = m.rsplit("-", 1)[-1]
            return (int(tail), m) if tail.isdigit() else (-1, m)
        return max(members, key=idx)

    def _handoff(self, new_ring: HashRing, *, op: str,
                 fresh: str | None = None,
                 retiring: str | None = None) -> dict:
        """Copy + tail-replay every key whose owner changes between the
        router's current ring and ``new_ring``, then fence-drain-flip.
        Returns counters for the operation timeline."""
        router = self.router
        old_ring = router.ring
        for m in old_ring.members:
            if self.runner.wal_dir(m) is None:
                raise RuntimeError(
                    "elastic handoff requires WAL-backed shards")

        def moves(pkey: str) -> bool:
            return old_ring.shard_for(pkey) != new_ring.shard_for(pkey)

        # per-donor session: replay horizon + the moved objects we
        # believe live (for deletion diffing across snapshot races)
        sessions: dict[str, dict] = {}
        bulk = tail = 0
        for donor in old_ring.members:
            if donor == fresh:
                continue
            try:
                self._post(donor, "/debug/snapshot")
            except Exception:  # noqa: BLE001 - donor may be respawning
                metrics.swallowed("shard.elastic", "donor snapshot")
            st = read_state(self.runner.wal_dir(donor),
                            CLUSTER_SCOPED_KINDS)
            moving: dict[tuple, dict] = {}
            for key, obj in st.objects.items():
                kind, ns, nm = key
                if kind in BROADCAST_KINDS or kind in LOCAL_KINDS:
                    continue
                pk = partition_key(kind, nm, ns)
                if old_ring.shard_for(pk) == donor and moves(pk):
                    moving[key] = obj
            if not moving and fresh is None:
                continue
            # recipients adopt the donor's rv horizon BEFORE any copy
            recipients = {new_ring.shard_for(
                partition_key(k[0], k[2], k[1])) for k in moving}
            if fresh is not None:
                recipients.add(fresh)
            for r in recipients:
                try:
                    self._post(r, "/debug/rv_floor", {"rv": st.rv})
                except Exception:  # noqa: BLE001
                    metrics.swallowed("shard.elastic", "rv floor")
            # a recipient may hold a stale range tombstone for a key
            # that left it in an EARLIER handoff and is now coming
            # back; lift it before adopting, or the recipient's next
            # respawn would purge the live range it just received
            incoming: dict[str, set] = {}
            for k in moving:
                pk = partition_key(k[0], k[2], k[1])
                incoming.setdefault(new_ring.shard_for(pk),
                                    set()).add(pk)
            for r, pks in incoming.items():
                try:
                    self._post(r, "/debug/tombstone",
                               {"clear": sorted(pks)})
                except Exception:  # noqa: BLE001
                    metrics.swallowed("shard.elastic",
                                      "recipient stone lift")
            # donor uid -> recipient uid: recipients mint fresh uids on
            # create, so every copied ownerReference must be remapped
            # or the recipient's controllers disown the copied children
            # (and duplicate them forever). Kind order applies owners
            # before their dependents, so the map is always warm.
            uids: dict[str, str] = {}
            if fresh is not None and not sessions:
                # first donor also seeds the fresh shard's replicated
                # broadcast kinds (ClusterRoles, CRDs, ...)
                for key, obj in st.objects.items():
                    if key[0] in BROADCAST_KINDS:
                        self._apply(fresh, obj, uids)
                        bulk += 1
            live: dict[tuple, str] = {}
            for key, obj in sorted(
                    moving.items(),
                    key=lambda kv: (_KIND_ORDER.get(kv[0][0], 5),
                                    kv[0])):
                recipient = new_ring.shard_for(
                    partition_key(key[0], key[2], key[1]))
                self._apply(recipient, obj, uids)
                live[key] = recipient
                bulk += 1
            sessions[donor] = {"horizon": st.seq,
                               "snap": st.snapshot_seq, "live": live,
                               "uids": uids}
        metrics.SHARD_HANDOFF_OBJECTS.labels(phase="bulk").inc(bulk)

        # seeded chaos: SIGKILL the busiest donor between COPY and
        # TAIL — the watchdog respawns it from this very WAL and the
        # tail passes below chase the recovered log
        if op == "split" and sessions:
            busiest = max(sessions, key=lambda d: len(
                sessions[d]["live"]))
            if chaos.split_kill_fault(f"split:{busiest}"):
                log.warning("chaos: SIGKILLing donor %s mid-split",
                            busiest)
                try:
                    self.runner.kill(busiest)
                except (OSError, KeyError):
                    metrics.swallowed("shard.elastic", "chaos kill")

        # TAIL: chase each donor's WAL until the whole pass is quiet
        passes = 0
        while passes < self.max_tail_passes:
            lag = 0
            for donor, sess in sessions.items():
                lag += self._tail_pass(donor, sess, new_ring, moves)
            tail += lag
            metrics.SHARD_HANDOFF_REPLAY_LAG.set(lag)
            if lag <= self.lag_threshold:
                break
            passes += 1
            time.sleep(0.02)

        # FENCE + DRAIN: hold moving-range writes, then read until two
        # consecutive passes find nothing — acks are WAL-durable
        # before clients see them, so quiet disk == quiet range
        router.fence(moves)
        try:
            quiet = 0
            deadline = time.monotonic() + 10.0
            while quiet < 2 and time.monotonic() < deadline:
                time.sleep(self.drain_settle_s)
                lag = 0
                for donor, sess in sessions.items():
                    lag += self._tail_pass(donor, sess, new_ring,
                                           moves)
                tail += lag
                quiet = quiet + 1 if lag == 0 else 0
            # FLIP: one topology swap; held writes re-resolve to the
            # new owners the moment the fence lifts
            urls = {m: self.runner.urls[m] for m in new_ring.members}
            router.set_topology(urls, pins=new_ring.pins)
            # TOMBSTONE: ownership has transferred but the donor's WAL
            # still holds the moved range. Stone it NOW — a donor that
            # crashes before CLEANUP below would otherwise respawn
            # with the moved objects live again (two owners, and the
            # donor's controllers reconciling ghosts). Not earlier: a
            # handoff that aborts pre-FLIP must leave the donor able
            # to recover its own (still-owned) range.
            for donor, sess in sessions.items():
                if donor == retiring or not sess["live"]:
                    continue
                pks = sorted({partition_key(k[0], k[2], k[1])
                              for k in sess["live"]})
                try:
                    self._post(donor, "/debug/tombstone", {"set": pks})
                except Exception:  # noqa: BLE001
                    metrics.swallowed("shard.elastic", "donor stone")
        finally:
            router.unfence()
        metrics.SHARD_HANDOFF_OBJECTS.labels(phase="tail").inc(tail)
        metrics.SHARD_HANDOFF_REPLAY_LAG.set(0)

        # CLEANUP: the donor's copies of moved objects are now inert
        # (ownership-filtered at the router); delete them best-effort
        # so the donor's controllers stop reconciling ghosts. A
        # retiring donor skips this — the whole process goes away.
        removed = 0
        for donor, sess in sessions.items():
            if donor == retiring:
                continue
            removed += self._cleanup_donor(donor, sess["live"])
            # the moved objects are deleted from the donor's WAL, so
            # its stones have done their job; lift them to keep the
            # stone set from accreting across many rebalances
            if sess["live"]:
                pks = sorted({partition_key(k[0], k[2], k[1])
                              for k in sess["live"]})
                try:
                    self._post(donor, "/debug/tombstone",
                               {"clear": pks})
                except Exception:  # noqa: BLE001
                    metrics.swallowed("shard.elastic",
                                      "donor stone lift")
        return {"objects_bulk": bulk, "objects_tail": tail,
                "tail_passes": passes, "cleaned": removed}

    def _tail_pass(self, donor: str, sess: dict, new_ring: HashRing,
                   moves) -> int:
        """One replay pass over ``donor``'s WAL past the session
        horizon; applies moving-range records to their recipients.
        Returns the number applied. Falls back to a full state re-read
        + diff when the donor compacted past our horizon (its
        background snapshot unlinked segments we had not read)."""
        wal = self.runner.wal_dir(donor)
        applied = 0
        doc = load_latest_snapshot(wal)
        disk_snap = int(doc["seq"]) if doc else 0
        if disk_snap > max(sess["horizon"], sess["snap"]):
            st = read_state(wal, CLUSTER_SCOPED_KINDS)
            fresh_live: dict[tuple, str] = {}
            for key, obj in st.objects.items():
                kind, ns, nm = key
                if kind in BROADCAST_KINDS or kind in LOCAL_KINDS:
                    continue
                pk = partition_key(kind, nm, ns)
                if not moves(pk):
                    continue
                recipient = new_ring.shard_for(pk)
                self._apply(recipient, obj, sess["uids"])
                fresh_live[key] = recipient
                applied += 1
            for key, recipient in sess["live"].items():
                if key not in fresh_live:
                    self._delete(recipient, key)
                    applied += 1
            sess["live"] = fresh_live
            sess["horizon"] = st.seq
            sess["snap"] = st.snapshot_seq
            return applied
        for rec in tail_records(wal, sess["horizon"]):
            sess["horizon"] = max(sess["horizon"],
                                  int(rec.get("seq", 0)))
            obj = rec.get("obj")
            if obj is None:
                continue
            kind = obj.get("kind")
            meta = obj.get("metadata") or {}
            if kind in BROADCAST_KINDS or kind in LOCAL_KINDS:
                continue
            ns = None if kind in CLUSTER_SCOPED_KINDS \
                else meta.get("namespace")
            nm = meta.get("name")
            pk = partition_key(kind, nm, ns)
            if not moves(pk):
                continue
            recipient = new_ring.shard_for(pk)
            key = (kind, ns, nm)
            if rec.get("verb") == "DELETE":
                self._delete(recipient, key)
                sess["live"].pop(key, None)
            else:
                self._apply(recipient, obj, sess["uids"])
                sess["live"][key] = recipient
            applied += 1
        return applied

    def _apply(self, shard: str, obj: dict,
               uid_map: dict | None = None) -> None:
        """Upsert one object through the recipient's normal API (its
        admission chain re-runs — idempotent for everything this
        platform writes). rv/uid are the DONOR's; strip them so the
        recipient issues fresh ones above its adopted rv floor, and
        record donor-uid -> recipient-uid in ``uid_map`` so copied
        children's ownerReferences re-attach to their copied owners
        (controllers match dependents strictly by owner uid)."""
        cli = self._client(shard)
        o = json.loads(json.dumps(obj))  # records are shared; never
        md = o.setdefault("metadata", {})  # mutate the caller's copy
        md.pop("resourceVersion", None)
        old_uid = md.pop("uid", None)
        if uid_map is not None:
            for ref in md.get("ownerReferences") or []:
                if ref.get("uid") in uid_map:
                    ref["uid"] = uid_map[ref["uid"]]

        def note(applied: dict) -> None:
            if uid_map is not None and old_uid:
                new_uid = (applied.get("metadata") or {}).get("uid")
                if new_uid:
                    uid_map[old_uid] = new_uid

        kind, nm = o.get("kind"), md.get("name")
        ns = md.get("namespace")
        for _attempt in range(4):
            try:
                note(self._ride_out(lambda: cli.create(o)))
                return
            except AlreadyExists:
                try:
                    cur = self._ride_out(lambda: cli.get(kind, nm, ns))
                except NotFound:
                    continue  # deleted underneath; retry the create
                md["resourceVersion"] = (cur.get("metadata") or {}).get(
                    "resourceVersion")
                try:
                    note(self._ride_out(lambda: cli.update(o)))
                    return
                except (Conflict, NotFound):
                    continue
            except APIError:
                # validation/admission refused the copy (e.g. a kind
                # with server-owned lifecycle): count it, move on —
                # the tail pass will retry if it changes again
                metrics.swallowed("shard.elastic", "apply refused")
                return
        metrics.swallowed("shard.elastic", "apply contention")

    def _delete(self, shard: str, key: tuple) -> None:
        kind, ns, nm = key
        try:
            self._ride_out(
                lambda: self._client(shard).delete(kind, nm, ns))
        except NotFound:
            pass
        except APIError:
            metrics.swallowed("shard.elastic", "handoff delete")

    def _cleanup_donor(self, donor: str, live: dict) -> int:
        """Best-effort removal of moved objects from a surviving
        donor, parents first so its controllers cascade instead of
        resurrect. Never touches the shard-local control plumbing."""
        cli = self._client(donor)
        removed = 0
        for key in sorted(live, key=lambda k: (_KIND_ORDER.get(
                k[0], 5), k)):
            kind, ns, nm = key
            if kind in LOCAL_KINDS or (kind, nm) == ("Namespace",
                                                     "kubeflow"):
                continue
            try:
                cli.delete(kind, nm, ns)
                removed += 1
            except NotFound:
                removed += 1
            except (APIError, OSError):
                metrics.swallowed("shard.elastic", "donor cleanup")
        return removed


class ShardAutoscaler:
    """Queue-depth + SLO-burn-driven elasticity: split on sustained
    pressure, merge back on sustained idle. Deterministic — the
    harness drives ``tick()``; nothing here owns a thread.

    Signals, per tick:
    - mean per-shard ``workqueue_depth`` from the federated TSDB
      (``instance=<shard>`` series the Observer scrapes), and
    - the r12 burn-rate engine: any watched SLO sitting in
      ``critical`` counts as pressure — but only while there is work
      queued. A critical *latency* SLO over an empty fleet means the
      burn windows still hold samples from traffic that already
      drained; capacity cannot fix a window, so it must not pin the
      fleet wide overnight.

    ``sustain`` consecutive pressure ticks split (up to ``max_shards``,
    the 2→6 of the diurnal story); ``sustain`` idle ticks merge (down
    to ``min_shards``). ``cooldown_s`` after every action stops
    thrash while the fleet re-settles.

    **Hot-namespace carve-off**: when ONE namespace accounts for at
    least ``carve_fraction`` of a deep shard's queue (the per-namespace
    ``workqueue_namespace_depth`` series the workqueues export), an
    even split would move random ranges while the hot tenant keeps
    drowning whichever shard the hash gives it. Instead the autoscaler
    carves: ``split(weight=carve_weight, dedicate=ns)`` admits a
    near-weightless shard (vnodes=1 claims ~no hash range) and pins
    the hot namespace to it — a dedicated shard for the noisy tenant,
    everyone else's routing untouched. A namespace that is already
    pinned is never re-carved; when it cools, the ordinary merge path
    retires its shard and ``without_member`` drops the pin."""

    def __init__(self, elastic: ElasticShardManager, observer, *,
                 min_shards: int = 2, max_shards: int = 6,
                 split_depth: float = 8.0, merge_depth: float = 1.0,
                 sustain: int = 3, cooldown_s: float = 5.0,
                 carve_fraction: float = 0.6, carve_weight: int = 1,
                 burn_slos: tuple = ("provision-p50", "wal-fsync",
                                     "scheduler-latency")):
        self.elastic = elastic
        self.observer = observer
        self.min_shards = int(min_shards)
        self.max_shards = int(max_shards)
        self.split_depth = float(split_depth)
        self.merge_depth = float(merge_depth)
        self.sustain = int(sustain)
        self.cooldown_s = float(cooldown_s)
        self.carve_fraction = float(carve_fraction)
        self.carve_weight = int(carve_weight)
        self.burn_slos = tuple(burn_slos)
        self._high = 0
        self._idle = 0
        self._hot_ns: str | None = None
        self._hot = 0
        self._last_action = 0.0
        #: decision log for the conformance artifact
        self.decisions: list[dict] = []

    def _burning(self) -> bool:
        eng = self.observer.engine
        for name in self.burn_slos:
            try:
                if eng.state_of(name) == "critical":
                    return True
            except KeyError:
                continue
        return False

    def _mean_depth(self) -> float:
        members = self.elastic.router.ring.members
        total = 0.0
        for shard in members:
            v = self.observer.tsdb.latest("workqueue_depth",
                                          {"instance": shard})
            total += v or 0.0
        return total / max(len(members), 1)

    def _hot_namespace(self) -> str | None:
        """The namespace dominating one deep shard's queue, or None.
        A namespace already pinned (previously carved, or a notebook
        live-migration pin) is never a candidate — its shard IS its
        dedicated shard; re-carving would thrash."""
        tsdb = self.observer.tsdb
        ring = self.elastic.router.ring
        try:
            spaces = tsdb.label_values("workqueue_namespace_depth",
                                       "namespace")
        except AttributeError:
            return None  # reduced fakes without the breakdown
        for shard in ring.members:
            total = tsdb.latest("workqueue_depth",
                                {"instance": shard}) or 0.0
            if total < self.split_depth:
                continue
            for ns in spaces:
                if ring.pins.get(ns) is not None \
                        or ring.shard_for(ns) != shard:
                    continue
                v = tsdb.latest("workqueue_namespace_depth",
                                {"instance": shard,
                                 "namespace": ns}) or 0.0
                if v / total >= self.carve_fraction:
                    return ns
        return None

    def tick(self, now: float | None = None) -> str:
        """One evaluation; returns the decision taken
        (``split`` | ``carve`` | ``merge`` | ``hold`` |
        ``cooldown``)."""
        now = time.monotonic() if now is None else now
        n = len(self.elastic.router.ring)
        depth = self._mean_depth()
        burning = self._burning()
        if depth >= self.split_depth or \
                (burning and depth > self.merge_depth):
            self._high += 1
            self._idle = 0
        elif depth <= self.merge_depth:
            self._idle += 1
            self._high = 0
        else:
            self._high = self._idle = 0
        hot = self._hot_namespace()
        if hot is not None and hot == self._hot_ns:
            self._hot += 1
        else:
            self._hot_ns = hot
            self._hot = 1 if hot is not None else 0
        decision = "hold"
        if self._last_action and \
                now - self._last_action < self.cooldown_s:
            decision = "cooldown"
        elif hot is not None and self._hot >= self.sustain \
                and n < self.max_shards:
            # carve beats even split: the pressure is one tenant, so
            # give THAT tenant a dedicated (near-weightless) shard
            self.elastic.split(weight=self.carve_weight, dedicate=hot)
            self._hot_ns, self._hot = None, 0
            self._high = 0
            self._last_action = time.monotonic()
            decision = "carve"
        elif self._high >= self.sustain and n < self.max_shards:
            self.elastic.split()
            self._high = 0
            self._last_action = time.monotonic()
            decision = "split"
        elif self._idle >= self.sustain and n > self.min_shards:
            self.elastic.merge()
            self._idle = 0
            self._last_action = time.monotonic()
            decision = "merge"
        metrics.SHARD_AUTOSCALE_DECISIONS_TOTAL.labels(
            decision=decision).inc()
        self.decisions.append({
            "t": round(now, 3), "decision": decision, "shards": n,
            "mean_depth": round(depth, 2), "burning": burning,
            "high": self._high, "idle": self._idle, "hot": hot})
        return decision
