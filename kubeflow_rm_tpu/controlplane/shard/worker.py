"""One control-plane shard, as a process.

A shard is the full single-host stack scoped to its ring partition:
the in-memory apiserver (optionally WAL-backed) with admission +
validation registered, the fake-kubelet manager marking pods Ready,
the kube REST facade on a FIXED port (the ring maps namespaces to
URLs, so a respawned shard must come back at the same address), and
the platform controller manager reconciling through a loopback kube
client — gated on a short-duration ``LeaderElector`` lease stored in
the shard's own store, so a respawn after SIGKILL takes over within
one lease duration instead of double-reconciling against a zombie.

``shard_worker_main`` is the ``multiprocessing`` (spawn) entry point;
everything it needs arrives in one picklable config dict.
"""

from __future__ import annotations

import logging
import threading

log = logging.getLogger("kubeflow_rm_tpu.shard.worker")

# short lease: sole-candidate acquisition is immediate, and a respawn
# after SIGKILL steals the dead holder's lease in ~one duration — the
# default 15s would dominate the chaos-recovery time budget
LEASE_DURATION_S = 3.0
LEASE_RENEW_S = 2.0
LEASE_RETRY_S = 0.5


def shard_worker_main(cfg: dict) -> None:
    """Boot one shard and serve forever (the runner SIGKILLs us).

    ``cfg``: name, port, wal_dir (None = no WAL), manager_workers,
    auto_ready, hang_dump_s.
    """
    logging.basicConfig(level=logging.WARNING)
    if cfg.get("hang_dump_s"):
        import faulthandler
        faulthandler.dump_traceback_later(cfg["hang_dump_s"], exit=True)

    from kubeflow_rm_tpu.controlplane import (
        WATCHED_KINDS,
        make_cluster_manager,
        metrics,
    )
    from kubeflow_rm_tpu.controlplane.api import notebook as nb_api
    from kubeflow_rm_tpu.controlplane.api import poddefault as pd_api
    from kubeflow_rm_tpu.controlplane.apiserver import APIServer
    from kubeflow_rm_tpu.controlplane.controllers.statefulset import (
        DeploymentController,
        StatefulSetController,
    )
    from kubeflow_rm_tpu.controlplane.deploy.kubeclient import (
        KubeAPIServer,
    )
    from kubeflow_rm_tpu.controlplane.deploy.restserver import RestServer
    from kubeflow_rm_tpu.controlplane.ha.leases import LeaderElector
    from kubeflow_rm_tpu.controlplane.runtime import Manager
    from kubeflow_rm_tpu.controlplane.webhook.notebook import (
        NotebookWebhook,
    )
    from kubeflow_rm_tpu.controlplane.webhook.poddefault import (
        PodDefaultWebhook,
    )
    from kubeflow_rm_tpu.controlplane.webhook.tpu_inject import (
        TpuInjectWebhook,
    )

    name = cfg["name"]
    metrics.set_shard(name)
    if cfg.get("tracing"):
        from kubeflow_rm_tpu.controlplane import tracing
        tracing.set_enabled(True)
        # spans exported via /debug/traces carry this so cross-shard
        # merges can show which process each hop ran in
        tracing.set_process(name)
    stop = threading.Event()

    # -- the shard's cluster: apiserver (+WAL) + admission + kubelet --
    capi = APIServer(wal_dir=cfg.get("wal_dir"), shard=name)
    capi.register_validator(nb_api.KIND, nb_api.validate)
    capi.register_validator(pd_api.KIND, pd_api.validate)
    NotebookWebhook(capi).register()
    PodDefaultWebhook(capi).register()
    TpuInjectWebhook(capi).register()
    kubelet = Manager(capi)
    kubelet.add(StatefulSetController(
        auto_ready=cfg.get("auto_ready", True)))
    kubelet.add(DeploymentController(
        auto_ready=cfg.get("auto_ready", True)))
    # after WAL replay some StatefulSets may have landed without their
    # pods (killed mid-fan-out): requeue everything once on boot
    kubelet.enqueue_all()
    threading.Thread(target=kubelet.run_forever, args=(stop, 0.05),
                     kwargs={"workers": 4}, daemon=True).start()

    # a deliberate scale-down (ShardRunner.remove_shard) SIGTERMs us:
    # flush + close the WAL so the merge coordinator reads a cleanly
    # closed log (SIGKILL stays crash-consistent via group commit —
    # this handler is an optimization, not a correctness requirement)
    import os as _os
    import signal as _signal

    def _graceful_exit(signum, frame):
        stop.set()
        try:
            capi.close_persistence()
        finally:
            _os._exit(0)

    _signal.signal(_signal.SIGTERM, _graceful_exit)

    rest = RestServer(capi, port=cfg["port"])
    rest.start()

    # lease namespace for the elector below (shard-local control ns)
    capi.ensure_namespace("kubeflow")

    # -- the shard's platform manager over a loopback kube client --
    import os
    kapi = KubeAPIServer(rest.url, identity=f"manager-{name}",
                         cache_reads=True)
    mgr = make_cluster_manager(kapi, enable_culling=False)
    for kind in WATCHED_KINDS:
        threading.Thread(target=kapi.watch_kind,
                         args=(kind, None, stop, 60),
                         daemon=True).start()
    elector = LeaderElector(
        kapi, identity=f"{name}-{os.getpid()}",
        lease_name=f"controlplane-manager-{name}",
        lease_duration_s=LEASE_DURATION_S,
        renew_deadline_s=LEASE_RENEW_S,
        retry_period_s=LEASE_RETRY_S)
    mgr.enqueue_all()
    log.info("shard %s serving on port %d (wal=%s)", name,
             cfg["port"], bool(cfg.get("wal_dir")))
    # blocks until the process is killed
    mgr.run_forever(stop, 0.05,
                    workers=cfg.get("manager_workers", 8),
                    elector=elector)
