"""The ``APIServer`` verb surface against a real kube-apiserver.

``KubeAPIServer`` is a drop-in replacement for the in-memory
``controlplane.apiserver.APIServer``: the SAME controllers, webhooks
and web apps run unchanged against a cluster (the reference gets this
for free from controller-runtime's client; here it's ~one REST call per
verb over ``requests``). Differences from the in-memory server, by
design:

- ``register_admission`` / ``register_validator`` are recorded but not
  invoked on writes — in a real cluster admission runs server-side
  (the HTTPS webhook server in ``webhook_server.py``) and validation is
  the CRD schema's job (``crds.py``).
- ``add_watcher`` wires into real watch streams: ``watch_kind`` runs
  one kind's watch loop (list+watch with resourceVersion resume, the
  informer pattern) and fans events into the registered watchers.
- ``access_review`` submits a real ``SubjectAccessReview``
  (the reference's ``crud_backend/authz.py:46-80``).

Auth: in-cluster ServiceAccount (token + CA at the usual paths) or an
explicit ``base_url``/``token``/``ca_cert`` (tests pass a fake server).
"""

from __future__ import annotations

import copy
import datetime
import json
import logging
import threading
import time
from typing import Callable

from kubeflow_rm_tpu.controlplane.api.meta import (
    fast_deepcopy,
    name_of,
    namespace_of,
    strategic_merge,
)
from kubeflow_rm_tpu.controlplane.apiserver import (
    AlreadyExists,
    APIError,
    Conflict,
    Invalid,
    NotFound,
    status_from_error,
)
from kubeflow_rm_tpu.controlplane import chaos, metrics, tracing
from kubeflow_rm_tpu.analysis.lockgraph import make_lock

log = logging.getLogger("kubeflow_rm_tpu.kubeclient")


class _WatchExpired(Exception):
    """410 Gone from the watch: the resume rv fell below the server's
    backlog horizon — only a full relist can resync."""

SA_TOKEN = "/var/run/secrets/kubernetes.io/serviceaccount/token"
SA_CA = "/var/run/secrets/kubernetes.io/serviceaccount/ca.crt"

# kind -> (api prefix, plural, namespaced). Core kinds live under
# /api/v1; everything else under /apis/<group>/<version>.
RESOURCES: dict[str, tuple[str, str, bool]] = {
    # core/v1
    "Pod": ("api/v1", "pods", True),
    "Service": ("api/v1", "services", True),
    "ConfigMap": ("api/v1", "configmaps", True),
    "Secret": ("api/v1", "secrets", True),
    "ServiceAccount": ("api/v1", "serviceaccounts", True),
    "Namespace": ("api/v1", "namespaces", False),
    "Event": ("api/v1", "events", True),
    "ResourceQuota": ("api/v1", "resourcequotas", True),
    "PersistentVolumeClaim": ("api/v1", "persistentvolumeclaims", True),
    "PersistentVolume": ("api/v1", "persistentvolumes", False),
    "Node": ("api/v1", "nodes", False),
    # apps/v1
    "StatefulSet": ("apis/apps/v1", "statefulsets", True),
    "Deployment": ("apis/apps/v1", "deployments", True),
    # coordination (leader-election Leases, ha/leases.py)
    "Lease": ("apis/coordination.k8s.io/v1", "leases", True),
    # rbac
    "RoleBinding": ("apis/rbac.authorization.k8s.io/v1",
                    "rolebindings", True),
    "ClusterRole": ("apis/rbac.authorization.k8s.io/v1",
                    "clusterroles", False),
    "ClusterRoleBinding": ("apis/rbac.authorization.k8s.io/v1",
                           "clusterrolebindings", False),
    # networking
    "NetworkPolicy": ("apis/networking.k8s.io/v1", "networkpolicies",
                      True),
    # istio + openshift (installed by overlays when present)
    "VirtualService": ("apis/networking.istio.io/v1beta1",
                       "virtualservices", True),
    "AuthorizationPolicy": ("apis/security.istio.io/v1beta1",
                            "authorizationpolicies", True),
    "Route": ("apis/route.openshift.io/v1", "routes", True),
    # this platform's CRDs (deploy/crds.py)
    "Notebook": ("apis/kubeflow.org/v1", "notebooks", True),
    "TPUJob": ("apis/kubeflow.org/v1", "tpujobs", True),
    "Profile": ("apis/kubeflow.org/v1", "profiles", False),
    "PodDefault": ("apis/kubeflow.org/v1alpha1", "poddefaults", True),
    "Tensorboard": ("apis/tensorboard.kubeflow.org/v1alpha1",
                    "tensorboards", True),
    "PVCViewer": ("apis/kubeflow.org/v1alpha1", "pvcviewers", True),
    "CustomResourceDefinition": (
        "apis/apiextensions.k8s.io/v1", "customresourcedefinitions",
        False),
}


class TokenBucket:
    """Client-side qps/burst throttle — client-go's
    ``flowcontrol.NewTokenBucketRateLimiter`` behind the reference's
    ``--qps``/``--burst`` flags (notebook-controller/main.go:71-85).

    ``acquire`` debits one token, sleeping when the bucket is dry.
    Tokens refill at ``qps``; the bucket holds at most ``burst``, so a
    cold client may send ``burst`` calls back-to-back before the
    steady-state rate applies. Tokens may go negative (waiters are
    effectively queued FIFO by their computed wait), which keeps the
    math lock-cheap and fair enough for a control-plane client.

    Thread-safe and shared across the adapter's per-thread sessions;
    ``clock``/``sleep`` are injectable for deterministic tests."""

    def __init__(self, qps: float, burst: int | None = None, *,
                 clock: Callable[[], float] | None = None,
                 sleep: Callable[[float], None] | None = None):
        import time as _time
        if qps <= 0:
            raise ValueError(f"qps must be > 0, got {qps}")
        self.qps = float(qps)
        self.burst = int(burst) if burst else max(1, int(2 * qps))
        self._clock = clock or _time.monotonic
        self._sleep = sleep or _time.sleep
        self._tokens = float(self.burst)
        self._last = self._clock()
        self._lock = make_lock("kubeclient.token_bucket")
        # total seconds of wait injected — surfaced for conformance
        self.throttled_seconds = 0.0
        self.throttled_calls = 0

    def acquire(self, n: float = 1.0) -> float:
        """Take ``n`` tokens, sleeping until they are covered. Returns
        the wait injected (0.0 when the bucket had capacity)."""
        with self._lock:
            now = self._clock()
            self._tokens = min(float(self.burst),
                               self._tokens + (now - self._last) * self.qps)
            self._last = now
            self._tokens -= n
            wait = 0.0 if self._tokens >= 0 else -self._tokens / self.qps
            if wait > 0:
                self.throttled_seconds += wait
                self.throttled_calls += 1
        if wait > 0:
            self._sleep(wait)
        return wait

    def try_acquire(self, n: float = 1.0) -> bool:
        """Non-blocking ``acquire``: debit ``n`` tokens iff the bucket
        covers them right now, else leave the bucket untouched and
        return False. The admission-control primitive — a serving
        gateway sheds an over-limit request immediately (the client
        retries with backoff) rather than queueing it into its own
        latency SLO the way the blocking ``acquire`` would."""
        with self._lock:
            now = self._clock()
            self._tokens = min(float(self.burst),
                               self._tokens + (now - self._last) * self.qps)
            self._last = now
            if self._tokens < n:
                self.throttled_calls += 1
                return False
            self._tokens -= n
            return True


class _Resp:
    """Minimal response shim over ``http.client.HTTPResponse`` with the
    slice of the requests API this module consumes."""

    def __init__(self, raw, eager: bool):
        self.raw = raw
        self.status_code = raw.status
        self._body: bytes | None = raw.read() if eager else None

    @property
    def ok(self) -> bool:
        return self.status_code < 400

    @property
    def text(self) -> str:
        return (self._body or b"").decode("utf-8", "replace")

    def json(self):
        return json.loads(self._body or b"null")

    def iter_lines(self):
        # HTTPResponse.readline() de-chunks transparently
        while True:
            line = self.raw.readline()
            if not line:
                return
            yield line.rstrip(b"\r\n")

    def close(self):
        try:
            self.raw.close()
        except Exception:
            metrics.swallowed("kubeclient", "stream close")


def _close_quietly(conn) -> None:
    try:
        conn.close()
    except Exception:
        metrics.swallowed("kubeclient", "conn close")


class _ConnPool:
    """Bounded pool of idle keep-alive connections shared by every
    per-thread session of one adapter. A request checks a connection
    out (exclusive use until checkin), and returns it once the response
    body has been read eagerly; stale connections are dropped by the
    retry logic in ``_FastSession._request``. Pooling replaces the
    one-connection-per-thread model: a 20-way storm's short-lived
    threads share warm connections instead of each paying a fresh
    ``connect()``, and the idle bound caps sockets held against the
    apiserver between bursts."""

    def __init__(self, max_idle: int = 16):
        self.max_idle = max_idle
        self._idle: list = []
        self._lock = make_lock("kubeclient.conn_pool")
        self.dials = 0    # fresh connections established
        self.reuses = 0   # requests served on a pooled connection

    def checkout(self):
        with self._lock:
            if self._idle:
                self.reuses += 1
                return self._idle.pop()
            self.dials += 1
        return None  # caller dials

    def checkin(self, conn) -> None:
        with self._lock:
            if len(self._idle) < self.max_idle:
                self._idle.append(conn)
                return
        try:
            conn.close()
        except Exception:
            metrics.swallowed("kubeclient", "pool checkin close")

    def close(self) -> None:
        with self._lock:
            idle, self._idle = self._idle, []
        for conn in idle:
            try:
                conn.close()
            except Exception:
                metrics.swallowed("kubeclient", "pool close")


class _FastSession:
    """Persistent-connection HTTP client on ``http.client``.

    Drop-in for the slice of ``requests.Session`` the adapter uses, at
    ~¼ the per-call CPU — ``requests`` spends ~0.6 ms/call on prepare/
    hook/cookie machinery, which at control-plane request rates (a
    20-way spawn storm is hundreds of calls) made the client library
    itself a top-3 profile entry. Verb requests draw keep-alive
    connections from a shared ``_ConnPool`` (per-session private pool
    when standalone); streaming calls (watches) get a dedicated
    connection so they don't starve the verb path."""

    def __init__(self, base_url: str, token: str | None,
                 ca_cert: str | bool,
                 extra_headers: dict[str, str] | None = None,
                 pool: _ConnPool | None = None):
        import urllib.parse
        u = urllib.parse.urlsplit(base_url)
        self._https = u.scheme == "https"
        self._host = u.hostname or "127.0.0.1"
        self._port = u.port or (443 if self._https else 80)
        self._headers = {"Content-Type": "application/json"}
        if token:
            self._headers["Authorization"] = f"Bearer {token}"
        if extra_headers:
            self._headers.update(extra_headers)
        self._ssl_ctx = None
        if self._https:
            import ssl
            if ca_cert is False:
                self._ssl_ctx = ssl._create_unverified_context()
            else:
                self._ssl_ctx = ssl.create_default_context(
                    cafile=ca_cert if isinstance(ca_cert, str) else None)
        # standalone sessions (tests construct _FastSession directly)
        # keep the historical one-warm-connection behavior via a
        # private single-slot pool
        self._pool = pool if pool is not None else _ConnPool(max_idle=1)

    def _connect(self, timeout: float | None):
        import http.client
        if self._https:
            return http.client.HTTPSConnection(
                self._host, self._port, timeout=timeout,
                context=self._ssl_ctx)
        return http.client.HTTPConnection(self._host, self._port,
                                          timeout=timeout)

    def _request(self, method: str, url: str, *, json_body=None,
                 params=None, headers=None, stream=False,
                 timeout=None):
        import http.client
        import urllib.parse
        path = urllib.parse.urlsplit(url).path
        if params:
            path += "?" + urllib.parse.urlencode(params)
        body = None if json_body is None else json.dumps(json_body)
        hdrs = dict(self._headers)
        if headers:
            hdrs.update(headers)
        # propagate the live trace context on EVERY rest call — this
        # single choke point covers all verbs of every session,
        # including the per-shard sessions ShardedKubeAPIServer routes
        # through, so a cross-shard hop stays one trace
        tp = tracing.current_traceparent()
        if tp is not None:
            hdrs.setdefault(tracing.TRACE_HEADER, tp)
        if not stream and chaos.active() is not None:
            # seeded apiserver-fault injection: the same choke point
            # that carries the trace header covers every verb of every
            # session, so an injected timeout (raises) or 5xx (synthetic
            # 503 the normal _raise_for path turns into APIError) hits
            # exactly where a real overloaded shard would
            injected = chaos.api_request_fault(method, path)
            if injected is not None:
                return injected
        if stream:
            conn = self._connect(timeout or 310)
            conn.request(method, path, body=body, headers=hdrs)
            return _Resp(conn.getresponse(), eager=False)
        conn_errors = (http.client.RemoteDisconnected,
                       http.client.BadStatusLine,
                       http.client.CannotSendRequest,
                       BrokenPipeError, ConnectionResetError,
                       ConnectionRefusedError, OSError)
        for attempt in (0, 1):
            # the retry attempt always dials fresh: after a shard
            # restart EVERY pooled socket is stale, and a checkout on
            # attempt 1 would just pop the next dead keep-alive
            conn = (self._pool.checkout() if attempt == 0 else None) \
                or self._connect(timeout or 60)
            try:
                conn.request(method, path, body=body, headers=hdrs)
            except conn_errors as e:
                # failed while SENDING on a stale keep-alive: the
                # server never saw a complete request, so a resend
                # is safe for any method
                _close_quietly(conn)
                if attempt:
                    raise
                if isinstance(e, (ConnectionResetError,
                                  BrokenPipeError)):
                    # peer went away (shard restart), not a quietly
                    # aged-out keep-alive: every idle socket in the
                    # pool is equally dead — discard them all so the
                    # reconcile loops behind this pool don't each eat
                    # one stale socket
                    self._pool.close()
                continue
            try:
                resp = _Resp(conn.getresponse(), eager=True)
            except conn_errors as e:
                # failed reading the RESPONSE: the server may have
                # processed the request — only idempotent reads may
                # retry (urllib3's default Retry excludes POST/PATCH
                # for the same reason)
                _close_quietly(conn)
                if isinstance(e, (ConnectionResetError,
                                  BrokenPipeError)):
                    self._pool.close()  # shard restart: all stale
                if attempt or method not in ("GET", "HEAD"):
                    raise
                continue
            # body fully read (eager): the connection is free for the
            # next caller — unless the server asked to close it
            if getattr(resp.raw, "will_close", False):
                _close_quietly(conn)
            else:
                self._pool.checkin(conn)
            return resp
        raise http.client.CannotSendRequest(
            f"{method} {path}: connection could not be established")

    def get(self, url, *, params=None, stream=False, timeout=None,
            headers=None):
        return self._request("GET", url, params=params, stream=stream,
                             timeout=timeout, headers=headers)

    def post(self, url, *, json=None, headers=None, params=None):
        return self._request("POST", url, json_body=json,
                             headers=headers, params=params)

    def put(self, url, *, json=None, headers=None):
        return self._request("PUT", url, json_body=json,
                             headers=headers)

    def patch(self, url, *, json=None, headers=None):
        return self._request("PATCH", url, json_body=json,
                             headers=headers)

    def delete(self, url, *, headers=None):
        return self._request("DELETE", url, headers=headers)


def _selector_param(label_selector: dict | None) -> dict:
    """Serialize a structured LabelSelector to the query-string grammar
    (the inverse of the REST facade's ``_selector_from``): matchLabels
    as ``k=v`` and matchExpressions as ``k!=v`` / ``k`` / ``!k`` /
    ``k in (a,b)`` / ``k notin (a,b)``."""
    if not label_selector:
        return {}
    if "matchLabels" in label_selector or \
            "matchExpressions" in label_selector:
        pairs = label_selector.get("matchLabels") or {}
        exprs = label_selector.get("matchExpressions") or []
    else:
        pairs, exprs = label_selector, []
    reqs = [f"{k}={v}" for k, v in sorted(pairs.items())]
    for e in exprs:
        key, op = e["key"], e["operator"]
        values = sorted(e.get("values") or [])
        if op == "In":
            reqs.append(f"{key} in ({','.join(values)})")
        elif op == "NotIn":
            if len(values) == 1:
                reqs.append(f"{key}!={values[0]}")
            else:
                reqs.append(f"{key} notin ({','.join(values)})")
        elif op == "Exists":
            reqs.append(key)
        elif op == "DoesNotExist":
            reqs.append(f"!{key}")
        else:
            raise Invalid(f"unknown selector operator {op!r}")
    return {"labelSelector": ",".join(reqs)}


class KubeAPIServer:
    def __init__(self, base_url: str | None = None, *,
                 token: str | None = None, ca_cert: str | bool = True,
                 clock: Callable[[], datetime.datetime] | None = None,
                 session=None, cache_reads: bool = True,
                 qps: float | None = None, burst: int | None = None,
                 identity: str | None = None):
        if base_url is None:
            # in-cluster defaults (KUBERNETES_SERVICE_HOST is set by
            # the kubelet for every pod)
            import os
            host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            base_url = f"https://{host}:{port}"
            if token is None and os.path.exists(SA_TOKEN):
                token = open(SA_TOKEN).read().strip()
            if ca_cert is True and os.path.exists(SA_CA):
                ca_cert = SA_CA
        self.base_url = base_url.rstrip("/")
        # Sessions are NOT thread-safe (cookie jar + connection-pool
        # mutation), and this adapter is shared by watch threads plus
        # the parallel Manager's reconcile workers — so each thread
        # lazily gets its own Session unless the caller injected one
        # explicitly (tests that stub transport do).
        self._explicit_session = session
        self._ca_cert = ca_cert
        self._token = token
        self._tls = threading.local()
        # keep-alive connections shared across the per-thread sessions
        self._pool = _ConnPool()
        # writer identity: stamped on every request so the facade's
        # apiserver write log can attribute writes (failover conformance)
        self.identity = identity
        # client-side qps/burst throttle, shared across the per-thread
        # sessions; None = unthrottled (the historical default). Watch
        # streams and cache-served reads are NOT debited — client-go
        # likewise exempts watches from the flowcontrol limiter.
        self.limiter = TokenBucket(qps, burst) if qps else None
        if session is not None:
            session.verify = ca_cert
            if token:
                session.headers["Authorization"] = f"Bearer {token}"
            if identity:
                session.headers["X-Writer-Identity"] = identity
        self.clock = clock or (
            lambda: datetime.datetime.now(datetime.timezone.utc))
        self._watchers: list[Callable[[str, dict, dict | None], None]] = []
        self._event_seq = 0
        self._event_lock = make_lock("kubeclient.events_seen")
        # informer read cache: the shared indexed ObjectStore
        # (controlplane/cache/store.py); a kind serves reads only once
        # its initial list has synced. ``cache_reads=False`` keeps the
        # store cold (nothing applied, nothing served) — the
        # conformance A/B's no-cache arm.
        self._cache_reads = cache_reads
        from kubeflow_rm_tpu.controlplane.cache.store import ObjectStore
        self.cache = ObjectStore(cluster_scoped={
            k for k, (_, _, namespaced) in RESOURCES.items()
            if not namespaced})

    # ---- informer read cache -----------------------------------------
    # controller-runtime's default client serves get/list from the
    # informer cache and sends only writes to the apiserver; the
    # reference's reconcilers lean on that (a Reconcile is ~10 reads +
    # 0-2 writes). Mirroring it here turned the 20-way spawn storm from
    # ~1400 live GETs into ~watch traffic. A kind is cache-served only
    # after its informer's initial list (``watch_kind``) has synced;
    # writes are applied to the cache from the server's response
    # (read-your-writes within a reconcile), and watch events reconcile
    # the rest — the store's rv comparison keeps an older event from
    # rolling back a newer write, and its tombstones keep a racing
    # relist from resurrecting a deletion.

    def _cache_apply(self, etype: str, obj: dict) -> None:
        if self._cache_reads:
            self.cache.apply(etype, obj)

    def _cache_serves(self, kind: str) -> bool:
        return self._cache_reads and self.cache.is_synced(kind)

    def wait_for_sync(self, kinds, timeout: float | None = None) -> bool:
        """Block until every kind's informer completed its initial list
        (vacuously true with the cache disabled)."""
        if not self._cache_reads:
            return True
        return self.cache.wait_for_sync(kinds, timeout)

    @property
    def _session(self):
        if self._explicit_session is not None:
            return self._explicit_session
        s = getattr(self._tls, "session", None)
        if s is None:
            extra = {"X-Writer-Identity": self.identity} \
                if self.identity else None
            s = _FastSession(self.base_url, self._token, self._ca_cert,
                             extra_headers=extra, pool=self._pool)
            self._tls.session = s
        return s

    def _throttle(self) -> None:
        if self.limiter is not None:
            self.limiter.acquire()

    # ---- wiring (admission/validation are server-side in-cluster) ----
    def register_admission(self, kind_pattern: str, fn: Callable) -> None:
        log.debug("admission for %s runs in-cluster via the webhook "
                  "server; registration is a no-op here", kind_pattern)

    def register_validator(self, kind: str, fn: Callable) -> None:
        log.debug("validation for %s is the CRD schema's job in-cluster",
                  kind)

    def add_watcher(self, fn: Callable[[str, dict, dict | None], None],
                    name: str | None = None) -> None:
        # ``name`` labels in-memory fanout gauges; the adapter's watch
        # threads deliver synchronously, so it's accepted and unused
        self._watchers.append(fn)

    # ---- URL plumbing ------------------------------------------------
    def _collection_url(self, kind: str, namespace: str | None) -> str:
        try:
            prefix, plural, namespaced = RESOURCES[kind]
        except KeyError:
            raise Invalid(f"kind {kind!r} has no REST mapping") from None
        if namespaced and namespace:
            return f"{self.base_url}/{prefix}/namespaces/{namespace}/{plural}"
        return f"{self.base_url}/{prefix}/{plural}"

    def _object_url(self, kind: str, name: str,
                    namespace: str | None) -> str:
        _, _, namespaced = RESOURCES.get(kind, (None, None, True))
        if namespaced and not namespace:
            raise Invalid(f"{kind}/{name}: namespaced kind requires "
                          "namespace")
        return f"{self._collection_url(kind, namespace)}/{name}"

    @staticmethod
    def _raise_for(resp, context: str):
        if resp.status_code == 404:
            raise NotFound(context)
        if resp.status_code == 409:
            body = resp.text
            if "AlreadyExists" in body or "already exists" in body:
                raise AlreadyExists(context + ": " + body[:200])
            raise Conflict(context + ": " + body[:200])
        if resp.status_code == 422 or resp.status_code == 400:
            raise Invalid(context + ": " + resp.text[:500])
        if not resp.ok:
            raise APIError(f"{context}: HTTP {resp.status_code} "
                           f"{resp.text[:500]}")

    # ---- verbs (the APIServer contract) ------------------------------
    def create(self, obj: dict) -> dict:
        kind = obj["kind"]
        self._throttle()
        resp = self._session.post(
            self._collection_url(kind, namespace_of(obj)), json=obj)
        self._raise_for(resp, f"create {kind}/{name_of(obj)}")
        out = resp.json()
        out.setdefault("kind", kind)
        self._cache_apply("ADDED", out)
        return out

    def create_many(self, objs: list[dict]) -> list[dict]:
        """Bulk create via ``POST <collection>?bulk=true`` — one HTTP
        round trip, one token debit, one server-side lock acquisition
        for the whole batch (all objects share one kind + namespace:
        the pods of a slice). Per-object failures come back as
        Status-shaped dicts at that object's index. Servers without
        the bulk verb (a real kube-apiserver) answer 404/405/400 —
        fall back to per-object creates with the same Status-dict
        failure shape, so callers are backend-agnostic."""
        if not objs:
            return []
        kind = objs[0]["kind"]
        self._throttle()
        resp = self._session.post(
            self._collection_url(kind, namespace_of(objs[0])),
            json={"items": objs}, params={"bulk": "true"})
        if resp.status_code in (400, 404, 405):
            return [self._create_one_status(o) for o in objs]
        self._raise_for(resp, f"bulk create {len(objs)} {kind}")
        out = []
        for item in resp.json().get("items", []):
            if (item or {}).get("kind") == "Status":
                out.append(item)
                continue
            item.setdefault("kind", kind)
            self._cache_apply("ADDED", item)
            out.append(item)
        return out

    def _create_one_status(self, obj: dict) -> dict:
        try:
            return self.create(obj)
        except APIError as e:
            return status_from_error(e)

    def get(self, kind: str, name: str,
            namespace: str | None = None) -> dict:
        if self._cache_serves(kind):
            obj = self.cache.get_ref(kind, name, namespace)
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            return fast_deepcopy(obj)
        self._throttle()
        resp = self._session.get(self._object_url(kind, name, namespace))
        self._raise_for(resp, f"{kind} {namespace}/{name} not found")
        return resp.json()

    def try_get(self, kind: str, name: str,
                namespace: str | None = None) -> dict | None:
        try:
            return self.get(kind, name, namespace)
        except NotFound:
            return None

    def list(self, kind: str, namespace: str | None = None,
             label_selector: dict | None = None) -> list[dict]:
        if self._cache_serves(kind):
            return [fast_deepcopy(o) for o in
                    self.cache.list_refs(kind, namespace, label_selector)]
        self._throttle()
        resp = self._session.get(
            self._collection_url(kind, namespace),
            params=_selector_param(label_selector))
        self._raise_for(resp, f"list {kind} in {namespace}")
        items = resp.json().get("items", [])
        for it in items:  # list responses omit kind/apiVersion per item
            it.setdefault("kind", kind)
        return items

    def scan(self, kind: str, namespace: str | None = None) -> list[dict]:
        """READ-ONLY ``list`` (store references, no copies) when the
        kind is cache-served; falls back to a live ``list`` otherwise.
        Same caller contract as the in-memory apiserver's ``scan``:
        never mutate the returned objects."""
        if self._cache_serves(kind):
            return self.cache.list_refs(kind, namespace)
        return self.list(kind, namespace)

    def update(self, obj: dict) -> dict:
        kind = obj["kind"]
        self._throttle()
        resp = self._session.put(
            self._object_url(kind, name_of(obj), namespace_of(obj)),
            json=obj)
        self._raise_for(resp, f"update {kind}/{name_of(obj)}")
        out = resp.json()
        out.setdefault("kind", kind)
        self._cache_apply("MODIFIED", out)
        return out

    def patch(self, kind: str, name: str, patch: dict,
              namespace: str | None = None) -> dict:
        self._throttle()
        resp = self._session.patch(
            self._object_url(kind, name, namespace), json=patch,
            headers={"Content-Type": "application/merge-patch+json"})
        self._raise_for(resp, f"patch {kind}/{name}")
        out = resp.json()
        out.setdefault("kind", kind)
        self._cache_apply("MODIFIED", out)
        return out

    def update_status(self, obj: dict) -> dict:
        kind = obj["kind"]
        url = self._object_url(kind, name_of(obj), namespace_of(obj)) \
            + "/status"
        self._throttle()
        resp = self._session.patch(
            url, json={"status": obj.get("status", {})},
            headers={"Content-Type": "application/merge-patch+json"})
        if resp.status_code == 404:
            # kinds without a status subresource: merge-patch the object
            return self.patch(kind, name_of(obj),
                              {"status": obj.get("status", {})},
                              namespace_of(obj))
        self._raise_for(resp, f"status {kind}/{name_of(obj)}")
        out = resp.json()
        out.setdefault("kind", kind)
        self._cache_apply("MODIFIED", out)
        return out

    def delete(self, kind: str, name: str,
               namespace: str | None = None) -> None:
        self._throttle()
        resp = self._session.delete(
            self._object_url(kind, name, namespace))
        self._raise_for(resp, f"delete {kind} {namespace}/{name}")
        # optimistic: a finalizer-bearing object isn't really gone;
        # its MODIFIED watch event (rv above the discard tombstone)
        # restores the cache entry within watch latency, and
        # level-triggered reconciles tolerate the brief miss (a
        # re-delete gets NotFound, a no-op)
        if self._cache_reads:
            self.cache.discard(kind, name, namespace)

    def ensure_namespace(self, namespace: str) -> dict:
        found = self.try_get("Namespace", namespace)
        if found is not None:
            return found
        return self.create({"apiVersion": "v1", "kind": "Namespace",
                            "metadata": {"name": namespace}})

    # ---- events ------------------------------------------------------
    def record_event(self, involved: dict, etype: str, reason: str,
                     message: str) -> dict:
        with self._event_lock:
            self._event_seq += 1
            seq = self._event_seq
        ns = namespace_of(involved) or "default"
        now = self.clock().isoformat()
        ev = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {"name": f"{name_of(involved)}.{seq:08x}",
                         "namespace": ns},
            "type": etype,
            "reason": reason,
            "message": message,
            "involvedObject": {
                "kind": involved["kind"],
                "name": name_of(involved),
                "namespace": ns,
                "uid": involved["metadata"].get("uid"),
            },
            "firstTimestamp": now,
            "lastTimestamp": now,
            "count": 1,
            "source": {"component": "kubeflow-rm-tpu"},
        }
        return self.create(ev)

    def events_for(self, involved: dict) -> list[dict]:
        ns = namespace_of(involved)
        if self._cache_serves("Event"):
            # involved-object index: the notebook controller re-emits
            # pod events every reconcile, and filtering the full Event
            # list per call made the storm O(notebooks × events)
            return [fast_deepcopy(e) for e in self.cache.events_for_ref(
                involved["kind"], name_of(involved), ns)]
        return [
            e for e in self.list("Event", ns)
            if (e.get("involvedObject") or {}).get("name")
            == name_of(involved)
            and (e.get("involvedObject") or {}).get("kind")
            == involved["kind"]
        ]

    def pod_logs(self, namespace: str, pod_name: str,
                 tail_lines: int | None = None) -> str:
        """``GET .../pods/<name>/log`` (the verb behind `kubectl logs`)."""
        params = {}
        if tail_lines is not None:
            params["tailLines"] = str(tail_lines)
        self._throttle()
        resp = self._session.get(
            self._object_url("Pod", pod_name, namespace) + "/log",
            params=params)
        self._raise_for(resp, f"logs {namespace}/{pod_name}")
        return resp.text

    # ---- SubjectAccessReview -----------------------------------------
    def access_review(self, user: str | None, verb: str, resource: str,
                      namespace: str | None = None) -> bool:
        if user is None:
            return False
        body = {
            "apiVersion": "authorization.k8s.io/v1",
            "kind": "SubjectAccessReview",
            "spec": {
                "user": user,
                "resourceAttributes": {
                    "verb": verb,
                    "resource": resource,
                    **({"namespace": namespace} if namespace else {}),
                },
            },
        }
        self._throttle()
        resp = self._session.post(
            f"{self.base_url}/apis/authorization.k8s.io/v1/"
            "subjectaccessreviews", json=body)
        self._raise_for(resp, f"subjectaccessreview {user} {verb} "
                              f"{resource}")
        return bool(resp.json().get("status", {}).get("allowed"))

    # ---- watch loop (the informer) -----------------------------------
    def watch_kind(self, kind: str, namespace: str | None = None,
                   stop: threading.Event | None = None,
                   timeout_s: int = 300) -> None:
        """List+watch one kind forever (until ``stop``), fanning events
        into the registered watchers. Run one thread per kind — the
        controller manager entrypoint does."""
        stop = stop or threading.Event()
        rv: str | None = None
        while not stop.is_set():
            try:
                if rv is None:
                    rv = self._initial_list(kind, namespace)
                # resume from the last seen rv on stream restart — the
                # server's backlog replays anything that landed in the
                # gap, so no event (crucially DELETEDs, which a relist
                # cannot re-synthesize) is ever lost; a 410 Gone (rv
                # below the backlog horizon) falls back to a relist
                rv = self._stream(kind, namespace, rv, stop, timeout_s)
            except (NotFound, Invalid):
                raise  # misconfigured kind: crash loudly
            except _WatchExpired as e:
                log.info("watch %s: %s; relisting", kind, e)
                rv = None
            except Exception as e:
                log.warning("watch %s: %s; retrying in 2s", kind, e)
                stop.wait(2.0)

    def _list_raw(self, kind: str,
                  namespace: str | None) -> tuple[list[dict], str]:
        """One live list: (items, collection resourceVersion). The
        shard router lists each shard through this and merges."""
        resp = self._session.get(self._collection_url(kind, namespace))
        self._raise_for(resp, f"list {kind}")
        body = resp.json()
        items = body.get("items", [])
        for item in items:
            item.setdefault("kind", kind)
        return items, body.get("metadata", {}).get("resourceVersion", "")

    def _initial_list(self, kind: str, namespace: str | None) -> str:
        items, rv = self._list_raw(kind, namespace)
        if self._cache_reads and namespace is None:
            # (re)list replaces the kind's store contents — objects
            # deleted while the watch was down drop out, entries newer
            # than the snapshot survive (ObjectStore.replace's horizon
            # merge) — and marks the kind cache-served from here on
            self.cache.replace(kind, items)
            from kubeflow_rm_tpu.controlplane import metrics
            metrics.INFORMER_SYNCED_KINDS.set(
                len(self.cache.synced_kinds()))
        for item in items:
            self._fan("ADDED", item)
        return rv

    def _stream(self, kind: str, namespace: str | None, rv: str,
                stop: threading.Event, timeout_s: int,
                fan: Callable[[str, dict], None] | None = None) -> str:
        """One watch stream; returns the last resourceVersion seen so
        the next stream resumes without a relist (informer resume).
        ``fan`` overrides event delivery (the shard router injects its
        merged-subscription fan)."""
        params = {"watch": "true",
                  "timeoutSeconds": str(timeout_s),
                  "allowWatchBookmarks": "true"}
        if rv:
            params["resourceVersion"] = rv
        resp = self._session.get(
            self._collection_url(kind, namespace), params=params,
            stream=True, timeout=timeout_s + 10)
        if resp.status_code == 410:
            # a real apiserver can reject the resume rv with a direct
            # HTTP 410 after compaction (client-go handles both forms)
            raise _WatchExpired(f"HTTP 410 resuming {kind} at rv {rv}")
        self._raise_for(resp, f"watch {kind}")
        last_rv = rv
        for line in resp.iter_lines():
            if stop.is_set():
                resp.close()
                return last_rv
            if not line:
                continue
            evt = json.loads(line)
            etype, obj = evt.get("type"), evt.get("object") or {}
            if etype == "BOOKMARK":
                continue
            if etype == "ERROR":  # expired rv -> relist
                raise _WatchExpired(str(obj.get("message") or obj))
            obj.setdefault("kind", kind)
            seen = (obj.get("metadata") or {}).get("resourceVersion")
            if seen:
                last_rv = seen
            (fan or self._fan)(etype, obj)
        return last_rv

    def _fan(self, etype: str, obj: dict) -> None:
        if self._cache_reads:
            self._cache_apply(etype, obj)
            from kubeflow_rm_tpu.controlplane import metrics
            kind = obj.get("kind")
            if kind:
                metrics.INFORMER_EVENTS_TOTAL.labels(kind=kind).inc()
            metrics.INFORMER_LAST_EVENT_TIMESTAMP.set(time.time())
        for w in list(self._watchers):
            try:
                w(etype, obj, None)
            except Exception:
                log.exception("watcher failed on %s %s", etype,
                              obj.get("kind"))


def strategic_patch_for(current: dict, desired: dict) -> dict:
    """Helper for callers migrating from in-memory ``patch`` semantics:
    the in-memory server applies ``strategic_merge`` locally; against a
    real apiserver we send merge-patch, which matches for the object
    shapes this platform writes (maps + whole-list replacement)."""
    return strategic_merge(current, desired)


# ---- shard-aware router ----------------------------------------------
# kinds replicated to EVERY shard instead of hashed: cluster-wide RBAC
# must be visible to whichever shard evaluates a SubjectAccessReview
# for its namespaces, and CRDs describe the schema every shard serves
BROADCAST_KINDS = frozenset(
    {"ClusterRole", "ClusterRoleBinding", "CustomResourceDefinition"})

def _is_transient(e: Exception) -> bool:
    # transport-level failures worth a routed retry: the shard is
    # restarting (connection refused while it replays its WAL) or just
    # restarted (every pooled keep-alive socket reset at once)
    import http.client
    return isinstance(e, (http.client.HTTPException, OSError)) \
        and not isinstance(e, Invalid)


class ShardedKubeAPIServer:
    """One ``KubeAPIServer``-shaped client over N apiserver shards.

    Routing: a namespaced object's NAMESPACE (a cluster-scoped
    object's name) hashes onto the consistent ring — one shard owns
    every object of a namespace, so per-object rv ordering, Conflict
    semantics, quota, and the profile→namespace→children chain all
    stay single-shard properties. ``BROADCAST_KINDS`` replicate to all
    shards. Cluster-wide lists fan out and merge.

    Retry-with-remap: a write hitting a restarting shard retries with
    backoff inside ``retry_window_s``, re-resolving the ring each
    attempt (the pooled stale sockets are dropped by ``_FastSession``'s
    fresh-dial retry; the window covers WAL replay + rebind time).

    Watch aggregation: ``watch_kind`` runs one list+stream loop PER
    SHARD and merges events into one subscription feeding the
    router-level ``ObjectStore`` and the registered watchers. Each
    shard's resourceVersion sequence is tracked independently (per-
    shard rv bookkeeping) — no global ordering exists or is claimed;
    per-OBJECT ordering holds because an object lives on exactly one
    shard. A shard's stream death falls back to a per-shard relist
    that synthesizes DELETEDs for that shard's vanished objects only.
    """

    def __init__(self, shard_urls: dict[str, str], *,
                 identity: str | None = None,
                 qps: float | None = None, burst: int | None = None,
                 retry_window_s: float = 10.0,
                 clock: Callable[[], datetime.datetime] | None = None):
        from kubeflow_rm_tpu.controlplane import metrics
        from kubeflow_rm_tpu.controlplane.cache.store import ObjectStore
        from kubeflow_rm_tpu.controlplane.shard.ring import HashRing
        if not shard_urls:
            raise Invalid("ShardedKubeAPIServer needs >= 1 shard url")
        self.shard_urls = dict(shard_urls)
        self.ring = HashRing(list(self.shard_urls))
        self.retry_window_s = retry_window_s
        self.identity = identity
        self._qps, self._burst = qps, burst
        self.clock = clock or (
            lambda: datetime.datetime.now(datetime.timezone.utc))
        # per-shard clients: caches OFF — the router owns the one
        # merged informer store; double-caching would double memory
        # and split rv bookkeeping
        self._clients = {
            name: KubeAPIServer(url, identity=identity, qps=qps,
                                burst=burst, cache_reads=False)
            for name, url in self.shard_urls.items()}
        self.limiter = None
        self._cache_reads = True
        # elastic handoff: predicate over partition keys whose writes
        # are held during the fence-drain-flip window, and the active
        # watch subscriptions (so a topology change can extend them to
        # an added shard)
        self._fence_pred: Callable[[str], bool] | None = None
        self._fence_clear = threading.Event()
        self._fence_clear.set()
        self._watch_specs: list[tuple] = []
        self.cache = ObjectStore(cluster_scoped={
            k for k, (_, _, namespaced) in RESOURCES.items()
            if not namespaced})
        self._watchers: list[Callable[[str, dict, dict | None], None]] = []
        # kind -> set of shards whose initial list completed (the
        # router cache serves a kind once EVERY shard has listed it)
        self._listed: dict[str, set[str]] = {}
        self._listed_lock = make_lock("kubeclient.router_listed")
        metrics.SHARD_RING_MEMBERS.labels(
            shard=metrics.shard_label()).set(len(self.ring))

    # ---- routing -----------------------------------------------------
    @staticmethod
    def _partition_key(kind: str, name: str | None,
                       namespace: str | None) -> str:
        _, _, namespaced = RESOURCES.get(kind, (None, None, True))
        return (namespace if namespaced else name) or ""

    def _client_for(self, kind: str, name: str | None,
                    namespace: str | None) -> "KubeAPIServer":
        key = self._partition_key(kind, name, namespace)
        return self._clients[self.ring.shard_for(key)]

    def shard_of(self, kind: str, name: str | None,
                 namespace: str | None) -> str:
        return self.ring.shard_for(
            self._partition_key(kind, name, namespace))

    # ---- elastic topology (split / merge / pinned migration) ---------
    def fence(self, predicate: Callable[[str], bool]) -> None:
        """Hold writes whose partition key satisfies ``predicate`` (the
        handoff coordinator passes "ownership changes between the old
        and new ring" — a predicate, not a key list, so a namespace
        CREATED during the fence window is held too). The coordinator
        fences the moving range, drains the donor's last WAL tail into
        the recipient, flips the ring, then unfences — an in-flight
        client write lands EITHER before the drain (donor WAL, carried
        by the drain) or after the flip (recipient), never in between.
        Fenced callers wait inside their normal retry window; reads
        served from the merged informer cache are unaffected."""
        self._fence_clear.clear()
        self._fence_pred = predicate

    def unfence(self) -> None:
        self._fence_pred = None
        self._fence_clear.set()

    def set_topology(self, shard_urls: dict[str, str], *,
                     pins: dict[str, str] | None = None) -> None:
        """Atomically adopt a new shard set (and pin map): rebuild the
        ring, keep surviving shards' clients (their pooled sockets and
        per-shard rv bookkeeping stay valid — ports never change),
        build clients for added shards, drop retired ones, and extend
        every active watch subscription to the added shards. Callers
        (the elastic coordinator) flip only AFTER the moving range is
        copied + drained, so routing and data never disagree."""
        from kubeflow_rm_tpu.controlplane import metrics
        from kubeflow_rm_tpu.controlplane.shard.ring import HashRing
        new_urls = dict(shard_urls)
        if not new_urls:
            raise Invalid("set_topology needs >= 1 shard url")
        new_ring = HashRing(list(new_urls), pins=pins)
        added = [n for n in new_urls if n not in self._clients]
        removed = [n for n in self._clients if n not in new_urls]
        clients = dict(self._clients)
        for name in removed:
            clients.pop(name)
        for name in added:
            clients[name] = KubeAPIServer(
                new_urls[name], identity=self.identity, qps=self._qps,
                burst=self._burst, cache_reads=False)
        # one assignment each: every in-flight ``_routed`` attempt
        # resolves against either the old or the new topology — both
        # route correctly for unmoved keys, and moved keys are fenced
        self.shard_urls = new_urls
        self.ring = new_ring
        self._clients = clients
        with self._listed_lock:
            for listed in self._listed.values():
                for name in removed:
                    listed.discard(name)
        # a retired shard's _watch_shard loops notice their name left
        # ``_clients`` and exit; added shards need fresh loops for
        # every live subscription
        for kind, namespace, stop, timeout_s in list(self._watch_specs):
            if stop.is_set():
                continue
            for shard in added:
                threading.Thread(
                    target=self._watch_shard, daemon=True,
                    name=f"router-watch-{kind}-{shard}",
                    args=(shard, kind, namespace, stop,
                          timeout_s)).start()
        metrics.SHARD_RING_MEMBERS.labels(
            shard=metrics.shard_label()).set(len(self.ring))

    def _routed(self, kind: str, name: str | None,
                namespace: str | None, fn: Callable, *,
                lost_reply: dict | None = None):
        """Run ``fn(client)`` against the owning shard, retrying with
        remap on transport failures inside the retry window (a
        restarting shard refuses connections while it replays its
        WAL; it rejoins the ring at the same position).

        ``lost_reply`` maps APIError types to ``handler(client)`` for
        the at-least-once ambiguity: a crashed shard may have
        COMMITTED the verb to its WAL with the reply lost in flight,
        so the retry's AlreadyExists (create) or NotFound (delete) IS
        success. Only consulted after a transport retry — a
        first-attempt conflict is a genuine caller error."""
        deadline = time.monotonic() + self.retry_window_s
        delay = 0.1
        retried = False
        while True:
            pred = self._fence_pred
            if pred is not None and pred(
                    self._partition_key(kind, name, namespace)):
                # handoff fence: this key's range is mid-flip; wait it
                # out (the coordinator unfences within its drain
                # budget) and then resolve against the NEW ring
                self._fence_clear.wait(self.retry_window_s)
            client = self._client_for(kind, name, namespace)
            try:
                return fn(client)
            except APIError as e:
                if retried and lost_reply:
                    for etype, handler in lost_reply.items():
                        if isinstance(e, etype):
                            log.debug(
                                "%s after shard retry: treating as "
                                "lost reply of a committed %s", type(e).
                                __name__, kind)
                            return handler(client)
                raise
            except Exception as e:
                if not _is_transient(e) or time.monotonic() > deadline:
                    raise
                log.debug("shard %s unreachable (%s); retrying",
                          self.shard_of(kind, name, namespace), e)
                time.sleep(delay)
                delay = min(delay * 2, 1.0)
                retried = True

    # ---- wiring ------------------------------------------------------
    def register_admission(self, kind_pattern: str, fn: Callable) -> None:
        log.debug("admission for %s runs inside each shard", kind_pattern)

    def register_validator(self, kind: str, fn: Callable) -> None:
        log.debug("validation for %s runs inside each shard", kind)

    def add_watcher(self, fn: Callable[[str, dict, dict | None], None],
                    name: str | None = None) -> None:
        self._watchers.append(fn)

    def wait_for_sync(self, kinds, timeout: float | None = None) -> bool:
        return self.cache.wait_for_sync(kinds, timeout)

    def _cache_serves(self, kind: str) -> bool:
        return self.cache.is_synced(kind)

    # ---- verbs -------------------------------------------------------
    def create(self, obj: dict) -> dict:
        kind = obj["kind"]
        if kind in BROADCAST_KINDS:
            out = None
            for client in self._clients.values():
                try:
                    res = client.create(obj)
                except AlreadyExists:
                    res = client.get(kind, name_of(obj))
                out = out or res
            self.cache.apply("ADDED", out)
            return out
        out = self._routed(
            kind, name_of(obj), namespace_of(obj),
            lambda c: c.create(obj),
            lost_reply={AlreadyExists: lambda c: c.get(
                kind, name_of(obj), namespace_of(obj))})
        self.cache.apply("ADDED", out)
        return out

    def create_many(self, objs: list[dict]) -> list[dict]:
        if not objs:
            return []
        kind = objs[0]["kind"]
        # one bulk POST per namespace (the collection URL carries the
        # namespace); each namespace lives wholly on one shard
        by_ns: dict[str | None, list[int]] = {}
        for i, o in enumerate(objs):
            by_ns.setdefault(namespace_of(o), []).append(i)
        results: list = [None] * len(objs)
        for _ns, idxs in by_ns.items():
            batch = [objs[i] for i in idxs]

            def one_by_one(c, b=batch):
                # lost-reply replay of a bulk POST: re-create each
                # object individually, absorbing the ones that landed
                outs = []
                for o in b:
                    try:
                        outs.append(c.create(o))
                    except AlreadyExists:
                        outs.append(c.get(kind, name_of(o),
                                          namespace_of(o)))
                return outs

            outs = self._routed(
                kind, name_of(batch[0]), namespace_of(batch[0]),
                lambda c, b=batch: c.create_many(b),
                lost_reply={AlreadyExists: one_by_one})
            for i, out in zip(idxs, outs):
                results[i] = out
                if not (out or {}).get("kind") == "Status":
                    self.cache.apply("ADDED", out)
        return results

    def get(self, kind: str, name: str,
            namespace: str | None = None) -> dict:
        if self._cache_serves(kind):
            obj = self.cache.get_ref(kind, name, namespace)
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            return fast_deepcopy(obj)
        return self._routed(kind, name, namespace,
                            lambda c: c.get(kind, name, namespace))

    def try_get(self, kind: str, name: str,
                namespace: str | None = None) -> dict | None:
        try:
            return self.get(kind, name, namespace)
        except NotFound:
            return None

    def list(self, kind: str, namespace: str | None = None,
             label_selector: dict | None = None) -> list[dict]:
        if self._cache_serves(kind):
            return [fast_deepcopy(o) for o in
                    self.cache.list_refs(kind, namespace, label_selector)]
        _, _, namespaced = RESOURCES.get(kind, (None, None, True))
        if namespaced and namespace is not None:
            return self._routed(
                kind, None, namespace,
                lambda c: c.list(kind, namespace, label_selector))
        # cluster-wide list: fan out and merge, deduping the broadcast
        # and cluster-scoped kinds by name (every shard holds a copy
        # of e.g. the "kubeflow" Namespace it needs locally)
        merged: dict[tuple, dict] = {}
        for client in self._clients.values():
            for o in client.list(kind, namespace, label_selector):
                merged.setdefault(
                    (namespace_of(o), name_of(o)), o)
        out = list(merged.values())
        out.sort(key=lambda o: (namespace_of(o) or "", name_of(o)))
        return out

    def scan(self, kind: str, namespace: str | None = None) -> list[dict]:
        if self._cache_serves(kind):
            return self.cache.list_refs(kind, namespace)
        return self.list(kind, namespace)

    def update(self, obj: dict) -> dict:
        kind = obj["kind"]
        out = self._routed(kind, name_of(obj), namespace_of(obj),
                           lambda c: c.update(obj))
        self.cache.apply("MODIFIED", out)
        return out

    def patch(self, kind: str, name: str, patch: dict,
              namespace: str | None = None) -> dict:
        out = self._routed(kind, name, namespace,
                           lambda c: c.patch(kind, name, patch, namespace))
        self.cache.apply("MODIFIED", out)
        return out

    def update_status(self, obj: dict) -> dict:
        out = self._routed(obj["kind"], name_of(obj), namespace_of(obj),
                           lambda c: c.update_status(obj))
        self.cache.apply("MODIFIED", out)
        return out

    def delete(self, kind: str, name: str,
               namespace: str | None = None) -> None:
        if kind in BROADCAST_KINDS:
            for client in self._clients.values():
                try:
                    client.delete(kind, name, namespace)
                except NotFound:
                    pass
        else:
            self._routed(kind, name, namespace,
                         lambda c: c.delete(kind, name, namespace),
                         lost_reply={NotFound: lambda c: None})
        self.cache.discard(kind, name, namespace)

    def ensure_namespace(self, namespace: str) -> dict:
        return self._routed(
            "Namespace", namespace, None,
            lambda c: c.ensure_namespace(namespace))

    def record_event(self, involved: dict, etype: str, reason: str,
                     message: str) -> dict:
        ns = namespace_of(involved) or "default"
        return self._routed(
            "Event", None, ns,
            lambda c: c.record_event(involved, etype, reason, message))

    def events_for(self, involved: dict) -> list[dict]:
        ns = namespace_of(involved)
        if self._cache_serves("Event"):
            return [fast_deepcopy(e) for e in self.cache.events_for_ref(
                involved["kind"], name_of(involved), ns)]
        return self._routed("Event", None, ns or "default",
                            lambda c: c.events_for(involved))

    def pod_logs(self, namespace: str, pod_name: str,
                 tail_lines: int | None = None) -> str:
        return self._routed(
            "Pod", pod_name, namespace,
            lambda c: c.pod_logs(namespace, pod_name, tail_lines))

    def access_review(self, user: str | None, verb: str, resource: str,
                      namespace: str | None = None) -> bool:
        return self._routed(
            "Namespace" if namespace is None else "Pod",
            namespace or "", namespace,
            lambda c: c.access_review(user, verb, resource, namespace))

    # ---- cross-shard watch aggregation -------------------------------
    def watch_kind(self, kind: str, namespace: str | None = None,
                   stop: threading.Event | None = None,
                   timeout_s: int = 300) -> None:
        """Merged subscription: one list+stream loop per shard, all
        feeding the router store + watchers. Blocks until ``stop``."""
        stop = stop or threading.Event()
        self._watch_specs.append((kind, namespace, stop, timeout_s))
        threads = [
            threading.Thread(
                target=self._watch_shard, daemon=True,
                name=f"router-watch-{kind}-{shard}",
                args=(shard, kind, namespace, stop, timeout_s))
            for shard in self._clients]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # topology changes spawn extra per-shard loops bound to the
        # same stop event; they exit with it (daemon threads)

    def _watch_shard(self, shard: str, kind: str,
                     namespace: str | None, stop: threading.Event,
                     timeout_s: int) -> None:
        fan = self._shard_fan(shard)
        rv: str | None = None
        while not stop.is_set():
            client = self._clients.get(shard)
            if client is None:
                return  # shard retired by a merge: subscription over
            try:
                if rv is None:
                    items, rv = client._list_raw(kind, namespace)
                    self._merge_shard_list(shard, kind, items)
                rv = client._stream(kind, namespace, rv, stop,
                                    timeout_s, fan=fan)
            except (NotFound, Invalid):
                raise  # misconfigured kind: crash loudly
            except _WatchExpired as e:
                log.info("watch %s@%s: %s; relisting", kind, shard, e)
                rv = None
            except Exception as e:
                # shard down (restarting): relist once it's back so
                # deletes that raced the outage aren't missed
                log.debug("watch %s@%s: %s; retrying", kind, shard, e)
                rv = None
                stop.wait(1.0)

    def _merge_shard_list(self, shard: str, kind: str,
                          items: list[dict]) -> None:
        """Fold one shard's (re)list into the merged store: upsert
        everything listed (rv-guarded), synthesize DELETED for THIS
        shard's entries that vanished while its watch was down, and
        mark the kind synced once every shard has listed."""
        present = set()
        for item in items:
            present.add(self.cache.key_for(
                kind, name_of(item), namespace_of(item)))
        stale = [
            ref for ref in self.cache.list_refs(kind)
            if self.cache.key_for(kind, name_of(ref), namespace_of(ref))
            not in present
            and kind not in BROADCAST_KINDS
            and self.shard_of(kind, name_of(ref),
                              namespace_of(ref)) == shard]
        fan = self._shard_fan(shard)
        for ref in stale:
            fan("DELETED", fast_deepcopy(ref))
        for item in items:
            fan("ADDED", item)
        with self._listed_lock:
            listed = self._listed.setdefault(kind, set())
            listed.add(shard)
            if listed >= set(self._clients):
                self.cache.mark_synced(kind)

    def _shard_fan(self, shard: str) -> Callable[[str, dict], None]:
        def fan(etype: str, obj: dict) -> None:
            from kubeflow_rm_tpu.controlplane import metrics
            kind_f = obj.get("kind")
            if shard not in self._clients:
                return  # retired by a merge: its tail of events is void
            if kind_f and kind_f not in BROADCAST_KINDS and \
                    self.shard_of(kind_f, name_of(obj),
                                  namespace_of(obj)) != shard:
                # ownership filter: after an elastic flip the donor
                # still holds (and may relist, update, or GC-delete)
                # stale copies of moved objects — events about a key
                # from a shard that no longer owns it must not touch
                # the merged cache, or a moved object could be
                # resurrected or deleted out from under its new owner
                return
            self.cache.apply(etype, obj)
            kind = obj.get("kind")
            if kind:
                metrics.INFORMER_EVENTS_TOTAL.labels(kind=kind).inc()
            metrics.INFORMER_LAST_EVENT_TIMESTAMP.set(time.time())
            for w in list(self._watchers):
                try:
                    w(etype, obj, None)
                except Exception:
                    log.exception("router watcher failed on %s %s",
                                  etype, kind)
        return fan
