"""HTTPS admission server: AdmissionReview v1 in, JSONPatch out.

The reference runs two admission processes — the PodDefault webhook's
plain net/http server (``admission-webhook/main.go:755-773``) and the
ODH notebook webhook inside controller-runtime's webhook server
(``odh-notebook-controller/main.go:107-150``). This server wraps the
SAME three webhook classes the in-memory apiserver chains
(``webhook/notebook.py``, ``webhook/poddefault.py``,
``webhook/tpu_inject.py``) behind kube's AdmissionReview v1 protocol:

- ``POST /mutate-notebook`` — NotebookWebhook (lock/image/CA/oauth +
  no-restart guard)
- ``POST /mutate-pod``      — PodDefaultWebhook then TpuInjectWebhook,
  in that order (PodDefault merge first, so TPU rendezvous env wins
  conflicts — the same order ``make_control_plane`` registers them)
- ``POST /convert``         — apiextensions ConversionReview for the
  multi-version Notebook CRD (``api/conversion.py``; the reference's
  ``api/*/notebook_conversion.go`` equivalents)

The mutation is returned as an RFC 6902 JSONPatch computed by diffing
the incoming object against the webhook chain's output, exactly how
controller-runtime's admission.PatchResponse works. ``AdmissionDenied``
becomes ``allowed: false`` with the message in ``status``.

TLS: pass ``certfile``/``keyfile`` (mounted from the webhook Secret in
the manifests); without them the server is plain HTTP for tests.
"""

from __future__ import annotations

import base64
import copy
import http.server
import json
import logging
import ssl
import threading

from kubeflow_rm_tpu.controlplane.apiserver import AdmissionDenied

log = logging.getLogger("kubeflow_rm_tpu.webhook")


# ---- RFC 6902 diff ---------------------------------------------------

def _escape(token: str) -> str:
    return token.replace("~", "~0").replace("/", "~1")


def json_patch(old, new, path: str = "") -> list[dict]:
    """Minimal JSONPatch diff: replace/add/remove on dict keys, whole-
    value replace on list or scalar changes. Lists are replaced
    wholesale — admission mutations append containers/env/volumes, and
    whole-list replace is both correct and what kube applies
    atomically."""
    if type(old) is not type(new):
        return [{"op": "replace", "path": path or "/", "value": new}]
    if isinstance(old, dict):
        ops: list[dict] = []
        for k in old:
            if k not in new:
                ops.append({"op": "remove",
                            "path": f"{path}/{_escape(k)}"})
            elif old[k] != new[k]:
                ops.extend(json_patch(old[k], new[k],
                                      f"{path}/{_escape(k)}"))
        for k in new:
            if k not in old:
                ops.append({"op": "add", "path": f"{path}/{_escape(k)}",
                            "value": new[k]})
        return ops
    if old != new:
        return [{"op": "replace", "path": path or "/", "value": new}]
    return []


# ---- AdmissionReview handling ----------------------------------------

class AdmissionHandler:
    """One path -> ordered chain of webhook callables
    (``fn(op, obj, old) -> mutated | None``)."""

    def __init__(self, chains: dict[str, list]):
        self.chains = chains

    def review(self, path: str, review: dict) -> dict:
        request = review.get("request") or {}
        uid = request.get("uid", "")
        op = request.get("operation", "CREATE")
        obj = request.get("object") or {}
        old = request.get("oldObject") or None
        response: dict = {"uid": uid, "allowed": True}
        try:
            mutated = copy.deepcopy(obj)
            for hook in self.chains.get(path, []):
                out = hook(op, mutated, old)
                if out is not None:
                    mutated = out
            ops = json_patch(obj, mutated)
            if ops:
                response["patchType"] = "JSONPatch"
                response["patch"] = base64.b64encode(
                    json.dumps(ops).encode()).decode()
        except AdmissionDenied as e:
            response["allowed"] = False
            response["status"] = {"code": 403, "message": str(e)}
        except Exception as e:  # fail closed, surface the reason
            log.exception("webhook %s failed", path)
            response["allowed"] = False
            response["status"] = {"code": 500, "message": str(e)}
        return {"apiVersion": "admission.k8s.io/v1",
                "kind": "AdmissionReview", "response": response}


class WebhookServer:
    """Serves AdmissionHandler over HTTP(S) with /healthz + /readyz."""

    def __init__(self, handler: AdmissionHandler, *, port: int = 8443,
                 certfile: str | None = None, keyfile: str | None = None):
        self.handler = handler
        self.port = port
        self.certfile, self.keyfile = certfile, keyfile
        self._httpd: http.server.ThreadingHTTPServer | None = None

    def start(self) -> int:
        """Bind and serve in a daemon thread; returns the bound port."""
        handler = self.handler

        class H(http.server.BaseHTTPRequestHandler):
            def _send(self, code: int, body: dict):
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802
                if self.path in ("/healthz", "/readyz"):
                    self._send(200, {"ok": True})
                else:
                    self._send(404, {"error": "not found"})

            def do_POST(self):  # noqa: N802
                length = int(self.headers.get("Content-Length", "0"))
                try:
                    review = json.loads(self.rfile.read(length))
                except Exception:
                    log.warning("rejecting undecodable AdmissionReview",
                                exc_info=True)
                    self._send(400, {"error": "bad AdmissionReview"})
                    return
                if self.path == "/convert":
                    # apiextensions ConversionReview (multi-version
                    # CRDs; strategy: Webhook in the Notebook CRD)
                    from kubeflow_rm_tpu.controlplane.api.conversion import (
                        convert_review,
                    )
                    self._send(200, convert_review(review))
                    return
                if self.path not in handler.chains:
                    self._send(404, {"error": f"no webhook at "
                                              f"{self.path}"})
                    return
                self._send(200, handler.review(self.path, review))

            def log_message(self, *a):  # quiet
                pass

        self._httpd = http.server.ThreadingHTTPServer(
            ("0.0.0.0", self.port), H)
        if self.certfile:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(self.certfile, self.keyfile)
            self._httpd.socket = ctx.wrap_socket(
                self._httpd.socket, server_side=True)
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        return self._httpd.server_address[1]

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()


def make_admission_handler(api) -> AdmissionHandler:
    """The production chain wiring (same order as
    ``make_control_plane``): Notebook mutations on /mutate-notebook,
    Pod mutations on /mutate-pod."""
    from kubeflow_rm_tpu.controlplane.webhook.notebook import (
        NotebookWebhook,
    )
    from kubeflow_rm_tpu.controlplane.webhook.poddefault import (
        PodDefaultWebhook,
    )
    from kubeflow_rm_tpu.controlplane.webhook.tpu_inject import (
        TpuInjectWebhook,
    )
    return AdmissionHandler({
        "/mutate-notebook": [NotebookWebhook(api)],
        "/mutate-pod": [PodDefaultWebhook(api), TpuInjectWebhook(api)],
    })
