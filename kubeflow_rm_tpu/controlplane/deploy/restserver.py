"""Kube-style REST facade over the in-memory ``APIServer``.

Serves the in-memory apiserver's store on real HTTP with the Kubernetes
path/verb/status-code conventions — ``/api/v1/...`` and
``/apis/<group>/<version>/...`` collections, merge-patch, the status
subresource, label selectors, SubjectAccessReview, and streaming
``?watch=true``. Three jobs:

1. Round-trip testing of ``deploy.kubeclient.KubeAPIServer``: the
   adapter is exercised against real kube REST semantics with no
   cluster (the role envtest plays in the reference —
   ``suite_test.go:50-110``).
2. Wall-clock conformance: web apps, webhook server and the controller
   manager run as real processes/threads against this server over
   sockets, so provisioning p50 is measured in wall time (BASELINE.json
   primary metric), not reconcile counts.
3. A fake-cluster e2e harness for CI without KinD credentials.

Admission/validation run INSIDE the wrapped APIServer (its registered
chains), so writes through this facade behave like a cluster whose
webhooks are installed — or construct the APIServer bare and register
nothing to model a cluster with no webhooks.
"""

from __future__ import annotations

import json
import logging
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from kubeflow_rm_tpu.controlplane.api.conversion import (
    CONVERTERS,
    GROUP,
    SERVED_VERSIONS,
    STORAGE_VERSION,
)
from kubeflow_rm_tpu.controlplane.apiserver import (
    AdmissionDenied,
    AlreadyExists,
    APIServer,
    Conflict,
    Invalid,
    NotFound,
    is_status,
)
from kubeflow_rm_tpu.controlplane.deploy.kubeclient import RESOURCES
from kubeflow_rm_tpu.controlplane import tracing
from kubeflow_rm_tpu.analysis.lockgraph import make_lock

log = logging.getLogger("kubeflow_rm_tpu.restserver")

# plural -> kind (reverse of the adapter's table, so both sides agree)
PLURALS: dict[str, str] = {
    plural: kind for kind, (_, plural, _ns) in RESOURCES.items()
}


def _status(code: int, reason: str, message: str) -> dict:
    return {"apiVersion": "v1", "kind": "Status", "status": "Failure",
            "code": code, "reason": reason, "message": message}


def _split_selector(raw: str) -> list[str]:
    """Split on commas OUTSIDE parentheses — ``k in (a,b),x=y`` is two
    requirements, not three."""
    parts, depth, start = [], 0, 0
    for i, ch in enumerate(raw):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth = max(0, depth - 1)
        elif ch == "," and depth == 0:
            parts.append(raw[start:i])
            start = i + 1
    parts.append(raw[start:])
    return [p.strip() for p in parts if p.strip()]


def _selector_from(params: dict) -> dict | None:
    """Parse a kube labelSelector query string into the structured
    LabelSelector ``matches_selector`` evaluates. Full requirement
    grammar: ``k=v``/``k==v`` (equality), ``k!=v`` (NotIn — previously
    misparsed as an equality match on the key ``k!``), bare ``k``
    (Exists), ``!k`` (DoesNotExist), ``k in (a,b)`` / ``k notin (a,b)``
    (set forms)."""
    raw = params.get("labelSelector", [None])[0]
    if not raw:
        return None
    pairs: dict[str, str] = {}
    exprs: list[dict] = []
    for part in _split_selector(raw):
        low = part.lower()
        if " notin (" in low:
            idx = low.index(" notin (")
            vals = part[idx + len(" notin ("):].rstrip(")").strip()
            exprs.append({"key": part[:idx].strip(), "operator": "NotIn",
                          "values": [v.strip() for v in vals.split(",")
                                     if v.strip()]})
        elif " in (" in low:
            idx = low.index(" in (")
            vals = part[idx + len(" in ("):].rstrip(")").strip()
            exprs.append({"key": part[:idx].strip(), "operator": "In",
                          "values": [v.strip() for v in vals.split(",")
                                     if v.strip()]})
        elif "!=" in part:
            k, _, v = part.partition("!=")
            exprs.append({"key": k.strip(), "operator": "NotIn",
                          "values": [v.strip()]})
        elif "=" in part:
            k, _, v = part.partition("==" if "==" in part else "=")
            pairs[k.strip()] = v.strip()
        elif part.startswith("!"):
            exprs.append({"key": part[1:].strip(),
                          "operator": "DoesNotExist"})
        else:
            exprs.append({"key": part, "operator": "Exists"})
    out: dict = {}
    if pairs:
        out["matchLabels"] = pairs
    if exprs:
        out["matchExpressions"] = exprs
    return out or None


class _Route:
    """Parsed collection/object path."""

    def __init__(self, kind: str, namespace: str | None,
                 name: str | None, subresource: str | None,
                 version: str | None = None):
        self.kind, self.namespace = kind, namespace
        self.name, self.subresource = name, subresource
        # the API version the CLIENT asked for — multi-version kinds
        # (conversion.CONVERTERS) are converted at this boundary, the
        # way a real apiserver converts storage-version objects to the
        # request's version
        self.version = version


def _parse_path(path: str) -> _Route | None:
    parts = [p for p in path.split("/") if p]
    # /api/v1/... or /apis/<group>/<version>/...
    if not parts:
        return None
    version = None
    if parts[0] == "api" and len(parts) >= 2:
        version = parts[1]
        rest = parts[2:]
    elif parts[0] == "apis" and len(parts) >= 3:
        version = parts[2]
        rest = parts[3:]
    else:
        return None
    namespace = None
    # /namespaces/{ns}/{plural}... (>=3 segments) is a namespaced
    # collection; /namespaces[/{name}] is the Namespace kind itself
    if len(rest) >= 3 and rest[0] == "namespaces":
        namespace = rest[1]
        rest = rest[2:]
    if not rest or rest[0] not in PLURALS:
        return None
    kind = PLURALS[rest[0]]
    name = rest[1] if len(rest) > 1 else None
    sub = rest[2] if len(rest) > 2 else None
    return _Route(kind, namespace, name, sub, version)


class RestServer:
    def __init__(self, api: APIServer, *, port: int = 0):
        self.api = api
        self.port = port
        self._httpd: ThreadingHTTPServer | None = None
        # watch fan-out: every active watch request owns a queue fed by
        # the apiserver watcher below. A bounded backlog of recent
        # events (rv-ordered) lets a watch opened with
        # ?resourceVersion=N replay what landed between the client's
        # list and its watch registration — without it, any write in
        # that gap (or between 300s stream restarts) reaches no watcher
        # until the next full relist.
        import collections
        self._watch_queues: list[tuple[str, queue.Queue]] = []
        self._backlog: collections.deque = collections.deque(maxlen=2048)
        # rv horizon of the backlog: anything <= this may have been
        # evicted, so a watch asking to resume below it gets 410 Gone
        # (the informer then relists — kubeclient.watch_kind). Starts
        # at the server's CURRENT rv: after a WAL-recovered restart the
        # pre-crash event stream is gone, so a client resuming at a
        # pre-crash rv must relist rather than silently miss the gap.
        self._backlog_floor = int(getattr(api, "_rv", 0) or 0)
        self._watch_lock = make_lock("restserver.watch_registry")
        api.add_watcher(self._on_event, name="rest")

    def _on_event(self, etype: str, obj: dict, old) -> None:
        if etype == "TOO_OLD":
            # our fanout queue overflowed upstream: an unknown window of
            # events never reached this facade, so the backlog has a
            # hole in it. Drop it, raise the horizon to the server's
            # current rv, and 410 every open stream — exactly what a
            # kube watch cache does when a client falls off its window.
            with self._watch_lock:
                self._backlog.clear()
                self._backlog_floor = max(
                    self._backlog_floor,
                    int(getattr(self.api, "_rv", 0) or 0))
                gone = {"type": "ERROR", "object": _status(
                    410, "Expired",
                    "watch window lost (fanout overflow); relist")}
                for _, q in self._watch_queues:
                    q.put((gone, None))
            return
        evt = {"type": {"ADDED": "ADDED",
                        "MODIFIED": "MODIFIED",
                        "DELETED": "DELETED"}.get(etype, etype),
               "object": obj}
        try:
            rv = int((obj.get("metadata") or {}).get("resourceVersion", 0))
        except (TypeError, ValueError):
            rv = 0
        # encode ONCE; every subscriber of this kind (and any backlog
        # replay) shares the same bytes — under a 20-way spawn storm
        # per-client json.dumps was per-event × per-stream CPU inside
        # what used to be the write path
        raw = json.dumps(evt).encode() + b"\n"
        with self._watch_lock:
            if len(self._backlog) == self._backlog.maxlen:
                self._backlog_floor = self._backlog[0][0]
            self._backlog.append((rv, obj.get("kind"), evt, raw))
            for kind, q in self._watch_queues:
                if obj.get("kind") == kind:
                    q.put((evt, raw))

    # ---- request handling -------------------------------------------
    def _handle(self, handler: BaseHTTPRequestHandler) -> None:
        # server-span boundary: adopt the client's traceparent (the
        # kube adapter injects it per call) so cross-process hops —
        # including shard-routed ones — stay one trace. Watch streams
        # are exempt: a 300s stream is a subscription, not a hop.
        if tracing.enabled() and "watch=true" not in handler.path:
            parent = tracing.parse_traceparent(
                handler.headers.get(tracing.TRACE_HEADER))
            if parent is not None:
                # only context-bearing requests get a span — informer
                # lists/watch registrations and metric scrapes carry no
                # traceparent and would otherwise mint orphan roots
                path = handler.path.split("?", 1)[0]
                with tracing.start_span(
                        f"{handler.command} {path}", kind="server",
                        parent=parent,
                        attrs={"component": "restserver"}):
                    self._handle_inner(handler)
                return
        self._handle_inner(handler)

    def _handle_inner(self, handler: BaseHTTPRequestHandler) -> None:
        parsed = urlparse(handler.path)
        params = parse_qs(parsed.query)
        method = handler.command
        # tag this request thread's writes in the apiserver audit log
        # (kubeclient sends the header when built with identity=...)
        self.api.set_writer(handler.headers.get("X-Writer-Identity"))

        if parsed.path == "/apis/authorization.k8s.io/v1/subjectaccessreviews" \
                and method == "POST":
            body = self._read_json(handler)
            attrs = (body.get("spec") or {}).get(
                "resourceAttributes") or {}
            allowed = self.api.access_review(
                (body.get("spec") or {}).get("user"),
                attrs.get("verb", ""), attrs.get("resource", ""),
                attrs.get("namespace"))
            body.setdefault("status", {})["allowed"] = allowed
            self._send(handler, 201, body)
            return
        if parsed.path in ("/healthz", "/readyz", "/livez"):
            self._send_raw(handler, 200, b"ok",
                           content_type="text/plain")
            return
        if parsed.path == "/debug/writelog" and method == "GET":
            # the apiserver's bounded write audit trail, serialized for
            # out-of-process consumers (the sharded conformance harness
            # reconstructs cross-shard phase breakdowns from these)
            self._send(handler, 200,
                       {"writes": list(self.api.write_log)})
            return
        if parsed.path == "/debug/rv_floor" and method == "POST":
            # handoff recipients adopt the donor's rv horizon so the
            # router cache's rv monotonicity survives the move
            body = self._read_json(handler)
            out = self.api.advance_rv_floor(int(body.get("rv", 0)))
            self._send(handler, 200, {"rv": out})
            return
        if parsed.path == "/debug/tombstone" and method == "POST":
            # elastic handoff crash fencing: the coordinator stones a
            # donor's moved partition keys right after the router FLIP
            # (so a donor crash before cleanup cannot resurrect them on
            # respawn) and lifts them after cleanup; recipients lift
            # stale stones for ranges moving back IN before adopting
            body = self._read_json(handler)
            if body.get("clear_all"):
                out = self.api.clear_range_tombstone()
            elif "clear" in body:
                out = self.api.clear_range_tombstone(
                    [str(k) for k in body.get("clear") or []])
            else:
                out = self.api.set_range_tombstone(
                    [str(k) for k in body.get("set") or []])
            self._send(handler, 200, {"tombstones": out})
            return
        if parsed.path == "/debug/snapshot" and method == "POST":
            # force a compacting snapshot NOW: the elastic-shard
            # handoff coordinator calls this on the donor before
            # reading its WAL directory, so the bulk copy reads one
            # snapshot file + a short tail instead of the full log
            took = self.api.snapshot_now()
            self._send(handler, 200, {"snapshotted": took})
            return
        if parsed.path == "/debug/traces" and method == "GET":
            # this process's span collector, serialized — the metrics
            # service (and the sharded conformance harness) merges
            # these per-shard exports into whole cross-process traces
            col = tracing.collector()
            self._send(handler, 200,
                       {"process": tracing.process_name(),
                        "spans": col.spans(),
                        "slow": col.slow_traces()})
            return
        if parsed.path == "/metrics" and method == "GET":
            # Prometheus exposition of the control-plane registry —
            # schedule_latency_seconds / readiness_wake_to_observe /
            # fanout gauges etc. scraped over the same socket the
            # conformance harness already talks to
            from kubeflow_rm_tpu.controlplane import metrics as cp_metrics
            from kubeflow_rm_tpu.controlplane import (
                scheduler as cp_scheduler,
            )
            # free-chip/fragmentation gauges are recomputed on stats();
            # refresh so a scrape between binds reads the live pool
            cp_scheduler.refresh_gauges()
            self._send_raw(handler, 200, cp_metrics.scrape(),
                           content_type="text/plain; version=0.0.4")
            return

        route = _parse_path(parsed.path)
        if route is None:
            self._send(handler, 404,
                       _status(404, "NotFound",
                               f"no route for {parsed.path}"))
            return
        try:
            self._dispatch(handler, method, route, params)
        except NotFound as e:
            self._send(handler, 404, _status(404, "NotFound", str(e)))
        except AlreadyExists as e:
            self._send(handler, 409,
                       _status(409, "AlreadyExists", str(e)))
        except Conflict as e:
            self._send(handler, 409, _status(409, "Conflict", str(e)))
        except (Invalid, AdmissionDenied) as e:
            self._send(handler, 422, _status(422, "Invalid", str(e)))
        except Exception as e:  # pragma: no cover - defensive
            log.exception("unhandled")
            self._send(handler, 500,
                       _status(500, "InternalError", str(e)))

    # ---- multi-version conversion at the serving boundary ------------
    # (api/conversion.py): reads convert storage-version objects to the
    # requested version; writes convert the client's version to storage
    # before hitting the store — what a real apiserver does around its
    # conversion webhook.
    @staticmethod
    def _needs_conversion(route: _Route) -> bool:
        # identity (storage-version) requests skip the convert copy —
        # this is the provision-latency hot path
        return (route.kind in CONVERTERS and route.version is not None
                and route.version != STORAGE_VERSION)

    @classmethod
    def _convert_out(cls, route: _Route, obj: dict) -> dict:
        if not cls._needs_conversion(route):
            return obj
        try:
            return CONVERTERS[route.kind](obj, route.version)
        except ValueError as e:
            raise Invalid(str(e)) from e

    @classmethod
    def _convert_in(cls, route: _Route, obj: dict) -> dict:
        if not cls._needs_conversion(route):
            return obj
        # the path, not the body's apiVersion, names the version the
        # client speaks — a real apiserver rejects mismatches; we
        # normalize (a v1 apiVersion pasted into a v1beta1 POST must
        # not make the annotations-shaped body skip conversion)
        obj["apiVersion"] = f"{GROUP}/{route.version}"
        try:
            return CONVERTERS[route.kind](obj, STORAGE_VERSION)
        except ValueError as e:
            raise Invalid(str(e)) from e

    def _dispatch(self, handler, method: str, route: _Route,
                  params: dict) -> None:
        api, kind = self.api, route.kind
        if method == "GET" and route.name is None:
            if params.get("watch", ["false"])[0] == "true":
                self._serve_watch(handler, route, params)
                return
            items = [self._convert_out(route, o)
                     for o in api.list(kind, route.namespace,
                                       _selector_from(params))]
            self._send(handler, 200, {
                "apiVersion": "v1", "kind": f"{kind}List",
                "metadata": {"resourceVersion": str(api._rv)},
                "items": items,
            })
        elif method == "GET" and route.subresource == "log" \
                and kind == "Pod":
            tail = params.get("tailLines", [None])[0]
            try:
                tail_n = int(tail) if tail is not None else None
            except ValueError:
                raise Invalid(f"tailLines must be an integer, got {tail!r}")
            text = api.pod_logs(route.namespace, route.name,
                                tail_lines=tail_n)
            self._send_raw(handler, 200, text.encode(),
                           content_type="text/plain")
        elif method == "GET":
            self._send(handler, 200, self._convert_out(
                route, api.get(kind, route.name, route.namespace)))
        elif method == "POST" and route.name is None and \
                params.get("bulk", ["false"])[0] == "true":
            # bulk create: {"items": [...]} -> 200 List whose items are
            # created objects or per-item Status failures, index-aligned
            # with the request (one bad object rejects only itself)
            body = self._read_json(handler)
            items = body.get("items")
            if not isinstance(items, list):
                raise Invalid("bulk create body must be "
                              '{"items": [...]}')
            objs = []
            for obj in items:
                obj.setdefault("kind", kind)
                meta = obj.setdefault("metadata", {})
                if route.namespace and not meta.get("namespace"):
                    meta["namespace"] = route.namespace
                objs.append(self._convert_in(route, obj))
            out = [item if is_status(item)
                   else self._convert_out(route, item)
                   for item in api.create_many(objs)]
            self._send(handler, 200, {
                "apiVersion": "v1", "kind": "List", "items": out})
        elif method == "POST":
            obj = self._read_json(handler)
            obj.setdefault("kind", kind)
            if route.namespace and not obj["metadata"].get("namespace"):
                obj["metadata"]["namespace"] = route.namespace
            obj = self._convert_in(route, obj)
            self._send(handler, 201,
                       self._convert_out(route, api.create(obj)))
        elif method == "PUT":
            obj = self._read_json(handler)
            obj.setdefault("kind", kind)
            obj = self._convert_in(route, obj)
            self._send(handler, 200,
                       self._convert_out(route, api.update(obj)))
        elif method == "PATCH":
            patch = self._read_json(handler)
            if route.subresource == "status":
                # status is version-invariant across served versions
                current = api.get(kind, route.name, route.namespace)
                current["status"] = patch.get("status", {})
                self._send(handler, 200, api.update_status(current))
            else:
                if self._needs_conversion(route):
                    # a merge-patch is expressed in the CLIENT's
                    # version: apply it there, then convert the result
                    # back to storage (what the real apiserver does).
                    # The read-merge-write isn't under the store lock
                    # like api.patch, so retry the rv CAS on Conflict
                    # rather than surfacing a 409 the storage-version
                    # path could never produce
                    from kubeflow_rm_tpu.controlplane.api.meta import (
                        strategic_merge,
                    )
                    for attempt in range(5):
                        current = self._convert_out(
                            route, api.get(kind, route.name,
                                           route.namespace))
                        merged = strategic_merge(current, patch)
                        merged["metadata"]["resourceVersion"] = \
                            current["metadata"]["resourceVersion"]
                        merged = self._convert_in(route, merged)
                        try:
                            out = api.update(merged)
                            break
                        except Conflict:
                            if attempt == 4:
                                raise
                    self._send(handler, 200,
                               self._convert_out(route, out))
                else:
                    self._send(handler, 200,
                               api.patch(kind, route.name, patch,
                                         route.namespace))
        elif method == "DELETE":
            obj = self._convert_out(
                route, api.get(kind, route.name, route.namespace))
            api.delete(kind, route.name, route.namespace)
            self._send(handler, 200, obj)
        else:
            self._send(handler, 405,
                       _status(405, "MethodNotAllowed", method))

    def _serve_watch(self, handler, route: _Route, params: dict) -> None:
        if (route.kind in CONVERTERS and route.version is not None
                and route.version not in SERVED_VERSIONS):
            # reject BEFORE the 200 + chunked headers go out — a
            # conversion error mid-stream would interleave a second
            # HTTP response into the open body
            raise Invalid(f"{route.kind} has no served version "
                          f"{route.version!r}")
        q: queue.Queue = queue.Queue()
        try:
            since_rv = int(params.get("resourceVersion", ["0"])[0] or 0)
        except ValueError:
            since_rv = 0
        with self._watch_lock:
            # replay-then-register atomically vs _on_event: events with
            # rv > the client's list rv land in q exactly once. A
            # since_rv below the backlog horizon cannot be replayed
            # faithfully -> 410 Gone ERROR event, client must relist.
            if since_rv and since_rv < self._backlog_floor:
                q.put(({"type": "ERROR", "object": _status(
                    410, "Expired",
                    f"resourceVersion {since_rv} is too old "
                    f"(horizon {self._backlog_floor})")}, None))
            elif since_rv:
                for rv, kind, evt, raw in self._backlog:
                    if kind == route.kind and rv > since_rv:
                        q.put((evt, raw))
            self._watch_queues.append((route.kind, q))
        timeout = float(params.get("timeoutSeconds", ["300"])[0])
        try:
            handler.send_response(200)
            handler.send_header("Content-Type", "application/json")
            handler.send_header("Transfer-Encoding", "chunked")
            handler.end_headers()

            def write_chunk(data: bytes):
                handler.wfile.write(f"{len(data):x}\r\n".encode())
                handler.wfile.write(data + b"\r\n")
                handler.wfile.flush()

            import time
            deadline = time.monotonic() + timeout
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    evt, raw = q.get(timeout=min(remaining, 1.0))
                except queue.Empty:
                    continue
                if evt.get("type") == "ERROR":
                    # 410 Gone: report and end the stream; the client
                    # must relist
                    write_chunk(json.dumps(evt).encode() + b"\n")
                    break
                if route.namespace and (
                        (evt["object"].get("metadata") or {})
                        .get("namespace")) != route.namespace:
                    continue
                if raw is not None and not self._needs_conversion(route):
                    # shared single-encode buffer (the common case:
                    # storage-version streams — every watcher of a kind
                    # writes the exact same bytes)
                    write_chunk(raw)
                    continue
                # multi-version kinds: the stream speaks the version
                # the client's path asked for (evt dicts are shared
                # across subscriber queues — convert a copy)
                out_obj = self._convert_out(route, evt["object"])
                if out_obj is not evt["object"]:
                    evt = dict(evt, object=out_obj)
                write_chunk(json.dumps(evt).encode() + b"\n")
            write_chunk(b"")  # terminal chunk
        except (BrokenPipeError, ConnectionResetError):
            pass
        finally:
            with self._watch_lock:
                try:
                    self._watch_queues.remove((route.kind, q))
                except ValueError:
                    pass

    # ---- plumbing ----------------------------------------------------
    @staticmethod
    def _read_json(handler) -> dict:
        length = int(handler.headers.get("Content-Length", "0"))
        return json.loads(handler.rfile.read(length) or b"{}")

    @staticmethod
    def _send(handler, code: int, body: dict) -> None:
        RestServer._send_raw(handler, code, json.dumps(body).encode())

    @staticmethod
    def _send_raw(handler, code: int, data: bytes,
                  content_type: str = "application/json") -> None:
        handler.send_response(code)
        handler.send_header("Content-Type", content_type)
        handler.send_header("Content-Length", str(len(data)))
        # explicit keep-alive: pins close_connection False so the
        # client's pooled connection survives the response even when a
        # proxy or an HTTP/1.0 client header would otherwise close it
        handler.send_header("Connection", "keep-alive")
        handler.end_headers()
        handler.wfile.write(data)

    def start(self) -> int:
        outer = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # status/headers and body go out as separate small send()s;
            # with Nagle on, the second write stalls ~40ms behind the
            # client's delayed ACK — which would dominate every
            # provision-latency number this server exists to measure
            disable_nagle_algorithm = True

            def _go(self):
                outer._handle(self)

            do_GET = do_POST = do_PUT = do_PATCH = do_DELETE = _go

            def log_message(self, *a):
                pass

        class S(ThreadingHTTPServer):
            # http.server's default listen backlog is 5; boot opens a
            # dozen-plus concurrent connections (watch streams per kind
            # per client, pooled writers, readiness long-polls) and a
            # SYN dropped off a full backlog retransmits after the
            # kernel's 1s initial RTO — a whole second of phantom
            # provision latency for whichever stream loses the race
            request_queue_size = 128

            # accepted sockets, so stop() can sever ESTABLISHED
            # keep-alive connections: shutdown()+server_close() only
            # stop the accept loop, leaving handler threads serving
            # pooled clients as if the shard never went down
            def get_request(self):
                sock, addr = super().get_request()
                with self._conn_lock:
                    self._conns = {c for c in self._conns
                                   if c.fileno() != -1}
                    self._conns.add(sock)
                return sock, addr

        S._conns = set()
        S._conn_lock = make_lock("restserver.conns")
        self._httpd = S(("127.0.0.1", self.port), H)
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self._httpd.server_address[1]}"

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            # release the listening socket too: without this the port
            # stays bound (the kernel keeps SYN-queueing clients into a
            # backlog nobody drains) and a restart-in-place at the same
            # address — the shard respawn path — gets EADDRINUSE
            self._httpd.server_close()
            # and sever established connections: a "stopped" server
            # must stop answering, or pooled keep-alive clients keep
            # getting clean replies from a shard that is supposed to
            # be down (their retry/lost-reply paths never engage)
            import socket as _socket
            with self._httpd._conn_lock:
                conns, self._httpd._conns = set(self._httpd._conns), set()
            for sock in conns:
                try:
                    sock.shutdown(_socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    sock.close()
                except OSError:
                    pass
