"""CRD manifests, generated from the platform's API modules.

The reference's CRDs are kubebuilder-generated Go structs
(``notebook-controller/config/crd/bases/kubeflow.org_notebooks.yaml``,
``profile-controller/config/crd/bases/``, admission-webhook's
PodDefault, tensorboard's and pvcviewer's). Here the Python API modules
(``api/notebook.py``, ``api/profile.py``, ``api/poddefault.py``, the
tensorboard/pvcviewer controllers) are the source of truth, and this
module renders openAPIV3Schema CRDs from them — the acceleratorType
enum comes live from ``api/tpu.py``'s topology table, so the CRD can
never drift from what the controller schedules.

``python -m kubeflow_rm_tpu.controlplane.deploy`` writes the YAML tree.
"""

from __future__ import annotations

from kubeflow_rm_tpu.controlplane.api import tpu as tpu_api
from kubeflow_rm_tpu.controlplane.api.notebook import MAX_SLICES

# Free-form object: pod specs / quota specs / plugin configs — CRDs
# model these as x-kubernetes-preserve-unknown-fields, exactly how the
# reference embeds corev1.PodSpec.
_ANY = {"type": "object", "x-kubernetes-preserve-unknown-fields": True}


def _crd(group: str, kind: str, plural: str, versions: list[dict],
         scope: str = "Namespaced", short_names: list[str] | None = None,
         categories: list[str] | None = None) -> dict:
    names = {
        "kind": kind,
        "listKind": f"{kind}List",
        "plural": plural,
        "singular": kind.lower(),
    }
    if short_names:
        names["shortNames"] = short_names
    if categories:
        names["categories"] = categories
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{plural}.{group}"},
        "spec": {
            "group": group,
            "names": names,
            "scope": scope,
            "versions": versions,
        },
    }


def _version(name: str, schema: dict, *, served: bool = True,
             storage: bool = True, status: bool = True,
             printer_columns: list[dict] | None = None) -> dict:
    v: dict = {
        "name": name,
        "served": served,
        "storage": storage,
        "schema": {"openAPIV3Schema": schema},
    }
    if status:
        v["subresources"] = {"status": {}}
    if printer_columns:
        v["additionalPrinterColumns"] = printer_columns
    return v


def notebook_crd() -> dict:
    """kubeflow.org/v1 Notebook with the first-class ``spec.tpu`` block
    (the validator in ``api/notebook.py:validate`` rendered as schema)."""
    tpu_block = {
        "type": "object",
        "required": ["acceleratorType"],
        "properties": {
            "acceleratorType": {
                "type": "string",
                # live from the topology table: quota, scheduling and
                # the spawner picker all share this vocabulary
                "enum": sorted(tpu_api.TOPOLOGIES),
            },
            "numSlices": {
                "type": "integer",
                "minimum": 1,
                "maximum": MAX_SLICES,
                "description": "Multislice width: >1 renders a DCN job "
                               "of identical slices with MEGASCALE_* "
                               "rendezvous injected.",
            },
        },
    }
    schema = {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "required": ["template"],
                "properties": {
                    "template": {
                        "type": "object",
                        "required": ["spec"],
                        "properties": {"spec": _ANY},
                    },
                    "tpu": tpu_block,
                },
            },
            "status": {
                "type": "object",
                "properties": {
                    "conditions": {"type": "array", "items": _ANY},
                    "readyReplicas": {"type": "integer"},
                    "containerState": _ANY,
                },
            },
        },
    }
    cols = [
        {"name": "Accelerator", "type": "string",
         "jsonPath": ".spec.tpu.acceleratorType"},
        {"name": "Ready", "type": "integer",
         "jsonPath": ".status.readyReplicas"},
        {"name": "Age", "type": "date",
         "jsonPath": ".metadata.creationTimestamp"},
    ]
    # v1beta1: the reference-era shape — NO spec.tpu; TPU placement
    # rides annotations (api/conversion.py hoists/demotes losslessly).
    # Served for API evolution parity with the reference, which serves
    # v1alpha1/v1beta1/v1 with conversion shims
    # (notebook-controller/api/v1beta1/notebook_types.go:27-34,
    # api/v1/notebook_conversion.go).
    import copy as _copy
    beta_schema = _copy.deepcopy(schema)
    del beta_schema["properties"]["spec"]["properties"]["tpu"]
    beta_cols = [
        {"name": "Accelerator", "type": "string",
         "jsonPath": ".metadata.annotations['notebooks\\.kubeflow\\."
                     "org/tpu-accelerator-type']"},
    ] + cols[1:]
    # v1alpha1: same annotation-carried shape under the pre-prefix
    # ``kubeflow.org/tpu-*`` keys (api/conversion.py LEGACY_*)
    alpha_schema = _copy.deepcopy(beta_schema)
    alpha_cols = [
        {"name": "Accelerator", "type": "string",
         "jsonPath": ".metadata.annotations['kubeflow\\.org/"
                     "tpu-accelerator-type']"},
    ] + cols[1:]
    crd = _crd("kubeflow.org", "Notebook", "notebooks",
               [_version("v1alpha1", alpha_schema, storage=False,
                         printer_columns=alpha_cols),
                _version("v1beta1", beta_schema, storage=False,
                         printer_columns=beta_cols),
                _version("v1", schema, printer_columns=cols)],
               short_names=["nb"], categories=["kubeflow"])
    crd["spec"]["conversion"] = {
        "strategy": "Webhook",
        "webhook": {
            "conversionReviewVersions": ["v1"],
            "clientConfig": {
                # same Service the admission configs point at
                # (deploy/manifests.py webhook_objects)
                "service": {
                    "name": "webhook",
                    "namespace": "kubeflow",
                    "path": "/convert",
                    "port": 443,
                },
                # caBundle patched in by the overlay / cert-manager
            },
        },
    }
    return crd


def tpujob_crd() -> dict:
    """kubeflow.org/v1 TPUJob — multi-role gang jobs (the validator in
    ``api/tpujob.py:validate`` rendered as schema). Spokes v1alpha1 and
    v1beta1 carry the role list as a JSON annotation
    (``api/conversion.py:TPU_JOB_ROLES_ANNOTATION``), converted through
    the same webhook as Notebook."""
    from kubeflow_rm_tpu.controlplane.api.tpujob import (
        MAX_ROLE_REPLICAS,
        MAX_ROLES,
    )
    role_schema = {
        "type": "object",
        "required": ["name"],
        "properties": {
            "name": {
                "type": "string",
                "pattern": r"^[a-z]([a-z0-9-]{0,30}[a-z0-9])?$",
            },
            "replicas": {
                "type": "integer",
                "minimum": 1,
                "maximum": MAX_ROLE_REPLICAS,
                "description": "Slices for TPU roles (pods = replicas "
                               "× hosts), pods for CPU roles.",
            },
            "tpu": {
                "type": "object",
                "required": ["acceleratorType"],
                "properties": {
                    "acceleratorType": {
                        "type": "string",
                        "enum": sorted(tpu_api.TOPOLOGIES),
                    },
                },
            },
            "cpu": {
                "type": "string",
                "description": "Per-pod CPU request for chipless "
                               "roles (quantity, e.g. \"2\" or "
                               "\"500m\").",
            },
            "template": _ANY,
        },
    }
    schema = {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "required": ["roles"],
                "properties": {
                    "roles": {
                        "type": "array",
                        "minItems": 1,
                        "maxItems": MAX_ROLES,
                        "items": role_schema,
                    },
                    "image": {"type": "string"},
                    "priorityClassName": {"type": "string"},
                },
            },
            "status": {
                "type": "object",
                "properties": {
                    "phase": {"type": "string"},
                    "readyPods": {"type": "integer"},
                    "totalPods": {"type": "integer"},
                    "roles": _ANY,
                },
            },
        },
    }
    cols = [
        {"name": "Phase", "type": "string", "jsonPath": ".status.phase"},
        {"name": "Ready", "type": "integer",
         "jsonPath": ".status.readyPods"},
        {"name": "Total", "type": "integer",
         "jsonPath": ".status.totalPods"},
        {"name": "Age", "type": "date",
         "jsonPath": ".metadata.creationTimestamp"},
    ]
    # spokes: spec.roles demoted to the JSON roles annotation
    import copy as _copy
    spoke_schema = _copy.deepcopy(schema)
    del spoke_schema["properties"]["spec"]["properties"]["roles"]
    spoke_schema["properties"]["spec"].pop("required", None)
    crd = _crd("kubeflow.org", "TPUJob", "tpujobs",
               [_version("v1alpha1", _copy.deepcopy(spoke_schema),
                         storage=False),
                _version("v1beta1", _copy.deepcopy(spoke_schema),
                         storage=False),
                _version("v1", schema, printer_columns=cols)],
               short_names=["tj"], categories=["kubeflow"])
    crd["spec"]["conversion"] = {
        "strategy": "Webhook",
        "webhook": {
            "conversionReviewVersions": ["v1"],
            "clientConfig": {
                "service": {
                    "name": "webhook",
                    "namespace": "kubeflow",
                    "path": "/convert",
                    "port": 443,
                },
            },
        },
    }
    return crd


def profile_crd() -> dict:
    schema = {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "required": ["owner"],
                "properties": {
                    "owner": {
                        "type": "object",
                        "required": ["kind", "name"],
                        "properties": {
                            "kind": {"type": "string",
                                     "enum": ["User", "Group",
                                              "ServiceAccount"]},
                            "name": {"type": "string"},
                        },
                    },
                    "resourceQuotaSpec": _ANY,
                    "plugins": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "required": ["kind"],
                            "properties": {"kind": {"type": "string"},
                                           "spec": _ANY},
                        },
                    },
                },
            },
            "status": _ANY,
        },
    }
    return _crd("kubeflow.org", "Profile", "profiles",
                [_version("v1", schema)], scope="Cluster")


def poddefault_crd() -> dict:
    from kubeflow_rm_tpu.controlplane.api.poddefault import MERGE_FIELDS
    props: dict = {
        "selector": _ANY,
        "desc": {"type": "string"},
        "serviceAccountName": {"type": "string"},
        "automountServiceAccountToken": {"type": "boolean"},
        "labels": {"type": "object",
                   "additionalProperties": {"type": "string"}},
        "annotations": {"type": "object",
                        "additionalProperties": {"type": "string"}},
        "command": {"type": "array", "items": {"type": "string"}},
        "args": {"type": "array", "items": {"type": "string"}},
    }
    for f in MERGE_FIELDS:
        props[f] = {"type": "array", "items": _ANY}
    schema = {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "required": ["selector"],
                "properties": props,
            },
        },
    }
    return _crd("kubeflow.org", "PodDefault", "poddefaults",
                [_version("v1alpha1", schema, status=False)])


def tensorboard_crd() -> dict:
    schema = {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "required": ["logspath"],
                "properties": {
                    "logspath": {
                        "type": "string",
                        "description": "pvc://claim/sub/path or "
                                       "gs://bucket/path",
                    },
                },
            },
            "status": _ANY,
        },
    }
    cols = [{"name": "Logspath", "type": "string",
             "jsonPath": ".spec.logspath"}]
    return _crd("tensorboard.kubeflow.org", "Tensorboard", "tensorboards",
                [_version("v1alpha1", schema, printer_columns=cols)])


def pvcviewer_crd() -> dict:
    schema = {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "required": ["pvc"],
                "properties": {
                    "pvc": {"type": "string"},
                    "podSpec": _ANY,
                    "networking": _ANY,
                    "rwoScheduling": {"type": "boolean"},
                },
            },
            "status": _ANY,
        },
    }
    return _crd("kubeflow.org", "PVCViewer", "pvcviewers",
                [_version("v1alpha1", schema)])


def all_crds() -> list[dict]:
    return [notebook_crd(), tpujob_crd(), profile_crd(),
            poddefault_crd(), tensorboard_crd(), pvcviewer_crd()]


def render_yaml(objs: list[dict]) -> str:
    import yaml
    return "---\n".join(
        yaml.safe_dump(o, sort_keys=False, default_flow_style=False)
        for o in objs)
