"""Kustomize manifest tree for the control plane.

Mirrors the reference's kubebuilder layout
(``notebook-controller/config/{crd,rbac,manager,webhook,default}`` and
``config/overlays/{standalone,...}``) with the TPU build's shape: one
controller-manager Deployment (all reconcilers in one process), one
webhook Deployment (HTTPS admission), five web-app Deployments, and
the CRDs from ``crds.py``. ``python -m kubeflow_rm_tpu.controlplane
manifests [dir]`` writes the tree; the checked-in ``manifests/`` dir is
its output (CI asserts they're in sync).
"""

from __future__ import annotations

import os

IMAGE = "kubeflow-rm-tpu/controlplane"
NAMESPACE = "kubeflow"
APP_LABEL = "app.kubernetes.io/part-of"


def _deployment(name: str, command: list[str], *, port: int,
                sa: str = "controlplane", env: list[dict] | None = None,
                volumes: list[dict] | None = None,
                mounts: list[dict] | None = None,
                probe_path: str = "/healthz") -> dict:
    container = {
        "name": name,
        "image": IMAGE,
        "command": command,
        "ports": [{"containerPort": port}],
        "env": env or [],
        "readinessProbe": {
            "httpGet": {"path": probe_path, "port": port,
                        **({"scheme": "HTTPS"} if name == "webhook"
                           else {})},
            "initialDelaySeconds": 3,
        },
        "resources": {
            "requests": {"cpu": "100m", "memory": "128Mi"},
            "limits": {"cpu": "1", "memory": "512Mi"},
        },
    }
    if mounts:
        container["volumeMounts"] = mounts
    pod_spec: dict = {"serviceAccountName": sa,
                      "containers": [container]}
    if volumes:
        pod_spec["volumes"] = volumes
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name,
                     "labels": {APP_LABEL: "kubeflow-rm-tpu",
                                "app": name}},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"app": name}},
            "template": {
                "metadata": {"labels": {"app": name}},
                "spec": pod_spec,
            },
        },
    }


def _service(name: str, port: int, target: int | None = None) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": name, "labels": {"app": name}},
        "spec": {"selector": {"app": name},
                 "ports": [{"port": port,
                            "targetPort": target or port}]},
    }


# gateway route prefix per app — the SAME path the SPA fetches and the
# VirtualService matches; each app serves its routes under this prefix
# in-cluster (APP_PREFIX) so the VS forwards without rewriting. The
# dashboard owns the root (SPA shell + /static + /api).
ROUTE_PREFIXES = {
    "jupyter-web-app": "/jupyter",
    "volumes-web-app": "/volumes",
    "tensorboards-web-app": "/tensorboards",
    "kfam": "/kfam",
    "dashboard": "",
}


def _webapp_pair(name: str, cmd: str, port: int) -> list[dict]:
    prefix = ROUTE_PREFIXES[name]
    return [
        _deployment(name, ["python", "-m",
                           "kubeflow_rm_tpu.controlplane", cmd],
                    port=port, probe_path=f"{prefix}/healthz",
                    env=[{"name": "PORT", "value": str(port)},
                         {"name": "APP_PREFIX", "value": prefix}]),
        _service(name, 80, port),
    ]


def controller_manager_objects() -> list[dict]:
    dep = _deployment(
        "controller-manager",
        ["python", "-m", "kubeflow_rm_tpu.controlplane",
         "controller-manager"],
        port=8081,
        env=[{"name": "ENABLE_CULLING", "value": "true"},
             {"name": "CULL_IDLE_TIME", "value": "1440"},
             {"name": "IDLENESS_CHECK_PERIOD", "value": "1"},
             # HA pair: both replicas run, the lease decides who
             # reconciles (controlplane/ha); POD_NAME is the election
             # identity, qps/burst bound the shared apiserver budget
             {"name": "LEADER_ELECT", "value": "true"},
             {"name": "POD_NAME", "valueFrom": {"fieldRef": {
                 "fieldPath": "metadata.name"}}},
             {"name": "KUBE_CLIENT_QPS", "value": "20"},
             {"name": "KUBE_CLIENT_BURST", "value": "40"}],
    )
    dep["spec"]["replicas"] = 2
    # the manager serves no HTTP; probe is exec-based liveness instead
    c = dep["spec"]["template"]["spec"]["containers"][0]
    del c["readinessProbe"]
    del c["ports"]
    c["livenessProbe"] = {
        "exec": {"command": ["python", "-c", "import kubeflow_rm_tpu"]},
        "periodSeconds": 60,
    }
    return [dep]


def webhook_objects() -> list[dict]:
    dep = _deployment(
        "webhook", ["python", "-m", "kubeflow_rm_tpu.controlplane",
                    "webhook-server"],
        port=8443,
        env=[{"name": "WEBHOOK_TLS_CERT",
              "value": "/etc/webhook/certs/tls.crt"},
             {"name": "WEBHOOK_TLS_KEY",
              "value": "/etc/webhook/certs/tls.key"}],
        volumes=[{"name": "certs",
                  "secret": {"secretName": "webhook-server-cert"}}],
        mounts=[{"name": "certs", "mountPath": "/etc/webhook/certs",
                 "readOnly": True}],
    )
    svc = _service("webhook", 443, 8443)
    cfg = {
        "apiVersion": "admissionregistration.k8s.io/v1",
        "kind": "MutatingWebhookConfiguration",
        "metadata": {"name": "kubeflow-rm-tpu-mutating"},
        "webhooks": [
            {
                "name": "notebooks.kubeflow.org",
                "admissionReviewVersions": ["v1"],
                "sideEffects": "None",
                "failurePolicy": "Fail",
                "clientConfig": {
                    "service": {"name": "webhook",
                                "namespace": NAMESPACE,
                                "path": "/mutate-notebook",
                                "port": 443},
                    # caBundle patched in by the overlay / cert-manager
                },
                "rules": [{"apiGroups": ["kubeflow.org"],
                           "apiVersions": ["v1"],
                           "operations": ["CREATE", "UPDATE"],
                           "resources": ["notebooks"]}],
            },
            {
                "name": "pods.kubeflow.org",
                "admissionReviewVersions": ["v1"],
                "sideEffects": "None",
                # pods must start even if the webhook is down — the
                # reference's PodDefault webhook is Ignore too
                "failurePolicy": "Ignore",
                "namespaceSelector": {
                    "matchLabels": {
                        "app.kubernetes.io/part-of": "kubeflow-profile"},
                },
                "clientConfig": {
                    "service": {"name": "webhook",
                                "namespace": NAMESPACE,
                                "path": "/mutate-pod",
                                "port": 443},
                },
                "rules": [{"apiGroups": [""],
                           "apiVersions": ["v1"],
                           "operations": ["CREATE"],
                           "resources": ["pods"]}],
            },
        ],
    }
    return [dep, svc, cfg]


def rbac_objects() -> list[dict]:
    sa = {"apiVersion": "v1", "kind": "ServiceAccount",
          "metadata": {"name": "controlplane"}}
    # everything the reconcilers + web apps touch (the union of the
    # reference's per-component roles)
    rules = [
        {"apiGroups": ["kubeflow.org", "tensorboard.kubeflow.org"],
         "resources": ["notebooks", "notebooks/status", "profiles",
                       "profiles/status", "poddefaults", "pvcviewers",
                       "pvcviewers/status", "tensorboards",
                       "tensorboards/status"],
         "verbs": ["*"]},
        {"apiGroups": [""],
         "resources": ["namespaces", "services", "serviceaccounts",
                       "configmaps", "secrets", "events", "pods",
                       "pods/log", "resourcequotas",
                       "persistentvolumeclaims", "nodes"],
         "verbs": ["*"]},
        {"apiGroups": ["apps"],
         "resources": ["statefulsets", "deployments"],
         "verbs": ["*"]},
        {"apiGroups": ["rbac.authorization.k8s.io"],
         "resources": ["rolebindings", "clusterroles",
                       "clusterrolebindings"],
         "verbs": ["*"]},
        {"apiGroups": ["networking.k8s.io"],
         "resources": ["networkpolicies"], "verbs": ["*"]},
        {"apiGroups": ["networking.istio.io", "security.istio.io"],
         "resources": ["virtualservices", "authorizationpolicies"],
         "verbs": ["*"]},
        {"apiGroups": ["route.openshift.io"], "resources": ["routes"],
         "verbs": ["*"]},
        {"apiGroups": ["authorization.k8s.io"],
         "resources": ["subjectaccessreviews"], "verbs": ["create"]},
        # leader-election lock for the two-replica manager
        {"apiGroups": ["coordination.k8s.io"],
         "resources": ["leases"],
         "verbs": ["get", "list", "watch", "create", "update"]},
    ]
    role = {"apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRole",
            "metadata": {"name": "kubeflow-rm-tpu-manager"},
            "rules": rules}
    rb = {"apiVersion": "rbac.authorization.k8s.io/v1",
          "kind": "ClusterRoleBinding",
          "metadata": {"name": "kubeflow-rm-tpu-manager"},
          "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                      "kind": "ClusterRole",
                      "name": "kubeflow-rm-tpu-manager"},
          "subjects": [{"kind": "ServiceAccount", "name": "controlplane",
                        "namespace": NAMESPACE}]}
    # the user-facing aggregated roles the profile controller binds
    user_roles = []
    for name, verbs in (("kubeflow-admin", ["*"]),
                        ("kubeflow-edit", ["*"]),
                        ("kubeflow-view", ["get", "list", "watch"])):
        user_roles.append({
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRole",
            "metadata": {"name": name},
            "rules": [
                {"apiGroups": ["kubeflow.org",
                               "tensorboard.kubeflow.org"],
                 "resources": ["notebooks", "poddefaults",
                               "tensorboards", "pvcviewers"],
                 "verbs": verbs},
                {"apiGroups": [""],
                 "resources": ["persistentvolumeclaims", "events",
                               "pods", "pods/log", "configmaps"],
                 "verbs": verbs if verbs == ["*"]
                 else ["get", "list", "watch"]},
            ],
        })
    return [sa, role, rb, *user_roles]


def webapp_objects() -> list[dict]:
    objs: list[dict] = []
    for name, cmd, port in (
            ("jupyter-web-app", "jupyter-web-app", 5000),
            ("volumes-web-app", "volumes-web-app", 5001),
            ("tensorboards-web-app", "tensorboards-web-app", 5002),
            ("kfam", "kfam", 8081),
            ("dashboard", "dashboard", 8082)):
        objs.extend(_webapp_pair(name, cmd, port))
    objs.append(_gateway_virtualservice())
    return objs


def _gateway_virtualservice() -> dict:
    """ONE VirtualService path-routing every web app behind the gateway
    (the reference dashboard's proxy table,
    ``centraldashboard/app/server.ts:56-91``). A single resource with
    ordered routes — dashboard's "/" catch-all LAST — because Istio's
    cross-resource merge order for the same host is undefined; within
    one VirtualService route order is contractual. No rewrites: each
    app serves its routes under its own prefix (APP_PREFIX in
    ``_webapp_pair``); destinations use the SERVICE port (80)."""
    ordered = sorted(ROUTE_PREFIXES.items(),
                     key=lambda kv: -len(kv[1]))  # "/" last
    return {
        "apiVersion": "networking.istio.io/v1beta1",
        "kind": "VirtualService",
        "metadata": {"name": "kubeflow-webapps", "namespace": "kubeflow"},
        "spec": {
            "hosts": ["*"],
            "gateways": ["kubeflow/kubeflow-gateway"],
            "http": [{
                "match": [{"uri": {"prefix": prefix + "/"}}],
                "route": [{"destination": {
                    "host": f"{name}.kubeflow.svc.cluster.local",
                    "port": {"number": 80},
                }}],
            } for name, prefix in ordered],
        },
    }


def _kustomization(resources: list[str], *, namespace: str | None = None,
                   extra: dict | None = None) -> dict:
    k: dict = {"apiVersion": "kustomize.config.k8s.io/v1beta1",
               "kind": "Kustomization",
               "resources": resources}
    if namespace:
        k["namespace"] = namespace
    if extra:
        k.update(extra)
    return k


def write_tree(outdir: str) -> list[str]:
    """Write the full kustomize tree; returns the files written."""
    import yaml

    from kubeflow_rm_tpu.controlplane.deploy.crds import all_crds

    def dump(objs) -> str:
        if isinstance(objs, dict):
            objs = [objs]
        return "---\n".join(
            yaml.safe_dump(o, sort_keys=False) for o in objs)

    files: dict[str, str] = {}

    crd_files = []
    for crd in all_crds():
        fname = f"crd/bases/{crd['metadata']['name']}.yaml"
        files[fname] = dump(crd)
        crd_files.append(os.path.basename(fname))
    files["crd/kustomization.yaml"] = dump(_kustomization(
        [f"bases/{f}" for f in crd_files]))

    files["rbac/rbac.yaml"] = dump(rbac_objects())
    files["rbac/kustomization.yaml"] = dump(_kustomization(["rbac.yaml"]))

    files["manager/manager.yaml"] = dump(controller_manager_objects())
    files["manager/kustomization.yaml"] = dump(
        _kustomization(["manager.yaml"]))

    files["webhook/webhook.yaml"] = dump(webhook_objects())
    files["webhook/kustomization.yaml"] = dump(
        _kustomization(["webhook.yaml"]))

    files["webapps/webapps.yaml"] = dump(webapp_objects())
    files["webapps/kustomization.yaml"] = dump(
        _kustomization(["webapps.yaml"]))

    files["default/kustomization.yaml"] = dump(_kustomization(
        ["../crd", "../rbac", "../manager", "../webhook", "../webapps",
         "namespace.yaml"],
        namespace=NAMESPACE,
        extra={"images": [{"name": IMAGE,
                           "newName": IMAGE, "newTag": "latest"}]}))
    files["default/namespace.yaml"] = dump({
        "apiVersion": "v1", "kind": "Namespace",
        "metadata": {"name": NAMESPACE}})

    # overlays: standalone (plain) and kind (CI: local image, no TLS
    # verification dance — cert generated by the e2e script)
    files["overlays/standalone/kustomization.yaml"] = dump(
        _kustomization(["../../default"]))
    files["overlays/kind/kustomization.yaml"] = dump(_kustomization(
        ["../../default"],
        extra={"images": [{"name": IMAGE,
                           "newName": "localhost/kubeflow-rm-tpu",
                           "newTag": "ci"}]}))

    written = []
    for rel, content in files.items():
        path = os.path.join(outdir, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(content if content.endswith("\n")
                    else content + "\n")
        written.append(path)
    return written
