"""Deployment path: everything that takes the control plane out of the
in-memory test harness and onto a real cluster.

The reference deploys via kubebuilder-generated CRDs
(``notebook-controller/config/crd/bases/``), kustomize overlays
(``config/overlays/``), controller processes (``main.go``) and an HTTPS
admission server (``admission-webhook/main.go:755-773``). This package
is the TPU build's equivalent:

- ``crds``            — CRD manifests generated from the SAME api/*.py
                        validators the in-memory apiserver enforces
- ``kubeclient``      — the ``APIServer`` verb surface implemented
                        against a real kube-apiserver over REST, so the
                        SAME controllers/webhooks run in-cluster
- ``webhook_server``  — HTTPS AdmissionReview v1 server wrapping the
                        three webhook classes
- ``manifests``       — kustomize tree renderer
"""
