"""TPU rendezvous injection webhook — the north-star seam.

The producer half of the contract consumed by
``kubeflow_rm_tpu/parallel/distributed.py``: every pod of a TPU-slice
notebook gets

- ``TPU_WORKER_ID``        — its ordinal (from the StatefulSet pod name),
- ``TPU_WORKER_HOSTNAMES`` — comma-joined stable DNS of all workers
  through the headless service,
- ``TPU_ACCELERATOR_TYPE`` / ``TPU_TOPOLOGY`` — the slice shape, so
  in-notebook code can build the right ``jax.sharding.Mesh``,
- a ``/dev/shm`` Memory volume (the reference injects the same for
  NCCL DDP — ``jupyter .../form.py:264-276``; libtpu uses shm for its
  per-host IPC too).

The reference has no counterpart (its servers are single-pod,
``notebook_controller.go:409-412``); SURVEY.md §2.6 designates the
PodDefault merge point as the natural home for this injection, which is
exactly where this webhook sits in the admission chain.
"""

from __future__ import annotations

import copy

from kubeflow_rm_tpu.controlplane.api import notebook as nb_api
from kubeflow_rm_tpu.controlplane.api import tpu as tpu_api
from kubeflow_rm_tpu.controlplane.api import tpujob as tj_api
from kubeflow_rm_tpu.controlplane.api.meta import (
    fast_deepcopy,
    annotations_of,
    deep_get,
    labels_of,
    name_of,
    namespace_of,
)
from kubeflow_rm_tpu.controlplane.apiserver import APIServer

SHM_VOLUME = {"name": "dshm", "emptyDir": {"medium": "Memory"}}
SHM_MOUNT = {"name": "dshm", "mountPath": "/dev/shm"}


class TpuInjectWebhook:
    def __init__(self, api: APIServer, cluster_domain: str = "cluster.local"):
        self.api = api
        self.cluster_domain = cluster_domain

    def register(self) -> None:
        self.api.register_admission("Pod", self)

    def __call__(self, op: str, pod: dict, old: dict | None) -> dict | None:
        if op != "CREATE":
            return None
        if annotations_of(pod).get(
                nb_api.TPU_INJECT_EXCLUDE_ANNOTATION) == "true":
            return None
        acc_type = labels_of(pod).get(nb_api.TPU_ACCELERATOR_LABEL)
        in_gang = tj_api.JOB_NAME_LABEL in labels_of(pod)
        if not acc_type:
            if in_gang:
                # CPU-only gang member (an actor): role rendezvous env
                # only — the TPU-scoped vars (TPU_WORKER_ID/
                # TPU_WORKER_HOSTNAMES) stay slice-scoped and are NOT
                # injected into chipless pods
                pod = fast_deepcopy(pod)
                self._inject_role_env(pod)
                return pod
            return None
        if in_gang:
            # a gang's chip pods (the learner slice) get BOTH the
            # role env and the slice-scoped TPU rendezvous below
            pod = fast_deepcopy(pod)
            self._inject_role_env(pod)
        topo = tpu_api.lookup(acc_type)
        nslices = int(labels_of(pod).get(
            nb_api.TPU_NUM_SLICES_LABEL, "1"))

        # multislice: ordinals are laid out slice-major, so ICI
        # rendezvous (TPU_WORKER_*) is per-slice while MEGASCALE_*
        # carries the DCN dimension
        ordinal = _pod_ordinal(pod)
        slice_id, worker_in_slice = divmod(ordinal, topo.hosts)
        slice_hosts = self._worker_hostnames(pod, topo, slice_id)

        pod = fast_deepcopy(pod)
        spec = pod["spec"]
        for c in spec.get("containers") or []:
            env = c.setdefault("env", [])
            _upsert(env, "TPU_WORKER_ID", str(worker_in_slice))
            _upsert(env, "TPU_WORKER_HOSTNAMES", ",".join(slice_hosts))
            _upsert(env, "TPU_ACCELERATOR_TYPE", topo.accelerator_type)
            _upsert(env, "TPU_TOPOLOGY", topo.topology)
            if nslices > 1:
                coord = self._worker_hostnames(pod, topo, 0)[0]
                _upsert(env, "MEGASCALE_NUM_SLICES", str(nslices))
                _upsert(env, "MEGASCALE_SLICE_ID", str(slice_id))
                _upsert(env, "MEGASCALE_COORDINATOR_ADDRESS", coord)
            mounts = c.setdefault("volumeMounts", [])
            if not any(m.get("mountPath") == "/dev/shm" for m in mounts):
                mounts.append(dict(SHM_MOUNT))
        vols = spec.setdefault("volumes", [])
        if not any(v.get("name") == SHM_VOLUME["name"] for v in vols):
            vols.append(copy.deepcopy(SHM_VOLUME))
        return pod

    def _inject_role_env(self, pod: dict) -> None:
        """Role-aware gang rendezvous (mutates ``pod`` in place):
        every member of a TPUJob gang — chip pods and CPU actors alike
        — learns its role, its ordinal within the role, its own role's
        peer hostnames, every sibling role's hostname list, and the
        learner's address (pod 0 of the anchor role), so the gang
        self-assembles without polling the control plane."""
        labels = labels_of(pod)
        job = labels.get(tj_api.JOB_NAME_LABEL) or ""
        role = labels.get(tj_api.JOB_ROLE_LABEL) or ""
        roles = tj_api.parse_roles_annotation(pod) or []
        ns = namespace_of(pod)
        ordinal = _pod_ordinal(pod)

        role_hosts: dict[str, list[str]] = {}
        for r in roles:
            rname = r.get("name")
            if not rname:
                continue
            svc = r.get("service") or tj_api.role_sts_name(job, rname)
            role_hosts[rname] = [
                f"{svc}-{i}.{svc}.{ns}.svc.{self.cluster_domain}"
                for i in range(int(r.get("pods") or 0))
            ]
        own_hosts = role_hosts.get(role, [])
        learner = tj_api.learner_role(roles)
        learner_addr = ""
        if learner is not None:
            anchor = role_hosts.get(learner.get("name") or "", [])
            if anchor:
                learner_addr = anchor[0]

        for c in pod["spec"].get("containers") or []:
            env = c.setdefault("env", [])
            _upsert(env, tj_api.ENV_JOB_NAME, job)
            _upsert(env, tj_api.ENV_JOB_ROLE, role)
            _upsert(env, tj_api.ENV_JOB_ROLE_INDEX, str(ordinal))
            _upsert(env, tj_api.ENV_JOB_ROLE_HOSTNAMES,
                    ",".join(own_hosts))
            for rname, hosts in role_hosts.items():
                suffix = rname.upper().replace("-", "_")
                _upsert(env, tj_api.ENV_JOB_HOSTNAMES_PREFIX + suffix,
                        ",".join(hosts))
            if learner_addr:
                _upsert(env, tj_api.ENV_LEARNER_ADDRESS, learner_addr)

    def _worker_hostnames(self, pod: dict, topo: tpu_api.SliceTopology,
                          slice_id: int = 0) -> list[str]:
        subdomain = deep_get(pod, "spec", "subdomain")
        ns = namespace_of(pod)
        base = _base_name(pod)
        if not subdomain:
            # single-host fallback: the pod's own DNS
            return [f"{name_of(pod)}.{ns}.svc.{self.cluster_domain}"]
        start = slice_id * topo.hosts
        return [
            f"{base}-{i}.{subdomain}.{ns}.svc.{self.cluster_domain}"
            for i in range(start, start + topo.hosts)
        ]


def _pod_ordinal(pod: dict) -> int:
    name = labels_of(pod).get("statefulset.kubernetes.io/pod-name") \
        or name_of(pod)
    tail = name.rsplit("-", 1)
    if len(tail) == 2 and tail[1].isdigit():
        return int(tail[1])
    return 0


def _base_name(pod: dict) -> str:
    name = labels_of(pod).get("statefulset.kubernetes.io/pod-name") \
        or name_of(pod)
    tail = name.rsplit("-", 1)
    if len(tail) == 2 and tail[1].isdigit():
        return tail[0]
    return name


def _upsert(env: list, name: str, value: str) -> None:
    for e in env:
        if e.get("name") == name:
            return  # user-set values win
    env.append({"name": name, "value": value})
