"""PodDefault mutating webhook: the pod merge engine.

Re-implements the reference's standalone admission webhook
(``components/admission-webhook/main.go``): select PodDefaults in the
pod's namespace by label selector (``filterPodDefaults`` ``:72-99``),
prove the merge is conflict-free BEFORE touching the pod
(``safeToApplyPodDefaultsOnPod`` ``:101-152`` — a conflicted merge is
rejected atomically, never half-applied), then merge env, envFrom,
volumes, volumeMounts, tolerations, sidecars, initContainers,
imagePullSecrets, serviceAccountName, command/args, labels and
annotations (``applyPodDefaultsOnPod`` ``:480-560``).

Registered on the in-memory apiserver's admission chain for Pods —
the same interposition point the real webhook has via
MutatingWebhookConfiguration.
"""

from __future__ import annotations

import copy

from kubeflow_rm_tpu.controlplane.api import poddefault as pd_api
from kubeflow_rm_tpu.controlplane.api.meta import (
    fast_deepcopy,
    annotations_of,
    deep_get,
    labels_of,
    matches_selector,
    name_of,
    namespace_of,
)
from kubeflow_rm_tpu.controlplane.apiserver import AdmissionDenied, APIServer


class PodDefaultWebhook:
    def __init__(self, api: APIServer):
        self.api = api

    def register(self) -> None:
        self.api.register_admission("Pod", self)

    def __call__(self, op: str, pod: dict, old: dict | None) -> dict | None:
        if op != "CREATE":
            return None
        if annotations_of(pod).get(pd_api.EXCLUDE_ANNOTATION) == "true":
            return None
        matching = self._filter(pod)
        if not matching:
            return None
        self._check_conflicts(pod, matching)
        pod = fast_deepcopy(pod)
        for pd in matching:
            self._apply(pod, pd)
        return pod

    # ---- selection (ref :72-99) --------------------------------------
    def _filter(self, pod: dict) -> list[dict]:
        ns = namespace_of(pod)
        out = []
        for pd in self.api.list(pd_api.KIND, ns):
            selector = deep_get(pd, "spec", "selector", default={})
            if matches_selector(labels_of(pod), selector):
                out.append(pd)
        out.sort(key=name_of)
        return out

    # ---- conflict detection (ref :101-152) ---------------------------
    def _check_conflicts(self, pod: dict, pds: list[dict]) -> None:
        env_seen: dict[str, tuple[str, object]] = {}
        for c in deep_get(pod, "spec", "containers", default=[]) or []:
            for e in c.get("env") or []:
                env_seen[e["name"]] = ("pod", _env_value(e))
        mount_seen: dict[str, tuple[str, str]] = {}
        for c in deep_get(pod, "spec", "containers", default=[]) or []:
            for m in c.get("volumeMounts") or []:
                mount_seen[m["mountPath"]] = ("pod", m.get("name", ""))
        vol_seen: dict[str, tuple[str, dict]] = {}
        for v in deep_get(pod, "spec", "volumes", default=[]) or []:
            vol_seen[v["name"]] = ("pod", v)

        for pd in pds:
            src = name_of(pd)
            for e in deep_get(pd, "spec", "env", default=[]) or []:
                prev = env_seen.get(e["name"])
                if prev is not None and prev[1] != _env_value(e):
                    raise AdmissionDenied(
                        f"PodDefault {src}: env {e['name']!r} conflicts "
                        f"with {prev[0]}")
                env_seen[e["name"]] = (src, _env_value(e))
            for m in deep_get(pd, "spec", "volumeMounts", default=[]) or []:
                prev = mount_seen.get(m["mountPath"])
                if prev is not None and prev[1] != m.get("name", ""):
                    raise AdmissionDenied(
                        f"PodDefault {src}: mountPath {m['mountPath']!r} "
                        f"conflicts with {prev[0]}")
                mount_seen[m["mountPath"]] = (src, m.get("name", ""))
            for v in deep_get(pd, "spec", "volumes", default=[]) or []:
                prev = vol_seen.get(v["name"])
                if prev is not None and prev[1] != v:
                    raise AdmissionDenied(
                        f"PodDefault {src}: volume {v['name']!r} conflicts "
                        f"with {prev[0]}")
                vol_seen[v["name"]] = (src, v)

    # ---- merge (ref :170-560) ----------------------------------------
    def _apply(self, pod: dict, pd: dict) -> None:
        spec = pod.setdefault("spec", {})
        pspec = pd.get("spec", {})

        for v in pspec.get("volumes") or []:
            vols = spec.setdefault("volumes", [])
            if not any(x["name"] == v["name"] for x in vols):
                vols.append(copy.deepcopy(v))

        for c in spec.get("containers") or []:
            for e in pspec.get("env") or []:
                env = c.setdefault("env", [])
                if not any(x["name"] == e["name"] for x in env):
                    env.append(copy.deepcopy(e))
            for ef in pspec.get("envFrom") or []:
                envfrom = c.setdefault("envFrom", [])
                if ef not in envfrom:
                    envfrom.append(copy.deepcopy(ef))
            for m in pspec.get("volumeMounts") or []:
                mounts = c.setdefault("volumeMounts", [])
                if not any(x["mountPath"] == m["mountPath"]
                           for x in mounts):
                    mounts.append(copy.deepcopy(m))
            if pspec.get("command") and not c.get("command"):
                c["command"] = list(pspec["command"])
            if pspec.get("args") and not c.get("args"):
                c["args"] = list(pspec["args"])

        for t in pspec.get("tolerations") or []:
            tols = spec.setdefault("tolerations", [])
            if t not in tols:
                tols.append(copy.deepcopy(t))
        for s in pspec.get("imagePullSecrets") or []:
            secrets = spec.setdefault("imagePullSecrets", [])
            if s not in secrets:
                secrets.append(copy.deepcopy(s))
        for sc in pspec.get("sidecars") or []:
            containers = spec.setdefault("containers", [])
            if not any(c["name"] == sc["name"] for c in containers):
                containers.append(copy.deepcopy(sc))
        for ic in pspec.get("initContainers") or []:
            inits = spec.setdefault("initContainers", [])
            if not any(c["name"] == ic["name"] for c in inits):
                inits.append(copy.deepcopy(ic))

        if pspec.get("serviceAccountName") and \
                spec.get("serviceAccountName") in (None, "", "default"):
            spec["serviceAccountName"] = pspec["serviceAccountName"]
        if "automountServiceAccountToken" in pspec:
            spec.setdefault("automountServiceAccountToken",
                            pspec["automountServiceAccountToken"])

        meta = pod["metadata"]
        for k, v in (pspec.get("labels") or {}).items():
            meta.setdefault("labels", {}).setdefault(k, v)
        for k, v in (pspec.get("annotations") or {}).items():
            meta.setdefault("annotations", {}).setdefault(k, v)
        meta.setdefault("annotations", {})[
            pd_api.APPLIED_ANNOTATION_PREFIX + name_of(pd)
        ] = pd["metadata"].get("resourceVersion", "0")


def _env_value(e: dict):
    return e.get("value") if "value" in e else e.get("valueFrom")
