"""Notebook mutating webhook: lock protocol, image resolution,
no-restart guard.

Re-implements the ODH NotebookWebhook
(``odh-notebook-controller/controllers/notebook_webhook.go``):

- **Reconciliation lock** (``:63-74``): on CREATE the webhook stamps the
  stop-annotation with the lock value, so the reconciler renders
  replicas=0 until prerequisites settle; the LockReleaseController
  below removes it (the ODH controller does this after the pull secret
  is mounted, with retry — ``notebook_controller.go:118-146``).
- **Image resolution** (``SetContainerImageFromRegistry`` ``:541-640``):
  short image names are resolved through the ``notebook-images``
  ConfigMap (the TPU stack's stand-in for OpenShift ImageStreams).
- **No-restart guard** (``maybeRestartRunningNotebook`` ``:314-371``):
  pod-template-affecting updates to a RUNNING notebook are rejected
  unless the restart annotation opts in — a multi-host TPU slice makes
  surprise restarts N times more expensive than the reference's single
  pod.
"""

from __future__ import annotations

import copy

from kubeflow_rm_tpu.controlplane.api import notebook as nb_api
from kubeflow_rm_tpu.controlplane.api.meta import (
    annotations_of,
    deep_get,
    name_of,
    namespace_of,
    remove_annotation,
)
from kubeflow_rm_tpu.controlplane.apiserver import (
    AdmissionDenied, APIServer, NotFound,
)
from kubeflow_rm_tpu.controlplane.runtime import Controller, Request

LOCK_VALUE = "reconciliation-lock"
IMAGE_CONFIGMAP = "notebook-images"
IMAGE_CONFIGMAP_NAMESPACE = "kubeflow"


class NotebookWebhook:
    def __init__(self, api: APIServer):
        self.api = api

    def register(self) -> None:
        self.api.register_admission(nb_api.KIND, self)

    def __call__(self, op: str, notebook: dict,
                 old: dict | None) -> dict | None:
        if op == "CREATE":
            notebook = copy.deepcopy(notebook)
            self._inject_lock(notebook)
            self._resolve_image(notebook)
            return notebook
        if op == "UPDATE" and old is not None:
            self._guard_restart(notebook, old)
            return None
        return None

    def _inject_lock(self, notebook: dict) -> None:
        ann = notebook["metadata"].setdefault("annotations", {})
        ann.setdefault(nb_api.STOP_ANNOTATION, LOCK_VALUE)

    def _resolve_image(self, notebook: dict) -> None:
        cm = self.api.try_get("ConfigMap", IMAGE_CONFIGMAP,
                              IMAGE_CONFIGMAP_NAMESPACE)
        if cm is None:
            return
        images = cm.get("data") or {}
        containers = deep_get(notebook, "spec", "template", "spec",
                              "containers", default=[]) or []
        for c in containers:
            img = c.get("image", "")
            if img in images:
                c["image"] = images[img]

    def _guard_restart(self, new: dict, old: dict) -> None:
        old_ann = annotations_of(old)
        new_ann = annotations_of(new)
        stopped = nb_api.STOP_ANNOTATION in old_ann
        if stopped:
            return  # stopped notebooks may change freely
        old_tmpl = deep_get(old, "spec", "template")
        new_tmpl = deep_get(new, "spec", "template")
        tpu_changed = deep_get(old, "spec", "tpu") != deep_get(new, "spec",
                                                               "tpu")
        if old_tmpl == new_tmpl and not tpu_changed:
            return
        if new_ann.get(nb_api.RESTART_ANNOTATION) == "true":
            return  # explicit opt-in
        raise AdmissionDenied(
            f"Notebook {namespace_of(new)}/{name_of(new)} is running; "
            "spec changes would restart the slice. Stop it first or set "
            f"annotation {nb_api.RESTART_ANNOTATION}=true"
        )


class LockReleaseController(Controller):
    """Removes the webhook's reconciliation lock once the notebook's
    prerequisites exist (ref ``notebook_controller.go:118-146`` waits on
    the pull secret; here: the namespace is fully provisioned)."""

    kind = nb_api.KIND

    def reconcile(self, api: APIServer, req: Request):
        try:
            notebook = api.get(nb_api.KIND, req.name, req.namespace)
        except NotFound:
            return None
        ann = annotations_of(notebook)
        if ann.get(nb_api.STOP_ANNOTATION) != LOCK_VALUE:
            return None
        remove_annotation(notebook, nb_api.STOP_ANNOTATION)
        api.update(notebook)
        return None
