"""Notebook mutating webhook: lock protocol, image resolution,
no-restart guard.

Re-implements the ODH NotebookWebhook
(``odh-notebook-controller/controllers/notebook_webhook.go``):

- **Reconciliation lock** (``:63-74``): on CREATE the webhook stamps the
  stop-annotation with the lock value, so the reconciler renders
  replicas=0 until prerequisites settle; the LockReleaseController
  below removes it (the ODH controller does this after the pull secret
  is mounted, with retry — ``notebook_controller.go:118-146``).
- **Image resolution** (``SetContainerImageFromRegistry`` ``:541-640``):
  short image names are resolved through the ``notebook-images``
  ConfigMap (the TPU stack's stand-in for OpenShift ImageStreams).
- **No-restart guard** (``maybeRestartRunningNotebook`` ``:314-371``):
  pod-template-affecting updates to a RUNNING notebook are rejected
  unless the restart annotation opts in — a multi-host TPU slice makes
  surprise restarts N times more expensive than the reference's single
  pod.
"""

from __future__ import annotations

import copy

from kubeflow_rm_tpu.controlplane.api import notebook as nb_api
from kubeflow_rm_tpu.controlplane.api.meta import (
    fast_deepcopy,
    annotations_of,
    deep_get,
    name_of,
    namespace_of,
    remove_annotation,
)
from kubeflow_rm_tpu.controlplane.apiserver import (
    AdmissionDenied, APIServer, NotFound,
)
from kubeflow_rm_tpu.controlplane.runtime import Controller, Request

LOCK_VALUE = "reconciliation-lock"
IMAGE_CONFIGMAP = "notebook-images"
IMAGE_CONFIGMAP_NAMESPACE = "kubeflow"


class NotebookWebhook:
    def __init__(self, api: APIServer):
        self.api = api

    def register(self) -> None:
        self.api.register_admission(nb_api.KIND, self)

    def __call__(self, op: str, notebook: dict,
                 old: dict | None) -> dict | None:
        if op == "CREATE":
            notebook = fast_deepcopy(notebook)
            self._inject_lock(notebook)
            self._resolve_image(notebook)
            self._mount_ca_bundle(notebook)
            self._inject_oauth_proxy(notebook)
            return notebook
        if op == "UPDATE" and old is not None:
            self._guard_restart(notebook, old)
            return None
        return None

    def _inject_lock(self, notebook: dict) -> None:
        ann = notebook["metadata"].setdefault("annotations", {})
        ann.setdefault(nb_api.STOP_ANNOTATION, LOCK_VALUE)

    def _resolve_image(self, notebook: dict) -> None:
        cm = self.api.try_get("ConfigMap", IMAGE_CONFIGMAP,
                              IMAGE_CONFIGMAP_NAMESPACE)
        if cm is None:
            return
        images = cm.get("data") or {}
        containers = deep_get(notebook, "spec", "template", "spec",
                              "containers", default=[]) or []
        for c in containers:
            img = c.get("image", "")
            if img in images:
                c["image"] = images[img]

    def _mount_ca_bundle(self, notebook: dict) -> None:
        """CheckAndMountCACertBundle (``notebook_webhook.go:373-420``):
        if the namespace carries the assembled trusted-CA ConfigMap
        (written by the AuthCompanionController), mount it where tls
        libraries look."""
        from kubeflow_rm_tpu.controlplane.controllers.authcompanion import (
            SOURCE_CA_BUNDLE, SOURCE_CA_NAMESPACE, TRUSTED_CA_BUNDLE,
        )
        # key on the CLUSTER source bundle, not the namespace copy: for
        # the first notebook in a namespace the AuthCompanionController
        # hasn't assembled the copy yet (it triggers off this very
        # Notebook). The volume is optional, so the kubelet back-fills
        # once the controller writes the ConfigMap.
        if self.api.try_get("ConfigMap", SOURCE_CA_BUNDLE,
                            SOURCE_CA_NAMESPACE) is None:
            return
        spec = deep_get(notebook, "spec", "template", "spec", default={})
        vols = spec.setdefault("volumes", [])
        if any(v.get("name") == "trusted-ca" for v in vols):
            return
        vols.append({
            "name": "trusted-ca",
            "configMap": {"name": TRUSTED_CA_BUNDLE, "optional": True,
                          "items": [{"key": "ca-bundle.crt",
                                     "path": "tls-ca-bundle.pem"}]},
        })
        for c in spec.get("containers", []):
            c.setdefault("volumeMounts", []).append({
                "name": "trusted-ca",
                "mountPath": "/etc/pki/tls/certs",
                "readOnly": True,
            })

    def _inject_oauth_proxy(self, notebook: dict) -> None:
        """InjectOAuthProxy (``notebook_webhook.go:76-233``): opt-in
        sidecar that authenticates every request before it reaches
        JupyterLab on worker 0."""
        from kubeflow_rm_tpu.controlplane.controllers.authcompanion import (
            OAUTH_PORT, OAUTH_PORT_NAME, oauth_enabled,
        )
        if not oauth_enabled(notebook):
            return
        name, ns = name_of(notebook), namespace_of(notebook)
        spec = deep_get(notebook, "spec", "template", "spec", default={})
        containers = spec.setdefault("containers", [])
        if any(c.get("name") == "oauth-proxy" for c in containers):
            return
        containers.append({
            "name": "oauth-proxy",
            "image": "oauth-proxy:latest",
            "args": [
                f"--provider=openshift",
                f"--upstream=http://localhost:8888",
                f"--https-address=:{OAUTH_PORT}",
                f"--openshift-service-account={name}",
                "--cookie-secret-file=/etc/oauth/config/cookie_secret",
                "--tls-cert=/etc/tls/private/tls.crt",
                "--tls-key=/etc/tls/private/tls.key",
                f"--openshift-sar={{\"verb\":\"get\",\"resource\":"
                f"\"notebooks\",\"namespace\":\"{ns}\"}}",
            ],
            "ports": [{"containerPort": OAUTH_PORT,
                       "name": OAUTH_PORT_NAME, "protocol": "TCP"}],
            "volumeMounts": [
                {"name": "oauth-config",
                 "mountPath": "/etc/oauth/config"},
                {"name": "tls-certificates",
                 "mountPath": "/etc/tls/private"},
            ],
        })
        spec.setdefault("volumes", []).extend([
            {"name": "oauth-config",
             "secret": {"secretName": f"{name}-oauth-config"}},
            {"name": "tls-certificates",
             "secret": {"secretName": f"{name}-tls", "optional": True}},
        ])
        spec["serviceAccountName"] = name

    def _guard_restart(self, new: dict, old: dict) -> None:
        old_ann = annotations_of(old)
        new_ann = annotations_of(new)
        stopped = nb_api.STOP_ANNOTATION in old_ann
        if stopped:
            return  # stopped notebooks may change freely
        old_tmpl = deep_get(old, "spec", "template")
        new_tmpl = deep_get(new, "spec", "template")
        tpu_changed = deep_get(old, "spec", "tpu") != deep_get(new, "spec",
                                                               "tpu")
        if old_tmpl == new_tmpl and not tpu_changed:
            return
        if new_ann.get(nb_api.RESTART_ANNOTATION) == "true":
            return  # explicit opt-in
        raise AdmissionDenied(
            f"Notebook {namespace_of(new)}/{name_of(new)} is running; "
            "spec changes would restart the slice. Stop it first or set "
            f"annotation {nb_api.RESTART_ANNOTATION}=true"
        )


class LockReleaseController(Controller):
    """Removes the webhook's reconciliation lock once the notebook's
    prerequisites actually exist, with exponential requeue-backoff while
    they don't (ref ``odh .../notebook_controller.go:118-146`` holds the
    lock until the pull secret is mounted, retrying with backoff).

    Prerequisites gated on (VERDICT r2 weak #1 — release must not be
    unconditional):

    1. **default-editor ServiceAccount** — only for profile-managed
       namespaces (``profile_api.OWNER_ANNOTATION`` present): the
       ProfileController owns SA creation there and pods reference it;
       ad-hoc namespaces have no SA contract to wait for.
    2. **Trusted-CA bundle copy** — if the cluster source bundle exists,
       the namespace copy must have been assembled by the
       AuthCompanionController before workloads that mount it start.
    3. **Image resolvable** — every container image must be a full
       reference or a key in the ``notebook-images`` ConfigMap; a short
       name that appears in the ConfigMap only *after* admission is
       resolved here (the webhook ran too early to see it).
    """

    kind = nb_api.KIND

    # controller-runtime's default item rate limiter starts at 5 ms
    # and doubles; a 1 s base here put a visible +1 s step into the
    # spawn p50 whenever the first attempt raced the informer sync
    BASE_BACKOFF_S = 0.05
    MAX_BACKOFF_S = 60.0

    def __init__(self):
        self._attempts: dict[tuple, int] = {}

    def reconcile(self, api: APIServer, req: Request):
        try:
            notebook = api.get(nb_api.KIND, req.name, req.namespace)
        except NotFound:
            self._attempts.pop((req.namespace, req.name), None)
            return None
        ann = annotations_of(notebook)
        if ann.get(nb_api.STOP_ANNOTATION) != LOCK_VALUE:
            self._attempts.pop((req.namespace, req.name), None)
            return None
        missing, resolved = self._missing_prerequisites(api, notebook)
        if missing:
            if resolved:  # partial progress: persist resolved images
                api.update(notebook)
            key = (req.namespace, req.name)
            n = self._attempts.get(key, 0) + 1
            self._attempts[key] = n
            if n == 1 or n % 8 == 0:  # don't spam one event per retry
                api.record_event(
                    notebook, "Normal", "ReconciliationLockHeld",
                    "waiting for: " + "; ".join(missing))
            return min(self.BASE_BACKOFF_S * 2 ** (n - 1),
                       self.MAX_BACKOFF_S)
        self._attempts.pop((req.namespace, req.name), None)
        remove_annotation(notebook, nb_api.STOP_ANNOTATION)
        api.update(notebook)  # one update: resolved images + release
        return None

    def _missing_prerequisites(
            self, api: APIServer,
            notebook: dict) -> tuple[list[str], bool]:
        """Returns (missing descriptions, images-resolved-in-place)."""
        from kubeflow_rm_tpu.controlplane.api import profile as profile_api
        from kubeflow_rm_tpu.controlplane.controllers.authcompanion import (
            SOURCE_CA_BUNDLE, SOURCE_CA_NAMESPACE, TRUSTED_CA_BUNDLE,
        )
        ns = namespace_of(notebook)
        missing: list[str] = []

        ns_obj = api.try_get("Namespace", ns)
        profile_managed = bool(
            ns_obj and annotations_of(ns_obj).get(
                profile_api.OWNER_ANNOTATION))
        if profile_managed and api.try_get(
                "ServiceAccount", profile_api.DEFAULT_EDITOR, ns) is None:
            missing.append(
                f"ServiceAccount {profile_api.DEFAULT_EDITOR} in {ns}")

        if (api.try_get("ConfigMap", SOURCE_CA_BUNDLE,
                        SOURCE_CA_NAMESPACE) is not None
                and api.try_get("ConfigMap", TRUSTED_CA_BUNDLE, ns) is None):
            missing.append(f"trusted-CA bundle copy in {ns}")

        cm = api.try_get("ConfigMap", IMAGE_CONFIGMAP,
                         IMAGE_CONFIGMAP_NAMESPACE)
        images = (cm.get("data") or {}) if cm else {}
        containers = deep_get(notebook, "spec", "template", "spec",
                              "containers", default=[]) or []
        resolved = False
        for c in containers:
            img = c.get("image", "")
            if img in images:  # short name the webhook missed: fix now
                c["image"] = images[img]
                resolved = True
            elif img and "/" not in img and ":" not in img:
                missing.append(f"unresolvable container image {img!r}")
        return missing, resolved
