"""Predictive admission: the memplan walker in the webhook path.

A Notebook or TPUJob that declares its training workload
(``tpu.kubeflow.org/declared-workload`` — preset or explicit model
dims plus optim/batch/accum/remat/seq/offload knobs) gets priced by
:mod:`kubeflow_rm_tpu.analysis.jaxcheck.pricer` **at admission**, before
any placement:

- the verdict (predicted peak vs the slice's HBM budget, which phase
  binds, the full breakdown) lands in ``status.admission``;
- the predicted slice HBM and FLOPs are stamped as annotations the
  controllers fan out per-pod, giving the scheduler its second packing
  axis;
- a config whose predicted peak exceeds the budget is marked
  ``verdict: rejected`` — the Notebook/TPUJob controllers refuse to
  render pods for it (rejected *before placement*), and the
  **advisor** writes the cheapest passing rung from the memplan ladder
  into the status so the user can fix the config without a single
  OOMed step;
- a declaration that fails to parse NEVER rejects: the webhook
  degrades to chip-count-only admission with a ``Warning`` event and a
  ``swallowed_errors_total`` increment (an annotation typo must not
  take down the create path).

The CR itself is always admitted — a rejected verdict must live
somewhere the user and the advisor can see, and a denied CREATE leaves
no object to carry it. "Rejected" therefore means: status says so, an
event says why, and no pod ever renders until an UPDATE reprices the
declaration to a fitting rung.
"""

from __future__ import annotations

import json

from kubeflow_rm_tpu.controlplane.api import notebook as nb_api
from kubeflow_rm_tpu.controlplane.api import tpu as tpu_api
from kubeflow_rm_tpu.controlplane.api import tpujob as tj_api
from kubeflow_rm_tpu.controlplane.api.meta import (
    annotations_of,
    deep_get,
    fast_deepcopy,
    name_of,
    namespace_of,
)
from kubeflow_rm_tpu.controlplane.apiserver import APIServer


def record_declared_drift(agreement) -> float:
    """Bridge ``memplan_agreement`` rows (the native walk of the
    shipped step vs what the pricer predicted from the declaration)
    into the metrics registry as the worst absolute delta ratio. The
    Observer's TSDB samples the gauge every tick and the warn-only
    ``declared-hbm-drift`` SLO surfaces a sustained >20% divergence at
    ``/api/alerts``. Flag-only by design: drift means the declared
    HBM axis the scheduler packs on is lying, so the operator repacks
    (reprices) before the next bind — nothing here pages or preempts.
    Returns the ratio it recorded."""
    from kubeflow_rm_tpu.controlplane import metrics

    worst = 0.0
    for row in agreement or ():
        declared = row.get("priced_on_chip_peak_gb")
        observed = row.get("native_on_chip_peak_gb")
        if declared:
            worst = max(worst, abs(observed - declared) / declared)
        elif row.get("delta_pct") is not None:
            worst = max(worst, abs(row["delta_pct"]) / 100.0)
    metrics.DECLARED_HBM_DRIFT_RATIO.set(worst)
    return worst


def slice_topology_of(obj: dict) -> tpu_api.SliceTopology | None:
    """The slice the declared workload would run on: a Notebook's
    ``spec.tpu``, or a TPUJob's first TPU role (the learner — the role
    the model lives on)."""
    if obj.get("kind") == nb_api.KIND:
        try:
            return nb_api.tpu_spec(obj)
        except tpu_api.UnknownAcceleratorType:
            return None
    if obj.get("kind") == tj_api.KIND:
        learner = tj_api.learner_role(tj_api.roles(obj))
        acc = learner and tj_api.role_accelerator(learner)
        if acc:
            try:
                return tpu_api.lookup(acc)
            except tpu_api.UnknownAcceleratorType:
                return None
    return None


def admission_status(obj: dict) -> dict | None:
    """The priced verdict the webhook stamped, if any."""
    adm = deep_get(obj, "status", "admission")
    return adm if isinstance(adm, dict) else None


def is_admission_rejected(obj: dict) -> bool:
    adm = admission_status(obj)
    return bool(adm and adm.get("verdict") == "rejected")


class AdmissionPricer:
    """Prices declared workloads on Notebook and TPUJob CREATE/UPDATE."""

    def __init__(self, api: APIServer):
        self.api = api

    def register(self) -> None:
        self.api.register_admission(nb_api.KIND, self)
        self.api.register_admission(tj_api.KIND, self)

    def __call__(self, op: str, obj: dict,
                 old: dict | None) -> dict | None:
        if op not in ("CREATE", "UPDATE"):
            return None
        declared = annotations_of(obj).get(
            tpu_api.DECLARED_WORKLOAD_ANNOTATION)
        if not declared:
            # declaration removed: drop the stale verdict so a
            # previously-rejected CR isn't gated forever
            if admission_status(obj) is not None:
                obj = fast_deepcopy(obj)
                self._clear(obj)
                return obj
            return None
        topo = slice_topology_of(obj)
        if topo is None:
            return None   # CPU workload: nothing to price against
        obj = fast_deepcopy(obj)
        try:
            self._price(op, obj, old, declared, topo)
        except Exception as e:
            # satellite bugfix contract: an unparseable (or untraceable)
            # declaration degrades to chip-count-only admission —
            # warning + counter, never a reject, never a crash
            self._clear(obj)
            if old is None or annotations_of(old).get(
                    tpu_api.DECLARED_WORKLOAD_ANNOTATION) != declared:
                # warn once per distinct bad declaration, not on every
                # status-mirror UPDATE that re-runs admission
                from kubeflow_rm_tpu.controlplane import metrics
                metrics.swallowed("admission",
                                  "declared-workload pricing")
                try:
                    self.api.record_event(
                        obj, "Warning", "DeclaredWorkloadUnparseable",
                        f"cannot price "
                        f"{tpu_api.DECLARED_WORKLOAD_ANNOTATION}: {e};"
                        f" admitting on chip count only")
                except Exception:
                    metrics.swallowed("admission", "unparseable event")
        return obj

    # -- internals -----------------------------------------------------

    def _price(self, op: str, obj: dict, old: dict | None,
               declared: str, topo: tpu_api.SliceTopology) -> None:
        from kubeflow_rm_tpu.analysis.jaxcheck import pricer

        decl = pricer.parse(declared)
        verdict = pricer.price(decl, chips=topo.chips,
                               hbm_gib_per_chip=topo.hbm_gib_per_chip)
        verdict["accelerator_type"] = topo.accelerator_type
        if verdict["verdict"] == "rejected":
            advice = pricer.advise(
                decl, chips=topo.chips,
                hbm_gib_per_chip=topo.hbm_gib_per_chip)
            verdict["advisor"] = advice  # None: no rung fits the slice
        obj.setdefault("status", {})["admission"] = verdict
        ann = obj["metadata"].setdefault("annotations", {})
        ann[tpu_api.PREDICTED_HBM_ANNOTATION] = str(
            verdict["predicted_peak_gb"])
        ann[tpu_api.PREDICTED_FLOPS_ANNOTATION] = str(
            verdict["flops_per_step"])
        if verdict["verdict"] == "rejected" and self._newly_rejected(
                obj, old, declared):
            advice = verdict.get("advisor")
            hint = (f"; advisor: {advice['note']} -> "
                    f"{json.dumps(advice['workload'], sort_keys=True)}"
                    if advice else
                    "; no ladder rung fits this slice — use a larger "
                    "accelerator")
            self.api.record_event(
                obj, "Warning", "AdmissionRejected",
                f"{obj['kind']} {namespace_of(obj)}/{name_of(obj)}: "
                f"{verdict['explanation']}{hint}")

    def _newly_rejected(self, obj: dict, old: dict | None,
                        declared: str) -> bool:
        """Emit the rejection event once per distinct declaration, not
        on every status-mirror UPDATE that flows through admission."""
        if old is None:
            return True
        old_declared = annotations_of(old).get(
            tpu_api.DECLARED_WORKLOAD_ANNOTATION)
        return old_declared != declared or not is_admission_rejected(old)

    @staticmethod
    def _clear(obj: dict) -> None:
        status = obj.get("status")
        if isinstance(status, dict):
            status.pop("admission", None)
        ann = obj["metadata"].get("annotations")
        if ann:
            ann.pop(tpu_api.PREDICTED_HBM_ANNOTATION, None)
            ann.pop(tpu_api.PREDICTED_FLOPS_ANNOTATION, None)
