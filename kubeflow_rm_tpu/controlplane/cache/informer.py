"""Shared informer: one watch-fed feed per backend, many read paths.

controller-runtime starts one informer per watched type and shares it
between every controller's cached client; this module is that object
for both backends the platform runs against:

- **in-memory ``APIServer``**: events arrive (ordered, per-kind rv
  order) on the apiserver's fanout dispatch thread — a kind is primed
  lazily (one ``list`` on first read) and the store's rv monotonicity
  plus the relist-merge horizon keep concurrent event delivery and
  priming from ever rolling the cache back. A ``TOO_OLD`` overflow
  sentinel forces a relist of every synced kind (the 410 path).
- **``KubeAPIServer``**: the adapter's ``watch_kind`` loops own the
  transport (list+watch with rv resume, full relist on 410 Gone) and
  feed the adapter's ``ObjectStore``; the informer adopts that store,
  spawns the watch threads, and exposes ``wait_for_sync`` over it.

Read-your-writes freshness is the ``CachedAPI``'s half of the deal: a
write's returned object (with its fresh rv) is folded into the same
store before the verb returns, and the store's rv comparison keeps a
lagging watch event from rolling it back.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Iterable

from kubeflow_rm_tpu.controlplane.cache.store import ObjectStore
from kubeflow_rm_tpu.analysis.lockgraph import make_lock

log = logging.getLogger("kubeflow_rm_tpu.cache")


class SharedInformer:
    def __init__(self, api, store: ObjectStore | None = None):
        self.api = api
        # a backend that maintains its own informer cache (the kube
        # adapter) shares it; otherwise the informer owns a fresh store
        # and rides the backend's synchronous watcher fanout
        backend_store = getattr(api, "cache", None)
        if store is None and isinstance(backend_store, ObjectStore):
            self.store = backend_store
            self._backend_fed = True
        else:
            self.store = store or ObjectStore()
            self._backend_fed = False
            api.add_watcher(self._on_event, name="informer")
        # lazy priming is only sound against the in-memory backend,
        # whose list() is exact at call time (events racing the prime
        # are reconciled by replace()'s rv horizon); a remote backend
        # must sync through its watch threads
        self.lazy = not hasattr(api, "watch_kind")
        self._prime_lock = make_lock("informer.prime")
        self._threads: list[threading.Thread] = []

    # ---- event feed (in-memory backend) ------------------------------
    def _on_event(self, etype: str, obj: dict, old: dict | None) -> None:
        if etype == "TOO_OLD":
            # the apiserver's fanout queue overflowed for this watcher:
            # the event window is gone, so relist every synced kind —
            # the same recovery a kube watch 410 forces, reusing the
            # store's relist-merge (rv horizon keeps later events sane)
            for kind in self.store.synced_kinds():
                try:
                    self.store.replace(kind, self.api.list(kind))
                except Exception:  # noqa: BLE001 - kind vanished mid-relist
                    log.exception("TOO_OLD relist of %s failed", kind)
            return
        self.store.apply(etype, obj)
        from kubeflow_rm_tpu.controlplane import metrics
        kind = obj.get("kind")
        if kind:
            metrics.INFORMER_EVENTS_TOTAL.labels(kind=kind).inc()
        metrics.INFORMER_LAST_EVENT_TIMESTAMP.set(time.time())

    # ---- sync --------------------------------------------------------
    def ensure_synced(self, kind: str) -> bool:
        """True when ``kind`` may be served from the store. Under a
        lazy (in-memory) backend a cold kind is primed here with one
        list; under a remote backend sync only comes from the watch
        threads' initial list."""
        if self.store.is_synced(kind):
            return True
        if not self.lazy:
            return False
        from kubeflow_rm_tpu.controlplane import metrics
        with self._prime_lock:
            if self.store.is_synced(kind):
                return True
            try:
                objs = self.api.list(kind)
            except Exception:  # noqa: BLE001 - kind may not be served
                metrics.swallowed("informer", f"lazy prime list {kind}")
                return False
            self.store.replace(kind, objs)
            metrics.INFORMER_SYNCED_KINDS.set(
                len(self.store.synced_kinds()))
        return True

    def wait_for_sync(self, kinds: Iterable[str],
                      timeout: float | None = None) -> bool:
        kinds = list(kinds)
        if self.lazy:
            return all(self.ensure_synced(k) for k in kinds)
        return self.store.wait_for_sync(kinds, timeout)

    # ---- watch threads (remote backend) ------------------------------
    def start(self, kinds: Iterable[str],
              stop: threading.Event | None = None,
              timeout_s: int = 300) -> list[threading.Thread]:
        """Spawn one list+watch loop per kind on the backend (remote
        backends only — the in-memory backend needs none). Relist on
        410 and rv-resume live in the backend's ``watch_kind``; the
        shared store both paths feed is what makes recovery invisible
        to readers."""
        if self.lazy:
            return []
        stop = stop or threading.Event()
        for kind in kinds:
            t = threading.Thread(
                target=self.api.watch_kind, args=(kind, None, stop,
                                                  timeout_s),
                daemon=True, name=f"informer-{kind}")
            t.start()
            self._threads.append(t)
        return self._threads
