"""controlplane.cache — shared informer read cache for the reconcile hot path.

controller-runtime serves all controller reads from a watch-fed
informer cache and sends only writes to the apiserver (its "cached
client"); NotebookOS leans on replicated cached state the same way to
keep interactive scheduling latency off the request path. This package
is that layer for both of this repo's backends:

- ``store.ObjectStore``  — indexed, thread-safe object store (kind/ns/
  name primary key; per-namespace, label and owner-UID secondary
  indices; per-key rv history for conflict rebase; relist-safe
  ``replace`` with deletion tombstones; ``wait_for_sync`` gating).
- ``informer.SharedInformer`` — feeds a store from ``add_watcher``
  events; lazily primes kinds from the backend's list on first read
  (in-memory backend) or rides the kube adapter's list+watch threads
  (remote backend, which owns 410-relist recovery in ``watch_kind``).
- ``cached.CachedAPI``   — the drop-in verb surface controllers, web
  apps and webhooks talk to: reads from memory once synced, writes to
  the server with no-op suppression and a conflict fast-path.
"""

from kubeflow_rm_tpu.controlplane.cache.cached import CachedAPI
from kubeflow_rm_tpu.controlplane.cache.informer import SharedInformer
from kubeflow_rm_tpu.controlplane.cache.store import ObjectStore

__all__ = ["CachedAPI", "ObjectStore", "SharedInformer"]
