"""Indexed, thread-safe object store — the informer cache's data half.

The store holds the latest observed version of every object of every
watched kind, exactly as client-go's ``ThreadSafeStore`` + ``Indexers``
do for controller-runtime's cached client. Three properties carry the
correctness load:

- **rv monotonicity**: ``apply`` never lets an older watch event roll
  back a newer write that was folded in directly (read-your-writes).
- **relist safety**: ``replace`` (the 410-Gone recovery path, and the
  lazy prime) merges a freshly-listed snapshot against events that
  raced it — entries newer than the snapshot survive, and deletion
  tombstones stop a stale snapshot from resurrecting an object deleted
  during the race window.
- **sync gating**: a kind serves reads only after its initial list
  (``is_synced``/``wait_for_sync``), so a cold cache can never report
  NotFound for objects it simply hasn't seen yet.

Stored objects are treated as immutable: ``apply``/``replace`` keep
references, readers receive references and MUST NOT mutate them (the
``CachedAPI`` copies before handing objects to callers; ``scan``-style
consumers honor the same contract the in-memory apiserver's ``scan``
documents). A bounded per-key rv history backs the conflict fast-path's
three-way rebase.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Iterable

from kubeflow_rm_tpu.controlplane.api.meta import (
    labels_of,
    matches_selector,
    name_of,
    namespace_of,
)
from kubeflow_rm_tpu.analysis.lockgraph import make_condition, make_rlock

# same scope table the in-memory apiserver and the kube adapter's REST
# mapping use — a cluster-scoped object is keyed under namespace None
# no matter what namespace a caller passes
CLUSTER_SCOPED_KINDS = {
    "Namespace", "Profile", "Node", "ClusterRole", "ClusterRoleBinding",
    "PersistentVolume", "CustomResourceDefinition",
}

# rv versions retained per key for the conflict fast-path's base lookup
HISTORY_DEPTH = 4


def rv_of(obj: dict | None) -> int:
    try:
        return int((obj.get("metadata") or {}).get("resourceVersion", 0))
    except (TypeError, ValueError, AttributeError):
        return 0


class ObjectStore:
    def __init__(self, cluster_scoped: set[str] | None = None):
        self._lock = make_rlock("cache.store")
        self._cond = make_condition("cache.store", lock=self._lock)
        self._cluster_scoped = cluster_scoped or CLUSTER_SCOPED_KINDS
        # kind -> {(ns, name): obj}
        self._by_kind: dict[str, dict[tuple, dict]] = {}
        # kind -> {ns: set[key]}
        self._by_ns: dict[str, dict[str | None, set[tuple]]] = {}
        # kind -> {(label_key, label_value): set[key]}
        self._by_label: dict[str, dict[tuple[str, str], set[tuple]]] = {}
        # kind -> {owner_uid: set[key]} (controller + non-controller refs)
        self._by_owner: dict[str, dict[str, set[tuple]]] = {}
        # Events only: (involved kind, ns, involved name) -> set[key] —
        # the notebook controller asks "events for this pod/STS" every
        # reconcile, which scanned the whole Event list per call
        self._by_involved: dict[tuple, set[tuple]] = {}
        # kind -> {key: {rv: obj}} bounded base history (conflict rebase)
        self._history: dict[str, dict[tuple, "collections.OrderedDict"]] = {}
        # kind -> {key: rv} deletion tombstones guarding replace races
        self._tombstones: dict[str, dict[tuple, int]] = {}
        self._synced: set[str] = set()
        # observability: events folded in + wall time of the last one
        self.events_applied = 0
        self.last_event_t: float = 0.0

    # ---- keys --------------------------------------------------------
    def key_for(self, kind: str, name: str,
                namespace: str | None) -> tuple:
        if kind in self._cluster_scoped:
            return (None, name)
        return (namespace, name)

    def _key_of(self, obj: dict) -> tuple:
        return self.key_for(obj["kind"], name_of(obj), namespace_of(obj))

    # ---- index maintenance (callers hold the lock) -------------------
    def _index_add(self, kind: str, key: tuple, obj: dict) -> None:
        ns = key[0]
        self._by_ns.setdefault(kind, {}).setdefault(ns, set()).add(key)
        lbl = self._by_label.setdefault(kind, {})
        for pair in labels_of(obj).items():
            lbl.setdefault(pair, set()).add(key)
        own = self._by_owner.setdefault(kind, {})
        for ref in obj["metadata"].get("ownerReferences") or []:
            uid = ref.get("uid")
            if uid:
                own.setdefault(uid, set()).add(key)
        if kind == "Event":
            ikey = self._involved_key(obj)
            if ikey is not None:
                self._by_involved.setdefault(ikey, set()).add(key)

    @staticmethod
    def _involved_key(event: dict) -> tuple | None:
        inv = event.get("involvedObject") or {}
        if not inv.get("kind") or not inv.get("name"):
            return None
        return (inv["kind"], namespace_of(event), inv["name"])

    def _index_remove(self, kind: str, key: tuple, obj: dict) -> None:
        ns_idx = self._by_ns.get(kind, {})
        bucket = ns_idx.get(key[0])
        if bucket:
            bucket.discard(key)
            if not bucket:
                ns_idx.pop(key[0], None)
        lbl = self._by_label.get(kind, {})
        for pair in labels_of(obj).items():
            bucket = lbl.get(pair)
            if bucket:
                bucket.discard(key)
                if not bucket:
                    lbl.pop(pair, None)
        own = self._by_owner.get(kind, {})
        for ref in obj["metadata"].get("ownerReferences") or []:
            bucket = own.get(ref.get("uid"))
            if bucket:
                bucket.discard(key)
                if not bucket:
                    own.pop(ref.get("uid"), None)
        if kind == "Event":
            ikey = self._involved_key(obj)
            bucket = self._by_involved.get(ikey)
            if bucket:
                bucket.discard(key)
                if not bucket:
                    self._by_involved.pop(ikey, None)

    def _remember(self, kind: str, key: tuple, obj: dict) -> None:
        hist = self._history.setdefault(kind, {}).setdefault(
            key, collections.OrderedDict())
        hist[rv_of(obj)] = obj
        while len(hist) > HISTORY_DEPTH:
            hist.popitem(last=False)

    # ---- writes ------------------------------------------------------
    def apply(self, etype: str, obj: dict) -> None:
        """Fold one watch event (or a write's server response) in.
        ADDED/MODIFIED upsert rv-compared; DELETED removes and leaves a
        tombstone so a racing relist can't resurrect the object."""
        kind = obj.get("kind")
        if not kind:
            return
        key = self._key_of(obj)
        with self._lock:
            store = self._by_kind.setdefault(kind, {})
            cur = store.get(key)
            if etype == "DELETED":
                self._tombstones.setdefault(kind, {})[key] = max(
                    rv_of(obj), rv_of(cur))
                if cur is not None:
                    self._index_remove(kind, key, cur)
                    del store[key]
                self._history.get(kind, {}).pop(key, None)
            else:
                if cur is not None and rv_of(obj) < rv_of(cur):
                    return  # stale event behind a folded-in write
                tombs = self._tombstones.get(kind, {})
                if key in tombs:
                    if rv_of(obj) <= tombs[key]:
                        return  # stale event from before the delete
                    del tombs[key]  # object genuinely came back
                if cur is not None:
                    self._index_remove(kind, key, cur)
                store[key] = obj
                self._index_add(kind, key, obj)
                self._remember(kind, key, obj)
            self.events_applied += 1
            self.last_event_t = time.time()

    def replace(self, kind: str, objs: Iterable[dict]) -> None:
        """Relist: replace a kind's contents with a fresh snapshot and
        mark it synced. Entries newer than the snapshot's horizon (rv
        above the snapshot's max) survive — they arrived through the
        watch/write path while the list was in flight — and tombstoned
        deletions newer than their snapshot version stay deleted."""
        objs = list(objs)
        horizon = max((rv_of(o) for o in objs), default=0)
        with self._lock:
            store = self._by_kind.setdefault(kind, {})
            tombs = self._tombstones.setdefault(kind, {})
            fresh: dict[tuple, dict] = {}
            for o in objs:
                key = self._key_of(o)
                if tombs.get(key, -1) >= rv_of(o):
                    continue  # deleted after this snapshot version
                cur = store.get(key)
                fresh[key] = cur if cur is not None and \
                    rv_of(cur) > rv_of(o) else o
            # keep racing additions the snapshot predates
            for key, cur in store.items():
                if key not in fresh and rv_of(cur) > horizon:
                    fresh[key] = cur
            for key, cur in store.items():
                self._index_remove(kind, key, cur)
            store.clear()
            for key, o in fresh.items():
                store[key] = o
                self._index_add(kind, key, o)
                self._remember(kind, key, o)
            # tombstones at/below the horizon can never matter again
            for key in [k for k, rv in tombs.items() if rv <= horizon]:
                del tombs[key]
            self._synced.add(kind)
            self._cond.notify_all()

    def discard(self, kind: str, name: str,
                namespace: str | None) -> None:
        """Optimistic local removal after a DELETE verb (no rv known):
        tombstoned at the current entry's rv so only a strictly newer
        snapshot/event can bring the object back (finalizer-bearing
        objects do return, via their MODIFIED watch event)."""
        key = self.key_for(kind, name, namespace)
        with self._lock:
            store = self._by_kind.get(kind, {})
            cur = store.get(key)
            if cur is not None:
                self._tombstones.setdefault(kind, {})[key] = rv_of(cur)
                self._index_remove(kind, key, cur)
                del store[key]
            self._history.get(kind, {}).pop(key, None)

    # ---- sync gating -------------------------------------------------
    def is_synced(self, kind: str) -> bool:
        with self._lock:
            return kind in self._synced

    def synced_kinds(self) -> set[str]:
        with self._lock:
            return set(self._synced)

    def mark_synced(self, kind: str) -> None:
        with self._lock:
            self._synced.add(kind)
            self._cond.notify_all()

    def unsync(self, kind: str) -> None:
        """Stop serving a kind (its watch died past recovery); reads
        fall through to the server until the next relist."""
        with self._lock:
            self._synced.discard(kind)

    def wait_for_sync(self, kinds: Iterable[str],
                      timeout: float | None = None) -> bool:
        """Block until every kind has completed its initial list.
        Returns False on timeout — callers decide whether a cold cache
        is fatal (a serving loop) or fine (reads fall through)."""
        kinds = set(kinds)
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while not kinds <= self._synced:
                remaining = None if deadline is None else \
                    deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True

    # ---- reads (references — callers must not mutate) ----------------
    def get_ref(self, kind: str, name: str,
                namespace: str | None = None) -> dict | None:
        key = self.key_for(kind, name, namespace)
        with self._lock:
            return self._by_kind.get(kind, {}).get(key)

    def base_ref(self, kind: str, name: str, namespace: str | None,
                 rv: int) -> dict | None:
        """The retained historical version at exactly ``rv`` (conflict
        fast-path three-way base), or None if it aged out."""
        key = self.key_for(kind, name, namespace)
        with self._lock:
            return self._history.get(kind, {}).get(key, {}).get(rv)

    def list_refs(self, kind: str, namespace: str | None = None,
                  label_selector: dict | None = None) -> list[dict]:
        with self._lock:
            store = self._by_kind.get(kind, {})
            if namespace is not None:
                keys = set(self._by_ns.get(kind, {}).get(namespace, ()))
            else:
                keys = None  # whole kind
            if label_selector:
                pairs = (label_selector.get("matchLabels")
                         if "matchLabels" in label_selector
                         or "matchExpressions" in label_selector
                         else label_selector) or {}
                # narrow through the label index on one required pair;
                # the full selector (expressions included) still runs
                for pair in pairs.items():
                    hits = set(self._by_label.get(kind, {}).get(pair, ()))
                    keys = hits if keys is None else keys & hits
                    break
            objs = (store.values() if keys is None
                    else [store[k] for k in keys if k in store])
            if label_selector:
                objs = [o for o in objs
                        if matches_selector(labels_of(o), label_selector)]
            else:
                objs = list(objs)
        objs.sort(key=lambda o: (namespace_of(o) or "", name_of(o)))
        return objs

    def events_for_ref(self, involved_kind: str, involved_name: str,
                       namespace: str | None) -> list[dict]:
        """Events whose involvedObject matches, via the involved-object
        index — O(matches), not O(events in namespace). Returns store
        references; callers must not mutate."""
        with self._lock:
            store = self._by_kind.get("Event", {})
            keys = self._by_involved.get(
                (involved_kind, namespace, involved_name), ())
            out = [store[k] for k in keys if k in store]
        out.sort(key=lambda o: (namespace_of(o) or "", name_of(o)))
        return out

    def owned_by(self, owner_uid: str,
                 kind: str | None = None) -> list[dict]:
        """Dependents carrying an ownerReference to ``owner_uid`` —
        the owner-UID index behind watch-map fanout and GC-style
        queries, without an O(store) scan."""
        with self._lock:
            kinds = [kind] if kind else list(self._by_owner)
            out = []
            for k in kinds:
                store = self._by_kind.get(k, {})
                for key in self._by_owner.get(k, {}).get(owner_uid, ()):
                    if key in store:
                        out.append(store[key])
        out.sort(key=lambda o: (o["kind"], namespace_of(o) or "",
                                name_of(o)))
        return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "kinds": len(self._by_kind),
                "objects": sum(len(s) for s in self._by_kind.values()),
                "synced_kinds": len(self._synced),
                "events_applied": self.events_applied,
                "last_event_t": self.last_event_t,
            }
