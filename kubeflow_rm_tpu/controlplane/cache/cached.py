"""``CachedAPI`` — controller-runtime's cached client for this repo.

Drop-in for the ``APIServer`` verb surface: reads (``get``/``try_get``/
``list``/``scan``/``events_for``) are served from the shared informer's
store once the kind has synced, writes go to the backing server with
the response folded straight back into the store (read-your-writes).
Two write-path optimizations ride on the cache:

- **no-op suppression**: ``update``/``update_status``/``patch`` deep-
  compare the desired object against the cached current one after
  normalization (volatile metadata — resourceVersion, generation,
  managedFields, creationTimestamp, uid, selfLink — stripped), and a
  semantically identical write returns the current object without
  touching the server. A steady-state reconcile of an unchanged object
  therefore issues zero write verbs.
- **conflict fast-path**: a Conflict normally costs GET + retry. Here
  the cache already holds the latest version AND the version the caller
  based its write on (bounded rv history), so the adapter does a
  three-way rebase in memory: if the caller's changes and the
  concurrent writer's changes touch disjoint paths, the caller's diff
  is replayed onto the latest object and retried once — no extra GET,
  and never a blind rv refresh (which would stomp the concurrent
  write).

Unknown attributes delegate to the backend, so backend-specific surface
(``watch_kind``, ``write_log``, ``set_writer``, ``limiter``, …) stays
reachable through the wrapper.
"""

from __future__ import annotations

import logging

from kubeflow_rm_tpu.controlplane.api.meta import (
    fast_deepcopy,
    name_of,
    namespace_of,
    strategic_merge,
)
from kubeflow_rm_tpu.controlplane.apiserver import Conflict, NotFound
from kubeflow_rm_tpu.controlplane.cache.informer import SharedInformer
from kubeflow_rm_tpu.controlplane.cache.store import rv_of

log = logging.getLogger("kubeflow_rm_tpu.cache")

# server-owned metadata that never makes a write semantically different
_VOLATILE_META = ("resourceVersion", "generation", "managedFields",
                  "creationTimestamp", "uid", "selfLink")

_DELETE = object()  # tombstone value in a leaf diff: "key removed"


def normalized(obj: dict) -> dict:
    """A copy with server-owned volatile metadata stripped — the shape
    no-op detection and the three-way diff compare on."""
    out = fast_deepcopy(obj)
    meta = out.get("metadata")
    if isinstance(meta, dict):
        for k in _VOLATILE_META:
            meta.pop(k, None)
    return out


def leaf_diff(base, new, prefix=()) -> dict:
    """Leaf-level changes turning ``base`` into ``new`` as
    ``{path_tuple: new_value | _DELETE}``. Dicts recurse; anything else
    (lists included) is one leaf — list surgery is not safely
    rebasable, so a changed list is one opaque change."""
    ops: dict = {}
    if isinstance(base, dict) and isinstance(new, dict):
        for k in set(base) | set(new):
            if k not in new:
                ops[prefix + (k,)] = _DELETE
            elif k not in base:
                ops[prefix + (k,)] = new[k]
            else:
                ops.update(leaf_diff(base[k], new[k], prefix + (k,)))
    elif base != new:
        ops[prefix] = new
    return ops


def _paths_clash(ours, theirs) -> bool:
    """True when any path pair overlaps (equal, or one a prefix of the
    other) — then the two writes touched the same region and a rebase
    would silently pick a winner."""
    for p in ours:
        for q in theirs:
            n = min(len(p), len(q))
            if p[:n] == q[:n]:
                return True
    return False


class CachedAPI:
    def __init__(self, api, informer: SharedInformer | None = None):
        self.api = api
        self.informer = informer or SharedInformer(api)
        self.store = self.informer.store
        from kubeflow_rm_tpu.controlplane import metrics
        # pre-bound label children: the read path runs per reconcile
        self._m_hit = {v: metrics.CACHE_READS_TOTAL.labels(
            verb=v, result="hit") for v in ("get", "list", "scan")}
        self._m_miss = {v: metrics.CACHE_READS_TOTAL.labels(
            verb=v, result="miss") for v in ("get", "list", "scan")}
        self._m_suppressed = {
            v: metrics.CACHE_SUPPRESSED_WRITES_TOTAL.labels(verb=v)
            for v in ("update", "update_status", "patch")}
        self._m_fastpath = {
            r: metrics.CACHE_CONFLICT_FASTPATH_TOTAL.labels(result=r)
            for r in ("noop", "rebased", "fallthrough")}

    # ---- plumbing ----------------------------------------------------
    def __getattr__(self, name):
        return getattr(self.api, name)

    def _serves(self, kind: str) -> bool:
        return self.informer.ensure_synced(kind)

    def wait_for_sync(self, kinds, timeout: float | None = None) -> bool:
        return self.informer.wait_for_sync(kinds, timeout)

    # ---- reads -------------------------------------------------------
    def get(self, kind: str, name: str,
            namespace: str | None = None) -> dict:
        if self._serves(kind):
            self._m_hit["get"].inc()
            obj = self.store.get_ref(kind, name, namespace)
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            return fast_deepcopy(obj)
        self._m_miss["get"].inc()
        return self.api.get(kind, name, namespace)

    def try_get(self, kind: str, name: str,
                namespace: str | None = None) -> dict | None:
        try:
            return self.get(kind, name, namespace)
        except NotFound:
            return None

    def list(self, kind: str, namespace: str | None = None,
             label_selector: dict | None = None) -> list[dict]:
        if self._serves(kind):
            self._m_hit["list"].inc()
            return [fast_deepcopy(o) for o in
                    self.store.list_refs(kind, namespace, label_selector)]
        self._m_miss["list"].inc()
        return self.api.list(kind, namespace, label_selector)

    def scan(self, kind: str, namespace: str | None = None) -> list[dict]:
        """READ-ONLY ``list``: store references, no copies — same
        contract as the in-memory apiserver's ``scan`` (callers must
        not mutate; write through ``update`` on a ``get()`` copy)."""
        if self._serves(kind):
            self._m_hit["scan"].inc()
            return self.store.list_refs(kind, namespace)
        self._m_miss["scan"].inc()
        return getattr(self.api, "scan", self.api.list)(kind, namespace)

    def events_for(self, involved: dict) -> list[dict]:
        if self._serves("Event"):
            # involved-object index: O(matches) per lookup where the
            # namespace filter was O(events) — the notebook controller
            # calls this per pod per reconcile (the re-emit storm)
            return [
                fast_deepcopy(e)
                for e in self.store.events_for_ref(
                    involved["kind"], name_of(involved),
                    namespace_of(involved))
            ]
        return self.api.events_for(involved)

    def ensure_namespace(self, namespace: str) -> dict:
        if self._serves("Namespace"):
            cur = self.store.get_ref("Namespace", namespace, None)
            if cur is not None:
                return fast_deepcopy(cur)
        out = self.api.ensure_namespace(namespace)
        self._fold("ADDED", out)
        return out

    # ---- writes ------------------------------------------------------
    def _fold(self, etype: str, obj: dict) -> None:
        """Read-your-writes: the server's response (fresh rv) lands in
        the store before the verb returns. A copy goes in — the caller
        keeps the returned object and may mutate it. rv-compared, so a
        concurrently-delivered watch event can't roll it back (nor the
        fold roll back anything newer)."""
        self.store.apply(etype, fast_deepcopy(obj))

    def create(self, obj: dict) -> dict:
        out = self.api.create(obj)
        self._fold("ADDED", out)
        return out

    def create_many(self, objs: list[dict]) -> list[dict]:
        """Bulk create through the backend's batch verb (one lock/HTTP
        round trip), folding each created object into the store;
        per-item Status failures pass through untouched. Backends
        without the verb fall back to per-object creates."""
        from kubeflow_rm_tpu.controlplane.apiserver import (
            APIError,
            is_status,
            status_from_error,
        )
        creator = getattr(self.api, "create_many", None)
        if creator is None:
            out = []
            for obj in objs:
                try:
                    out.append(self.create(obj))
                except APIError as e:
                    out.append(status_from_error(e))
            return out
        out = creator(objs)
        for item in out:
            if not is_status(item):
                self._fold("ADDED", item)
        return out

    def update(self, obj: dict) -> dict:
        kind = obj["kind"]
        if self._serves(kind):
            cur = self.store.get_ref(kind, name_of(obj),
                                     namespace_of(obj))
            if cur is not None and normalized(obj) == normalized(cur):
                self._m_suppressed["update"].inc()
                return fast_deepcopy(cur)
        try:
            out = self.api.update(obj)
        except Conflict:
            out = self._resolve_conflict(obj)
        self._fold("MODIFIED", out)
        return out

    def update_status(self, obj: dict) -> dict:
        kind = obj["kind"]
        if self._serves(kind):
            cur = self.store.get_ref(kind, name_of(obj),
                                     namespace_of(obj))
            if cur is not None and \
                    obj.get("status", {}) == cur.get("status", {}):
                self._m_suppressed["update_status"].inc()
                return fast_deepcopy(cur)
        out = self.api.update_status(obj)
        self._fold("MODIFIED", out)
        return out

    def patch(self, kind: str, name: str, patch: dict,
              namespace: str | None = None) -> dict:
        if self._serves(kind):
            cur = self.store.get_ref(kind, name, namespace)
            if cur is not None:
                merged = strategic_merge(fast_deepcopy(cur), patch)
                if normalized(merged) == normalized(cur):
                    self._m_suppressed["patch"].inc()
                    return fast_deepcopy(cur)
        out = self.api.patch(kind, name, patch, namespace)
        self._fold("MODIFIED", out)
        return out

    def delete(self, kind: str, name: str,
               namespace: str | None = None) -> None:
        out = self.api.delete(kind, name, namespace)
        # read-your-writes: the in-memory server's DELETED/MODIFIED
        # event arrives on the fanout thread, so reconcile the store
        # from the backend's post-delete truth before returning — gone
        # means discard, finalizer-pending means fold the
        # deletionTimestamp. (The kube adapter feeds its own shared
        # store and already discards optimistically in its delete.)
        if not self.informer._backend_fed and self._serves(kind):
            cur = self.api.try_get(kind, name, namespace)
            if cur is None:
                self.store.discard(kind, name, namespace)
            else:
                self._fold("MODIFIED", cur)
        return out

    def record_event(self, involved: dict, etype: str, reason: str,
                     message: str) -> dict:
        out = self.api.record_event(involved, etype, reason, message)
        self._fold("ADDED", out)
        return out

    # ---- conflict fast-path ------------------------------------------
    def _resolve_conflict(self, desired: dict) -> dict:
        """Resolve one Conflict without a server GET. Safe outcomes
        only: (a) the write is a semantic no-op against the latest
        cached version — return it; (b) the caller's changes (diffed
        against the exact base version it read, from the store's rv
        history) touch paths disjoint from the concurrent writer's —
        replay them onto latest and retry once. Anything else re-raises
        for the caller's own retry loop (which re-reads). A blind rv
        refresh is deliberately NOT done: it would overwrite the
        concurrent write with the caller's stale copy."""
        kind = desired["kind"]
        name, ns = name_of(desired), namespace_of(desired)
        if not self._serves(kind):
            raise
        latest = self.store.get_ref(kind, name, ns)
        if latest is None:
            raise  # deleted under us: the caller's NotFound handling wins
        if normalized(desired) == normalized(latest):
            self._m_fastpath["noop"].inc()
            return fast_deepcopy(latest)
        base = self.store.base_ref(kind, name, ns, rv_of(desired))
        if base is None:
            self._m_fastpath["fallthrough"].inc()
            raise  # base aged out of history — can't prove disjointness
        ours = leaf_diff(normalized(base), normalized(desired))
        theirs = leaf_diff(normalized(base), normalized(latest))
        if not ours:
            self._m_fastpath["noop"].inc()
            return fast_deepcopy(latest)
        if _paths_clash(ours, theirs):
            self._m_fastpath["fallthrough"].inc()
            raise  # overlapping edits: a rebase would pick a winner
        rebased = fast_deepcopy(latest)
        for path, val in ours.items():
            node = rebased
            for k in path[:-1]:
                nxt = node.get(k)
                if not isinstance(nxt, dict):
                    nxt = {}
                    node[k] = nxt
                node = nxt
            if val is _DELETE:
                node.pop(path[-1], None)
            else:
                node[path[-1]] = fast_deepcopy(val) \
                    if isinstance(val, (dict, list)) else val
        out = self.api.update(rebased)  # a second Conflict propagates
        self._m_fastpath["rebased"].inc()
        return out
