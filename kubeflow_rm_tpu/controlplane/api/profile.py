"""Profile resource: per-user namespace onboarding.

Mirrors ``profile-controller/api/v1/profile_types.go:36-44``: a
cluster-scoped CR carrying the owner subject, an optional
ResourceQuotaSpec (where TPU-chip quotas live —
``profile_controller.go:252-281``), and a plugin list.
"""

from __future__ import annotations

from kubeflow_rm_tpu.controlplane.api.meta import make_object

API_VERSION = "kubeflow.org/v1"
KIND = "Profile"

OWNER_ANNOTATION = "owner"
QUOTA_NAME = "kf-resource-quota"
DEFAULT_EDITOR = "default-editor"
DEFAULT_VIEWER = "default-viewer"


def make_profile(name: str, owner_email: str, *,
                 quota_hard: dict | None = None,
                 plugins: list | None = None) -> dict:
    spec: dict = {"owner": {"kind": "User", "name": owner_email}}
    if quota_hard:
        spec["resourceQuotaSpec"] = {"hard": dict(quota_hard)}
    if plugins:
        spec["plugins"] = list(plugins)
    return make_object(API_VERSION, KIND, name, spec=spec)
