"""PodDefault resource: label-selected pod-patch bundles.

Mirrors the reference CRD
(``admission-webhook/pkg/apis/settings/v1alpha1/poddefault_types.go:27-125``):
a namespaced bundle of env/envFrom/volumes/volumeMounts/sidecars/
initContainers/tolerations/labels/annotations/serviceAccountName/
command/args/imagePullSecrets applied to every pod in the namespace
whose labels match ``spec.selector``.
"""

from __future__ import annotations

from kubeflow_rm_tpu.controlplane.api.meta import deep_get, make_object

API_VERSION = "kubeflow.org/v1alpha1"
KIND = "PodDefault"

EXCLUDE_ANNOTATION = "poddefault.admission.kubeflow.org/exclude"
APPLIED_ANNOTATION_PREFIX = "poddefault.admission.kubeflow.org/poddefault-"

MERGE_FIELDS = (
    "env", "envFrom", "volumes", "volumeMounts", "sidecars",
    "initContainers", "tolerations", "imagePullSecrets",
)


def make_poddefault(name: str, namespace: str, *, selector: dict,
                    desc: str = "", **spec_fields) -> dict:
    spec = {"selector": selector, "desc": desc or name}
    for k, v in spec_fields.items():
        if k not in MERGE_FIELDS and k not in (
                "serviceAccountName", "automountServiceAccountToken",
                "labels", "annotations", "command", "args"):
            raise ValueError(f"unknown PodDefault spec field {k!r}")
        spec[k] = v
    return make_object(API_VERSION, KIND, name, namespace, spec=spec)


def validate(pd: dict) -> None:
    if deep_get(pd, "spec", "selector") is None:
        raise ValueError("PodDefault spec.selector is required")
