"""TPUJob — multi-role gang jobs (Podracer-style actor–learner).

A ``TPUJob`` is the first CRD whose children are heterogeneous: its
spec holds an **ordered list of role groups**, each materialised as one
StatefulSet, and the whole job is scheduled as ONE gang — the learner
slice's chip pods and the actors' CPU-only pods bind all-or-nothing in
a single assume transaction (``scheduler.SchedulerCache.gang_bind``).
Podracer (arxiv 2104.06272) is the workload template: a learner on a
TPU slice plus many CPU actors feeding it trajectories; NotebookOS
(arxiv 2503.20591) shows one control plane multiplexing such
heterogeneous roles.

Spec shape (v1, the storage version)::

    spec:
      roles:
        - name: learner
          replicas: 1                 # slices for TPU roles
          tpu: {acceleratorType: v5p-16}
        - name: actors
          replicas: 4                 # pods for CPU roles
          cpu: "2"                    # per-pod CPU request
      priorityClassName: default      # optional

A TPU role's pod count is ``replicas × hosts(acceleratorType)`` (one
pod per host, exactly like a Notebook slice); a CPU role's is
``replicas``. The controller stamps every gang pod with
``JOB_NAME_LABEL``/``JOB_ROLE_LABEL`` and the gang-wide
``JOB_ROLES_ANNOTATION`` so the webhook can inject role-aware
rendezvous env (``TPU_JOB_ROLE``, ``TPU_JOB_ROLE_INDEX``, per-role
hostname lists, the learner address) without the client polling.

Suspend/resume reuses the Notebook annotation vocabulary
(``notebook.SUSPEND_ANNOTATION`` etc.) so ``controlplane/suspend.py``
helpers drive both kinds; parking a TPUJob scales EVERY role to zero —
no half-gang ever runs.
"""

from __future__ import annotations

import json
import re

from kubeflow_rm_tpu.controlplane.api import notebook as nb_api
from kubeflow_rm_tpu.controlplane.api import tpu as tpu_api
from kubeflow_rm_tpu.controlplane.api.meta import (
    annotations_of,
    deep_get,
    labels_of,
    make_object,
    name_of,
    parse_quantity,
)

API_VERSION = "kubeflow.org/v1"
KIND = "TPUJob"

#: stamped on every gang pod (and role STS pod template) — the webhook
#: keys role injection off these, the controller maps Pod events back
#: to the job, and the binder collects the whole gang by this label
JOB_NAME_LABEL = "tpu.kubeflow.org/job"
JOB_ROLE_LABEL = "tpu.kubeflow.org/job-role"

#: gang-wide role metadata, JSON on every gang pod:
#: ``[{"name", "pods", "service", "tpu"}, ...]`` in spec order — enough
#: for the webhook to render every role's hostname list and for the
#: StatefulSet binder to know the expected gang size without a CR read
JOB_ROLES_ANNOTATION = "tpu.kubeflow.org/job-roles"

# ---- the rendezvous env contract (webhook → launcher) ----------------
ENV_JOB_NAME = "TPU_JOB_NAME"
ENV_JOB_ROLE = "TPU_JOB_ROLE"
ENV_JOB_ROLE_INDEX = "TPU_JOB_ROLE_INDEX"
ENV_JOB_ROLE_HOSTNAMES = "TPU_JOB_ROLE_HOSTNAMES"
#: + TPU_JOB_HOSTNAMES_<ROLE> (uppercased, ``-``→``_``) per role
ENV_JOB_HOSTNAMES_PREFIX = "TPU_JOB_HOSTNAMES_"
ENV_LEARNER_ADDRESS = "TPU_JOB_LEARNER_ADDRESS"

# ---- job phases ------------------------------------------------------
PENDING_PHASE = "Pending"
PROVISIONING_PHASE = "Provisioning"
RUNNING_PHASE = "Running"
SUCCEEDED_PHASE = "Succeeded"
FAILED_PHASE = "Failed"
#: parked gangs report the shared suspend phase
SUSPENDED_PHASE = nb_api.SUSPENDED_PHASE

MAX_ROLES = 8
MAX_ROLE_REPLICAS = 512

_ROLE_NAME_RE = re.compile(r"^[a-z]([a-z0-9-]{0,30}[a-z0-9])?$")

DEFAULT_IMAGE = "jupyter-jax:latest"


def roles(job: dict) -> list[dict]:
    """The ordered role groups (spec order is rendezvous order — the
    first role's STS is the gang's binder)."""
    return deep_get(job, "spec", "roles", default=[]) or []


def role_accelerator(role: dict) -> str | None:
    return deep_get(role, "tpu", "acceleratorType")


def role_pods(role: dict) -> int:
    """Pods this role materialises: slices × hosts for TPU roles,
    replicas for CPU roles."""
    replicas = int(role.get("replicas", 1))
    acc = role_accelerator(role)
    if acc:
        return replicas * tpu_api.lookup(acc).hosts
    return replicas


def total_pods(job: dict) -> int:
    return sum(role_pods(r) for r in roles(job))


def role_sts_name(job_name: str, role_name: str) -> str:
    """One StatefulSet (and identically-named headless Service) per
    role — pod DNS is ``{job}-{role}-{i}.{job}-{role}.{ns}.svc...``."""
    return f"{job_name}-{role_name}"


def learner_role(job_roles: list[dict]) -> dict | None:
    """The role whose pod 0 is the gang's rendezvous anchor: the role
    named ``learner`` if present, else the first TPU role, else the
    first role. Accepts both spec-shape roles (``tpu`` is a dict) and
    annotation-shape roles (``tpu`` is the accelerator string)."""
    if not job_roles:
        return None
    for r in job_roles:
        if r.get("name") == "learner":
            return r
    for r in job_roles:
        if r.get("tpu"):
            return r
    return job_roles[0]


def roles_annotation_value(job: dict) -> str:
    """The JSON the controller stamps on every gang pod."""
    out = []
    for r in roles(job):
        out.append({
            "name": r["name"],
            "pods": role_pods(r),
            "service": role_sts_name(name_of(job), r["name"]),
            "tpu": role_accelerator(r),
        })
    return json.dumps(out, separators=(",", ":"))


def parse_roles_annotation(pod: dict) -> list[dict] | None:
    """Decode ``JOB_ROLES_ANNOTATION`` off a gang pod (or a pod
    template dict); None when absent or malformed."""
    raw = annotations_of(pod).get(JOB_ROLES_ANNOTATION)
    if not raw:
        return None
    try:
        parsed = json.loads(raw)
    except (TypeError, ValueError):
        return None
    if not isinstance(parsed, list):
        return None
    return parsed


def priority_of(job: dict) -> int:
    cls = deep_get(job, "spec", "priorityClassName",
                   default="default")
    return nb_api.PRIORITY_CLASSES.get(cls, nb_api.DEFAULT_PRIORITY)


def is_suspended(job: dict) -> bool:
    return nb_api.SUSPEND_ANNOTATION in annotations_of(job)


def is_stopped(job: dict) -> bool:
    return nb_api.STOP_ANNOTATION in annotations_of(job)


def make_tpujob(name: str, namespace: str | None = None, *,
                roles: list[dict],
                image: str = DEFAULT_IMAGE,
                priority_class: str | None = None,
                labels: dict | None = None,
                annotations: dict | None = None) -> dict:
    spec: dict = {"roles": roles, "image": image}
    if priority_class:
        spec["priorityClassName"] = priority_class
    return make_object(API_VERSION, KIND, name, namespace,
                       labels=labels, annotations=annotations,
                       spec=spec)


def validate(job: dict) -> None:
    """Admission validation (raises ValueError on a bad spec)."""
    job_roles = roles(job)
    if not job_roles:
        raise ValueError("spec.roles must name at least one role group")
    if len(job_roles) > MAX_ROLES:
        raise ValueError(
            f"spec.roles has {len(job_roles)} groups; max {MAX_ROLES}")
    seen: set[str] = set()
    for r in job_roles:
        rname = r.get("name")
        if not rname or not _ROLE_NAME_RE.match(str(rname)):
            raise ValueError(
                f"role name {rname!r} must be a short DNS label "
                "(lowercase alphanumerics and '-')")
        if rname in seen:
            raise ValueError(f"duplicate role name {rname!r}")
        seen.add(rname)
        replicas = r.get("replicas", 1)
        if not isinstance(replicas, int) or \
                not 1 <= replicas <= MAX_ROLE_REPLICAS:
            raise ValueError(
                f"role {rname!r}: replicas must be an integer in "
                f"[1, {MAX_ROLE_REPLICAS}], got {replicas!r}")
        acc = role_accelerator(r)
        if acc:
            tpu_api.lookup(acc)  # raises UnknownAcceleratorType
        cpu = r.get("cpu")
        if cpu is not None:
            try:
                amount = parse_quantity(cpu)
            except (TypeError, ValueError):
                raise ValueError(
                    f"role {rname!r}: cpu {cpu!r} is not a quantity"
                ) from None
            if amount <= 0:
                raise ValueError(
                    f"role {rname!r}: cpu must be positive, got {cpu!r}")
    cls = deep_get(job, "spec", "priorityClassName")
    if cls is not None and cls not in nb_api.PRIORITY_CLASSES:
        raise ValueError(
            f"unknown priorityClassName {cls!r}; known: "
            f"{sorted(nb_api.PRIORITY_CLASSES)}")


def job_name_of_pod(pod: dict) -> str | None:
    """The owning TPUJob's name, for any pod carrying the gang label."""
    return labels_of(pod).get(JOB_NAME_LABEL)
