"""TPU slice topology model — the platform's accelerator vocabulary.

The reference's accelerator model is a single resource-limit key chosen
from a vendor list (``nvidia.com/gpu`` / ``amd.com/gpu`` —
``crud-web-apps/jupyter/backend/apps/common/form.py:226-250``,
``spawner_ui_config.yaml:119-135``). A TPU slice is richer: an
accelerator *type* implies a chip topology, a number of hosts
(one pod per host), chips per host, and the GKE node labels that the
scheduler matches (``cloud.google.com/gke-tpu-accelerator``,
``cloud.google.com/gke-tpu-topology``). This module is the single
source of truth the controller, webhook, quota, and spawner all render
from, so a Notebook only ever says ``tpu: {acceleratorType: v5p-16}``.

Naming follows Cloud TPU: v5e slices are ``v5litepod-N`` with N =
chips; v4/v5p slices are ``v{4,5p}-N`` with N = TensorCores
(2 cores/chip), so v5p-8 is 4 chips on one host.
"""

from __future__ import annotations

from dataclasses import dataclass

GOOGLE_TPU_RESOURCE = "google.com/tpu"
#: synthetic allocatable key carrying a node's aggregate HBM (GiB as a
#: decimal string) — the scheduler's second packing axis. Real GKE
#: exposes HBM only through the accelerator type; the fake kubelet
#: surfaces it as a first-class quantity so per-node accounting mirrors
#: the chip/cpu axes exactly.
GOOGLE_TPU_HBM_RESOURCE = "google.com/tpu-hbm-gib"
NODE_LABEL_ACCELERATOR = "cloud.google.com/gke-tpu-accelerator"
NODE_LABEL_TOPOLOGY = "cloud.google.com/gke-tpu-topology"

# ---- predictive admission vocabulary (tpu.kubeflow.org/) -------------
#: JSON declaration of the training workload a Notebook/TPUJob intends
#: to run (preset or explicit model dims + optim/batch/accum/remat/seq/
#: dtype/offload knobs) — priced by the memplan walker at admission
DECLARED_WORKLOAD_ANNOTATION = "tpu.kubeflow.org/declared-workload"
#: stamped by the admission pricer: predicted peak HBM for the whole
#: slice (decimal GB, float as str) and predicted FLOPs per step —
#: controllers fan the per-pod share onto pod templates, the scheduler
#: packs on it
PREDICTED_HBM_ANNOTATION = "tpu.kubeflow.org/predicted-hbm-gb"
PREDICTED_FLOPS_ANNOTATION = "tpu.kubeflow.org/predicted-flops"


@dataclass(frozen=True)
class SliceTopology:
    accelerator_type: str   # user-facing, e.g. "v5litepod-16"
    gke_accelerator: str    # node label value, e.g. "tpu-v5-lite-podslice"
    topology: str           # node label value, e.g. "4x4"
    chips: int              # total chips in the slice
    hosts: int              # pods per slice (one per host)
    chip_flops_bf16: float  # peak dense bf16 FLOPs/sec per chip
    hbm_gib_per_chip: float = 16.0  # HBM per chip (GiB)

    @property
    def chips_per_host(self) -> int:
        return self.chips // self.hosts

    @property
    def multihost(self) -> bool:
        return self.hosts > 1

    @property
    def hbm_gib_per_host(self) -> float:
        return self.chips_per_host * self.hbm_gib_per_chip


_V5E = "tpu-v5-lite-podslice"
_V5P = "tpu-v5p-slice"
_V4 = "tpu-v4-podslice"
_V6E = "tpu-v6e-slice"

_TOPOLOGIES = [
    # v5e: 1 TensorCore/chip, 4-chip hosts (8-chip single-host variant for -8)
    SliceTopology("v5litepod-1", _V5E, "1x1", 1, 1, 197e12),
    SliceTopology("v5litepod-4", _V5E, "2x2", 4, 1, 197e12),
    SliceTopology("v5litepod-8", _V5E, "2x4", 8, 1, 197e12),
    SliceTopology("v5litepod-16", _V5E, "4x4", 16, 4, 197e12),
    SliceTopology("v5litepod-32", _V5E, "4x8", 32, 8, 197e12),
    SliceTopology("v5litepod-64", _V5E, "8x8", 64, 16, 197e12),
    SliceTopology("v5litepod-128", _V5E, "8x16", 128, 32, 197e12),
    SliceTopology("v5litepod-256", _V5E, "16x16", 256, 64, 197e12),
    # v5p: 2 TensorCores/chip, 4-chip hosts, 3D torus topologies up to
    # the full 8960-chip pod (cube-ish shapes, the GKE-offered set)
    SliceTopology("v5p-8", _V5P, "2x2x1", 4, 1, 459e12, 95.0),
    SliceTopology("v5p-16", _V5P, "2x2x2", 8, 2, 459e12, 95.0),
    SliceTopology("v5p-32", _V5P, "2x2x4", 16, 4, 459e12, 95.0),
    SliceTopology("v5p-64", _V5P, "2x4x4", 32, 8, 459e12, 95.0),
    SliceTopology("v5p-128", _V5P, "4x4x4", 64, 16, 459e12, 95.0),
    SliceTopology("v5p-256", _V5P, "4x4x8", 128, 32, 459e12, 95.0),
    SliceTopology("v5p-512", _V5P, "4x8x8", 256, 64, 459e12, 95.0),
    SliceTopology("v5p-1024", _V5P, "8x8x8", 512, 128, 459e12, 95.0),
    SliceTopology("v5p-2048", _V5P, "8x8x16", 1024, 256, 459e12, 95.0),
    SliceTopology("v5p-4096", _V5P, "8x16x16", 2048, 512, 459e12, 95.0),
    SliceTopology("v5p-8192", _V5P, "16x16x16", 4096, 1024, 459e12, 95.0),
    SliceTopology("v5p-12288", _V5P, "16x16x24", 6144, 1536, 459e12, 95.0),
    # v4: 2 TensorCores/chip, 4-chip hosts, up to the 3072-chip pod
    SliceTopology("v4-8", _V4, "2x2x1", 4, 1, 275e12, 32.0),
    SliceTopology("v4-16", _V4, "2x2x2", 8, 2, 275e12, 32.0),
    SliceTopology("v4-32", _V4, "2x2x4", 16, 4, 275e12, 32.0),
    SliceTopology("v4-64", _V4, "2x4x4", 32, 8, 275e12, 32.0),
    SliceTopology("v4-128", _V4, "4x4x4", 64, 16, 275e12, 32.0),
    SliceTopology("v4-256", _V4, "4x4x8", 128, 32, 275e12, 32.0),
    SliceTopology("v4-512", _V4, "4x8x8", 256, 64, 275e12, 32.0),
    SliceTopology("v4-1024", _V4, "8x8x8", 512, 128, 275e12, 32.0),
    SliceTopology("v4-2048", _V4, "8x8x16", 1024, 256, 275e12, 32.0),
    SliceTopology("v4-4096", _V4, "8x16x16", 2048, 512, 275e12, 32.0),
    SliceTopology("v4-6144", _V4, "16x16x12", 3072, 768, 275e12, 32.0),
    # v6e (Trillium): 1 TensorCore/chip, 4-chip hosts (8 for -8),
    # 2D topologies up to the 256-chip pod
    SliceTopology("v6e-1", _V6E, "1x1", 1, 1, 918e12, 32.0),
    SliceTopology("v6e-4", _V6E, "2x2", 4, 1, 918e12, 32.0),
    SliceTopology("v6e-8", _V6E, "2x4", 8, 1, 918e12, 32.0),
    SliceTopology("v6e-16", _V6E, "4x4", 16, 4, 918e12, 32.0),
    SliceTopology("v6e-32", _V6E, "4x8", 32, 8, 918e12, 32.0),
    SliceTopology("v6e-64", _V6E, "8x8", 64, 16, 918e12, 32.0),
    SliceTopology("v6e-128", _V6E, "8x16", 128, 32, 918e12, 32.0),
    SliceTopology("v6e-256", _V6E, "16x16", 256, 64, 918e12, 32.0),
]

TOPOLOGIES: dict[str, SliceTopology] = {
    t.accelerator_type: t for t in _TOPOLOGIES
}


class UnknownAcceleratorType(ValueError):
    pass


def lookup(accelerator_type: str) -> SliceTopology:
    try:
        return TOPOLOGIES[accelerator_type]
    except KeyError:
        raise UnknownAcceleratorType(
            f"unknown TPU acceleratorType {accelerator_type!r}; known: "
            f"{sorted(TOPOLOGIES)}"
        ) from None


def by_node_labels(gke_accelerator: str, topology: str) -> SliceTopology | None:
    """Reverse lookup from GKE node labels (spawner capacity discovery)."""
    for t in _TOPOLOGIES:
        if t.gke_accelerator == gke_accelerator and t.topology == topology:
            return t
    return None
