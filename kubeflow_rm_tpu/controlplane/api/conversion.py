"""Multi-version Notebook API + conversion (the platform's API
evolution story).

The reference serves ``kubeflow.org/{v1alpha1,v1beta1,v1} Notebook``
with conversion shims between structurally-identical types
(``notebook-controller/api/v1beta1/notebook_types.go:27-34``,
``api/v1/notebook_conversion.go:1-30`` — v1beta1 is the storage "hub",
the others convert through it). This framework serves three versions
with REAL schema deltas, because the TPU block is the field that
actually evolved here:

- ``v1`` (storage/hub): first-class ``spec.tpu {acceleratorType,
  numSlices}`` — what every controller in this repo consumes.
- ``v1beta1`` (served): the reference-era shape — no ``spec.tpu``;
  TPU placement rides the ``notebooks.kubeflow.org/tpu-accelerator-
  type`` / ``tpu-num-slices`` annotations (the same strings the
  controller stamps on pods, so reference-era tooling already knows
  them).
- ``v1alpha1`` (served): the oldest shape — annotation-carried like
  v1beta1 but under the bare ``kubeflow.org/tpu-*`` keys that predate
  the ``notebooks.`` prefix convention.

Conversion is lossless in every direction: spoke→v1 hoists the
annotations into ``spec.tpu``; v1→spoke demotes ``spec.tpu`` into
that spoke's annotation keys. Everything else (the embedded PodSpec,
status, behavior annotations) is version-invariant, exactly as in the
reference.

Served by two paths that must agree (tests assert both):

- the apiextensions ConversionReview endpoint
  (``deploy/webhook_server.py`` ``POST /convert``) — what a real
  cluster calls;
- the REST facade (``deploy/restserver.py``), which converts at the
  collection boundary so a client reading
  ``/apis/kubeflow.org/v1beta1/...`` sees v1beta1 objects over the
  same store.
"""

from __future__ import annotations

from kubeflow_rm_tpu.controlplane.api.meta import fast_deepcopy

GROUP = "kubeflow.org"
STORAGE_VERSION = "v1"
SERVED_VERSIONS = ("v1alpha1", "v1beta1", "v1")

#: v1beta1 carries the TPU block as annotations (not labels — these
#: describe the CR itself; the controller separately stamps pod LABELS
#: with the same suffixes for the webhook to read)
TPU_ACCELERATOR_ANNOTATION = "notebooks.kubeflow.org/tpu-accelerator-type"
TPU_NUM_SLICES_ANNOTATION = "notebooks.kubeflow.org/tpu-num-slices"

#: v1alpha1 predates the ``notebooks.`` prefix convention: same
#: annotation-shaped TPU placement under the bare group keys (the
#: oldest tooling's strings). Structurally identical otherwise — the
#: reference's v1alpha1 is likewise a rename-era twin of v1beta1.
LEGACY_TPU_ACCELERATOR_ANNOTATION = "kubeflow.org/tpu-accelerator-type"
LEGACY_TPU_NUM_SLICES_ANNOTATION = "kubeflow.org/tpu-num-slices"


def version_of(obj: dict) -> str:
    api_version = obj.get("apiVersion") or f"{GROUP}/{STORAGE_VERSION}"
    return api_version.rsplit("/", 1)[-1]


def convert_notebook(obj: dict, to_version: str) -> dict:
    """Convert a Notebook between served versions (hub = v1).

    Returns a new object; the input is not mutated. Unknown versions
    raise ValueError (a real conversion webhook answers those with a
    Failure status)."""
    if to_version not in SERVED_VERSIONS:
        raise ValueError(f"unknown Notebook version {to_version!r} "
                         f"(served: {', '.join(SERVED_VERSIONS)})")
    cur = version_of(obj)
    if cur not in SERVED_VERSIONS:
        raise ValueError(f"cannot convert from unknown version {cur!r}")
    out = fast_deepcopy(obj)
    if cur != STORAGE_VERSION:
        out = _annotations_to_hub(out, *_TPU_KEYS[cur])
    if to_version != STORAGE_VERSION:
        out = _hub_to_annotations(out, *_TPU_KEYS[to_version])
    out["apiVersion"] = f"{GROUP}/{to_version}"
    return out


#: spoke version -> (accelerator key, num-slices key): both pre-hub
#: shapes are annotation-carried, they just disagree on key names
_TPU_KEYS = {
    "v1beta1": (TPU_ACCELERATOR_ANNOTATION, TPU_NUM_SLICES_ANNOTATION),
    "v1alpha1": (LEGACY_TPU_ACCELERATOR_ANNOTATION,
                 LEGACY_TPU_NUM_SLICES_ANNOTATION),
}


def _annotations_to_hub(obj: dict, acc_key: str,
                        slices_key: str) -> dict:
    """Hoist the TPU annotations into first-class ``spec.tpu``. An
    object that (illegally) carries both keeps ``spec.tpu`` — the
    structured field is authoritative."""
    ann = (obj.get("metadata") or {}).get("annotations") or {}
    spec = obj.setdefault("spec", {})
    acc = ann.pop(acc_key, None)
    raw_slices = ann.pop(slices_key, None)
    if acc and "tpu" not in spec:
        tpu: dict = {"acceleratorType": acc}
        if raw_slices is not None:
            try:
                n = int(raw_slices)
            except ValueError as e:
                raise ValueError(
                    f"{slices_key}={raw_slices!r} is "
                    "not an integer") from e
            if n != 1:
                tpu["numSlices"] = n
        spec["tpu"] = tpu
    if not ann and "annotations" in (obj.get("metadata") or {}):
        obj["metadata"].pop("annotations", None)
    elif ann:
        obj["metadata"]["annotations"] = ann
    return obj


def _hub_to_annotations(obj: dict, acc_key: str,
                        slices_key: str) -> dict:
    """Demote ``spec.tpu`` into the annotations the pre-hub shapes
    use."""
    spec = obj.get("spec") or {}
    tpu = spec.pop("tpu", None)
    if tpu:
        ann = obj.setdefault("metadata", {}).setdefault(
            "annotations", {})
        ann[acc_key] = tpu["acceleratorType"]
        n = int(tpu.get("numSlices", 1))
        if n != 1:
            ann[slices_key] = str(n)
    return obj


# ---- TPUJob (multi-role gang jobs) -----------------------------------

#: both pre-hub TPUJob spokes carry the role list as ONE JSON
#: annotation under the same key — the kind predates neither prefix
#: convention (it is new), so there is no key rename to model; the
#: spokes exist to exercise the conversion seam the moment the roles
#: schema evolves, and the JSON carrier is lossless for ANY role set
TPU_JOB_ROLES_ANNOTATION = "kubeflow.org/tpu-job-roles"


def convert_tpujob(obj: dict, to_version: str) -> dict:
    """Convert a TPUJob between served versions (hub = v1).

    v1 carries ``spec.roles`` first-class; v1alpha1/v1beta1 demote it
    to a JSON annotation (``TPU_JOB_ROLES_ANNOTATION``). Image and
    priorityClassName are version-invariant."""
    import json

    if to_version not in SERVED_VERSIONS:
        raise ValueError(f"unknown TPUJob version {to_version!r} "
                         f"(served: {', '.join(SERVED_VERSIONS)})")
    cur = version_of(obj)
    if cur not in SERVED_VERSIONS:
        raise ValueError(f"cannot convert from unknown version {cur!r}")
    out = fast_deepcopy(obj)
    if cur != STORAGE_VERSION:
        ann = (out.get("metadata") or {}).get("annotations") or {}
        raw = ann.pop(TPU_JOB_ROLES_ANNOTATION, None)
        spec = out.setdefault("spec", {})
        if raw is not None and "roles" not in spec:
            try:
                spec["roles"] = json.loads(raw)
            except ValueError as e:
                raise ValueError(
                    f"{TPU_JOB_ROLES_ANNOTATION} is not valid JSON"
                ) from e
        if not ann and "annotations" in (out.get("metadata") or {}):
            out["metadata"].pop("annotations", None)
        elif ann:
            out["metadata"]["annotations"] = ann
    if to_version != STORAGE_VERSION:
        spec = out.get("spec") or {}
        job_roles = spec.pop("roles", None)
        if job_roles is not None:
            ann = out.setdefault("metadata", {}).setdefault(
                "annotations", {})
            ann[TPU_JOB_ROLES_ANNOTATION] = json.dumps(
                job_roles, separators=(",", ":"))
    out["apiVersion"] = f"{GROUP}/{to_version}"
    return out


#: kind -> converter; the webhook server and REST facade both dispatch
#: through this table, so adding a multi-version kind is one entry
CONVERTERS = {"Notebook": convert_notebook, "TPUJob": convert_tpujob}


def convert_review(review: dict) -> dict:
    """Answer an apiextensions.k8s.io/v1 ConversionReview request —
    the wire protocol a real apiserver speaks to the conversion
    webhook (strategy: Webhook in the CRD)."""
    req = review.get("request") or {}
    desired = (req.get("desiredAPIVersion") or "").rsplit("/", 1)[-1]
    converted, err = [], None
    for obj in req.get("objects") or []:
        kind = obj.get("kind")
        fn = CONVERTERS.get(kind)
        if fn is None:
            err = f"no conversion registered for kind {kind!r}"
            break
        try:
            converted.append(fn(obj, desired))
        except ValueError as e:
            err = str(e)
            break
    resp: dict = {"uid": req.get("uid")}
    if err is None:
        resp["convertedObjects"] = converted
        resp["result"] = {"status": "Success"}
    else:
        resp["result"] = {"status": "Failed", "message": err}
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "ConversionReview",
        "response": resp,
    }
