"""Kubernetes-style object model: plain dicts + typed helpers.

Objects are nested dicts shaped exactly like their Kubernetes wire form
(``apiVersion``/``kind``/``metadata``/``spec``/``status``). The
reference manipulates the same shapes through Go structs
(e.g. ``components/notebook-controller/api/v1beta1/notebook_types.go:27-63``);
here the dict IS the API object and these helpers give the handful of
typed operations every controller needs (deep access, owner refs,
label selection) without inventing a parallel corev1.
"""

from __future__ import annotations

import copy
import itertools
import json
from typing import Any


def fast_deepcopy(obj):
    """Deep copy for JSON-shaped objects via serialize/parse — ~3-5×
    cheaper than ``copy.deepcopy`` for the dict/list/scalar trees every
    kube object is, and measurably load-bearing in the 20-way spawn
    path. Falls back to deepcopy for non-JSON leaves."""
    try:
        return json.loads(json.dumps(obj))
    except (TypeError, ValueError):
        return copy.deepcopy(obj)

_uid_counter = itertools.count(1)


def new_uid() -> str:
    return f"uid-{next(_uid_counter):08d}"


def make_object(api_version: str, kind: str, name: str,
                namespace: str | None = None, *,
                labels: dict | None = None,
                annotations: dict | None = None,
                spec: Any = None) -> dict:
    meta: dict[str, Any] = {"name": name}
    if namespace is not None:
        meta["namespace"] = namespace
    if labels:
        meta["labels"] = dict(labels)
    if annotations:
        meta["annotations"] = dict(annotations)
    obj = {"apiVersion": api_version, "kind": kind, "metadata": meta}
    if spec is not None:
        obj["spec"] = spec
    return obj


def name_of(obj: dict) -> str:
    return obj["metadata"]["name"]


def namespace_of(obj: dict) -> str | None:
    return obj["metadata"].get("namespace")


def uid_of(obj: dict) -> str | None:
    return obj["metadata"].get("uid")


def labels_of(obj: dict) -> dict:
    return obj["metadata"].get("labels") or {}


def annotations_of(obj: dict) -> dict:
    return obj["metadata"].get("annotations") or {}


def set_annotation(obj: dict, key: str, value: str) -> None:
    obj["metadata"].setdefault("annotations", {})[key] = value


def remove_annotation(obj: dict, key: str) -> None:
    obj["metadata"].get("annotations", {}).pop(key, None)


def set_label(obj: dict, key: str, value: str) -> None:
    obj["metadata"].setdefault("labels", {})[key] = value


def deep_get(obj: Any, *path, default=None):
    cur = obj
    for p in path:
        if isinstance(cur, dict):
            if p not in cur:
                return default
            cur = cur[p]
        elif isinstance(cur, list):
            if not isinstance(p, int) or p >= len(cur):
                return default
            cur = cur[p]
        else:
            return default
    return cur


def deep_set(obj: dict, *path_and_value) -> None:
    *path, value = path_and_value
    cur = obj
    for p in path[:-1]:
        cur = cur.setdefault(p, {})
    cur[path[-1]] = value


def owner_reference(owner: dict, *, controller: bool = True,
                    block_owner_deletion: bool = True) -> dict:
    return {
        "apiVersion": owner["apiVersion"],
        "kind": owner["kind"],
        "name": name_of(owner),
        "uid": uid_of(owner),
        "controller": controller,
        "blockOwnerDeletion": block_owner_deletion,
    }


def set_controller_reference(owner: dict, obj: dict) -> None:
    refs = obj["metadata"].setdefault("ownerReferences", [])
    for r in refs:
        if r.get("controller"):
            if r.get("uid") != uid_of(owner):
                raise ValueError(
                    f"{obj['kind']}/{name_of(obj)} already owned by "
                    f"{r['kind']}/{r['name']}"
                )
            return
    refs.append(owner_reference(owner))


def controller_owner(obj: dict) -> dict | None:
    for r in obj["metadata"].get("ownerReferences", []):
        if r.get("controller"):
            return r
    return None


def matches_selector(labels: dict, selector: dict) -> bool:
    """Kubernetes LabelSelector: matchLabels + matchExpressions
    (In/NotIn/Exists/DoesNotExist)."""
    for k, v in (selector.get("matchLabels") or {}).items():
        if labels.get(k) != v:
            return False
    for expr in selector.get("matchExpressions") or []:
        key, op = expr["key"], expr["operator"]
        values = expr.get("values") or []
        if op == "In":
            if labels.get(key) not in values:
                return False
        elif op == "NotIn":
            if key in labels and labels[key] in values:
                return False
        elif op == "Exists":
            if key not in labels:
                return False
        elif op == "DoesNotExist":
            if key in labels:
                return False
        else:
            raise ValueError(f"unknown selector operator {op!r}")
    return True


def strategic_merge(base: Any, patch: Any) -> Any:
    """Merge-patch semantics: dicts merge recursively, ``None`` deletes a
    key, lists and scalars replace. (Good enough for the PATCH surface
    the web apps and controllers use — the reference patches
    annotations/replicas the same way.)"""
    if isinstance(base, dict) and isinstance(patch, dict):
        out = dict(base)
        for k, v in patch.items():
            if v is None:
                out.pop(k, None)
            elif k in out:
                out[k] = strategic_merge(out[k], v)
            else:
                out[k] = fast_deepcopy(v)
        return out
    return fast_deepcopy(patch)


def get_condition(obj: dict, ctype: str) -> dict | None:
    for c in deep_get(obj, "status", "conditions", default=[]) or []:
        if c.get("type") == ctype:
            return c
    return None


def set_condition(obj: dict, condition: dict) -> None:
    conds = obj.setdefault("status", {}).setdefault("conditions", [])
    for i, c in enumerate(conds):
        if c.get("type") == condition.get("type"):
            conds[i] = condition
            return
    conds.append(condition)


def parse_quantity(q) -> float:
    """Parse a Kubernetes resource quantity ("500m", "1Gi", "4") to a
    float in base units."""
    if isinstance(q, (int, float)):
        return float(q)
    s = str(q).strip()
    suffixes = {
        "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50,
        "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12, "P": 1e15,
    }
    for suf, mult in suffixes.items():
        if s.endswith(suf):
            return float(s[:-len(suf)]) * mult
    if s.endswith("m"):
        return float(s[:-1]) / 1000.0
    return float(s)
